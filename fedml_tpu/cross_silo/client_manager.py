"""Cross-silo FL client: trainer + message FSM.

reference: ``cross_silo/client/fedml_client_master_manager.py:17-176`` — FSM:
connection_ready → send ONLINE status → S2C_INIT → train → C2S model →
S2C_SYNC … → S2C_FINISH. The "hierarchical" DDP path
(``fedml_trainer_dist_adapter.py``, ``process_group_manager.py``) is replaced
by JAX intra-host data parallelism: a silo with multiple local chips trains
its local shard under one jit with a batch-sharded mesh — no process groups
to manage.

Liveness / resync FSM (``--heartbeat_s``, docs/robustness.md "Server
failover & resync"): RUNNING --(heartbeat-ack silence past the miss
window, or a send failure)--> RESYNC --(bounded exponential ``c2s_resync``
attempts)--> RUNNING on ``s2c_resync_ack``. The ack tells this client
whether its last trained update was durably aggregated; if not, the cached
stamped message is replayed verbatim — a restarted server (fresh dedup
window) accepts it, a server that never died dedups it, so a crash can
neither lose nor double-count a contribution.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import constants
from ..core.distributed import FedMLCommManager, Message
from ..core.dp import FedPrivacyMechanism
from ..delivery import VersionedModelStore, WireCodec, flatten_leaves
from ..delivery.delta_codec import DELTA_KEY, payload_nbytes
from ..delivery.device_codec import host_view
from ..delivery.payload_filter import filter_from_args
from .message_define import MyMessage

logger = logging.getLogger(__name__)


class ClientMasterManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank=0, size=0,
                 backend=constants.COMM_BACKEND_LOOPBACK, dataset=None,
                 silo_plane=None, silo_shard=None):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer  # ClientTrainer or TrainerDistAdapter
        self.ds = dataset
        # hierarchical silo: master's handle on DCN slaves + its own slice
        # of the silo shard (reference: fedml_client_master_manager.py with
        # process_group_manager; here client_slave_manager.SiloMasterPlane)
        self.silo_plane = silo_plane
        self.silo_shard = silo_shard
        self.client_index = rank - 1
        self.round_idx = 0
        # highest round already trained + the exact stamped message we
        # answered it with: a re-delivered or delayed S2C_SYNC/INIT for
        # that round must not RETRAIN (same params in, same model out, but
        # a fresh seq could double-count at a server whose dedup window
        # rotated) — instead the cached message is re-sent verbatim. Same
        # seq, so a live server dedups it, while a RESTARTED server (fresh
        # dedup window, re-broadcasting the uncommitted round it lost)
        # gets the model it needs — without this, every client would drop
        # the replay and the resumed round could never complete.
        self._last_trained_round = -1
        self._last_model_msg: Optional[Message] = None
        self.done = threading.Event()
        self.dp = (
            FedPrivacyMechanism.from_args(args)
            if bool(getattr(args, "enable_dp", False))
            and str(getattr(args, "dp_type", "cdp")) == "ldp"
            else None
        )
        self._treedef: Optional[object] = None
        self._shapes: Optional[list] = None
        # wire compression of the C2S update delta (core/compression.UpdateCodec)
        from ..core.compression import UpdateCodec

        self.codec = UpdateCodec(args)
        self._round_global_vec = None  # broadcast params, codec reference
        # -- delta delivery plane (fedml_tpu/delivery/, docs/delivery.md) --
        # the client end of the version-indexed store: every received
        # global is kept (flat, host memory) so an S2C delta frame against
        # any version we ACKed decodes losslessly. s2c_delta=off keeps the
        # plane fully out of the path (full frames both ways).
        self._s2c_delta_on = (
            str(getattr(args, "s2c_delta", "auto") or "auto").lower()
            != "off"
        )
        self._base_store = VersionedModelStore(
            int(getattr(args, "delta_store_versions", 8) or 8),
            metric_prefix="comm.delta.client_store",
        ) if self._s2c_delta_on else None
        # wire-path facade (shared knob with the server): device-kernel
        # decode feeds tree_unflatten_from_vector without a host round-trip
        self.wire = WireCodec(getattr(args, "wire_path", "auto"),
                              scoped=self.world.telemetry)
        # adapter-only C2S payloads — built with the treedef (needs the
        # model skeleton for leaf names)
        self._filter = None
        self._client_pull = (
            str(getattr(args, "aggregation_mode", "sync") or "sync").lower()
            == "async"
            and str(getattr(args, "async_dispatch", "sync_on_consume")
                    or "sync_on_consume").lower() == "client_pull"
        )
        # -- liveness / resync FSM (docs/robustness.md) ---------------------
        # heartbeat_s = 0 keeps the whole plane inert (the pre-failover
        # wire behavior, bitwise). All FSM state is guarded by _fsm_lock:
        # the comm thread (handlers) and the heartbeat/backoff timer
        # threads both drive transitions.
        self._hb_s = float(getattr(args, "heartbeat_s", 0.0) or 0.0)
        self._hb_miss_limit = max(
            int(getattr(args, "heartbeat_miss_limit", 3) or 3), 1)
        self._resync_base_s = float(
            getattr(args, "resync_backoff_s", 0.5) or 0.5)
        self._resync_max_s = float(
            getattr(args, "resync_backoff_max_s", 10.0) or 10.0)
        self._resync_max_attempts = int(
            getattr(args, "resync_max_attempts", 30) or 30)
        self._fsm_lock = threading.Lock()
        self._fsm_state = "running"   # running | resync | lost
        self._resync_attempt = 0
        self._last_server_traffic = time.monotonic()
        # seeded backoff jitter (docs/robustness.md "thundering herd"):
        # an edge kill orphans a whole lease block at once — bare
        # exponential backoff would retry every orphan on the same
        # schedule against the adoptive edge. U[0.5,1.5) per attempt,
        # deterministic per (world seed, rank).
        seed = int(getattr(args, "random_seed", 0) or 0)
        self._backoff_rng = np.random.RandomState(
            (seed * 1_000_003 + rank * 7919) % (2 ** 31 - 1))
        # -- hierarchical edge tier (docs/robustness.md "Edge tier failure
        # domains"): this client's serving target is its HOME EDGE, not the
        # root; on edge death the resync budget against the corpse runs out
        # and the client re-homes around the sibling ring, then to the root
        from ..hierarchy import Topology

        topo = Topology.from_args(args)
        if topo is not None and topo.is_client(rank):
            self._server_rank = topo.home_edge(rank)
            self._rehome_targets = topo.rehome_targets(rank)
            self._rehome_after = int(
                getattr(args, "rehome_after_attempts", 3) or 3)
        else:
            self._server_rank = 0
            self._rehome_targets = []
            self._rehome_after = 0

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_connection_ready
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_init
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._on_sync
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self._on_finish
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SHED_NOTICE, self._on_shed
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_HEARTBEAT_ACK, self._on_heartbeat_ack
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_RESYNC_ACK, self._on_resync_ack
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_E2C_RESOLICIT, self._on_resolicit
        )

    def _on_connection_ready(self, msg: Message) -> None:
        self._note_server_traffic()
        try:
            self._announce_online()
        except Exception as e:  # noqa: BLE001 — classified below
            if self._hb_s <= 0:
                raise  # no liveness plane: keep the fail-fast behavior
            # the server is not up (yet, or anymore): the resync loop is
            # the announcement path — its handshake doubles as ONLINE
            self._suspect_connection(f"online announce failed: {e}")
        self._arm_heartbeat()

    def _announce_online(self) -> None:
        """The ONE ONLINE announcement (connection-ready AND the delta
        base-missing recovery both send it — the server resets this
        client's liveness and ACK state on receipt)."""
        status = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank,
                         self._server_rank)
        status.add(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                   MyMessage.CLIENT_STATUS_ONLINE)
        self.send_message(status)

    # -- liveness / resync FSM (docs/robustness.md) -------------------------

    def _note_server_traffic(self) -> None:
        """Any S2C message renews the server's lease (heartbeat acks are
        just the guaranteed-minimum traffic)."""
        with self._fsm_lock:
            self._last_server_traffic = time.monotonic()

    def _arm_heartbeat(self) -> None:
        if self._hb_s <= 0 or self.done.is_set():
            return
        t = threading.Timer(self._hb_s, self._on_heartbeat_tick)
        t.daemon = True
        # tethered (graftiso I005): finish() -> world.shutdown() cancels
        # the pending tick when the federation ends
        self.world.register_timer(t)
        t.start()

    def _on_heartbeat_tick(self) -> None:
        """One lease check: silence past the miss window enters RESYNC;
        otherwise send a heartbeat. Re-arms itself until FINISH."""
        if self.done.is_set():
            return
        enter_resync = False
        with self._fsm_lock:
            silence = time.monotonic() - self._last_server_traffic
            running = self._fsm_state == "running"
            if running and silence > self._hb_miss_limit * self._hb_s:
                self._fsm_state = "resync"
                self._resync_attempt = 0
                enter_resync = True
        if enter_resync:
            self.world.telemetry.counter_inc("comm.heartbeat_misses")
            logger.warning(
                "client %d: no server traffic for %.2fs (> %d x %.2fs) — "
                "entering resync", self.rank, silence,
                self._hb_miss_limit, self._hb_s,
            )
            self._attempt_resync()
        elif running:
            hb = Message(MyMessage.MSG_TYPE_C2S_HEARTBEAT, self.rank,
                         self._server_rank)
            hb.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
            # clock probe (docs/tracing.md): our monotonic send time rides
            # the heartbeat; the ack echoes it with the server's clocks
            hb.add(MyMessage.MSG_ARG_KEY_HB_T_SEND, time.monotonic())
            try:
                self.send_message(hb)
            except Exception as e:  # noqa: BLE001 — any send failure
                self._suspect_connection(f"heartbeat send failed: {e}")
        self._arm_heartbeat()

    def _suspect_connection(self, reason: str) -> None:
        """A failed send (gRPC UNAVAILABLE past the retry budget, MQTT
        drop) or heartbeat silence: RUNNING -> RESYNC. Idempotent — a
        caller racing an already-resyncing FSM no-ops."""
        if self._hb_s <= 0 or self.done.is_set():
            return
        with self._fsm_lock:
            if self._fsm_state != "running":
                return
            self._fsm_state = "resync"
            self._resync_attempt = 0
        self.world.telemetry.counter_inc("comm.heartbeat_misses")
        logger.warning("client %d: connection suspect (%s) — entering "
                       "resync", self.rank, reason)
        self._attempt_resync()

    def _attempt_resync(self) -> None:
        """One bounded-exponential reconnect attempt: send ``c2s_resync``
        (fresh stamp each attempt — the server's ack is idempotent) and
        re-arm the backoff timer until the ack flips the FSM back to
        RUNNING or the attempt budget runs out."""
        if self.done.is_set():
            return
        with self._fsm_lock:
            if self._fsm_state != "resync":
                return
            self._resync_attempt += 1
            attempt = self._resync_attempt
        if attempt > self._resync_max_attempts:
            with self._fsm_lock:
                self._fsm_state = "lost"
            logger.error(
                "client %d: resync gave up after %d attempts — the server "
                "never came back", self.rank, self._resync_max_attempts,
            )
            return
        if self._rehome_after > 0 and attempt > self._rehome_after \
                and self._rehome_targets:
            # the resync budget against this edge ran out and siblings
            # remain: abandon the corpse instead of burning the rest of
            # the attempt budget on it
            self._rehome()
            return
        self.world.telemetry.counter_inc("comm.reconnects")
        msg = Message(MyMessage.MSG_TYPE_C2S_RESYNC, self.rank,
                      self._server_rank)
        msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self._last_trained_round)
        if self._s2c_delta_on:
            # the resync doubles as a delta ACK: this client still holds
            # the global it last trained from, so S2C deltas can resume
            # against it without a full-frame round-trip
            msg.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
        try:
            self.send_message(msg)
        except Exception as e:  # noqa: BLE001 — server still down: back off
            logger.info("client %d: resync attempt %d failed to send (%s)",
                        self.rank, attempt, e)
        delay = min(self._resync_base_s * (2.0 ** (attempt - 1)),
                    self._resync_max_s)
        # seeded jitter x U[0.5,1.5): de-synchronizes a lease block's worth
        # of orphans without breaking per-world determinism
        delay *= 0.5 + self._backoff_rng.rand()
        t = threading.Timer(delay, self._attempt_resync)
        t.daemon = True
        self.world.register_timer(t)
        t.start()

    def _rehome(self) -> None:
        """Adopt the next failover target (sibling ring, then root): bump
        the delivery epoch, re-target the cached update, and send
        ``c2e_rehome`` — its ``s2c_resync_ack`` flips us back to RUNNING
        and replays the cached update iff the adoptive edge's committed
        record does not cover it.

        The epoch bump is what makes the replay land exactly once: the
        stamp's seq counter is shared across receivers, so the cached
        update's ORIGINAL seq sits below the adoptive edge's dedup-window
        floor (a false duplicate), while the old — possibly merely
        partitioned — edge still dedups the original-stamped copy it
        already accepted. Fresh epoch: new window at the adoptive edge,
        stale-epoch drops for any late sends to nobody."""
        with self._fsm_lock:
            if self._fsm_state != "resync" or not self._rehome_targets:
                return
            old = self._server_rank
            target = self._rehome_targets.pop(0)
            self._server_rank = target
            self._resync_attempt = 0
        self.world.telemetry.counter_inc("comm.rehomes")
        logger.warning(
            "client %d: edge %d unreachable — re-homing to %s %d",
            self.rank, old, "root" if target == 0 else "edge", target,
        )
        self.bump_epoch()
        cached = self._last_model_msg
        if cached is not None:
            # re-target the cached round result and strip its stamp: the
            # replay (resync-ack path) restamps it under the new epoch
            params = {
                k: v for k, v in cached.get_params().items()
                if k not in (Message.MSG_ARG_KEY_SEQ,
                             Message.MSG_ARG_KEY_EPOCH)
            }
            params[Message.MSG_ARG_KEY_RECEIVER] = target
            fresh = Message()
            fresh.init(params)
            fresh.set_arrays(cached.get_arrays())
            self._last_model_msg = fresh
        msg = Message(MyMessage.MSG_TYPE_C2E_REHOME, self.rank, target)
        msg.add(MyMessage.MSG_ARG_KEY_OLD_EDGE, old)
        msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self._last_trained_round)
        if self._s2c_delta_on and self._last_trained_round >= 0:
            # delta ACK: the globals we hold came from the root's single
            # source of truth — the adoptive edge's replica has the same
            # bytes, so S2C deltas can resume against our last version
            msg.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
        try:
            self.send_message(msg)
        except Exception as e:  # noqa: BLE001 — next attempt retries
            logger.info("client %d: rehome send failed (%s)", self.rank, e)
        delay = self._resync_base_s * (0.5 + self._backoff_rng.rand())
        t = threading.Timer(delay, self._attempt_resync)
        t.daemon = True
        self.world.register_timer(t)
        t.start()

    def _on_resolicit(self, msg: Message) -> None:
        """A restarted home edge recovering its fold buffer
        (``e2c_resolicit``): re-offer the cached still-stamped update
        verbatim — the restarted edge's fresh dedup window accepts it, the
        root's committed-round guard drops it if the dead edge had already
        shipped it. An edge we re-homed AWAY from gets nothing (our
        contribution rides the adoptive edge now)."""
        if msg.get_sender_id() != self._server_rank:
            return
        self._note_server_traffic()
        cached = self._last_model_msg
        if cached is None:
            return
        self.world.telemetry.counter_inc("comm.resolicit_replays")
        logger.info(
            "client %d: edge %d re-solicited — re-offering round-%d update",
            self.rank, msg.get_sender_id(), self._last_trained_round,
        )
        try:
            self.send_message(cached)
        except Exception as e:  # noqa: BLE001
            self._suspect_connection(f"resolicit replay failed: {e}")

    def _on_heartbeat_ack(self, msg: Message) -> None:
        self._note_server_traffic()
        t_echo = msg.get(MyMessage.MSG_ARG_KEY_HB_T_ECHO)
        t_recv = msg.get(MyMessage.MSG_ARG_KEY_HB_T_RECV)
        t_reply = msg.get(MyMessage.MSG_ARG_KEY_HB_T_REPLY)
        if t_echo is not None and t_recv is not None and t_reply is not None:
            # close the NTP-style probe pair: (our send, server recv,
            # server reply, our recv) → per-peer offset/uncertainty
            est = self.world.trace.clock_probe(
                peer=self._server_rank, t_send=float(t_echo),
                t_peer_recv=float(t_recv),
                t_peer_send=float(t_reply), t_recv=time.monotonic())
            if est is not None:
                self.world.telemetry.gauge_set(
                    "trace.clock_offset_s", est[0])
                self.world.telemetry.gauge_set(
                    "trace.clock_uncertainty_s", est[1])

    def _on_resync_ack(self, msg: Message) -> None:
        """The handshake's answer: back to RUNNING, and replay the cached
        unACKed update iff the server's committed record does not cover it
        — verbatim (same seq), so a server that never died dedups the
        replay while a restarted one (fresh window) accepts it. Either
        way the contribution is folded exactly once."""
        self._note_server_traffic()
        with self._fsm_lock:
            was = self._fsm_state
            self._fsm_state = "running"
            self._resync_attempt = 0
        committed = int(msg.get(MyMessage.MSG_ARG_KEY_COMMITTED_ROUND, -1))
        cached = self._last_model_msg
        try:
            if (was != "running" and cached is not None
                    and self._last_trained_round > committed):
                self.world.telemetry.counter_inc("comm.resync_replays")
                logger.info(
                    "client %d: round-%d update not covered by the server "
                    "(committed %d) — replaying the cached stamped message",
                    self.rank, self._last_trained_round, committed,
                )
                self.send_message(cached)
            if was != "running" and self._client_pull \
                    and self._last_trained_round >= 0:
                # client_pull dispatch: re-park our pull — a restarted
                # server lost the parking, and a live one parks the
                # fresh pull idempotently (it is a set)
                pull = Message(MyMessage.MSG_TYPE_C2S_PULL_REQUEST,
                               self.rank, self._server_rank)
                pull.add(MyMessage.MSG_ARG_KEY_ROUND_IDX,
                         self._last_trained_round)
                if self._s2c_delta_on:
                    pull.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
                self.send_message(pull)
        except Exception as e:  # noqa: BLE001
            self._suspect_connection(f"resync replay failed: {e}")

    def _ensure_skeleton(self) -> None:
        if self._treedef is not None:
            return
        # initialize a skeleton to learn the treedef (and leaf shapes, the
        # delta-frame unflatten substrate)
        skeleton = self.trainer.model.init(
            jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0)))
        )
        leaves, self._treedef = jax.tree.flatten(skeleton)
        self._shapes = [l.shape for l in leaves]
        self._filter = filter_from_args(self.args, skeleton)

    def _install_params(self, msg: Message,
                        version: Optional[int] = None) -> bool:
        # span: wire decode + model install — parents to the dispatch span
        # the comm layer adopted from the S2C message's trace context
        with self.world.trace.span("decode", client=self.rank):
            return self._install_params_traced(msg, version)

    def _install_params_traced(self, msg: Message,
                               version: Optional[int] = None) -> bool:
        """Install a dispatched model — a full leaf list, or an S2C delta
        frame decoded against the version we last held (docs/delivery.md).
        Returns False when a delta's base version is gone (a restarted
        client whose store died — the server falls back to full frames
        once our next ONLINE clears its ACK)."""
        self._ensure_skeleton()
        dmeta = msg.get(DELTA_KEY)
        new_vec = None
        if dmeta is not None:
            from ..utils.tree import tree_unflatten_from_vector

            on_device = self.wire.path == "device"
            if self._base_store is None:
                base = None
            elif on_device:
                # device-resident ring head: the base we ACKed last round
                # is already on device — the decode never re-uploads it
                base = self._base_store.get_device(
                    int(dmeta["base_version"]))
            else:
                base = self._base_store.get(int(dmeta["base_version"]))
            if base is None:
                self.world.telemetry.counter_inc(
                    "comm.delta.client_base_missing")
                logger.error(
                    "client %d: S2C delta references version %s which this "
                    "client no longer holds — dropping the frame and "
                    "re-announcing ONLINE so the server clears our ACK "
                    "(its next dispatch falls back to a full frame)",
                    self.rank, dmeta.get("base_version"),
                )
                self._announce_online()
                return False
            # device path: new_vec IS a device array — it feeds the
            # unflatten below directly (jnp.asarray no-ops) instead of
            # round-tripping the reconstructed model through host memory
            new_vec = self.wire.decode(base, msg.get_arrays(), dmeta)
            params = tree_unflatten_from_vector(
                jnp.asarray(new_vec), self._treedef, self._shapes)
        else:
            leaves = [jnp.asarray(a) for a in msg.get_arrays()]
            params = jax.tree.unflatten(self._treedef, leaves)
        self.trainer.set_model_params(params)
        if self._base_store is not None and version is not None:
            if new_vec is None:
                new_vec = flatten_leaves(jax.tree.leaves(params))
            if isinstance(new_vec, np.ndarray):
                self._base_store.put(int(version), new_vec)
            else:
                # seed the device ring-head cache with the buffer we
                # already hold — next round's delta decodes against it
                # with zero uploads
                self._base_store.put(
                    int(version),
                    host_view(new_vec, scoped=self.world.telemetry),
                    device=new_vec)
        if self.codec.enabled():
            from ..utils.tree import tree_flatten_to_vector

            if self._filter is not None:
                # filtered payloads: the codec's reference is the filtered
                # sub-vector (what actually rides the wire)
                self._round_global_vec = jnp.asarray(
                    self._filter.select_vector(jax.tree.leaves(params)))
            else:
                self._round_global_vec, _, _ = tree_flatten_to_vector(params)
        return True

    def _on_init(self, msg: Message) -> None:
        self._note_server_traffic()
        self.client_index = int(
            msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, self.client_index)
        )
        round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        if self._replay_guard("INIT", round_idx):
            return
        if not self._install_params(msg, version=round_idx):
            return
        self.round_idx = round_idx
        self._train_and_send()

    def _on_sync(self, msg: Message) -> None:
        self._note_server_traffic()
        round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        if self._replay_guard("SYNC", round_idx):
            return
        if not self._install_params(msg, version=round_idx):
            return
        self.round_idx = round_idx
        self._train_and_send()

    def _replay_guard(self, kind: str, round_idx: int) -> bool:
        """Idempotent INIT/SYNC: for the round we LAST answered, re-send
        the cached stamped message (a restarted server needs it; a live
        server dedups it by seq); older rounds are dropped outright.
        Returns True when the caller must not retrain."""
        if round_idx > self._last_trained_round:
            return False
        if (round_idx == self._last_trained_round
                and self._last_model_msg is not None):
            logger.info(
                "client %d: replayed %s for round %d — re-sending the "
                "cached round result", self.rank, kind, round_idx,
            )
            self.send_message(self._last_model_msg)
        else:
            logger.info(
                "client %d: stale %s for round %d ignored (already trained "
                "round %d)", self.rank, kind, round_idx,
                self._last_trained_round,
            )
        return True

    def _on_shed(self, msg: Message) -> None:
        """The async server's admission control shed our update
        (docs/traffic.md): back off retry_after_s, then re-offer the SAME
        trained update as a freshly-stamped message — the shed happened
        AFTER the server's dedup window recorded the original seq, so a
        verbatim re-send of the cached message would be dropped as a wire
        duplicate and the contribution lost for good."""
        self._note_server_traffic()
        shed_round = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        if shed_round != self._last_trained_round \
                or self._last_model_msg is None:
            return  # a newer round superseded the shed update
        delay = max(
            float(msg.get(MyMessage.MSG_ARG_KEY_RETRY_AFTER_S, 0.1)), 0.01)
        self.world.telemetry.counter_inc("traffic.client_retries")
        logger.info(
            "client %d: round %d update shed (%s) — re-offering in %.3fs",
            self.rank, shed_round,
            msg.get(MyMessage.MSG_ARG_KEY_SHED_REASON, "?"), delay,
        )
        t = threading.Timer(delay, self._reoffer_model, args=(shed_round,))
        t.daemon = True
        # tethered (graftiso I005): finish() -> world.shutdown() cancels a
        # backoff still pending when the federation ends
        self.world.register_timer(t)
        t.start()

    def _reoffer_model(self, shed_round: int) -> None:
        cached = self._last_model_msg
        if cached is None or shed_round != self._last_trained_round:
            return  # superseded while we backed off
        params = {
            k: v for k, v in cached.get_params().items()
            if k not in (Message.MSG_ARG_KEY_SEQ, Message.MSG_ARG_KEY_EPOCH)
        }
        # re-target in the dict BEFORE init() — init re-derives receiver_id
        # from the params (a re-home may have moved us since the shed)
        params[Message.MSG_ARG_KEY_RECEIVER] = self._server_rank
        fresh = Message()
        fresh.init(params)
        fresh.set_arrays(cached.get_arrays())
        self.send_message(fresh)

    def _on_finish(self, msg: Message) -> None:
        self._note_server_traffic()
        self._install_params(msg)
        logger.info("client %d: finished", self.rank)
        if self.silo_plane is not None:
            self.silo_plane.broadcast_finish()
        # release retained payloads (graftmem M005): the resync-replay copy
        # of the last upload and the codec's broadcast reference are dead
        # once the federation finishes — both pin full model arrays
        self._last_model_msg = None
        self._round_global_vec = None
        self.done.set()
        self.finish()

    def _train_and_send(self) -> None:
        """reference: __train + send_model_to_server (:109-127,160)."""
        self._last_trained_round = self.round_idx
        self.args.round_idx = self.round_idx
        with self.world.trace.span("train", round_idx=self.round_idx,
                                   client=self.rank):
            if self.silo_plane is not None:
                params, n, metrics = self._train_hierarchical()
            else:
                x, y, n = self.ds.client_shard(self.client_index)
                metrics = self.trainer.train((x, y, n), None, self.args)
                params = self.trainer.get_model_params()
        if self.dp is not None:
            key = jax.random.fold_in(
                jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0)) + self.rank),
                self.round_idx,
            )
            params = self.dp.randomize(params, key)
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                      self._server_rank)
        msg.add(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n))
        msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        msg.add(MyMessage.MSG_ARG_KEY_TRAIN_LOSS,
                float(metrics.get("train_loss", 0.0)))
        if self._s2c_delta_on:
            # capability + ACK: this message's version tag becomes the S2C
            # delta base the server encodes our next sync against
            msg.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
        leaves = jax.tree.leaves(params)
        raw_nbytes = payload_nbytes(leaves)
        if self._filter is not None:
            from ..delivery.payload_filter import FILTER_KEY

            msg.add(FILTER_KEY, self._filter.meta())
        if self.codec.enabled() and self._round_global_vec is not None:
            from ..utils.tree import tree_flatten_to_vector

            if self._filter is not None:
                vec = jnp.asarray(self._filter.select_vector(leaves))
            else:
                vec, _, _ = tree_flatten_to_vector(params)
            arrays, meta = self.codec.encode(
                self._round_global_vec, vec, self.round_idx
            )
            msg.add(self.codec.META_KEY, meta)
            msg.set_arrays(arrays)
        elif self._filter is not None:
            msg.set_arrays(
                [np.asarray(l) for l in self._filter.select(leaves)])
        else:
            msg.set_arrays([np.asarray(l) for l in leaves])
        if self.codec.enabled() or self._filter is not None:
            sent = payload_nbytes(msg.get_arrays())
            self.world.telemetry.counter_inc(
                "comm.delta.c2s_bytes_saved", max(raw_nbytes - sent, 0))
        self._last_model_msg = msg
        try:
            # the upload span's context rides the C2S header (stamped by
            # send_message while this span is innermost), so the server's
            # admission span continues THIS trace
            with self.world.trace.span("upload", round_idx=self.round_idx,
                                       client=self.rank):
                self.send_message(msg)
            if self._client_pull:
                # client_pull dispatch (docs/delivery.md): ask for the next
                # version now — the server answers as soon as it bumps past
                # the round we just trained
                pull = Message(MyMessage.MSG_TYPE_C2S_PULL_REQUEST,
                               self.rank, self._server_rank)
                pull.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
                if self._s2c_delta_on:
                    pull.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
                self.send_message(pull)
        except Exception as e:  # noqa: BLE001 — classified below
            if self._hb_s <= 0:
                raise  # no liveness plane: keep the fail-fast behavior
            # the update is CACHED (stamped) — the resync handshake will
            # replay it once the server answers again, so a send into a
            # dead/partitioned server costs a reconnect, not the round
            self._suspect_connection(f"model send failed: {e}")

    def _train_hierarchical(self):
        """Silo-parallel round: broadcast to DCN slaves, train the master's
        own slice (possibly itself chip-parallel via TrainerDistAdapter),
        weighted-average the silo before one update goes to the server.

        reference: the DDP round of fedml_trainer_dist_adapter.py:24-36 —
        re-founded as per-step psum over ICI (adapter) + round-level
        averaging over DCN (this method).
        """
        global_params = self.trainer.get_model_params()
        self.silo_plane.broadcast_sync(global_params, self.round_idx)
        x, y, n = self.silo_shard
        metrics = self.trainer.train((x, y, n), None, self.args)
        own = self.trainer.get_model_params()
        results = self.silo_plane.collect(
            self.round_idx,
            timeout=float(getattr(self.args, "silo_timeout", 120.0)),
        )
        leaves_list = [jax.tree.leaves(own)] + [r[1] for r in results]
        weights = np.asarray([float(n)] + [r[0] for r in results], np.float64)
        w = weights / max(weights.sum(), 1e-12)
        treedef = jax.tree.structure(own)
        avg_leaves = [
            sum(wi * jnp.asarray(ls[j]) for wi, ls in zip(w, leaves_list))
            for j in range(len(leaves_list[0]))
        ]
        params = jax.tree.unflatten(treedef, avg_leaves)
        self.trainer.set_model_params(params)
        n_total = float(weights.sum())
        losses = [metrics.get("train_loss", 0.0)] + [r[2] for r in results]
        agg_metrics = dict(metrics)
        agg_metrics["train_loss"] = float(
            sum(wi * li for wi, li in zip(w, losses))
        )
        return params, n_total, agg_metrics
