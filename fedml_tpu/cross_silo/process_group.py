"""Silo-local device group: the chips one cross-silo client trains over.

reference: ``cross_silo/client/process_group_manager.py:8-44`` — wraps
``torch.distributed.init_process_group`` so the silo's N processes form a DDP
group. TPU-native re-design: a silo's accelerators are ICI-connected chips on
one host slice, so the "process group" is a ``jax.sharding.Mesh`` over a
device subset with one ``silo_dp`` axis; gradient all-reduce becomes a
``psum`` XLA emits inside the jitted step — there is no NCCL rendezvous, no
master port, nothing to tear down.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

SILO_AXIS = "silo_dp"


class SiloProcessGroup:
    """The device mesh backing one silo's intra-silo data parallelism.

    ``device_indices`` selects chips from ``jax.devices()`` (a silo owns a
    slice of the host's chips; distinct silos co-hosted in one test process
    use disjoint slices). Default: all local devices.
    """

    def __init__(self, device_indices: Optional[Sequence[int]] = None):
        devs = jax.devices()
        if device_indices is not None:
            devs = [devs[i] for i in device_indices]
        self.devices = devs
        self.mesh = Mesh(np.asarray(devs), (SILO_AXIS,))
        logger.info(
            "silo process group: %d device(s) on axis %r",
            len(devs), SILO_AXIS,
        )

    @property
    def size(self) -> int:
        return len(self.devices)
