"""LightSecAgg client FSM.

reference: ``cross_silo/lightsecagg/`` client managers (~1,199 LoC across the
flow). Per round: train → quantize model to the field → draw mask z, LCC-encode
N shares, route them via the server → upload masked model → on the server's
survivor announcement, reply with the sum of the survivors' shares.
≤T colluding parties learn nothing about z; the server never sees an unmasked
model (core/mpc/lightsecagg.py for the math).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ... import constants
from ...core.distributed import FedMLCommManager, Message
from ...core.mpc import lightsecagg as lsa
from ...utils.tree import tree_flatten_to_vector, tree_unflatten_from_vector
from .lsa_message_define import LSAMessage

logger = logging.getLogger(__name__)


class LightSecAggClientManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank=0, size=0,
                 backend=constants.COMM_BACKEND_LOOPBACK, dataset=None):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.ds = dataset
        self.client_index = rank - 1
        self.N = size - 1
        self.T = int(getattr(args, "lsa_privacy_threshold", max(1, (self.N - 1) // 2)))
        self.U = int(getattr(args, "lsa_target_survivors", self.T + 1 if self.T + 1 <= self.N else self.N))
        self.q_bits = int(getattr(args, "lsa_quantize_bits", 8))
        self.round_idx = 0
        self.done = threading.Event()
        self._treedef = None
        self._shapes = None
        self._dim: Optional[int] = None
        self._local_mask: Optional[np.ndarray] = None
        self._received_shares: Dict[int, np.ndarray] = {}
        self._pending_survivors: Optional[list] = None
        self._lock = threading.Lock()

    # -- registration --------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        reg = self.register_message_receive_handler
        reg(LSAMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_ready)
        reg(LSAMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_init_or_sync)
        reg(LSAMessage.MSG_TYPE_S2C_SYNC_MODEL, self._on_init_or_sync)
        reg(LSAMessage.MSG_TYPE_S2C_FORWARD_SHARE, self._on_forward_share)
        reg(LSAMessage.MSG_TYPE_S2C_REQUEST_AGG_SHARES, self._on_request_agg)
        reg(LSAMessage.MSG_TYPE_S2C_FINISH, self._on_finish)

    def _on_ready(self, msg: Message) -> None:
        status = Message(LSAMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        status.add(LSAMessage.ARG_CLIENT_STATUS, LSAMessage.STATUS_ONLINE)
        self.send_message(status)

    # -- round ---------------------------------------------------------------
    def _on_init_or_sync(self, msg: Message) -> None:
        round_idx = int(msg.get(LSAMessage.ARG_ROUND_IDX, 0))
        # replay guard (graftproto P004): the server's round only advances,
        # so an INIT/SYNC for an OLDER round is a delayed/replayed frame —
        # adopting it would rewind round_idx and poison the (round, src)
        # share bookkeeping for the round actually in flight
        if round_idx < self.round_idx:
            logger.info(
                "lsa client %d: stale sync for round %d ignored (already "
                "at round %d)", self.rank, round_idx, self.round_idx,
            )
            return
        self.round_idx = round_idx
        leaves = [jnp.asarray(a) for a in msg.get_arrays()]
        if self._treedef is None:
            skeleton = self.trainer.model.init(
                jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0)))
            )
            vec, self._treedef, self._shapes = tree_flatten_to_vector(skeleton)
            self._dim = int(vec.size)
        params = jax.tree.unflatten(
            jax.tree.structure(
                tree_unflatten_from_vector(
                    jnp.zeros(self._dim), self._treedef, self._shapes
                )
            ),
            leaves,
        )
        self.trainer.set_model_params(params)
        with self._lock:
            self._pending_survivors = None

        # 1. local training
        self.args.round_idx = self.round_idx
        x, y, n = self.ds.client_shard(self.client_index)
        self.trainer.train((x, y, n), None, self.args)
        vec, _, _ = tree_flatten_to_vector(self.trainer.get_model_params())
        quantized = lsa.quantize_to_field(np.asarray(vec), self.q_bits)

        # 2. mask + shares
        rng = np.random.RandomState(
            (int(getattr(self.args, "random_seed", 0)) * 7919 + self.round_idx)
            * 104729 + self.client_index
        )
        z, shares = lsa.mask_encoding(self._dim, self.N, self.U, self.T, rng)
        self._local_mask = z
        share_msg = Message(LSAMessage.MSG_TYPE_C2S_MASK_SHARES, self.rank, 0)
        share_msg.add(LSAMessage.ARG_ROUND_IDX, self.round_idx)
        share_msg.set_arrays([shares])  # [N, m]; server routes row j → rank j+1
        self.send_message(share_msg)

        # 3. masked model upload
        masked = np.asarray(
            lsa.model_masking(
                jnp.asarray(quantized, jnp.int32),
                jnp.asarray(np.resize(z, self._dim), jnp.int32),
            )
        )
        up = Message(LSAMessage.MSG_TYPE_C2S_MASKED_MODEL, self.rank, 0)
        up.add(LSAMessage.ARG_ROUND_IDX, self.round_idx)
        up.add(LSAMessage.ARG_NUM_SAMPLES, float(n))
        up.set_arrays([masked])
        self.send_message(up)

    def _on_forward_share(self, msg: Message) -> None:
        """Shares are buffered per (round, src): transports (gRPC) don't
        guarantee cross-sender ordering, so a share for round r+1 may arrive
        while this client is still finishing round r."""
        src = int(msg.get(LSAMessage.ARG_SRC_CLIENT))
        rnd = int(msg.get(LSAMessage.ARG_ROUND_IDX, 0))
        with self._lock:
            self._received_shares[(rnd, src)] = msg.get_arrays()[0]
            pending = self._pending_survivors
        if pending is not None:
            self._try_send_agg(pending)

    def _on_request_agg(self, msg: Message) -> None:
        survivors = list(msg.get(LSAMessage.ARG_SURVIVORS))
        with self._lock:
            self._pending_survivors = survivors
        self._try_send_agg(survivors)

    def _try_send_agg(self, survivors) -> None:
        with self._lock:
            rnd = self.round_idx
            if not all((rnd, s) in self._received_shares for s in survivors):
                return  # wait for outstanding forwards
            agg = lsa.aggregate_shares(
                [self._received_shares[(rnd, s)] for s in survivors]
            )
            # prune older rounds
            self._received_shares = {
                k: v for k, v in self._received_shares.items() if k[0] >= rnd
            }
            self._pending_survivors = None
        out = Message(LSAMessage.MSG_TYPE_C2S_AGG_SHARES, self.rank, 0)
        out.add(LSAMessage.ARG_ROUND_IDX, self.round_idx)
        out.set_arrays([agg])
        self.send_message(out)

    def _on_finish(self, msg: Message) -> None:
        leaves = [jnp.asarray(a) for a in msg.get_arrays()]
        if self._treedef is not None:
            skeleton = tree_unflatten_from_vector(
                jnp.zeros(self._dim), self._treedef, self._shapes
            )
            self.trainer.set_model_params(
                jax.tree.unflatten(jax.tree.structure(skeleton), leaves)
            )
        self.done.set()
        self.finish()
