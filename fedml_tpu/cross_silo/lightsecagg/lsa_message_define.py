"""LightSecAgg message protocol.

reference: ``cross_silo/lightsecagg/lsa_message_define.py:2-13`` — the
documented message order (init → mask shares → forward → masked models →
aggregate-share request → reconstruction). Names kept close to the reference.
"""


class LSAMessage:
    MSG_TYPE_CONNECTION_IS_READY = "connection_ready"
    MSG_TYPE_C2S_CLIENT_STATUS = "c2s_client_status"

    MSG_TYPE_S2C_INIT_CONFIG = "lsa_s2c_init_config"
    MSG_TYPE_C2S_MASK_SHARES = "lsa_c2s_mask_shares"  # client → server (to fwd)
    MSG_TYPE_S2C_FORWARD_SHARE = "lsa_s2c_forward_share"  # server fwd i→j
    MSG_TYPE_C2S_MASKED_MODEL = "lsa_c2s_masked_model"
    MSG_TYPE_S2C_REQUEST_AGG_SHARES = "lsa_s2c_request_agg_shares"
    MSG_TYPE_C2S_AGG_SHARES = "lsa_c2s_agg_shares"
    MSG_TYPE_S2C_SYNC_MODEL = "lsa_s2c_sync_model"
    MSG_TYPE_S2C_FINISH = "lsa_s2c_finish"

    ARG_ROUND_IDX = "round_idx"
    ARG_CLIENT_INDEX = "client_idx"
    ARG_NUM_SAMPLES = "num_samples"
    ARG_SRC_CLIENT = "src_client"
    ARG_SURVIVORS = "survivors"
    ARG_CLIENT_STATUS = "client_status"
    STATUS_ONLINE = "ONLINE"
