"""LightSecAgg secure-aggregation flow (reference: ``cross_silo/lightsecagg/``)."""

from .lsa_client_manager import LightSecAggClientManager
from .lsa_server_manager import LightSecAggServerManager

__all__ = ["LightSecAggClientManager", "LightSecAggServerManager"]
