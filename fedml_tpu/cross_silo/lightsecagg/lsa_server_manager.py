"""LightSecAgg server FSM.

reference: ``cross_silo/lightsecagg/LightSecAggAggregator`` + server manager
(337 + ~400 LoC). The server only ever sees masked models and coded shares:
it routes clients' share rows, collects masked models, announces the survivor
set, decodes Σz from U aggregate shares, and unmasks the sum — then
dequantizes and averages. Dropout tolerance: any U of N clients suffice
(the one fault-tolerance mechanism the reference framework has; SURVEY.md §5).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ... import constants
from ...core.distributed import FedMLCommManager, Message
from ...core.mpc import lightsecagg as lsa
from ...ml.evaluate import make_eval_fn
from ...utils.tree import tree_flatten_to_vector, tree_unflatten_from_vector
from .lsa_message_define import LSAMessage

logger = logging.getLogger(__name__)


class LightSecAggServerManager(FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, rank=0, size=0,
                 backend=constants.COMM_BACKEND_LOOPBACK, dataset=None,
                 model=None):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.ds = dataset
        self.bundle = model
        self.round_num = int(args.comm_round)
        self.round_idx = 0
        self.N = size - 1
        self.T = int(getattr(args, "lsa_privacy_threshold", max(1, (self.N - 1) // 2)))
        self.U = int(getattr(args, "lsa_target_survivors",
                             self.T + 1 if self.T + 1 <= self.N else self.N))
        self.q_bits = int(getattr(args, "lsa_quantize_bits", 8))
        self.global_params = (
            aggregator.get_model_params()
            if aggregator.get_model_params() is not None
            else model.init(jax.random.PRNGKey(int(args.random_seed)))
        )
        vec, self._treedef, self._shapes = tree_flatten_to_vector(self.global_params)
        self._dim = int(vec.size)
        self._online = set()
        self._init_sent = False
        self._masked: Dict[int, np.ndarray] = {}
        self._agg_shares: Dict[int, np.ndarray] = {}
        self._survivors: Optional[list] = None
        self._request_sent = False
        self._lock = threading.Lock()
        self.final_metrics: Optional[dict] = None
        self.done = threading.Event()

    def register_message_receive_handlers(self) -> None:
        reg = self.register_message_receive_handler
        reg(LSAMessage.MSG_TYPE_CONNECTION_IS_READY, lambda m: None)
        reg(LSAMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_status)
        reg(LSAMessage.MSG_TYPE_C2S_MASK_SHARES, self._on_mask_shares)
        reg(LSAMessage.MSG_TYPE_C2S_MASKED_MODEL, self._on_masked_model)
        reg(LSAMessage.MSG_TYPE_C2S_AGG_SHARES, self._on_agg_shares)

    # -- barrier → init ------------------------------------------------------
    def _on_status(self, msg: Message) -> None:
        with self._lock:
            self._online.add(msg.get_sender_id())
            ready = len(self._online) == self.N and not self._init_sent
            if ready:
                self._init_sent = True
        if ready:
            self._broadcast_model(LSAMessage.MSG_TYPE_S2C_INIT_CONFIG)

    def _broadcast_model(self, msg_type: str) -> None:
        leaves = [np.asarray(l) for l in jax.tree.leaves(self.global_params)]
        for r in range(1, self.size):
            m = Message(msg_type, self.rank, r)
            m.add(LSAMessage.ARG_ROUND_IDX, self.round_idx)
            m.add(LSAMessage.ARG_CLIENT_INDEX, r - 1)
            m.set_arrays(leaves)
            self.send_message(m)

    def _is_stale(self, msg: Message) -> bool:
        """Drop messages from a previous round (a slow client's duplicate
        agg-share must not pollute the next round's state)."""
        return int(msg.get(LSAMessage.ARG_ROUND_IDX, -1)) != self.round_idx

    # -- share routing -------------------------------------------------------
    def _on_mask_shares(self, msg: Message) -> None:
        """Forward row j of client i's share matrix to client j."""
        if self._is_stale(msg):
            return
        src = msg.get_sender_id() - 1  # 0-based client index
        shares = msg.get_arrays()[0]  # [N, m]
        for j in range(self.N):
            fwd = Message(LSAMessage.MSG_TYPE_S2C_FORWARD_SHARE, self.rank, j + 1)
            fwd.add(LSAMessage.ARG_SRC_CLIENT, src)
            fwd.add(LSAMessage.ARG_ROUND_IDX, self.round_idx)
            fwd.set_arrays([shares[j]])
            self.send_message(fwd)

    # -- masked model collection --------------------------------------------
    def _on_masked_model(self, msg: Message) -> None:
        if self._is_stale(msg):
            return
        with self._lock:
            self._masked[msg.get_sender_id() - 1] = msg.get_arrays()[0]
            # survivors = every client whose masked model arrived; round
            # proceeds once all N (or at least U after a dropout) are in
            ready = len(self._masked) >= self.N and not self._request_sent
            if ready:
                self._request_sent = True
                self._survivors = sorted(self._masked.keys())
        if ready:
            self._request_agg_shares()

    def _request_agg_shares(self) -> None:
        for r in range(1, self.size):
            m = Message(LSAMessage.MSG_TYPE_S2C_REQUEST_AGG_SHARES, self.rank, r)
            m.add(LSAMessage.ARG_SURVIVORS, self._survivors)
            m.add(LSAMessage.ARG_ROUND_IDX, self.round_idx)
            self.send_message(m)

    # -- reconstruction ------------------------------------------------------
    def _on_agg_shares(self, msg: Message) -> None:
        if self._is_stale(msg):
            return
        with self._lock:
            self._agg_shares[msg.get_sender_id() - 1] = msg.get_arrays()[0]
            ready = len(self._agg_shares) >= self.U
            if ready and self._survivors is None:
                ready = False
        if ready:
            self._reconstruct_and_advance()

    def _reconstruct_and_advance(self) -> None:
        with self._lock:
            if self._survivors is None:
                return
            survivors = list(self._survivors)
            responders = sorted(self._agg_shares.keys())[: self.U]
            agg_shares = [self._agg_shares[r] for r in responders]
            masked = [self._masked[s] for s in survivors]
            self._survivors = None
            self._masked = {}
            self._agg_shares = {}
            self._request_sent = False

        # Σ masked models over survivors (field), Σ z via LCC decode, unmask
        masked_sum = np.zeros(self._dim, np.int64)
        for mvec in masked:
            masked_sum = (masked_sum + mvec.astype(np.int64)) % lsa.FIELD_P
        survivor_points = [s + 1 for s in responders]  # α_j = rank index
        mask_sum = lsa.decode_aggregate_mask(
            agg_shares, survivor_points, self._dim, self.N, self.U, self.T
        )
        clear = np.asarray(
            lsa.model_unmasking(
                jnp.asarray(masked_sum % lsa.FIELD_P, jnp.int32),
                jnp.asarray(mask_sum % lsa.FIELD_P, jnp.int32),
            )
        )
        avg = lsa.dequantize_from_field(clear, self.q_bits) / max(len(masked), 1)
        self.global_params = tree_unflatten_from_vector(
            jnp.asarray(avg), self._treedef, self._shapes
        )
        self.aggregator.set_model_params(self.global_params)

        if self.ds is not None:
            self.final_metrics = make_eval_fn(self.bundle)(
                self.global_params, self.ds.test_x, self.ds.test_y
            )
            logger.info(
                "lsa round %d: acc=%.4f", self.round_idx,
                self.final_metrics["test_acc"],
            )

        self.round_idx += 1
        if self.round_idx < self.round_num:
            self._broadcast_model(LSAMessage.MSG_TYPE_S2C_SYNC_MODEL)
        else:
            self._broadcast_model(LSAMessage.MSG_TYPE_S2C_FINISH)
            self.done.set()
            self.finish()
