"""Cross-silo FL server: aggregator + message FSM.

reference: ``cross_silo/server/fedml_server_manager.py`` (276 LoC) +
``fedml_aggregator.py`` (248 LoC); FSM at SURVEY.md §3.4:
CONNECTION_READY → wait for ONLINE from all selected clients → S2C_INIT with
the global model → collect C2S models → aggregate (attack/defense/DP hook
order preserved) → eval → S2C_SYNC … → S2C_FINISH.

Two aggregation modes (``--aggregation_mode``, docs/traffic.md):

- **sync** (default, the reference semantics above): one global round
  barrier; a round aggregates when every live client answered (or the
  round deadline fires). Bitwise-identical to the pre-traffic-plane
  server — pinned by tests/test_traffic.py.
- **async** (FedBuff-style, ISSUE 7 tentpole): no cohort barrier. The
  round index doubles as the **server model version**; every dispatched
  model is version-tagged, accepted updates fold into a K-update buffer
  with staleness-decayed weights (fedml_tpu/traffic/async_aggregator.py),
  and a server step fires per K folds. C2S_SEND_MODEL sits behind
  admission control (token bucket + bounded fold queue,
  fedml_tpu/traffic/admission.py): overload degrades to an explicit
  S2C_SHED_NOTICE NACK with retry_after, never to memory growth. Both
  modes share ONE aggregation core (``_aggregate_models`` — the
  attack → defend → DP hook chain), which is what makes the sync-parity
  pin (async K=N, alpha=0 ≡ sync FedAvg, bitwise) possible.

Delta delivery plane (ISSUE 9, ``fedml_tpu/delivery/``, docs/delivery.md):
the server keeps a :class:`VersionedModelStore` of the last V dispatched
global vectors. Compressed C2S updates decode against the exact version
their sender trained from (async×compression is no longer refused), and
S2C_SYNC ships a LOSSLESS delta frame against each client's last-ACKed
version — falling back loudly to full-model frames when the base was
evicted. ``--async_dispatch`` picks the FedBuff dispatch policy
(sync-on-consume / server-push / client-pull via ``c2s_pull_request``),
and ``--payload_filter`` restricts C2S payloads to adapter leaves.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import constants
from ..core.aggregate import stack_trees, weighted_average
from ..core.containers import BoundedDict
from ..core.distributed import FedMLCommManager, Message
from ..core.mlops.tracing import NULL_SPAN
from ..core.dp import FedPrivacyMechanism
from ..core.security.defender import FedMLDefender
from ..delivery import (
    VersionedModelStore, WireCodec, delivery_identity, flatten_leaves,
)
from ..delivery.delta_codec import DELTA_KEY, payload_nbytes
from ..delivery.payload_filter import FILTER_KEY, filter_from_args
from ..hierarchy import Topology, unpack_summary
from ..ml.evaluate import make_eval_fn
from ..utils.tree import tree_flatten_to_vector, tree_unflatten_from_vector
from .message_define import MyMessage

logger = logging.getLogger(__name__)


class FedMLServerManager(FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, rank=0, size=0,
                 backend=constants.COMM_BACKEND_LOOPBACK, dataset=None,
                 model=None):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.ds = dataset
        self.bundle = model
        self.round_num = int(args.comm_round)
        self.round_idx = 0
        # hierarchical edge tier (fedml_tpu/hierarchy/, docs/traffic.md
        # "Hierarchical edge tier"): in a tiered world the rank space is
        # [root, clients 1..N, edges base..base+E-1] — client_num counts
        # the CLIENTS, never the edge ranks
        self.topology = Topology.from_args(args)
        self.client_num = (self.topology.clients if self.topology is not None
                           else self.size - 1)
        # tiered serving state (all guarded by self._lock): which edges
        # completed their handshake (the tiered init barrier), each edge's
        # last piggybacked health stats, clients adopted DIRECTLY after
        # exhausting their sibling ring (degraded mode), and the async
        # in-flight (sender, client_version) set — with _committed_client_
        # round it makes at-least-once summary delivery exactly-once
        self._edge_online: set = set()
        # bounded (graftmem M001): keyed by edge rank, evicted
        # oldest-first well above any deployable edge-tier width
        self._edge_stats: Dict[int, dict] = BoundedDict(
            512, name="server.edge_stats")
        self._direct_clients: set = set()
        self._pending_folds: set = set()
        self._online = set()
        self._dead = set()  # clients that went OFFLINE or timed out
        self._offline_declared = set()  # explicit departures (never resync)
        self._models: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._init_sent = False
        # dropout tolerance (the reference's MQTT last-will analog +
        # a cohort deadline it never had): after round_timeout seconds the
        # round aggregates whoever answered, if at least min_clients did
        self.round_timeout = float(getattr(args, "round_timeout", 0.0) or 0.0)
        self.min_clients = int(getattr(args, "min_clients_per_round", 1))
        self._round_timer: Optional[threading.Timer] = None
        # graceful degradation under stragglers (docs/robustness.md
        # "Partial cohorts under deadline"): --round_deadline_s closes a
        # sync round with the K' <= K updates that arrived — reweighting
        # exactly (weighted_average normalizes over PRESENT weights, so a
        # full cohort stays bitwise-identical to plain FedAvg) — and a
        # straggler's LATE update folds into the round in progress with
        # the async staleness weight (1+s)^-alpha instead of being
        # dropped. Unlike round_timeout, the deadline does NOT declare
        # stragglers dead: they stay in the cohort, their late folds
        # count toward the next round's quorum.
        self.round_deadline_s = float(
            getattr(args, "round_deadline_s", 0.0) or 0.0)
        self._late_fold = self.round_deadline_s > 0
        self.late_alpha = float(
            getattr(args, "async_staleness_alpha", 0.0) or 0.0)
        # the client round each pending model was trained at (== its
        # message's round tag; differs from the aggregation round for
        # late folds) — parallel to _models, guarded by self._lock
        self._model_rounds: Dict[int, int] = {}
        # per-client highest trained round whose contribution was
        # aggregated (sync) or folded into a committed step (async) —
        # what the resync ack reports so a reconnecting client knows
        # whether to replay its last unACKed update. Rebuilt from the
        # ledger on restart; guarded by self._lock. LRU-bounded (graftmem
        # M001): an evicted client's replay re-folds at most once and the
        # round-index guard drops anything older than the current round.
        self._committed_client_round: Dict[int, int] = BoundedDict(
            65536, lru=True, name="server.committed_clients")
        # chaos kill switch (core/distributed/faults.py kill_server):
        # SIGKILL at a protocol phase — consulted via _maybe_kill
        self._fault_plan = getattr(args, "fault_plan", None)
        self.global_params = (
            aggregator.get_model_params()
            if aggregator.get_model_params() is not None
            else model.init(jax.random.PRNGKey(int(args.random_seed)))
        )
        self.aggregator.set_model_params(self.global_params)
        _, self._treedef, self._shapes = tree_flatten_to_vector(self.global_params)
        self.defender = FedMLDefender.get_instance()
        self.defender.init(args)
        self.dp = (
            FedPrivacyMechanism.from_args(args)
            if bool(getattr(args, "enable_dp", False))
            else None
        )
        self.final_metrics: Optional[dict] = None
        self.done = threading.Event()
        self.preempted = False
        # -- async traffic plane (fedml_tpu/traffic/, docs/traffic.md) ------
        self.async_mode = (
            str(getattr(args, "aggregation_mode", "sync") or "sync").lower()
            == "async"
        )
        self._rx: Optional["queue.Queue"] = None
        self._async_worker: Optional[threading.Thread] = None
        # -- delta delivery plane (fedml_tpu/delivery/, docs/delivery.md) ---
        # the version-indexed reference store is what lets a compressed C2S
        # delta decode against the exact global its sender trained from —
        # including stale senders in async mode (the old async×compression
        # refusal is gone) — and lets S2C_SYNC ship a lossless delta
        # against each client's last-ACKed version
        self.store = VersionedModelStore(
            int(getattr(args, "delta_store_versions", 8) or 8),
            metric_prefix="comm.delta.server_store",
        )
        self.s2c_delta_on = (
            str(getattr(args, "s2c_delta", "auto") or "auto").lower()
            != "off"
        )
        # the wire-path facade: jit'd device kernels (or the host numpy
        # reference) behind one encode/decode surface, byte-identical
        # frames either way (--wire_path host|device|auto)
        self.wire = WireCodec(getattr(args, "wire_path", "auto"),
                              scoped=self.world.telemetry)
        # with the plane fully opted out (--s2c_delta off, no
        # --compression) the store never serves a decode or encode — skip
        # the per-version full-vector copy + digest entirely. A tiered
        # world always keeps the store: edges delta-encode their summary
        # entries against replica versions, and OUR copy of those versions
        # is what decodes them (root and edge stores hold bitwise-equal
        # vectors — both installed from the same dispatch).
        self._store_active = (self.s2c_delta_on or bool(
            str(getattr(args, "compression", "") or ""))
            or self.topology is not None)
        self.async_dispatch = str(
            getattr(args, "async_dispatch", "sync_on_consume")
            or "sync_on_consume").lower()
        # last version each client echoed on a delta-capable message —
        # the S2C delta base; cleared on ONLINE (a restarted client lost
        # its side of the store). Guarded by self._lock (comm thread +
        # async worker both touch it).
        self._acked: Dict[int, int] = {}
        # client_pull dispatch: ranks whose pull waits for the next
        # version bump (guarded by self._lock)
        self._pending_pulls: set = set()
        # adapter-only payloads: C2S messages carry only matching leaves;
        # the rest stay frozen at the server's global on merge
        self.payload_filter = filter_from_args(args, self.global_params)
        if self.async_mode:
            from ..traffic.admission import (
                AdmissionController, queue_limit_from_args,
            )
            from ..traffic.async_aggregator import AsyncConfig, AsyncUpdateBuffer

            self.async_cfg = AsyncConfig.from_args(args, self.client_num)
            self.buffer = AsyncUpdateBuffer(self.async_cfg)
            self.admission = AdmissionController.from_args(
                args, self.async_cfg.buffer_size)
            self._rx = queue.Queue(
                maxsize=queue_limit_from_args(args, self.async_cfg.buffer_size)
            )
            self._async_worker = threading.Thread(
                target=self._async_worker_loop, daemon=True,
                name="async-aggregator",
            )
            # tethered (graftiso I005): _close_and_finish -> finish() ->
            # world.shutdown() joins it after done.set() stops the loop
            self.world.register_thread(self._async_worker)
        # per-round contribution counters: how many times each client's
        # model was ACCEPTED into a round's aggregation. The delivery-layer
        # dedup keeps every count at 1 even under retries/duplication —
        # the chaos harness and the deadline-race tests assert exactly that.
        # Bounded (graftmem M001) by round: only the trailing rounds matter
        # for dedup assertions; ancient rounds are evicted oldest-first.
        self.contrib_counts: Dict[int, Dict[int, int]] = BoundedDict(
            1024, name="server.contrib_rounds")
        # round checkpoint/resume (the reference restarts every killed run
        # from round 0 — SURVEY §5): with args.checkpoint_dir the aggregated
        # global + round index persist via Orbax after every round round
        # boundary, the durable run ledger (core/runstate.py) records each
        # committed round (cohort + contribution counts), and a restarted
        # server resumes the federation where it died — clients re-joining
        # get the restored global in their INIT
        self._ckpt = None
        self._ledger = None
        self._guard = None
        ckpt_dir = str(getattr(args, "checkpoint_dir", "") or "")
        if ckpt_dir:
            from ..checkpoint import CheckpointManager
            from ..core import runstate

            self._ckpt = CheckpointManager(ckpt_dir)
            try:
                self._init_resume(args, ckpt_dir, runstate)
            except Exception:
                # a refused resume (mode conflict, run_meta identity
                # mismatch) must not leak the orbax manager's worker
                # threads into the process
                self._ckpt.close()
                raise
        # seed the reference store with the version INIT dispatches (the
        # post-resume round index): the first C2S deltas decode against it
        if self._store_active:
            self.store.put(self.round_idx,
                           flatten_leaves(jax.tree.leaves(self.global_params)))

    def _init_resume(self, args, ckpt_dir: str, runstate) -> None:
        """The checkpointed-world half of __init__: resume-mode checks,
        state restore, ledger identity, preemption guard, and — on an
        actual restart — hot-state reconstruction."""
        mode = runstate.resume_mode(args)
        step = self._ckpt.latest_step()
        if mode == "never" and step is not None:
            raise RuntimeError(
                f"--resume never, but {ckpt_dir} already holds a "
                f"checkpoint (step {step}) — point at a fresh "
                "checkpoint_dir or use --resume auto"
            )
        if mode == "require" and step is None:
            raise RuntimeError(
                f"--resume require, but {ckpt_dir} holds no checkpoint "
                "to resume from"
            )
        if step is not None:
            restored = self._ckpt.restore_latest(
                {"global_params": self.global_params}
            )
            self.global_params = restored["global_params"]
            self.aggregator.set_model_params(self.global_params)
            self.round_idx = step + 1
            self.world.telemetry.counter_inc("run.resumes")
            self.world.telemetry.counter_inc("run.server_recoveries")
            logger.info(
                "server: resumed federation at round %d from %s",
                self.round_idx, ckpt_dir,
            )
        # identity pins engine + world size, NOT comm_round: restarting
        # a finished federation with a larger round budget is the
        # supported "extend the run" pattern
        self._ledger = runstate.RunLedger.for_checkpoint_dir(ckpt_dir)
        world = {
            "engine": type(self).__name__,
            "client_num": self.client_num,
        }
        if self.async_mode:
            # buffer state is run identity: resuming an async ledger
            # with a different mode/buffer/decay is a different
            # federation — ensure_meta's world comparison rejects it.
            # (sync ledgers stay byte-identical to the pre-traffic
            # format, so old checkpoints keep resuming.)
            world.update(
                aggregation_mode="async",
                buffer_size=self.async_cfg.buffer_size,
                staleness_alpha=self.async_cfg.staleness_alpha,
                max_staleness=self.async_cfg.max_staleness,
            )
            if self.async_dispatch != "sync_on_consume":
                # which clients re-enter training when decides who
                # trains what — dispatch policy is run identity too
                # (default omitted: pre-delta async ledgers keep
                # resuming)
                world["dispatch"] = self.async_dispatch
        delivery_id = delivery_identity(args)
        if delivery_id is not None:
            # lossy C2S codec config, adapter filter and store depth
            # all change what aggregation ever sees — resuming this
            # ledger under a different delivery configuration is a
            # different federation and is refused (plain worlds keep
            # the pre-delta ledger format)
            world["delivery"] = delivery_id
        self._ledger.ensure_meta(
            seed=int(getattr(args, "random_seed", 0)),
            world=world,
        )
        # preemption-safe drain: SIGTERM/SIGINT latches; the in-flight
        # round finishes aggregating, commits checkpoint + ledger, and
        # the FSM stops instead of dispatching the next round
        self._guard = runstate.preemption_guard()
        if bool(getattr(args, "preempt_signals", True)):
            self._guard.install()
        self._guard.reset()
        if step is not None:
            # crash-failover (docs/robustness.md "Server failover &
            # resync"): a restarted server reconstructs its hot
            # serving state from durable substrate alone — the
            # version-store ring from the retained Orbax steps and
            # the per-client committed-contribution map from the run
            # ledger. The async fold buffer restarts EMPTY but
            # consistent: its in-flight (uncommitted) contributions
            # are re-solicited through the resync handshake, never
            # silently dropped.
            self._recover_serving_state()

    def _recover_serving_state(self) -> None:
        """Rebuild the restart-survivable half of the hot serving state
        from durable substrate (crash-failover, docs/robustness.md).

        - **Version-store ring**: re-derived from the retained Orbax
          checkpoint steps (version = step + 1 — the version that round's
          commit dispatched). Only versions still inside the ring's
          capacity window are restored, so a version the pre-kill store
          had already evicted stays evicted — a stale delta against it
          gets the same loud fallback either side of the crash.
        - **Committed-contribution map**: replayed from the ledger's
          round entries. A sync round's contributions were trained AT
          that round unless the entry recorded explicit
          ``client_versions`` (late folds and async steps do). The
          resync ack reports this map, which is what lets a client
          decide replay-vs-rejoin without guessing.
        """
        if self._ledger is not None:
            for e in self._ledger.rounds():
                cohort = [int(c) for c in (e.get("cohort") or [])]
                versions = [
                    int(v) for v in (e.get("client_versions")
                                     or [e["round"]] * len(cohort))
                ]
                for sender, cv in zip(cohort, versions):
                    if cv > self._committed_client_round.get(sender, -1):
                        self._committed_client_round[sender] = cv
        if self._store_active:
            head = self.round_idx  # the version the resumed INIT ships
            floor = head - self.store.capacity
            rebuilt = 0
            for s in self._ckpt.steps():
                version = s + 1
                if version <= floor or version >= head:
                    continue  # evicted / the head (seeded from the
                    # restored global right after this method)
                restored = self._ckpt.restore(
                    s, {"global_params": self.global_params})
                self.store.put(version, flatten_leaves(
                    jax.tree.leaves(restored["global_params"])))
                rebuilt += 1
            self.world.telemetry.counter_inc(
                "comm.delta.server_store.rebuilt_versions", rebuilt)
            logger.info(
                "server: rebuilt %d version-store entries from the "
                "checkpoint retention window (head version %d)",
                rebuilt, head,
            )

    def _maybe_kill(self, phase: str, round_idx: int) -> None:
        """Chaos kill switch (faults.FaultPlan.kill_server): SIGKILL this
        process at a protocol phase — the crash-failover soak's trigger."""
        # flight-recorder phase mark (docs/tracing.md): the post-mortem's
        # ``last_phase`` names exactly where a no-drain SIGKILL landed
        self.world.trace.note_phase(phase, round_idx)
        plan = self._fault_plan
        if plan is not None:
            if (self.world.trace.enabled and plan.kill_phase == phase
                    and plan.kill_round == int(round_idx)):
                # the kill below is a TRUE fail-stop (no drain, no atexit):
                # the post-mortem and the sink's buffered tail must land
                # NOW, on this thread, before the signal
                self.world.trace.flush_flight(f"kill_server:{phase}")
            plan.maybe_kill_server(phase, round_idx)

    # -- FSM ----------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_connection_ready
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_client_status
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_HEARTBEAT, self._on_heartbeat
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_RESYNC, self._on_resync
        )
        # hierarchical edge tier: summaries + the edge handshake, and the
        # degraded-mode direct adoption of a client whose sibling ring is
        # exhausted (c2e_rehome addressed to rank 0)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_E2S_EDGE_SUMMARY, self._on_edge_summary
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_E2S_EDGE_RESYNC, self._on_edge_resync
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2E_REHOME, self._on_rehome_root
        )
        if self.async_mode:
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                self._on_model_received_async,
            )
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_C2S_PULL_REQUEST, self._on_pull_request,
            )
            if not self._async_worker.is_alive():
                self._async_worker.start()
        else:
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                self._on_model_received,
            )

    def _on_connection_ready(self, msg: Message) -> None:
        logger.info("server: connection ready")

    def _on_client_status(self, msg: Message) -> None:
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        finish = False
        finish_round = -1
        with self._lock:
            if status == MyMessage.CLIENT_STATUS_ONLINE:
                self._online.add(msg.get_sender_id())
                self._dead.discard(msg.get_sender_id())
                self._offline_declared.discard(msg.get_sender_id())
                # a (re)connecting client lost its side of the version
                # store — forget its ACK so it gets full frames until it
                # echoes a version again
                self._acked.pop(msg.get_sender_id(), None)
                self._pending_pulls.discard(msg.get_sender_id())
            elif status == MyMessage.CLIENT_STATUS_OFFLINE:
                # explicit departure (the MQTT last-will analog): stop
                # waiting for this client from now on
                self._dead.add(msg.get_sender_id())
                self._offline_declared.add(msg.get_sender_id())
                self._online.discard(msg.get_sender_id())
                logger.warning(
                    "server: client %d went OFFLINE", msg.get_sender_id()
                )
                finish = not self.async_mode and self._round_complete_locked()
                finish_round = self.round_idx
            ready = self._barrier_ready_locked()
            if ready:
                self._init_sent = True
        if ready:
            self._post_barrier()
        elif finish:
            self._finish_round(finish_round)

    def _barrier_ready_locked(self) -> bool:
        """Caller holds the lock. The init barrier counts the dead as
        resolved — a client that died during startup must not stall the
        federation forever. A tiered world barriers on its E edges
        instead: clients announce ONLINE to their edge, never here."""
        if self.topology is not None:
            return (len(self._edge_online) >= self.topology.edges
                    and not self._init_sent)
        return (
            len(self._online) + len(self._dead) >= self.client_num
            and len(self._online) > 0
            and not self._init_sent
        )

    def _post_barrier(self) -> None:
        """The init barrier just completed (this caller flipped
        ``_init_sent``): start the federation — or, on a RESTART of an
        already-completed one (resumed round_idx == comm_round), do not
        train past the budget: deliver the final model and finish."""
        if self.round_idx >= self.round_num:
            self._broadcast_finish(
                "server: federation already complete after %d rounds")
            if self.ds is not None and self.final_metrics is None:
                self.final_metrics = make_eval_fn(self.bundle)(
                    self.global_params, self.ds.test_x, self.ds.test_y
                )
            self._close_and_finish()
        else:
            self._send_init_msg()

    # -- liveness / resync (docs/robustness.md "Server failover & resync") --

    def _on_heartbeat(self, msg: Message) -> None:
        """Heartbeat lease: a heartbeat from a KNOWN client proves it
        lives; the ack renews the sender's lease on US (a missed-ack
        window is how the client detects a dead or partitioned-away
        server). A heartbeat from a client this server has no session
        with — a RESTARTED server draining the dead process's mailbox —
        is deliberately left unanswered: silence is what lease-trips that
        client into the resync handshake that (re)introduces it. Acking
        it would wedge the federation — a leased client never resyncs,
        and the restarted server's init barrier never completes."""
        if self.done.is_set():
            return
        t_recv = time.monotonic()  # clock probe: our receive timestamp
        sender = msg.get_sender_id()
        with self._lock:
            # NB: a heartbeat does NOT clear a _dead mark — reviving a
            # client whose dispatch failed without re-delivering what it
            # missed would grow the quorum back while the client still
            # waits for a model, wedging the round. Revival stays where
            # re-delivery (or fresh work) actually happens: a model
            # arrival or a resync.
            known = sender in self._online
            head = self.round_idx
        if not known:
            self.world.telemetry.counter_inc("comm.heartbeat_unknown")
            return
        ack = Message(MyMessage.MSG_TYPE_S2C_HEARTBEAT_ACK, self.rank,
                      sender)
        ack.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, head)
        t_send = msg.get(MyMessage.MSG_ARG_KEY_HB_T_SEND)
        if t_send is not None:
            # NTP-style probe echo (docs/tracing.md "Clock alignment"):
            # the client's send stamp comes back next to our receive/reply
            # clocks, closing one offset-estimation pair per heartbeat
            ack.add(MyMessage.MSG_ARG_KEY_HB_T_ECHO, float(t_send))
            ack.add(MyMessage.MSG_ARG_KEY_HB_T_RECV, t_recv)
            ack.add(MyMessage.MSG_ARG_KEY_HB_T_REPLY, time.monotonic())
        self._send_or_mark_dead(sender, ack)

    def _on_resync(self, msg: Message) -> None:
        """Idempotent reconnect handshake. A resync counts as an ONLINE
        announcement (a restarted server's init barrier accepts it), but
        — unlike ONLINE — does NOT clear the sender's delta ACK: a
        resyncing client kept its version store; only a restarted client
        (fresh ONLINE) lost it. The ack carries the server's head round
        and the sender's last durably-aggregated contribution round, so
        the client replays its cached unACKed update exactly when it is
        NOT covered — through the existing dedup window, which makes the
        replay safe against a server that never actually died."""
        sender = msg.get_sender_id()
        self.world.telemetry.counter_inc("comm.resyncs")
        # a delta-capable resync re-ACKs the version its sender still
        # holds — S2C deltas resume against it without a full-frame trip
        self._record_ack(msg)
        if self.done.is_set():
            # the federation finished while this client was away: deliver
            # the final model so it terminates too (idempotent — FINISH
            # handling tolerates repeats)
            m = Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, sender)
            m.set_arrays(
                [np.asarray(l) for l in jax.tree.leaves(self.global_params)])
            self._send_or_mark_dead(sender, m)
            return
        with self._lock:
            self._online.add(sender)
            self._dead.discard(sender)
            self._offline_declared.discard(sender)
            if (self.topology is not None
                    and self.topology.is_client(sender)):
                # a client resyncing DIRECTLY against the root in a tiered
                # world is already re-homed here — keep serving it
                self._direct_clients.add(sender)
            # a parked client_pull survives the resync (unlike ONLINE,
            # which drops it — a restarted client re-pulls after INIT):
            # the reconnecting client is still waiting for the version
            # bump it asked for, and it also re-issues the pull on the
            # ack in case THIS server is a restart that lost the parking
            committed = self._committed_client_round.get(sender, -1)
            head = self.round_idx
            ready = self._barrier_ready_locked()
            if ready:
                self._init_sent = True
        logger.info(
            "server: client %d resynced (head round %d, committed-for-it "
            "%d)", sender, head, committed,
        )
        ack = Message(MyMessage.MSG_TYPE_S2C_RESYNC_ACK, self.rank, sender)
        ack.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, head)
        ack.add(MyMessage.MSG_ARG_KEY_COMMITTED_ROUND, committed)
        self._send_or_mark_dead(sender, ack)
        if ready:
            self._post_barrier()

    def _round_complete_locked(self) -> bool:
        """Caller holds the lock. True when every still-live client of the
        current round has reported. Models from clients that died AFTER
        submitting don't count toward the live quorum — a healthy on-time
        client must not have its round discarded because someone else both
        contributed and left."""
        live_models = sum(1 for s in self._models if s not in self._dead)
        # only CLIENT deaths shrink the quorum — a dead edge rank (tiered
        # worlds mark unreachable edges dead too) is a transport failure
        # domain, not a missing contribution
        dead_clients = sum(1 for d in self._dead
                           if 1 <= d <= self.client_num)
        expected = self.client_num - dead_clients
        return live_models >= max(expected, self.min_clients) > 0

    def _arm_round_timer(self) -> None:
        # --round_deadline_s (partial cohorts, stragglers fold late) wins
        # over the legacy round_timeout (stragglers dropped dead)
        deadline = self.round_deadline_s or self.round_timeout
        if deadline <= 0 or self.async_mode:
            return  # async mode has no cohort barrier to deadline
        if self._round_timer is not None:
            self._round_timer.cancel()
        self._round_timer = threading.Timer(
            deadline, self._on_round_timeout, args=(self.round_idx,)
        )
        self._round_timer.daemon = True
        self.world.register_timer(self._round_timer)
        self._round_timer.start()

    def _on_round_timeout(self, round_idx: int) -> None:
        """Cohort deadline fired: aggregate the K' <= K updates that
        arrived. Under ``--round_deadline_s`` the stragglers stay LIVE
        cohort members — their late updates fold into the next open round
        through the staleness path; under the legacy ``round_timeout``
        they are marked dead (they rejoin by re-sending ONLINE status)."""
        if self.done.is_set():
            # a callback that already started when _close_and_finish
            # cancelled the timer: it must not re-arm into (or aggregate
            # for) a finished federation
            return
        with self._lock:
            if round_idx != self.round_idx:
                return
            if not self._models or len(self._models) < self.min_clients:
                logger.warning(
                    "server round %d: timeout with %d/%d models "
                    "(< min_clients %d) — keep waiting",
                    round_idx, len(self._models), self.client_num,
                    self.min_clients,
                )
                self._arm_round_timer()  # keep the deadline alive
                return
            missing = (
                set(range(1, self.client_num + 1)) - set(self._models)
                - self._dead
            )
            if not self._late_fold:
                self._dead.update(missing)
        if missing and self._late_fold:
            self.world.telemetry.counter_inc("traffic.partial_rounds")
            logger.warning(
                "server round %d: deadline (%.3fs) passed; closing a "
                "PARTIAL cohort of %d/%d — stragglers %s stay live, their "
                "late updates fold via the staleness path",
                round_idx, self.round_deadline_s, len(self._models),
                self.client_num, sorted(missing),
            )
        elif missing:
            logger.warning(
                "server round %d: deadline passed; dropping %s and "
                "aggregating %d/%d models",
                round_idx, sorted(missing), len(self._models), self.client_num,
            )
        self._finish_round(round_idx)

    def _dispatch_targets(self) -> List[int]:
        """Ranks a model fan-out addresses: every client in a flat world;
        in a tiered one the E edges (each relays to its lease block from
        its replica) plus any degraded-mode direct clients — the root's
        fan-out cost is O(E), which is the entire scalability story."""
        if self.topology is None:
            return list(range(1, self.size))
        with self._lock:
            direct = sorted(self._direct_clients)
        return self.topology.edge_ranks + direct

    def _send_init_msg(self) -> None:
        """reference: fedml_server_manager.py:93-118 (online barrier → init)."""
        leaves = [np.asarray(l) for l in jax.tree.leaves(self.global_params)]
        trc = self.world.trace
        targets = self._dispatch_targets()
        for client_rank in targets:
            msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, client_rank)
            msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
            msg.add(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, client_rank - 1)
            msg.set_arrays(leaves)
            # the INIT fan-out roots round 0's trace exactly like a SYNC
            # dispatch roots every later round's
            with (trc.span("dispatch", round_idx=self.round_idx,
                           client=client_rank)
                  if trc.sampled(self.round_idx) else NULL_SPAN):
                self._send_or_mark_dead(client_rank, msg)
        logger.info("server: init sent to %d ranks", len(targets))
        self._arm_round_timer()

    def _on_model_received(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        if self.topology is not None:
            # tiered worlds fold summaries; a per-client update at the
            # root means degraded mode (the swarm smoke asserts zero)
            self.world.telemetry.counter_inc("edge.direct_client_updates")
        msg_round = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX,
                                self.round_idx))
        with self._lock:
            head = self.round_idx
        if msg_round != head and not (self._late_fold and msg_round < head):
            # without a deadline plane, a stale-round model is dropped (the
            # pre-deadline semantics, bitwise-pinned); with one, the late
            # update folds through the staleness path below
            logger.warning(
                "server: stale round model from client %d ignored", sender
            )
            return
        self._maybe_kill("pre_fold", msg_round)
        from ..core.compression import UpdateCodec

        self._record_ack(msg)
        # sync-mode fold: decode + staleness bookkeeping on the receive
        # thread — continues the client's upload trace (adopted context)
        tctx = self.world.trace.current_context()
        sp = (self.world.trace.span("fold", round_idx=msg_round,
                                    client=sender)
              if tctx is not None else NULL_SPAN)
        with sp:
            params = self._reconstruct_update(
                sender, msg_round, msg.get_arrays(),
                msg.get(UpdateCodec.META_KEY), msg.get(FILTER_KEY),
            )
        if params is None:
            # undecodable (filter mismatch / evicted base) — counted and
            # logged by _reconstruct_update. In sync mode a client whose
            # every message is undecodable must not stall the quorum
            # forever (round_timeout defaults to 0): mark it dead so the
            # round can complete without it; a later decodable message
            # revives it like any other dropped client.
            with self._lock:
                self._dead.add(sender)
                have_all = self._round_complete_locked()
            if have_all:
                self._finish_round(msg_round)
            return
        n = float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0))
        self._fold_sync_update(sender, msg_round, params, n)

    def _fold_sync_update(self, sender: int, msg_round: int, params,
                          n: float) -> None:
        """Fold ONE decoded update into the open sync round — the shared
        tail of the flat C2S path and the tiered edge-summary path (a
        summary batches transport only; every entry folds through HERE,
        which is the load-bearing half of the bitwise-parity argument).

        The committed-round guard turns at-least-once delivery into
        exactly-once at the ledger: a (client, round) contribution that
        already aggregated — a re-homed client's replay racing the dead
        edge's shipped summary, or a partition-healed edge re-shipping its
        last summary verbatim — drops here instead of double-counting."""
        late = False
        staleness = 0
        with self._lock:
            if msg_round <= self._committed_client_round.get(sender, -1):
                self.world.telemetry.counter_inc(
                    "traffic.replay_dedup_drops")
                logger.info(
                    "server: round-%d update from client %d already "
                    "aggregated — replay dropped", msg_round, sender)
                return
            staleness = self.round_idx - msg_round
            if staleness < 0:
                return  # a round tag from the future: corrupt header
            if staleness > 0:
                if not self._late_fold:
                    return  # round closed between the unlocked check & here
                # Partial-cohort plane (docs/robustness.md): the straggler
                # missed its round's deadline — fold the update into the
                # round IN PROGRESS with the async staleness decay
                # (exactly the FedBuff treatment of a stale arrival),
                # unless this client already contributed something at
                # least as fresh to the open round.
                if (sender in self._models
                        and self._model_rounds.get(sender, -1) >= msg_round):
                    self.world.telemetry.counter_inc(
                        "traffic.late_superseded")
                    return
                from ..traffic.async_aggregator import staleness_weight

                late = True
                weight = n * staleness_weight(staleness, self.late_alpha)
            else:
                weight = n
                if (self._late_fold and sender in self._models
                        and self._model_rounds.get(sender, msg_round)
                        < msg_round):
                    # this client's own FRESH update replaces its pending
                    # late fold in the open round — the older contribution
                    # is consumed, and counted, exactly like the mirror
                    # direction (late arriving after fresh):
                    # late_folds − late_superseded = late folds that
                    # actually aggregated
                    self.world.telemetry.counter_inc(
                        "traffic.late_superseded")
            self._models[sender] = (weight, params)
            self._model_rounds[sender] = msg_round
            # a model from a previously-dropped client revives it — one
            # missed deadline must not exclude a live client forever
            self._dead.discard(sender)
            self._offline_declared.discard(sender)
            have_all = self._round_complete_locked()
            fold_round = self.round_idx
        if late:
            self.world.telemetry.counter_inc("traffic.late_folds")
            logger.info(
                "server: late round-%d update from client %d folded into "
                "round %d (staleness %d)", msg_round, sender, fold_round,
                staleness,
            )
        if have_all:
            self._finish_round(fold_round)

    # -- hierarchical edge tier (fedml_tpu/hierarchy/, docs/traffic.md) -----

    def _on_edge_summary(self, msg: Message) -> None:
        """One pre-folded edge summary: expand its entry list and run
        every entry through the SAME decode + fold path a flat client
        message takes (entry-preserving parity, hierarchy/summary.py).
        The root folds E summaries per bump instead of N messages — the
        transport scales, the math never changes. Admission composes per
        tier: the whole summary is offered once; a shed NACKs the EDGE,
        which re-offers it freshly stamped after retry_after_s."""
        edge = msg.get_sender_id()
        self._record_ack(msg)
        meta = msg.get(MyMessage.MSG_ARG_KEY_SUMMARY_META) or {}
        try:
            entries = unpack_summary(meta, msg.get_arrays())
        except ValueError as e:
            self.world.telemetry.counter_inc("edge.summary_decode_errors")
            logger.error("server: undecodable summary from edge %d: %s",
                         edge, e)
            return
        with self._lock:
            # a summary proves the edge lives (partition heal without a
            # separate handshake) and refreshes its piggybacked stats
            self._edge_online.add(edge)
            self._dead.discard(edge)
            stats = meta.get("stats")
            if stats:
                self._edge_stats[edge] = stats
            head = self.round_idx
        self.world.telemetry.counter_inc("edge.summaries_folded")
        self.world.telemetry.counter_inc("edge.summary_entries",
                                         len(entries))
        if self.async_mode:
            self._enqueue_summary_entries(edge, head, entries)
            return
        self._maybe_kill("pre_fold", head)
        for e in entries:
            params = self._reconstruct_entry(e)
            if params is None:
                continue
            self._fold_sync_update(int(e["sender"]),
                                   int(e["client_version"]), params,
                                   float(e["num_samples"]))

    def _reconstruct_entry(self, e: Dict):
        """Decode one summary entry into a full params pytree. An edge's
        lossless delta re-encode (``dmeta``) decodes against OUR store —
        root and edge replicas hold bitwise-equal version vectors, both
        installed from the same dispatch, so the round-trip is exact.
        Client-encoded entries (compression codec / payload filter) and
        plain frames go through the flat ``_reconstruct_update``."""
        dmeta = e.get("dmeta")
        if dmeta is None:
            return self._reconstruct_update(
                int(e["sender"]), int(e["client_version"]), e["arrays"],
                e.get("codec_meta"), e.get("filter_meta"))
        base = self.store.get(int(dmeta["base_version"]))
        if base is None:
            self.world.telemetry.counter_inc("comm.delta.c2s_base_missing")
            logger.warning(
                "server: edge summary entry references version %s the "
                "store evicted — dropping the entry (client %s resyncs)",
                dmeta.get("base_version"), e.get("sender"))
            return None
        vec = self.wire.decode(base, e["arrays"], dmeta)
        return tree_unflatten_from_vector(jnp.asarray(vec), self._treedef,
                                          self._shapes)

    def _enqueue_summary_entries(self, edge: int, head: int,
                                 entries: List[Dict]) -> None:
        """Async tiered ingest: expand a summary into per-entry fold-queue
        items (edge delta frames decode HERE, on the comm thread, against
        the store — losslessly back to plain leaves — so the aggregator
        worker's flat decode path applies unchanged). One admission offer
        covers the whole summary: all entries enqueue or none do."""
        items = []
        for e in entries:
            arrays = e["arrays"]
            codec_meta, filter_meta = e.get("codec_meta"), e.get("filter_meta")
            dmeta = e.get("dmeta")
            if dmeta is not None:
                base = self.store.get(int(dmeta["base_version"]))
                if base is None:
                    self.world.telemetry.counter_inc(
                        "comm.delta.c2s_base_missing")
                    logger.warning(
                        "server: edge summary entry references version %s "
                        "the store evicted — dropping the entry",
                        dmeta.get("base_version"))
                    continue
                vec = np.asarray(self.wire.decode(base, arrays, dmeta))
                sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
                arrays = [seg.reshape(s) for seg, s in zip(
                    np.split(vec, np.cumsum(sizes)[:-1]), self._shapes)]
                codec_meta = filter_meta = None
            items.append((
                time.monotonic(), int(e["sender"]),
                int(e["client_version"]), float(e["num_samples"]),
                arrays, codec_meta, filter_meta, None,
            ))
        if not items:
            return
        verdict = self.admission.offer(lambda: self._try_enqueue_many(items))
        if not verdict.admitted:
            self._shed_reply(edge, head, verdict)

    def _try_enqueue_many(self, items: List) -> bool:
        """All-or-nothing enqueue for one summary's entries. The comm
        receive thread is the only producer, so the capacity probe cannot
        race another enqueue."""
        if (self._rx.maxsize > 0
                and self._rx.qsize() + len(items) > self._rx.maxsize):
            return False
        for it in items:
            self._rx.put_nowait(it)
        return True

    def _on_edge_resync(self, msg: Message) -> None:
        """The edge handshake — ONLINE announcement, partition-heal resync
        and restart re-seed in one idempotent message (the client resync
        one tier up). The ack's head round doubles as the edge's restart
        detector: an edge holding a fresh replica (version < 0) in an
        already-running world re-solicits its lease block's cached
        updates instead of losing the buffer its predecessor held."""
        edge = msg.get_sender_id()
        edge_version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        self.world.telemetry.counter_inc("comm.edge_resyncs")
        self._record_ack(msg)
        if self.done.is_set():
            fin = Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, edge)
            fin.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
            fin.set_arrays(
                [np.asarray(l) for l in jax.tree.leaves(self.global_params)])
            self._send_or_mark_dead(edge, fin)
            return
        with self._lock:
            self._edge_online.add(edge)
            self._dead.discard(edge)
            head = self.round_idx
            init_sent = self._init_sent
            ready = self._barrier_ready_locked()
            if ready:
                self._init_sent = True
        logger.info("server: edge %d resynced (replica at %d, head %d)",
                    edge, edge_version, head)
        ack = Message(MyMessage.MSG_TYPE_S2C_RESYNC_ACK, self.rank, edge)
        ack.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, head)
        ack.add(MyMessage.MSG_ARG_KEY_COMMITTED_ROUND, -1)
        self._send_or_mark_dead(edge, ack)
        if ready:
            self._post_barrier()
        elif init_sent and edge_version < head:
            # partition-healed or mid-world edge: re-seed its replica with
            # the head (delta against an ACKed base when it echoed one)
            self._send_model_to(
                edge, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)

    def _on_rehome_root(self, msg: Message) -> None:
        """Degraded mode: a client that exhausted its sibling ring homes
        directly on the root, which serves it exactly like a flat client
        from here on (the fan-out adds it alongside the edges)."""
        sender = msg.get_sender_id()
        self.world.telemetry.counter_inc("edge.root_adoptions")
        self._record_ack(msg)
        if self.done.is_set():
            fin = Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, sender)
            fin.set_arrays(
                [np.asarray(l) for l in jax.tree.leaves(self.global_params)])
            self._send_or_mark_dead(sender, fin)
            return
        with self._lock:
            self._direct_clients.add(sender)
            self._online.add(sender)
            self._dead.discard(sender)
            self._offline_declared.discard(sender)
            committed = self._committed_client_round.get(sender, -1)
            head = self.round_idx
            init_sent = self._init_sent
        logger.info("server: adopted re-homed client %d (old edge %s)",
                    sender, msg.get(MyMessage.MSG_ARG_KEY_OLD_EDGE))
        ack = Message(MyMessage.MSG_TYPE_S2C_RESYNC_ACK, self.rank, sender)
        ack.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, head)
        ack.add(MyMessage.MSG_ARG_KEY_COMMITTED_ROUND, committed)
        self._send_or_mark_dead(sender, ack)
        # re-engage: the ack's committed round decides the client's replay;
        # a missed version bump restarts its round loop
        client_round = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        if init_sent and client_round < head:
            self._send_model_to(
                sender, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)

    def edge_report(self) -> Dict[str, dict]:
        """Per-edge health block for the swarm report / `fedml_tpu top`:
        each edge's last piggybacked stats snapshot (fold count, re-homed
        clients, re-solicited updates, summary staleness histogram)."""
        with self._lock:
            return {str(k): dict(v) for k, v in self._edge_stats.items()}

    # -- delta delivery plane: C2S decode (fedml_tpu/delivery/) -------------

    def _record_ack(self, msg: Message) -> None:
        """A delta-capable C2S message proves its sender holds the global
        of the version it is tagged with — that version becomes the S2C
        delta base for this client."""
        if not msg.get(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE):
            return
        version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        if version < 0:
            return
        sender = msg.get_sender_id()
        with self._lock:
            if version > self._acked.get(sender, -1):
                self._acked[sender] = version

    def _filter_for(self, filter_meta) -> Optional[object]:
        """The configured payload filter, validated against what the
        message announces. Raises on any mismatch — merging leaves under a
        different pattern would silently scramble the model."""
        if not filter_meta:
            if self.payload_filter is not None:
                raise ValueError(
                    "server runs --payload_filter "
                    f"{self.payload_filter.pattern!r} but the client sent a "
                    "full payload — both ends must configure the filter"
                )
            return None
        if self.payload_filter is None:
            raise ValueError(
                f"client sent a filtered payload ({filter_meta!r}) but this "
                "server has no --payload_filter configured"
            )
        if filter_meta.get("pattern") != self.payload_filter.pattern:
            raise ValueError(
                f"payload filter mismatch: client {filter_meta!r} vs server "
                f"{self.payload_filter.pattern!r}"
            )
        return self.payload_filter

    def _reconstruct_update(self, sender: int, client_version: int, arrays,
                            codec_meta, filter_meta):
        """Decode one C2S payload into a FULL params pytree.

        A compressed delta decodes against the store's vector for the
        version the client trained from — exactly the FedBuff requirement
        that used to make async×compression impossible. Filtered payloads
        merge into the CURRENT global, so unselected leaves are frozen at
        the head for every buffer entry (their weighted average is the
        head itself). Returns None when the update is undecodable (base
        evicted, filter mismatch) — counted, logged, never folded.
        """
        from ..core.compression import UpdateCodec

        try:
            filt = self._filter_for(filter_meta)
        except ValueError as e:
            self.world.telemetry.counter_inc(
                "comm.delta.filter_mismatch_drops")
            logger.error("server: dropping update from client %d: %s",
                         sender, e)
            return None
        with self._lock:
            head = self.global_params
        head_leaves = jax.tree.leaves(head)
        if codec_meta:
            # device wire path: the base rides the store's device-resident
            # ring-head cache — folding a stream of async updates decodes
            # every one of them against ONE upload per version instead of
            # re-crossing the host/device boundary per arrival. The
            # filtered path slices the host vector, so it keeps host reads.
            use_device = (self.wire.path == "device" and filt is None)
            base_vec = (self.store.get_device(client_version) if use_device
                        else self.store.get(client_version))
            if base_vec is None:
                self.world.telemetry.counter_inc(
                    "comm.delta.c2s_base_missing")
                logger.warning(
                    "server: client %d's compressed delta references "
                    "version %d, which the store evicted (capacity %d) — "
                    "dropping the update and resyncing the client",
                    sender, client_version, self.store.capacity,
                )
                return None
            self.world.telemetry.counter_inc(
                "comm.delta.c2s_delta_decodes")
            if filt is not None:
                # the filtered base is a fixed set of slices of the stored
                # flat vector — never materialize (or device-place) the
                # full model just to pull out the adapter leaves
                sub_base = filt.select_from_vector(base_vec)
                sub_vec = UpdateCodec.decode(
                    jnp.asarray(sub_base), arrays, codec_meta)
                sub_leaves = [
                    jnp.asarray(l)
                    for l in filt.split_vector(np.asarray(sub_vec))
                ]
                leaves = filt.merge(head_leaves, sub_leaves)
                return jax.tree.unflatten(jax.tree.structure(head), leaves)
            vec = UpdateCodec.decode(jnp.asarray(base_vec), arrays,
                                     codec_meta)
            return tree_unflatten_from_vector(vec, self._treedef,
                                              self._shapes)
        if filt is not None:
            sub_leaves = [jnp.asarray(a) for a in arrays]
            leaves = filt.merge(head_leaves, sub_leaves)
            return jax.tree.unflatten(jax.tree.structure(head), leaves)
        leaves = [jnp.asarray(a) for a in arrays]
        return jax.tree.unflatten(jax.tree.structure(head), leaves)

    def _aggregate_models(self, raw, senders, round_r):
        """The ONE aggregation core both modes share: attack hooks →
        defense → weighted average → central DP → post hooks. ``raw`` is
        ``[(weight, params), ...]`` in ``senders`` order (sync passes raw
        sample counts; async passes staleness-decayed weights). The rng
        folds ``round_r + 1`` — the value the pre-refactor code read from
        ``self.round_idx`` after its increment — so the sync trajectory is
        bitwise-unchanged."""
        raw = self.aggregator.on_before_aggregation(raw)
        weights = jnp.asarray([n for n, _ in raw])
        stacked = stack_trees([p for _, p in raw])
        rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0))),
            round_r + 1,
        )
        if self.defender.is_defense_enabled():
            gvec, treedef, shapes = tree_flatten_to_vector(self.global_params)
            flat = jax.vmap(lambda t: tree_flatten_to_vector(t)[0])(stacked)
            agg_vec = self.defender.defend(
                flat, weights, gvec, rng, client_ids=senders
            )
            agg = tree_unflatten_from_vector(agg_vec, treedef, shapes)
        else:
            agg = weighted_average(stacked, weights)
        if self.dp is not None and self.dp.dp_type == "cdp":
            agg = self.dp.randomize_global(agg, jax.random.fold_in(rng, 7))
        agg = self.aggregator.on_after_aggregation(agg)
        if self.payload_filter is not None:
            # adapter-only semantics (docs/delivery.md): unselected leaves
            # are FROZEN — restore them from the previous global bitwise,
            # so float averaging of identical values (or central DP noise)
            # can never drift a leaf no client is allowed to train
            with self._lock:
                prev_leaves = jax.tree.leaves(self.global_params)
            agg_leaves = jax.tree.leaves(agg)
            merged = self.payload_filter.merge(
                prev_leaves, self.payload_filter.select(agg_leaves))
            agg = jax.tree.unflatten(jax.tree.structure(agg), merged)
        with self._lock:
            # published under the lock: in async mode this runs on the
            # aggregator worker while the comm thread reads the global for
            # FINISH/INIT broadcasts
            self.global_params = agg
        self.aggregator.set_model_params(agg)
        return agg

    def _finish_round(self, expected_round: int) -> None:
        with self._lock:
            if expected_round != self.round_idx:
                # the round this caller saw already closed (a late timer
                # callback racing a completing model arrival, or vice
                # versa): the early arrivals of round expected_round+1 now
                # sitting in self._models belong to THAT round — touching
                # them here would aggregate a partial cohort early and
                # double-count the closing round (ISSUE 7 satellite;
                # regression-pinned in tests/test_faults.py)
                return
            if not self._models:
                return  # already aggregated (timeout/model-arrival race)
            if self._round_timer is not None:
                self._round_timer.cancel()
                self._round_timer = None
            senders = sorted(self._models)
            raw = [self._models[r] for r in senders]
            # the round each aggregated update was actually trained at
            # (== the round for on-time updates; older for late folds) —
            # what the resync ack reports and what a restarted server
            # rebuilds from the ledger's client_versions
            trained_at = [self._model_rounds.get(s, self.round_idx)
                          for s in senders]
            self._models.clear()
            self._model_rounds.clear()
            # close the round window NOW: any round-r straggler arriving
            # while the (slow) aggregation below runs must be rejected by
            # the stale-round check, not counted toward round r+1
            round_r = self.round_idx
            self.round_idx += 1
            # count each aggregated contribution: a value > 1 would mean a
            # client entered the SAME round's aggregation twice (a wire
            # duplicate that slipped dedup, or a double-fired round) — the
            # chaos harness and deadline-race tests assert all-ones
            per_round = self.contrib_counts.setdefault(round_r, {})
            for s in senders:
                per_round[s] = per_round.get(s, 0) + 1
            for s, tr in zip(senders, trained_at):
                if tr > self._committed_client_round.get(s, -1):
                    self._committed_client_round[s] = tr
        self._maybe_kill("mid_fold", round_r)
        agg = self._aggregate_models(raw, senders, round_r)
        ledger_extra = {}
        if any(tr != round_r for tr in trained_at):
            # late folds: record the trained-at rounds so a restarted
            # server rebuilds the committed-contribution map exactly
            # (plain full-cohort rounds keep the pre-deadline format)
            ledger_extra["client_versions"] = trained_at
        preempt = self._commit_and_eval(round_r, agg, senders,
                                        log_label="server round",
                                        **ledger_extra)
        self._maybe_kill("post_commit", round_r)
        if preempt and self.round_idx < self.round_num:
            self._preempt_exit(round_r)
            return

        if self.round_idx < self.round_num:
            # round_r + 1 is THE version these params are: re-reading
            # self.round_idx mid-fan-out could see a further bump (timer
            # thread racing the receive thread) and mis-tag the frames
            version = round_r + 1
            leaves = [np.asarray(l) for l in jax.tree.leaves(agg)]
            vec = flatten_leaves(leaves)
            # commit the new version to the reference store BEFORE any
            # dispatch: a client may answer (and its delta be decoded)
            # before this fan-out finishes. VersionedModelStore is
            # internally locked — comm-thread readers and this writer
            # serialize on the store's own mutex.
            if self._store_active:
                self.store.put(version, vec)  # graftlint: disable=G005
            cache: Dict[int, tuple] = {}
            targets = [r for r in self._dispatch_targets()
                       if r not in self._offline_declared]
            self._prefill_encode_cache(targets, vec, cache, version)
            for client_rank in targets:
                # dropped clients still receive the sync (maybe the stall was
                # transient); they rejoin the quorum when a model arrives.
                # Clients that DECLARED OFFLINE have torn down — skip them.
                self._send_model_to(
                    client_rank, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                    leaves=leaves, vec=vec, cache=cache, version=version,
                )
            self._arm_round_timer()
        else:
            self._broadcast_finish(
                "server: training finished after %d rounds")
            self._close_and_finish()

    # -- the post-aggregation tail both modes share -------------------------

    def _commit_and_eval(self, round_r, agg, senders, log_label,
                         **ledger_extra) -> bool:
        """Checkpoint + ledger commit on cadence, eval on cadence.
        Returns whether a preemption drain is latched (the caller stops
        instead of dispatching the next round/version)."""
        preempt = self._guard is not None and self._guard.requested()
        if self._ckpt is not None:
            from ..core import runstate

            every = runstate.checkpoint_cadence(self.args)
            # the save blocks the calling thread (Orbax
            # wait_until_finished) — the checkpoint cadence bounds that
            # cost, same as the sp engine; a preemption drain commits
            # regardless of cadence
            if ((round_r + 1) % every == 0 or round_r == self.round_num - 1
                    or preempt):
                self._ckpt.save({"global_params": agg}, step=round_r)
                if self._ledger is not None:
                    with self._lock:
                        contrib = dict(self.contrib_counts.get(round_r, {}))
                    self._ledger.commit_round(
                        round_r, ckpt_step=round_r, cohort=senders,
                        contrib={str(k): v for k, v in contrib.items()},
                        **ledger_extra,
                    )
        if self.ds is not None:
            freq = max(int(getattr(self.args, "frequency_of_the_test", 1)),
                       1)
            if round_r % freq == 0 or round_r == self.round_num - 1:
                metrics = make_eval_fn(self.bundle)(
                    agg, self.ds.test_x, self.ds.test_y
                )
                with self._lock:
                    self.final_metrics = metrics
                logger.info("%s %d: acc=%.4f", log_label, round_r,
                            metrics["test_acc"])
        return preempt

    def _preempt_exit(self, round_r: int) -> None:
        """Preemption drain: round_r is aggregated + committed; stop HERE
        instead of dispatching round_r+1 — the restarted server resumes at
        exactly round_r+1 with the committed global."""
        self.world.telemetry.counter_inc("run.preemptions")
        logger.warning(
            "server: preempted after committing round %d — resumable "
            "with --resume auto", round_r,
        )
        with self._lock:
            self.preempted = True
        self._close_and_finish()

    def _broadcast_finish(self, log_msg: str) -> None:
        leaves = [np.asarray(l) for l in jax.tree.leaves(self.global_params)]
        for client_rank in self._dispatch_targets():
            # tiered worlds address the edges, each of which relays the
            # FINISH (with the final arrays) to its whole lease block
            msg = Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank,
                          client_rank)
            msg.set_arrays(leaves)
            self._send_or_mark_dead(client_rank, msg)
        logger.info(log_msg, self.round_num)

    def _close_and_finish(self) -> None:
        if self._ckpt is not None:
            self._ckpt.close()
        with self._lock:
            # a round deadline armed for a round that will never close
            # must not fire into a finished federation
            if self._round_timer is not None:
                self._round_timer.cancel()
                self._round_timer = None
            # drain membership/parking state (graftmem M001/M004): a
            # finished federation holds no per-peer rosters or parked work
            self._edge_online.clear()
            self._direct_clients.clear()
            self._pending_pulls.clear()
            self._pending_folds.clear()
        self.done.set()
        self.finish()

    def _send_or_mark_dead(self, client_rank: int, msg: Message) -> None:
        """Transport-level liveness: an unreachable peer (dead gRPC channel)
        is marked dead instead of crashing the FSM."""
        try:
            self.send_message(msg)
        except Exception as e:
            logger.warning(
                "server: send to client %d failed (%s) — marking dead",
                client_rank, e,
            )
            with self._lock:
                self._dead.add(client_rank)

    # -- async traffic plane (aggregation_mode=async; docs/traffic.md) ------

    def _on_model_received_async(self, msg: Message) -> None:
        """C2S_SEND_MODEL behind admission control. The comm thread only
        gates and enqueues (header-cheap); decode, staleness judgment and
        folding run on the aggregator worker — a slow defense/DP step
        backpressures into load-shedding, never into queue growth."""
        from ..core.compression import UpdateCodec

        sender = msg.get_sender_id()
        if self.topology is not None:
            self.world.telemetry.counter_inc("edge.direct_client_updates")
        client_version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        # admission span continues the client's upload trace (the comm
        # layer adopted the wire context before dispatching here); its own
        # context rides the queue item so the fold-side spans — running on
        # the aggregator worker thread — keep the same causal chain
        tctx = self.world.trace.current_context()
        sp = (self.world.trace.span(
            "admission", round_idx=client_version, client=sender)
            if tctx is not None else NULL_SPAN)
        with sp:
            self._record_ack(msg)
            item = (
                time.monotonic(), sender, client_version,
                float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0)),
                msg.get_arrays(),
                msg.get(UpdateCodec.META_KEY), msg.get(FILTER_KEY),
                sp.context() if tctx is not None else None,
            )
            verdict = self.admission.offer(lambda: self._try_enqueue(item))
            if not verdict.admitted:
                sp.annotate("shed", verdict.reason)
        if not verdict.admitted:
            self._shed_reply(sender, client_version, verdict)

    def _try_enqueue(self, item) -> bool:
        try:
            self._rx.put_nowait(item)
            return True
        except queue.Full:
            return False

    def _shed_reply(self, sender: int, client_version: int,
                    verdict) -> None:
        """Explicit NACK: the client re-offers the SAME trained update after
        retry_after_s (as a freshly-stamped message — the shed happened
        after dedup recorded the original seq)."""
        logger.info(
            "server: shed update from client %d (version %d, %s) — "
            "retry after %.3fs", sender, client_version, verdict.reason,
            verdict.retry_after_s,
        )
        nack = Message(MyMessage.MSG_TYPE_S2C_SHED_NOTICE, self.rank, sender)
        nack.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, client_version)
        nack.add(MyMessage.MSG_ARG_KEY_RETRY_AFTER_S,
                 float(verdict.retry_after_s))
        nack.add(MyMessage.MSG_ARG_KEY_SHED_REASON, verdict.reason)
        self._send_or_mark_dead(sender, nack)

    def _async_worker_loop(self) -> None:
        """Aggregator worker: drain the bounded queue, fold, and take a
        server step per ``buffer_size`` accepted updates — or flush a
        partial buffer after ``async_flush_s`` of stall, so a dropped-out
        tail cohort can never wedge the federation."""
        last_progress = time.monotonic()
        while not self.done.is_set():
            try:
                item = self._rx.get(timeout=0.05)
            except queue.Empty:
                item = None
            if item is not None:
                try:
                    self._async_fold(item)
                except Exception:
                    # one malformed update (wrong leaf count, hostile
                    # client, version skew) must cost ITSELF, not the
                    # aggregator thread — a dead worker would livelock the
                    # federation behind queue_full sheds with no error
                    self.world.telemetry.counter_inc("traffic.fold_errors")
                    logger.exception(
                        "server: dropping malformed update from client %s",
                        item[1],
                    )
                last_progress = time.monotonic()
            if self.done.is_set():
                return
            stepped = False
            try:
                if self.buffer.ready():
                    stepped = self._async_step()
                elif (self.async_cfg.flush_s > 0
                        and self.buffer.occupancy() > 0
                        and time.monotonic() - last_progress
                        >= self.async_cfg.flush_s):
                    logger.warning(
                        "server: flushing a partial async buffer (%d/%d) "
                        "after %.1fs without progress",
                        self.buffer.occupancy(),
                        self.async_cfg.buffer_size, self.async_cfg.flush_s,
                    )
                    stepped = self._async_step()
            except Exception:
                # a failed step already drained its buffer; surface the
                # error loudly but keep serving — the next K updates get
                # their step
                self.world.telemetry.counter_inc("traffic.step_errors")
                logger.exception("server: async step failed")
                stepped = True
            if stepped:
                last_progress = time.monotonic()

    def _async_fold(self, item) -> None:
        """Decode one admitted update and fold it into the buffer with its
        exact staleness (server version at fold minus the version tag the
        dispatched model carried). A compressed delta decodes against the
        STORE's vector for the client's tagged version — the whole point of
        the version-indexed store: staleness-weighted folding is unchanged,
        only the reference global is version-correct."""
        t_enq, sender, client_version, n, arrays, codec_meta, \
            filter_meta, tctx = item
        self._maybe_kill("pre_fold", self.round_idx)
        with self._lock:
            # exactly-once under at-least-once delivery (tiered replays:
            # a re-homed client's cached update racing the dead edge's
            # shipped summary, or a healed edge re-shipping verbatim):
            # drop a (client, version) already committed to a step — or
            # already sitting in the fold buffer awaiting one
            dup = (client_version <= self._committed_client_round.get(
                       sender, -1)
                   or (sender, client_version) in self._pending_folds)
        if dup:
            self.world.telemetry.counter_inc("traffic.replay_dedup_drops")
            logger.info(
                "server: version-%d update from client %d already "
                "folded/committed — replay dropped", client_version, sender)
            return
        trc = self.world.trace
        traced = trc.enabled and tctx is not None
        fold_parent = None
        if traced:
            # fold-queue wait, measured retroactively from the enqueue
            # stamp — the same t_enq the dispatch_ready histogram uses, so
            # queue_wait + fold decompose that scalar additively
            fold_parent = trc.record_span(
                "queue_wait", t_enq, time.monotonic() - t_enq,
                ctx=tctx, client=sender)
        sp = (trc.span("fold", round_idx=tctx.round_idx,
                       parent=fold_parent, client=sender)
              if traced else NULL_SPAN)
        with sp:
            t_lookup = time.monotonic()
            params = self._reconstruct_update(
                sender, client_version, arrays, codec_meta, filter_meta)
            if traced:
                # the version-store lookup + C2S decode inside the fold
                trc.record_span(
                    "store_lookup", t_lookup, time.monotonic() - t_lookup,
                    round_idx=tctx.round_idx, parent=sp.span_id,
                    client=sender)
            if params is None:
                # base version evicted from the store: the update is
                # undecodable — same remedy as an over-stale update, the
                # sender rejoins at version head with a fresh model
                sp.annotate("outcome", "undecodable")
                self._send_model_to(
                    sender, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
                return
            verdict = self.buffer.fold(
                sender, n, params, client_version, self.round_idx
            )
            with self._lock:
                # an accepted (or even stale) update proves the client lives
                self._dead.discard(sender)
                self._offline_declared.discard(sender)
                if verdict == "buffered":
                    # in-buffer half of the exactly-once guard — cleared
                    # when the step that drains this entry commits
                    self._pending_folds.add((sender, client_version))
            if verdict == "stale":
                # beyond max_staleness: the update is discarded, but the
                # sender rejoins at version head with a fresh model
                sp.annotate("outcome", "stale")
                self._send_model_to(
                    sender, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
                return
            self.world.telemetry.observe(
                "traffic.dispatch_ready_s", time.monotonic() - t_enq)

    def _async_step(self) -> bool:
        """One FedBuff server step: drain the buffer, aggregate through the
        shared hook chain, bump the model version, commit/eval on cadence,
        and dispatch the new version to this step's contributors."""
        t0 = time.monotonic()
        entries = self.buffer.drain()
        if not entries:
            return False
        senders = [e.sender for e in entries]
        raw = [(e.weight, e.params) for e in entries]
        with self._lock:
            # close the version window NOW (same discipline as the sync
            # round): updates folded after this belong to the next version
            round_r = self.round_idx
            self.round_idx += 1
            per_round = self.contrib_counts.setdefault(round_r, {})
            for e in entries:
                per_round[e.sender] = per_round.get(e.sender, 0) + 1
                self._pending_folds.discard((e.sender, e.client_version))
                # what the resync ack reports: the client's last trained
                # version whose update entered a server step
                if e.client_version > self._committed_client_round.get(
                        e.sender, -1):
                    self._committed_client_round[e.sender] = \
                        e.client_version
        self._maybe_kill("mid_fold", round_r)
        agg = self._aggregate_models(raw, senders, round_r)
        self.world.telemetry.counter_inc("traffic.server_steps")
        preempt = self._commit_and_eval(
            round_r, agg, senders, log_label="server step",
            mode="async", staleness=[e.staleness for e in entries],
            client_versions=[e.client_version for e in entries],
        )
        self._maybe_kill("post_commit", round_r)
        self.world.telemetry.observe("traffic.step_s",
                                     time.monotonic() - t0)
        if preempt and self.round_idx < self.round_num:
            self._preempt_exit(round_r)
            return True
        if self.round_idx >= self.round_num:
            self._broadcast_finish(
                "server: async training finished after %d steps")
            self._close_and_finish()
            return True
        # FedBuff dispatch policy (--async_dispatch, docs/delivery.md):
        # sync_on_consume ships the new version to this step's
        # contributors (a client re-enters training when its update is
        # consumed); server_push pushes every version bump to all live
        # clients; client_pull answers the pulls parked since the last
        # bump. (pytree→numpy conversion hoisted out of the per-recipient
        # loop; the delta encode per distinct base is cached across it)
        with self._lock:
            skip = set(self._offline_declared)
            pulls = set(self._pending_pulls)
            self._pending_pulls.clear()
        version = round_r + 1  # the version these params ARE (see
        leaves = [np.asarray(l) for l in jax.tree.leaves(agg)]  # _send_model_to)
        vec = flatten_leaves(leaves)
        if self._store_active:
            self.store.put(version, vec)
        if self.topology is not None:
            # tiered: every version bump goes to every edge — each relays
            # to its whole lease block from its replica (client replay
            # guards absorb repeats) — plus the degraded-mode directs.
            # No client→edge map at the root, by design: re-homing moves
            # a lease without telling us.
            targets = [r for r in self._dispatch_targets() if r not in skip]
        elif self.async_dispatch == "server_push":
            targets = [r for r in range(1, self.size) if r not in skip]
        elif self.async_dispatch == "client_pull":
            targets = sorted(pulls - skip)
            # one answer fan-out per version bump: how many parked pulls
            # each bump batched (docs/telemetry.md traffic.* family)
            self.world.telemetry.observe("traffic.pull_batch_size",
                                         float(len(targets)))
        else:
            targets = [r for r in sorted(set(senders)) if r not in skip]
        cache: Dict[int, tuple] = {}
        self._prefill_encode_cache(targets, vec, cache, version)
        for client_rank in targets:
            self._send_model_to(
                client_rank, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                leaves=leaves, vec=vec, cache=cache, version=version)
        return True

    def _on_pull_request(self, msg: Message) -> None:
        """client_pull dispatch (docs/delivery.md): the client asks for a
        model newer than the version it carries. Answer immediately when
        the head version already is newer; otherwise park the pull — the
        next server step answers it with the bumped version."""
        sender = msg.get_sender_id()
        client_version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        self._record_ack(msg)
        self.world.telemetry.counter_inc("traffic.pull_requests")
        with self._lock:
            if client_version < self.round_idx:
                defer = False
            else:
                defer = True
                self._pending_pulls.add(sender)
        if defer:
            self.world.telemetry.counter_inc("traffic.pulls_deferred")
        elif not self.done.is_set():
            self._send_model_to(
                sender, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)

    def _send_model_to(self, client_rank: int, msg_type: str,
                       leaves=None, vec=None, cache=None,
                       version=None) -> None:
        """Version-tagged model dispatch (the version IS the round index —
        the client echoes it back, making staleness exact). Ships a
        lossless delta frame against the client's last-ACKed version when
        possible (docs/delivery.md); ``cache`` memoizes the encode per
        distinct base version across one fan-out.

        Fan-out callers pass ``version`` with their snapshotted leaves: a
        round can close mid-fan-out (timer thread vs receive thread), and
        re-reading ``self.round_idx`` here would tag round r+1's params
        with version r+2 — poisoning both stores' version indexing."""
        if leaves is None:
            with self._lock:
                # version tag and content snapshotted together: a dispatch
                # racing a round/version bump must not tag version v+1 on
                # version v's params
                params = self.global_params
                version = self.round_idx
            leaves = [np.asarray(l) for l in jax.tree.leaves(params)]
        elif version is None:
            version = self.round_idx
        m = Message(msg_type, self.rank, client_rank)
        m.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, version)
        # dispatch span: the ROOT of round `version`'s causal trace — its
        # context rides the S2C header (stamped by send_message while this
        # span is innermost), so the client's decode/train/upload and the
        # fold they feed all hang off it. Sampling is decided HERE, once
        # per round, deterministically: an unsampled round stamps no
        # context and the whole federation stays silent for it.
        trc = self.world.trace
        sp = (trc.span("dispatch", round_idx=int(version),
                       client=client_rank)
              if trc.sampled(int(version)) else NULL_SPAN)
        with sp:
            t_enc = time.monotonic()
            arrays, delta_meta = self._encode_model_payload(
                client_rank, leaves, vec, cache, version=version)
            if sp.span_id is not None:
                trc.record_span(
                    "wire_encode", t_enc, time.monotonic() - t_enc,
                    round_idx=int(version), parent=sp.span_id,
                    client=client_rank,
                    delta=bool(delta_meta is not None))
            if delta_meta is not None:
                m.add(DELTA_KEY, delta_meta)
            m.set_arrays(arrays)
            self._send_or_mark_dead(client_rank, m)

    def _prefill_encode_cache(self, targets, vec, cache, version) -> None:
        """Batched per-cohort encode (device wire path): ONE vmapped kernel
        dispatch covers every distinct ACKed base in this fan-out — the
        stacked-base axis replaces E sequential host loops. Evicted bases
        are left for the per-client path (which logs the fallback once per
        base via the same cache). No-op off the device path: the host
        codec's per-distinct-base memoization is already one encode each.
        """
        if not self.s2c_delta_on or self.wire.path != "device":
            return
        with self._lock:
            acked = {self._acked.get(r) for r in targets}
        acked.discard(None)
        versions, bases = [], []
        for v in sorted(acked):
            base = self.store.get_device(v)
            if base is not None:
                versions.append(v)
                bases.append(base)
        if len(bases) < 2:
            return  # 0/1 distinct bases: one per-client encode covers it
        new_dev = self.store.get_device(version)  # one dispatch
        for v, entry in zip(versions, self.wire.encode_batch(
                bases, new_dev if new_dev is not None else vec)):
            cache[v] = entry

    def _encode_model_payload(self, client_rank: int, leaves, vec=None,
                              cache=None, version=None):
        """``(arrays, delta_meta-or-None)`` for one model dispatch: a
        lossless delta against the client's last-ACKed version when that
        base is still in the store, else the full leaf list — LOUDLY when
        the fallback is an eviction (the operator sized the store too
        small for the federation's staleness)."""
        if not self.s2c_delta_on:
            return leaves, None
        with self._lock:
            acked = self._acked.get(client_rank)
        if acked is None:
            # nothing ACKed yet (fresh/restarted client, or a peer that
            # never advertised delta capability — swarm devices, pre-delta
            # clients): full frame, quietly
            self.world.telemetry.counter_inc("comm.delta.s2c_full_frames")
            return leaves, None
        entry = cache.get(acked) if cache is not None else None
        if entry is None:
            # ONE store lookup + ONE encode per distinct ACKed base per
            # fan-out (client-pull batching, docs/delivery.md): a thousand
            # parked pulls on the same base hit the store once; the evicted
            # case is cached too so the fallback never re-probes per client
            on_device = self.wire.path == "device"
            base_vec = (self.store.get_device(acked) if on_device
                        else self.store.get(acked))
            if base_vec is None:
                logger.warning(
                    "server: ACKed version %d (client %d) was evicted from "
                    "the %d-version store — falling back to full-model "
                    "frames for this base (raise --delta_store_versions to "
                    "keep deltas flowing)",
                    acked, client_rank, self.store.capacity,
                )
                entry = (None, None)
            else:
                new_vec = None
                if on_device and version is not None:
                    # the committed head is (or becomes) device-resident in
                    # the store ring — every encode in this fan-out, and
                    # every later round's base, reads that one upload
                    new_vec = self.store.get_device(version)
                if new_vec is None:
                    new_vec = vec if vec is not None else flatten_leaves(
                        leaves)
                entry = self.wire.encode(base_vec, new_vec)
            if cache is not None:
                cache[acked] = entry
        arrays, meta = entry
        if meta is None:
            self.world.telemetry.counter_inc("comm.delta.s2c_full_frames")
            return leaves, None
        raw = payload_nbytes(leaves)
        self.world.telemetry.counter_inc("comm.delta.s2c_delta_frames")
        self.world.telemetry.counter_inc(
            "comm.delta.s2c_bytes_saved",
            max(raw - payload_nbytes(arrays), 0),
        )
        return arrays, {**meta, "base_version": int(acked)}
