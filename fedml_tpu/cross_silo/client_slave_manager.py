"""Silo slave: a DCN-separated silo member driven by the silo master.

reference: ``cross_silo/client/fedml_client_slave_manager.py`` — non-master
ranks of a silo block on ``train_ready`` broadcasts from rank 0 and train in
DDP lock-step. TPU-native re-design: ICI-connected chips already train in
lock-step inside one jit (``trainer_dist_adapter``), so the slave FSM only
remains for silo members on *other hosts* (DCN), where per-step psum is not
economical. Protocol, over the silo's own comm world (disjoint from the
FL server world):

    master --SILO_SYNC(params, round)--> slave     (train this round)
    slave  --SILO_RESULT(params, n)--> master      (locally-trained update)
    master --SILO_FINISH--> slave                  (tear down)

The master weighted-averages its own result with the slaves' before sending
one silo update to the FL server — round-level averaging over DCN, per-step
psum over ICI.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import constants
from ..core.distributed import FedMLCommManager, Message
from .message_define import MyMessage

logger = logging.getLogger(__name__)


class ClientSlaveManager(FedMLCommManager):
    """One DCN silo member. ``rank`` is silo-local (master = 0)."""

    def __init__(self, args, trainer, comm=None, rank=1, size=0,
                 backend=constants.COMM_BACKEND_LOOPBACK, dataset=None):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.ds = dataset
        self.round_idx = 0
        self.done = threading.Event()
        self._treedef: Optional[object] = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_SILO_SYNC, self._on_sync
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_SILO_FINISH, self._on_finish
        )

    def _install_params(self, msg: Message) -> None:
        if self._treedef is None:
            skeleton = self.trainer.model.init(
                jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0)))
            )
            self._treedef = jax.tree.structure(skeleton)
        leaves = [jnp.asarray(a) for a in msg.get_arrays()]
        self.trainer.set_model_params(jax.tree.unflatten(self._treedef, leaves))

    def _on_sync(self, msg: Message) -> None:
        round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        # replay guard (graftproto P004): the master broadcasts each round
        # once and rounds only advance, so a SYNC for an OLDER round is a
        # delayed/replayed frame — retraining it would waste the slave and
        # ship a result the master's staleness check discards anyway
        if round_idx < self.round_idx:
            logger.info(
                "silo slave %d: stale SILO_SYNC for round %d ignored "
                "(already at round %d)", self.rank, round_idx, self.round_idx,
            )
            return
        self.round_idx = round_idx
        self._install_params(msg)
        self.args.round_idx = self.round_idx
        # this slave's sub-shard: the silo's client shard is range-split by
        # silo rank in the data layer; here the slave owns the shard slice
        # the master assigned at construction (dataset already sliced)
        x, y, n = self.ds
        metrics = self.trainer.train((x, y, n), None, self.args)
        params = self.trainer.get_model_params()
        reply = Message(MyMessage.MSG_TYPE_SILO_RESULT, self.rank, 0)
        reply.add(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n))
        reply.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        reply.add(MyMessage.MSG_ARG_KEY_TRAIN_LOSS,
                  float(metrics.get("train_loss", 0.0)))
        reply.set_arrays([np.asarray(l) for l in jax.tree.leaves(params)])
        self.send_message(reply)

    def _on_finish(self, msg: Message) -> None:
        logger.info("silo slave %d: finished", self.rank)
        self.done.set()
        self.finish()


class SiloMasterPlane(FedMLCommManager):
    """The master's handle on the silo world (rank 0 of the silo comm).

    reference: the master side of the process-group rendezvous
    (``fedml_client_master_manager.py`` + torch ``broadcast``); here a tiny
    message FSM: broadcast SILO_SYNC, block-collect SILO_RESULTs.
    """

    def __init__(self, args, comm=None, size=0,
                 backend=constants.COMM_BACKEND_LOOPBACK):
        import queue

        super().__init__(args, comm, 0, size, backend)
        self._results: "queue.Queue[tuple]" = queue.Queue()
        self.register_message_receive_handlers()
        self.run_async()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_SILO_RESULT, self._on_result
        )

    def _on_result(self, msg: Message) -> None:
        leaves = [jnp.asarray(a) for a in msg.get_arrays()]
        self._results.put((
            float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0)),
            leaves,
            float(msg.get(MyMessage.MSG_ARG_KEY_TRAIN_LOSS, 0.0)),
            int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1)),
        ))

    def broadcast_sync(self, params, round_idx: int) -> None:
        leaves = [np.asarray(l) for l in jax.tree.leaves(params)]
        for slave_rank in range(1, self.size):
            msg = Message(MyMessage.MSG_TYPE_SILO_SYNC, 0, slave_rank)
            msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, round_idx)
            msg.set_arrays(leaves)
            self.send_message(msg)

    def collect(self, round_idx: int, timeout: float = 120.0):
        """Block for the slaves' round-``round_idx`` results:
        [(n, leaves, loss), ...].

        A slave that misses the deadline is dropped for the round (the silo
        proceeds with whoever answered) — a dead slave must not take the
        master's receive thread, and with it the whole federation, down.
        A stale result from a PREVIOUS round (slave answered after the
        deadline; the queue persists) is discarded, not mistaken for this
        round's.
        """
        import queue

        out = []
        while len(out) < self.size - 1:
            try:
                n, leaves, loss, r = self._results.get(timeout=timeout)
            except queue.Empty:
                logger.warning(
                    "silo master: %d/%d slave result(s) missing after %.0fs; "
                    "continuing with partial silo",
                    self.size - 1 - len(out), self.size - 1, timeout,
                )
                break
            if r != round_idx:
                logger.warning(
                    "silo master: discarding stale round-%d slave result "
                    "(current round %d)", r, round_idx,
                )
                continue
            out.append((n, leaves, loss))
        return out

    def broadcast_finish(self) -> None:
        for slave_rank in range(1, self.size):
            self.send_message(
                Message(MyMessage.MSG_TYPE_SILO_FINISH, 0, slave_rank)
            )
        self.finish()


def padded_silo_split(x, y, n: int, m: int, batch_size: int = 1):
    """Shared split geometry for both silo paths (ICI mesh + DCN slaves).

    Pads the packed shard so each of the m members owns ``local`` rows where
    ``local`` is a non-zero ``batch_size`` multiple (the local training
    kernel's batch grid requires it), and computes per-member real-sample
    counts (real rows sit contiguously at the front of the packed layout).

    Returns ``(x_padded, y_padded, local, counts)``.
    """
    x, y = np.asarray(x), np.asarray(y)
    cap = int(x.shape[0])
    local = -(-cap // m)  # ceil
    local = max(-(-local // batch_size) * batch_size, batch_size)
    pad = local * m - cap
    if pad:
        x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
    counts = np.asarray(
        [min(local, max(0, int(n) - s * local)) for s in range(m)], np.int32
    )
    return x, y, local, counts


def split_silo_shard(x, y, n: int, m: int, batch_size: int = 1):
    """Range-split one client shard among m silo members (DCN path).

    Returns [(x_s, y_s, n_s)]; padding rows stay at the tail of the last
    slices.
    """
    x, y, local, counts = padded_silo_split(x, y, n, m, batch_size)
    return [
        (x[s * local:(s + 1) * local], y[s * local:(s + 1) * local],
         int(counts[s]))
        for s in range(m)
    ]
