"""``fedml`` CLI.

reference: ``python/fedml/cli/cli.py:29-685`` (click app: version / status /
logs / login / logout / build / register / env). TPU re-grounding: argparse
(no extra deps); the MLOps-platform commands (login/register against
open.fedml.ai) are out of scope as platform glue (SURVEY.md §7 stage 8) —
``build`` packages a training dir into a deployable zip, ``env`` collects the
environment report (reference: cli/env/collect_env.py:6-68), ``logs`` tails a
run's JSONL event log.

Run as ``python -m fedml_tpu.cli <command>``.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import zipfile


def cmd_version(_args) -> int:
    from . import __version__

    print(f"fedml_tpu version: {__version__}")
    return 0


def cmd_env(_args) -> int:
    """reference: collect_env — fedml/OS/python/torch/device info."""
    from . import __version__

    print(f"fedml_tpu: {__version__}")
    print(f"python: {sys.version.split()[0]}")
    print(f"os: {platform.platform()}")
    try:
        import jax

        print(f"jax: {jax.__version__}")
        devs = jax.devices()
        print(f"devices: {[str(d) for d in devs]}")
        print(f"default backend: {jax.default_backend()}")
    except Exception as e:  # pragma: no cover - env-specific
        print(f"jax: unavailable ({e})")
    for mod in ("flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            import importlib

            m = importlib.import_module(mod)
            print(f"{mod}: {getattr(m, '__version__', '?')}")
        except ImportError:
            print(f"{mod}: not installed")
    return 0


def cmd_status(_args) -> int:
    runs_dir = ".fedml_tpu_runs"
    if not os.path.isdir(runs_dir):
        print("no runs directory; nothing tracked")
        return 0
    for fn in sorted(os.listdir(runs_dir)):
        path = os.path.join(runs_dir, fn)
        with open(path) as f:
            lines = f.readlines()
        last = json.loads(lines[-1]) if lines else {}
        print(f"{fn}: {len(lines)} events, last={last.get('kind', '?')}")
    return 0


def cmd_logs(args) -> int:
    """Tail a run's event log (reference: fedml logs)."""
    path = args.file or ""
    if not path:
        runs_dir = ".fedml_tpu_runs"
        files = sorted(os.listdir(runs_dir)) if os.path.isdir(runs_dir) else []
        if not files:
            print("no logs found")
            return 1
        path = os.path.join(runs_dir, files[-1])
    with open(path) as f:
        lines = f.readlines()
    for line in lines[-args.n:]:
        print(line.rstrip())
    return 0


def cmd_build(args) -> int:
    """Package a training directory into a deployable zip
    (reference: cli.py ``build`` — client/server MLOps packages)."""
    src = os.path.abspath(args.source_folder)
    if not os.path.isdir(src):
        print(f"error: {src} is not a directory")
        return 1
    out = os.path.abspath(args.output or f"{os.path.basename(src)}_package.zip")
    entry = args.entry_point
    if entry and not os.path.exists(os.path.join(src, entry)):
        print(f"error: entry point {entry!r} not found in {src}")
        return 1
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        for root, _dirs, files in os.walk(src):
            for fn in files:
                if fn.endswith((".pyc", ".pyo")) or "__pycache__" in root:
                    continue
                full = os.path.join(root, fn)
                z.write(full, os.path.relpath(full, src))
        manifest = {"type": args.type, "entry_point": entry or "main.py"}
        z.writestr("fedml_package.json", json.dumps(manifest, indent=2))
    print(f"built {args.type} package: {out}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fedml_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version", help="print version")
    sub.add_parser("env", help="environment report")
    sub.add_parser("status", help="tracked run status")

    p_logs = sub.add_parser("logs", help="show run event logs")
    p_logs.add_argument("--file", default="", help="specific event file")
    p_logs.add_argument("-n", type=int, default=20, help="tail lines")

    p_build = sub.add_parser("build", help="package a training dir")
    p_build.add_argument("--type", "-t", choices=("client", "server"),
                         default="client")
    p_build.add_argument("--source_folder", "-sf", required=True)
    p_build.add_argument("--entry_point", "-ep", default="")
    p_build.add_argument("--output", "-o", default="")

    args = parser.parse_args(argv)
    handlers = {
        "version": cmd_version,
        "env": cmd_env,
        "status": cmd_status,
        "logs": cmd_logs,
        "build": cmd_build,
    }
    if args.command is None:
        parser.print_help()
        return 1
    return handlers[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
