"""``fedml`` CLI.

reference: ``python/fedml/cli/cli.py:29-685`` (click app: version / status /
logs / login / logout / build / register / env). TPU re-grounding: argparse
(no extra deps). ``build`` packages a training dir into a deployable zip
(reference: build — client/server MLOps packages), ``env`` collects the
environment report (reference: cli/env/collect_env.py:6-68), ``logs`` tails a
run's JSONL event log. The deployment surface binds to the directory-queue
agent plane in ``fedml_tpu/agent.py``: ``login``/``logout`` bind/unbind this
host as an edge device (reference: cli/edge_deployment/client_login.py),
``launch`` submits a built package to a job queue, and ``agent`` runs the
edge/server daemon that claims and executes queued jobs (reference:
client_daemon.py / client_runner.py).

Run as ``python -m fedml_tpu.cli <command>``.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import zipfile


def cmd_version(_args) -> int:
    from . import __version__

    print(f"fedml_tpu version: {__version__}")
    return 0


def cmd_env(_args) -> int:
    """reference: collect_env — fedml/OS/python/torch/device info."""
    from . import __version__

    print(f"fedml_tpu: {__version__}")
    print(f"python: {sys.version.split()[0]}")
    print(f"os: {platform.platform()}")
    try:
        import jax

        print(f"jax: {jax.__version__}")
        devs = jax.devices()
        print(f"devices: {[str(d) for d in devs]}")
        print(f"default backend: {jax.default_backend()}")
    except Exception as e:  # pragma: no cover - env-specific
        print(f"jax: unavailable ({e})")
    for mod in ("flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            import importlib

            m = importlib.import_module(mod)
            print(f"{mod}: {getattr(m, '__version__', '?')}")
        except ImportError:
            print(f"{mod}: not installed")
    return 0


def cmd_status(_args) -> int:
    runs_dir = ".fedml_tpu_runs"
    if not os.path.isdir(runs_dir):
        print("no runs directory; nothing tracked")
        return 0
    for fn in sorted(os.listdir(runs_dir)):
        path = os.path.join(runs_dir, fn)
        with open(path) as f:
            lines = f.readlines()
        last = json.loads(lines[-1]) if lines else {}
        print(f"{fn}: {len(lines)} events, last={last.get('kind', '?')}")
    return 0


def _resolve_run_file(path: str) -> str:
    """Explicit path, else the newest (by mtime — lexicographic order lies
    once run ids pass one digit) ``.jsonl`` in the default runs dir ('')."""
    if path:
        return path
    runs_dir = ".fedml_tpu_runs"
    if not os.path.isdir(runs_dir):
        return ""
    files = [os.path.join(runs_dir, f) for f in os.listdir(runs_dir)
             if f.endswith(".jsonl")]
    return max(files, key=os.path.getmtime) if files else ""


def cmd_logs(args) -> int:
    """Tail a run's event log (reference: fedml logs)."""
    path = _resolve_run_file(args.file)
    if not path:
        print("no logs found")
        return 1
    with open(path) as f:
        lines = f.readlines()
    for line in lines[-args.n:]:
        print(line.rstrip())
    return 0


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def cmd_top(args) -> int:
    """Phase-latency breakdown for a finished run's RoundRecords.

    Reads the JSONL event log a tracked run wrote (``--enable_tracking``)
    and prints, per phase, call count / total / mean / p50 / p95 and the
    share of total round wall-clock — the "where does a round's time go"
    table the 2,217-LoC reference MLOps plane never had.
    """
    from .core.mlops import read_events

    path = _resolve_run_file(args.file)
    if not path or not os.path.exists(path):
        print("no run event log found (run with --enable_tracking)")
        return 1
    events = read_events(path)
    records = [e for e in events if e.get("kind") == "round_record"]
    if not records:
        print(f"{path}: {len(events)} events but no round_record entries "
              "(tracked runs emit one per round)")
        return 1

    phases = {}
    # dispatch→ready latency overlaps the dispatch+device_wait spans, so it
    # stays OUT of the phase table (whose % wall must not double-count) and
    # is summarised separately below
    dispatch_lat = []
    for r in records:
        for name, dur in (r.get("phases") or {}).items():
            phases.setdefault(name, []).append(float(dur))
        dl = r.get("dispatch_latency_s")
        if dl is not None:
            dispatch_lat.append(float(dl))
    wall = sum(float(r.get("wall_s") or 0.0) for r in records)
    rounds = len(records)

    print(f"run: {path}")
    print(f"rounds: {rounds}   wall: {wall:.3f}s   "
          f"rounds/s: {rounds / wall if wall else float('nan'):.2f}")
    examples = sum(float(r.get("examples") or 0.0) for r in records)
    if examples:
        print(f"examples: {examples:.0f}   examples/s: "
              f"{examples / wall if wall else float('nan'):.0f}")
    compiles = sum(int(r.get("compiles") or 0) for r in records)
    fused = sum(1 for r in records if r.get("fused"))
    hbm_peaks = [r.get("hbm_peak_mb") for r in records
                 if r.get("hbm_peak_mb") is not None]
    print(f"fused rounds: {fused}/{rounds}   compile events: {compiles}"
          + (f"   hbm peak: {max(hbm_peaks):.1f} MB" if hbm_peaks else ""))
    if dispatch_lat:
        ds = sorted(dispatch_lat)
        print(f"dispatch→ready: mean "
              f"{1e3 * sum(ds) / len(ds):.3f}ms   "
              f"p50 {1e3 * _percentile(ds, 0.5):.3f}ms   "
              f"p95 {1e3 * _percentile(ds, 0.95):.3f}ms")
    print()
    header = (f"{'phase':<18} {'calls':>6} {'total s':>9} {'mean ms':>9} "
              f"{'p50 ms':>8} {'p95 ms':>8} {'% wall':>7}")
    print(header)
    print("-" * len(header))
    for name, vals in sorted(phases.items(), key=lambda kv: -sum(kv[1])):
        vs = sorted(vals)
        total = sum(vals)
        pct = 100.0 * total / wall if wall else 0.0
        print(f"{name:<18} {len(vals):>6} {total:>9.3f} "
              f"{1e3 * total / len(vals):>9.3f} "
              f"{1e3 * _percentile(vs, 0.5):>8.3f} "
              f"{1e3 * _percentile(vs, 0.95):>8.3f} {pct:>6.1f}%")
    summary = next((e for e in reversed(events)
                    if e.get("kind") == "telemetry_summary"), None)
    if summary:
        metrics = summary.get("metrics") or {}
        counters = metrics.get("counters", {})
        hits = counters.get("jax.compilation_cache.hits", 0)
        misses = counters.get("jax.compilation_cache.misses", 0)
        if hits or misses:
            print(f"\ncompilation cache: {hits:.0f} hits / "
                  f"{misses:.0f} misses")
        _print_traffic_summary(metrics)
        _print_delta_summary(metrics)
        _print_wire_summary(metrics)
        _print_recovery_summary(metrics)
        _print_edge_summary(metrics)
        _print_mem_summary(metrics)
    _print_trace_summary(events)
    return 0


def _print_trace_summary(events: list) -> None:
    """The distributed-tracing story (docs/tracing.md): where the gating
    milliseconds of each round went (critical-path segment shares) and
    which clients gated rounds (straggler top-k). Reads the ``trace_span``
    records riding the same JSONL file; silent when the run was untraced."""
    from .core.mlops import tracing

    spans = [e for e in events
             if e.get("kind") == tracing.SPAN_KIND and "span" in e]
    if not spans:
        return
    clocks = [e for e in events if e.get("kind") == tracing.CLOCK_KIND]
    merged = tracing.merge_trace(spans, clocks)
    shares = tracing.critical_path_shares(merged)
    total = sum(shares.values())
    print(f"\ntrace (critical path over {len(merged['rounds'])} rounds, "
          f"{len(merged['spans'])} spans):")
    for name, dur in sorted(shares.items(), key=lambda kv: -kv[1]):
        pct = 100.0 * dur / total if total else 0.0
        print(f"  {name:<18} {dur:>9.4f}s {pct:>6.1f}%")
    stragglers = tracing.straggler_attribution(merged, k=5)
    if stragglers:
        print("  stragglers: " + "   ".join(
            f"client {s['client']} (+{s['wait_s']:.3f}s, "
            f"gated {s['rounds_gated']})" for s in stragglers))


def _print_wire_summary(metrics: dict) -> None:
    """The wire-path story (comm.wire.* family, docs/delivery.md
    device-direct): which codec served encodes/decodes, the per-call time
    histograms, and bytes that had to be materialized host-side. Silent
    when no wire codec ever ran."""
    counters = metrics.get("counters", {})
    hists = metrics.get("histograms", {})
    enc = hists.get("comm.wire.encode_s") or {}
    dec = hists.get("comm.wire.decode_s") or {}
    dev_enc = counters.get("comm.wire.device_encodes", 0)
    dev_dec = counters.get("comm.wire.device_decodes", 0)
    fallbacks = counters.get("comm.wire.host_fallbacks", 0)
    if not (enc.get("count") or dec.get("count") or dev_enc or fallbacks):
        return
    print("\nwire path (delta codec kernels):")
    print(f"  encodes: {enc.get('count', 0):.0f} "
          f"({dev_enc:.0f} device)   decodes: {dec.get('count', 0):.0f} "
          f"({dev_dec:.0f} device)   host fallbacks: {fallbacks:.0f}")
    if enc.get("count"):
        print(f"  encode_s p50 {1e3 * (enc.get('p50') or 0):.2f}ms   "
              f"p99 {1e3 * (enc.get('p99') or 0):.2f}ms")
    if dec.get("count"):
        print(f"  decode_s p50 {1e3 * (dec.get('p50') or 0):.2f}ms   "
              f"p99 {1e3 * (dec.get('p99') or 0):.2f}ms")
    copied = counters.get("comm.wire.host_bytes_copied", 0)
    if copied:
        print(f"  host bytes copied: {copied / 1e6:.2f} MB "
              "(non-dlpack transfers)")


def _print_recovery_summary(metrics: dict) -> None:
    """The survivable-serving-plane story (docs/robustness.md): a soak
    that silently survived a server kill, a partition, or straggler
    deadlines must be VISIBLE instead of indistinguishable from a clean
    run. Silent when nothing recovery-shaped happened."""
    counters = metrics.get("counters", {})
    recoveries = counters.get("run.server_recoveries", 0)
    resyncs = counters.get("comm.resyncs", 0)
    reconnects = counters.get("comm.reconnects", 0)
    misses = counters.get("comm.heartbeat_misses", 0)
    partial = counters.get("traffic.partial_rounds", 0)
    late = counters.get("traffic.late_folds", 0)
    if not (recoveries or resyncs or reconnects or misses or partial
            or late):
        return
    print("\nrecovery plane (failover / resync / deadlines):")
    print(f"  server recoveries: {recoveries:.0f}   client resyncs: "
          f"{resyncs:.0f} (replays "
          f"{counters.get('comm.resync_replays', 0):.0f})")
    print(f"  heartbeat misses: {misses:.0f}   reconnect attempts: "
          f"{reconnects:.0f}")
    if partial or late:
        print(f"  partial rounds: {partial:.0f}   late folds: {late:.0f}"
              f"   late superseded: "
              f"{counters.get('traffic.late_superseded', 0):.0f}")


def _print_edge_summary(metrics: dict) -> None:
    """The hierarchical-tier story (edge.* family, docs/traffic.md
    "Hierarchical edge tier"): how many pre-folded summaries the root
    consumed instead of raw client updates, and what the edge failure
    domains absorbed (re-homing, re-solicited replays, degraded-mode
    adoptions). Silent when the run was flat."""
    counters = metrics.get("counters", {})
    folded = counters.get("edge.summaries_folded", 0)
    folds = counters.get("edge.folds", 0)
    if not (folded or folds):
        return
    print("\nedge tier (hierarchical aggregation):")
    print(f"  summaries folded at root: {folded:.0f} "
          f"({counters.get('edge.summary_entries', 0):.0f} client entries)"
          f"   edge folds: {folds:.0f}   direct client updates: "
          f"{counters.get('edge.direct_client_updates', 0):.0f}")
    rehomed = counters.get("comm.rehomes", 0)
    adopted = counters.get("edge.rehomed_clients", 0)
    root_adopt = counters.get("edge.root_adoptions", 0)
    resolicited = counters.get("edge.resolicited_updates", 0)
    dedup = counters.get("edge.buffer_dedup_drops", 0)
    replay_drops = counters.get("traffic.replay_dedup_drops", 0)
    if rehomed or adopted or root_adopt or resolicited or dedup \
            or replay_drops:
        print(f"  re-homed clients: {rehomed:.0f} "
              f"(edge adoptions {adopted:.0f}, root adoptions "
              f"{root_adopt:.0f})   re-solicited replays: "
              f"{resolicited:.0f}")
        print(f"  dedup drops: {dedup:.0f} edge buffer / "
              f"{replay_drops:.0f} root replay")


def _print_mem_summary(metrics: dict) -> None:
    """The retention story (mem.* family, docs/graftmem.md): per-container
    occupancy and eviction counts from the serving plane's BoundedDicts —
    the runtime face of the graftmem static gate. Silent when no bounded
    container published (a run predating the mem.* family)."""
    gauges = metrics.get("gauges", {})
    counters = metrics.get("counters", {})
    rows = []
    for name in sorted(gauges):
        if name.startswith("mem.") and name.endswith(".occupancy"):
            container = name[len("mem."):-len(".occupancy")]
            rows.append((container, gauges[name],
                         counters.get(f"mem.{container}.evictions", 0.0)))
    if not rows:
        return
    print("\nmemory (bounded serving-plane containers):")
    for container, occ, ev in rows:
        print(f"  {container:<28} occupancy {occ:>8.0f}   "
              f"evictions {ev:>6.0f}")


def _print_delta_summary(metrics: dict) -> None:
    """The delta delivery plane's wire story (comm.delta.* family,
    docs/delivery.md): delta hit rate and bytes saved per direction, plus
    the version store's occupancy/eviction health. Silent when the plane
    never engaged (no delta frame, no compressed decode)."""
    counters = metrics.get("counters", {})
    gauges = metrics.get("gauges", {})
    s2c_delta = counters.get("comm.delta.s2c_delta_frames", 0)
    s2c_full = counters.get("comm.delta.s2c_full_frames", 0)
    c2s_decodes = counters.get("comm.delta.c2s_delta_decodes", 0)
    if not (s2c_delta or c2s_decodes):
        return
    print("\ndelivery plane (delta shipping):")
    total = s2c_delta + s2c_full
    rate = s2c_delta / total if total else 0.0
    print(f"  s2c: {s2c_delta:.0f} delta / {s2c_full:.0f} full frames   "
          f"delta hit rate {rate:.2f}   "
          f"saved {counters.get('comm.delta.s2c_bytes_saved', 0) / 1e6:.2f} "
          "MB")
    print(f"  c2s: {c2s_decodes:.0f} delta decodes   saved "
          f"{counters.get('comm.delta.c2s_bytes_saved', 0) / 1e6:.2f} MB   "
          f"base-missing drops "
          f"{counters.get('comm.delta.c2s_base_missing', 0):.0f}")
    occ = gauges.get("comm.delta.server_store.occupancy")
    ev = counters.get("comm.delta.server_store.evictions", 0)
    if occ is not None or ev:
        print(f"  store: occupancy {occ if occ is not None else 0:.0f}   "
              f"evictions {ev:.0f}")


def _print_traffic_summary(metrics: dict) -> None:
    """The async plane's backpressure story (traffic.* family, PR 7) next
    to the phase table: accepted vs shed, staleness actually folded, and
    how close the dispatch buffer ran to its limit."""
    counters = metrics.get("counters", {})
    gauges = metrics.get("gauges", {})
    hists = metrics.get("histograms", {})
    accepted = counters.get("traffic.accepted_updates", 0)
    shed_rate = counters.get("traffic.shed_rate_limited", 0)
    shed_queue = counters.get("traffic.shed_queue_full", 0)
    stale = counters.get("traffic.stale_dropped_updates", 0)
    steps = counters.get("traffic.server_steps", 0)
    if not (accepted or shed_rate or shed_queue or stale or steps):
        return  # sync run: the async plane never engaged
    print("\ntraffic plane (async aggregation):")
    print(f"  accepted: {accepted:.0f}   shed: "
          f"{shed_rate + shed_queue:.0f} "
          f"(rate-limited {shed_rate:.0f}, queue-full {shed_queue:.0f})   "
          f"stale-dropped: {stale:.0f}")
    line = f"  server steps: {steps:.0f}"
    occupancy = gauges.get("traffic.buffer_occupancy")
    if occupancy is not None:
        line += f"   buffer occupancy: {occupancy:.0f}"
    print(line)
    for name, label in (("traffic.staleness", "staleness"),
                        ("traffic.dispatch_ready_s", "dispatch→ready")):
        h = hists.get(name)
        if not h or not h.get("count"):
            continue
        unit = "" if name == "traffic.staleness" else "s"
        print(f"  {label}: p50 {h['p50']:.3f}{unit}   "
              f"p95 {h['p95']:.3f}{unit}   p99 {h['p99']:.3f}{unit} "
              f"(n={h['count']:.0f})")


def cmd_trace(args) -> int:
    """Merge a federation's per-process span files into ONE clock-aligned
    causal trace (docs/tracing.md): collect every run JSONL sink + flight-
    recorder post-mortem in the trace dir, align each process's monotonic
    timeline (heartbeat probe offsets, wall-anchor fallback), and print the
    per-round critical path, segment shares, and straggler attribution —
    or export Chrome trace-event JSON for Perfetto (``--chrome``)."""
    from .core.mlops import tracing

    trace_dir = args.dir or ".fedml_tpu_runs"
    files = tracing.collect_trace_files(trace_dir,
                                        run_id=args.run_id or None)
    if not files:
        print(f"no trace files in {trace_dir} "
              "(run with --enable_tracing + --enable_tracking)")
        return 1
    spans, clocks = tracing.read_trace(files)
    merged = tracing.merge_trace(spans, clocks)
    if not merged["spans"]:
        print(f"{len(files)} files in {trace_dir} but no trace_span "
              "records (was the run traced?)")
        return 1
    if args.chrome:
        with open(args.chrome, "w", encoding="utf-8") as f:
            json.dump(tracing.to_chrome(merged), f)
    shares = tracing.critical_path_shares(merged)
    stragglers = tracing.straggler_attribution(merged, k=args.top)
    round_idx = (args.round if args.round >= 0
                 else (merged["rounds"][-1] if merged["rounds"] else -1))
    path = tracing.critical_path(merged, round_idx) if round_idx >= 0 else []
    if args.json:
        print(json.dumps({
            "files": len(files), "spans": len(merged["spans"]),
            "procs": [list(p) for p in merged["procs"]],
            "rounds": merged["rounds"], "orphans": merged["orphans"],
            "critical_path_round": round_idx,
            "critical_path": path,
            "critical_path_segments": shares,
            "stragglers": stragglers,
        }, indent=2, sort_keys=True))
        return 0
    print(f"trace dir: {trace_dir}   files: {len(files)}")
    print(f"spans: {len(merged['spans'])}   "
          f"processes: {len(merged['procs'])}   "
          f"rounds: {len(merged['rounds'])}   "
          f"orphans: {len(merged['orphans'])}")
    if args.chrome:
        print(f"chrome trace: {args.chrome} "
              "(load in Perfetto or chrome://tracing)")
    if path:
        print(f"\ncritical path (round {round_idx}):")
        for seg in path:
            who = (f"client {seg['client']}" if seg.get("client") is not None
                   else f"rank {seg.get('rank')}")
            label = seg["name"]
            if label == "transit":
                label = f"transit {seg.get('from')}→{seg.get('to')}"
            print(f"  {label:<28} {1e3 * seg['dur_s']:>9.3f}ms  {who}")
    total = sum(shares.values())
    if shares:
        print("\ncritical-path segment shares (all rounds):")
        for name, dur in sorted(shares.items(), key=lambda kv: -kv[1]):
            pct = 100.0 * dur / total if total else 0.0
            print(f"  {name:<18} {dur:>9.4f}s {pct:>6.1f}%")
    if stragglers:
        print("\nstragglers (attributed wait vs the round's fastest "
              "chain):")
        for s in stragglers:
            print(f"  client {s['client']:<4} +{s['wait_s']:.4f}s  "
                  f"gated {s['rounds_gated']} rounds")
    return 0


def cmd_build(args) -> int:
    """Package a training directory into a deployable zip
    (reference: cli.py ``build`` — client/server MLOps packages)."""
    src = os.path.abspath(args.source_folder)
    if not os.path.isdir(src):
        print(f"error: {src} is not a directory")
        return 1
    out = os.path.abspath(args.output or f"{os.path.basename(src)}_package.zip")
    entry = args.entry_point
    if entry and not os.path.exists(os.path.join(src, entry)):
        print(f"error: entry point {entry!r} not found in {src}")
        return 1
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        for root, _dirs, files in os.walk(src):
            for fn in files:
                if fn.endswith((".pyc", ".pyo")) or "__pycache__" in root:
                    continue
                full = os.path.join(root, fn)
                z.write(full, os.path.relpath(full, src))
        manifest = {"type": args.type, "entry_point": entry or "main.py"}
        z.writestr("fedml_package.json", json.dumps(manifest, indent=2))
    print(f"built {args.type} package: {out}")
    return 0


def cmd_login(args) -> int:
    """Bind this host as an edge device (reference: fedml login)."""
    from .agent import login

    state = login(args.account_id, role=args.role, state_dir=args.state_dir)
    print(f"bound as {state['role']} device {state['device_id']} "
          f"(account {state['account_id']})")
    return 0


def cmd_logout(args) -> int:
    from .agent import logout

    print("unbound" if logout(state_dir=args.state_dir) else "not bound")
    return 0


def cmd_launch(args) -> int:
    """Submit a built package to a job queue (reference: run-start msg)."""
    from .agent import submit_job

    job_id = submit_job(args.package, args.jobs_dir,
                        run_args=args.run_args or [])
    print(f"submitted {job_id} to {args.jobs_dir}")
    return 0


def cmd_agent(args) -> int:
    """Run the edge/server job daemon (reference: client_daemon.py)."""
    from .agent import Agent, agent_state

    state = agent_state(state_dir=args.state_dir)
    role = args.role or (state or {}).get("role", "client")
    agent = Agent(args.jobs_dir, args.work_dir, role=role)
    if args.once:
        result = agent.run_once()
        print("no pending jobs" if result is None
              else f"{result.job_id}: {result.status}")
        return 0 if result is None or result.status == "FINISHED" else 1
    agent.run_forever(max_jobs=args.max_jobs)
    return 0


def cmd_cache(args) -> int:
    """Inspect / clear the persistent XLA compilation cache.

    The cache is what lets repeat runs (and the driver's bench legs) skip
    the compile wall — wire it into a run with ``--compilation_cache_dir``
    (or the ``compilation_cache_dir`` YAML key; see fedml_tpu.init).
    """
    from . import constants

    cache_dir = (
        args.dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.environ.get("BENCH_COMPILE_CACHE_DIR")
        # bench.py's default cache — the one the documented bench workflow
        # actually writes to
        or constants.BENCH_COMPILE_CACHE_DIR_DEFAULT
    )
    if not os.path.isdir(cache_dir):
        print(f"compilation cache: {cache_dir} (empty — no directory)")
        _report_cache_telemetry(getattr(args, "run_file", ""))
        return 0
    entries, total = [], 0
    for root, _dirs, files in os.walk(cache_dir):
        for fn in files:
            full = os.path.join(root, fn)
            try:
                total += os.path.getsize(full)
                entries.append(full)
            except OSError:
                pass
    if args.clear:
        for full in entries:
            try:
                os.remove(full)
            except OSError:
                pass
        print(f"compilation cache: cleared {len(entries)} entries "
              f"({total / 1e6:.1f} MB) from {cache_dir}")
        return 0
    print(f"compilation cache: {cache_dir}")
    print(f"  entries: {len(entries)}")
    print(f"  size:    {total / 1e6:.1f} MB")
    _report_cache_telemetry(getattr(args, "run_file", ""))
    return 0


def _report_cache_telemetry(run_file: str) -> None:
    """Hit/miss counts from the newest tracked run's telemetry summary, so
    repeat-run compile savings are visible next to the cache's disk state."""
    from .core.mlops import read_events

    path = _resolve_run_file(run_file)
    if not path or not os.path.exists(path):
        return
    summary = next(
        (e for e in reversed(read_events(path))
         if e.get("kind") == "telemetry_summary"), None)
    if summary is None:
        return
    counters = (summary.get("metrics") or {}).get("counters", {})
    hits = counters.get("jax.compilation_cache.hits", 0)
    misses = counters.get("jax.compilation_cache.misses", 0)
    saved = counters.get("jax.compilation_cache.time_saved_s", 0.0)
    compiles = counters.get("jax.compiles", 0)
    if not (hits or misses or compiles):
        return
    print(f"  last tracked run ({os.path.basename(path)}):")
    print(f"    cache hits/misses: {hits:.0f}/{misses:.0f}"
          + (f", ~{saved:.1f}s compile time saved" if saved else ""))
    print(f"    backend compiles:  {compiles:.0f}")


def cmd_lint(args) -> int:
    """Run the static-analysis suites over the tree. Default: graftlint
    (tools/graftlint) — trace-safety (G001), donation (G002), recompile
    (G003), purity (G004) and thread-safety (G005). ``--proto``: graftproto
    (tools/graftproto) — message-flow graph (P001–P003), FSM replay/
    termination (P004/P005), delivery invariants (P006/P007) and lock-order
    analysis (P008/P009). ``--shard``: graftshard (tools/graftshard) —
    partition-rule coverage (S001), spec validity (S002), implicit-reshard
    (S003), host-transfer (S004) and static HBM budgets (S005, via
    ``--model``/``--mesh``). ``--rep``: graftrep (tools/graftrep) —
    determinism discipline (D001 key reuse, D002 seed provenance, D003
    unordered accumulation, D004 dtype drift, D005 run-identity leaks) and
    fused/unfused round structural equivalence (``--equiv``). ``--iso``:
    graftiso (tools/graftiso) — serving-plane state ownership (I001
    module-global state in handlers, I002 unscoped singleton access, I003
    class-level defaults & cross-instance aliasing, I004 ambient config,
    I005 untethered thread lifecycle). ``--mem``: graftmem (tools/graftmem)
    — serving-plane retention (M001 unbounded keyed growth, M002
    capacity-less caches, M003 telemetry cardinality explosion, M004
    undrained parking, M005 payload retention past commit). Shells into
    the same entry points CI uses, anchored at the repo root so results
    are identical from any cwd.

    Exit codes (all suites): 0 clean, 1 findings, 2 the analyzer itself
    crashed (or usage error) — CI failures are diagnosable at a glance."""
    import subprocess

    picked = [flag for flag in ("proto", "shard", "rep", "iso", "mem")
              if getattr(args, flag, False)]
    if len(picked) > 1:
        print(f"fedml_tpu lint: --{picked[0]} and --{picked[1]} are "
              "different suites — pick one (or run all six like "
              "tools/lint_smoke.sh does)")
        return 2
    suite = ("graftproto" if getattr(args, "proto", False)
             else "graftshard" if getattr(args, "shard", False)
             else "graftrep" if getattr(args, "rep", False)
             else "graftiso" if getattr(args, "iso", False)
             else "graftmem" if getattr(args, "mem", False)
             else "graftlint")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo_root, "tools", suite)):
        print(f"fedml_tpu lint: tools/{suite} not found next to the "
              f"package (looked in {repo_root}) — run from a source checkout")
        return 2
    # absolutize user paths: the subprocess runs with cwd=repo_root, which
    # would otherwise re-resolve relative paths against the wrong directory
    paths = [os.path.abspath(p) for p in args.paths] or ["fedml_tpu"]
    cmd = [sys.executable, "-m", f"tools.{suite}", *paths]
    if args.format != "text":
        cmd += ["--format", args.format]
    if args.runtime:
        if suite == "graftproto":
            print("fedml_tpu lint: --runtime is a graftlint/graftshard "
                  "pass; it does not combine with --proto")
            return 2
        if suite == "graftrep":
            print("fedml_tpu lint: --runtime is a graftlint/graftshard "
                  "pass; graftrep's jax-backed pass is --equiv")
            return 2
        if suite == "graftiso":
            print("fedml_tpu lint: --runtime is a graftlint/graftshard "
                  "pass; graftiso's runtime witness is the swarm/chaos "
                  "thread-leak assertion (fedml_tpu swarm / chaos)")
            return 2
        if suite == "graftmem":
            print("fedml_tpu lint: --runtime is a graftlint/graftshard "
                  "pass; graftmem's runtime witness is the RSS-slope soak "
                  "(fedml_tpu swarm --leak_check)")
            return 2
        cmd.append("--runtime")
    if getattr(args, "equiv", False):
        if suite != "graftrep":
            print("fedml_tpu lint: --equiv is the graftrep round-"
                  "equivalence pass — add --rep")
            return 2
        cmd.append("--equiv")
    if getattr(args, "model", ""):
        if suite != "graftshard":
            print("fedml_tpu lint: --model is the graftshard HBM "
                  "estimator — add --shard")
            return 2
        cmd += ["--model", args.model]
        if getattr(args, "mesh", ""):
            cmd += ["--mesh", args.mesh]
    elif getattr(args, "mesh", ""):
        print("fedml_tpu lint: --mesh needs --shard --model")
        return 2
    for flag, value in (("--check-rules", getattr(args, "check_rules", "")),
                        ("--check-state-rules",
                         getattr(args, "check_state_rules", ""))):
        if value:
            if suite != "graftshard":
                print(f"fedml_tpu lint: {flag} is a graftshard rule-set "
                      "check — add --shard")
                return 2
            cmd += [flag, value]
    return subprocess.call(cmd, cwd=repo_root)


def cmd_chaos(args) -> int:
    """Chaos soak harness (fedml_tpu/chaos.py): run a loopback cross-silo
    federation under a seeded fault matrix (visible loss + duplication +
    payload corruption + mid-run self-SIGTERM), restart it with
    ``--resume auto``, and verify the recovered run's final global params
    are bitwise-equal to a fault-free reference run with no contribution
    counted twice. CI entry: ``tools/chaos_smoke.sh``."""
    import logging as _logging

    from .chaos import main as chaos_main

    _logging.basicConfig(level=_logging.INFO)
    return chaos_main(args)


def cmd_swarm(args) -> int:
    """Client-swarm traffic soak (fedml_tpu/traffic/swarm.py): drive the
    async cross-silo server (``aggregation_mode=async``, FedBuff-style
    buffered aggregation + admission control) with thousands of concurrent
    simulated devices — seeded think-time/dropout processes over loopback
    or real multiprocess gRPC — and report p99 dispatch→ready latency plus
    the traffic.* backpressure counters as JSON. CI entry:
    ``tools/swarm_smoke.sh``."""
    import logging as _logging

    from .traffic.swarm import run_device_worker, run_swarm

    _logging.basicConfig(
        level=_logging.WARNING if args.worker else _logging.INFO)
    if args.worker:
        return run_device_worker(args)
    return run_swarm(args)


def cmd_multihost(args) -> int:
    """Spawn N coordinated worker processes (analog: mpirun -np N).

    reference: the MPI launch plane; here jax.distributed under one mesh —
    see ``parallel/multihost.py``.
    """
    import sys as _sys

    from .parallel.multihost import spawn

    try:
        results = spawn(
            [args.script, *args.script_args],
            n_processes=args.np, local_device_count=args.local_devices,
            timeout_s=args.timeout,
        )
    except (RuntimeError, TimeoutError) as e:
        print(e)
        return 1
    for r in results:
        _sys.stdout.write(r.stdout)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fedml_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version", help="print version")
    sub.add_parser("env", help="environment report")
    sub.add_parser("status", help="tracked run status")

    p_logs = sub.add_parser("logs", help="show run event logs")
    p_logs.add_argument("--file", default="", help="specific event file")
    p_logs.add_argument("-n", type=int, default=20, help="tail lines")

    p_top = sub.add_parser(
        "top", help="phase-latency breakdown of a tracked run"
    )
    p_top.add_argument("file", nargs="?", default="",
                       help="run JSONL event file (default: newest run)")

    p_trace = sub.add_parser(
        "trace",
        help="merge per-process span files into one clock-aligned trace: "
        "round critical path, segment shares, straggler attribution, "
        "Perfetto export (docs/tracing.md)",
    )
    p_trace.add_argument("dir", nargs="?", default="",
                         help="trace dir holding run_*.jsonl sinks + "
                         "flight_*.json post-mortems "
                         "(default: .fedml_tpu_runs)")
    p_trace.add_argument("--run_id", default="",
                         help="only merge this run's files")
    p_trace.add_argument("--round", type=int, default=-1,
                         help="print the critical path of this round "
                         "(default: the last traced round)")
    p_trace.add_argument("--chrome", default="", metavar="OUT.json",
                         help="also write Chrome trace-event JSON "
                         "(Perfetto / chrome://tracing)")
    p_trace.add_argument("--top", type=int, default=5,
                         help="straggler top-k")
    p_trace.add_argument("--json", action="store_true",
                         help="machine-readable output")

    p_build = sub.add_parser("build", help="package a training dir")
    p_build.add_argument("--type", "-t", choices=("client", "server"),
                         default="client")
    p_build.add_argument("--source_folder", "-sf", required=True)
    p_build.add_argument("--entry_point", "-ep", default="")
    p_build.add_argument("--output", "-o", default="")

    p_login = sub.add_parser("login", help="bind this host as an edge device")
    p_login.add_argument("account_id")
    p_login.add_argument("--role", "-r", choices=("client", "server"),
                         default="client")
    p_login.add_argument("--state_dir", default=".fedml_tpu_agent")

    p_logout = sub.add_parser("logout", help="unbind this host")
    p_logout.add_argument("--state_dir", default=".fedml_tpu_agent")

    p_launch = sub.add_parser(
        "launch", help="submit a package to a job queue",
        usage="%(prog)s [--jobs_dir DIR] package [run_args ...]",
    )
    p_launch.add_argument("--jobs_dir", "-j", default=".fedml_tpu_jobs")
    p_launch.add_argument("package")
    # REMAINDER: everything after the package — flags included — goes to the
    # job's entry point verbatim (launch options must precede the package):
    #   fedml_tpu launch -j /queue pkg.zip --lr 0.1
    p_launch.add_argument("run_args", nargs=argparse.REMAINDER)

    p_agent = sub.add_parser("agent", help="run the edge/server job daemon")
    p_agent.add_argument("--role", choices=("client", "server"), default="")
    p_agent.add_argument("--jobs_dir", "-j", default=".fedml_tpu_jobs")
    p_agent.add_argument("--work_dir", "-w", default=".fedml_tpu_work")
    p_agent.add_argument("--state_dir", default=".fedml_tpu_agent")
    p_agent.add_argument("--once", action="store_true",
                         help="claim and run at most one job, then exit")
    p_agent.add_argument("--max_jobs", type=int, default=None)

    p_cache = sub.add_parser(
        "cache", help="inspect/clear the persistent XLA compilation cache"
    )
    p_cache.add_argument("--dir", default="",
                         help="cache dir (default: $JAX_COMPILATION_CACHE_DIR,"
                         " $BENCH_COMPILE_CACHE_DIR, or the bench default "
                         "/tmp/fedml_tpu_bench_jax_cache)")
    p_cache.add_argument("--clear", action="store_true",
                         help="delete every cache entry")
    p_cache.add_argument("--run_file", default="",
                         help="run JSONL to read hit/miss telemetry from "
                         "(default: newest run)")

    p_lint = sub.add_parser(
        "lint",
        help="run static analysis over the tree (graftlint; --proto for "
        "the comm-plane protocol suite, --shard for the TPU execution "
        "plane's sharding/HBM suite, --rep for the determinism & "
        "round-equivalence suite)",
    )
    p_lint.add_argument("paths", nargs="*", default=[],
                        help="files/dirs to lint (default: fedml_tpu)")
    p_lint.add_argument("--format", choices=("text", "json"), default="text")
    p_lint.add_argument("--proto", action="store_true",
                        help="run graftproto (message-flow graph, FSM "
                        "replay/termination, delivery invariants, lock "
                        "order) instead of graftlint")
    p_lint.add_argument("--shard", action="store_true",
                        help="run graftshard (partition-rule coverage, "
                        "spec validity, implicit-reshard/host-transfer "
                        "detection, static HBM budgets) instead of "
                        "graftlint")
    p_lint.add_argument("--iso", action="store_true",
                        help="run graftiso (tools/graftiso: state-"
                        "ownership, tenant-isolation & thread-lifecycle "
                        "verification of the serving plane) instead of "
                        "graftlint")
    p_lint.add_argument("--mem", action="store_true",
                        help="run graftmem (tools/graftmem: unbounded-"
                        "state & retention verification of the serving "
                        "plane — bounded containers, drained parking, "
                        "released payloads) instead of graftlint")
    p_lint.add_argument("--rep", action="store_true",
                        help="run graftrep (PRNG-key discipline, seed "
                        "provenance, unordered accumulation, dtype drift, "
                        "run-identity leaks) instead of graftlint")
    p_lint.add_argument("--equiv", action="store_true",
                        help="(--rep) also prove fused/unfused round "
                        "structural equivalence: _train_round vs "
                        "build_round_core under jax.make_jaxpr for "
                        "FedAvg/FedOpt/SCAFFOLD")
    p_lint.add_argument("--runtime", action="store_true",
                        help="also run the suite's runtime pass: graftlint "
                        "traces the round engine under jax.make_jaxpr, "
                        "graftshard diffs declared vs inferred shardings "
                        "over a forced multi-device CPU mesh")
    p_lint.add_argument("--model", default="",
                        help="(--shard) run the S005 HBM-budget estimator "
                        "for this model registry entry (e.g. 7b)")
    p_lint.add_argument("--mesh", default="",
                        help="(--shard) mesh rows for --model, e.g. "
                        "'4x4' or 'v5e:2x4,v5p:2x2x2'")
    p_lint.add_argument("--check-rules", default="", dest="check_rules",
                        help="(--shard) validate a --mesh_partition_rules "
                        "string (S001 catch-all + S002 axis validity)")
    p_lint.add_argument("--check-state-rules", default="",
                        dest="check_state_rules",
                        help="(--shard) validate a --mesh_state_rules "
                        "string the same way")

    p_chaos = sub.add_parser(
        "chaos",
        help="chaos soak: faults + kill/restart must reproduce the "
        "fault-free run bitwise",
    )
    p_chaos.add_argument("--clients", type=int, default=2)
    p_chaos.add_argument("--rounds", type=int, default=4)
    p_chaos.add_argument("--epochs", type=int, default=1)
    p_chaos.add_argument("--seed", type=int, default=7)
    p_chaos.add_argument("--loss", type=float, default=0.1,
                         help="visible (retryable) per-message loss prob")
    p_chaos.add_argument("--duplicate", type=float, default=0.2,
                         help="wire-duplication probability")
    p_chaos.add_argument("--corrupt", type=float, default=0.2,
                         help="payload-corruption probability")
    p_chaos.add_argument("--kill-round", type=int, default=1, metavar="R",
                         help="self-SIGTERM once the ledger commits round R "
                         "(-1 disables the kill)")
    p_chaos.add_argument("--compression", default="",
                         choices=("", "topk", "quantize", "qsgd"),
                         help="run BOTH legs with this C2S update "
                         "compression: dedup + digests must survive delta "
                         "frames bitwise (stateless schemes only — eftopk's "
                         "client residual does not survive a restart)")
    p_chaos.add_argument("--compression_ratio", type=float, default=0.1,
                         help="top-k fraction for --compression topk")
    p_chaos.add_argument("--checkpoint_rounds", type=int, default=1)
    p_chaos.add_argument("--workdir", default="",
                         help="scratch dir (default: a fresh temp dir)")
    p_chaos.add_argument("--timeout", type=float, default=240.0,
                         help="per-leg subprocess timeout (seconds)")
    p_chaos.add_argument("--transport", choices=("loopback", "grpc"),
                         default="loopback",
                         help="faulty-leg transport: loopback threads, or "
                         "REAL multiprocess gRPC clients (the reference "
                         "leg stays loopback — parity must hold across "
                         "transports)")
    p_chaos.add_argument("--kill-phase", dest="kill_phase", default="",
                         choices=("", "pre_fold", "mid_fold",
                                  "post_commit"),
                         help="crash-failover soak: SIGKILL the server "
                         "process (no drain) at this protocol phase of "
                         "--kill-round, restart it with --resume auto, and "
                         "require bitwise parity with the fault-free run; "
                         "with --transport grpc the client processes "
                         "SURVIVE the kill and resync onto the restarted "
                         "server (heartbeat miss -> c2s_resync -> replay)")
    p_chaos.add_argument("--edges", type=int, default=0, metavar="E",
                         help="hierarchical edge-aggregation tier for the "
                         "FAULTY leg: E edge aggregators between clients "
                         "and root (the reference leg stays flat — the "
                         "bitwise verdict proves 2-tier ≡ flat). Loopback "
                         "transport only")
    p_chaos.add_argument("--kill-edge", dest="kill_edge", default="",
                         choices=("", "pre_fold", "mid_fold",
                                  "post_commit"),
                         help="fail-stop the FIRST edge aggregator at this "
                         "protocol phase (first hit): its clients must "
                         "detect the death, re-home to a sibling edge (or "
                         "the root), replay their cached updates, and the "
                         "run must still finish bitwise-equal with "
                         "exactly-once contributions. Needs --edges >= 2")
    p_chaos.add_argument("--edge-partition", dest="edge_partition",
                         default="", metavar="START:DURATION",
                         help="cut the FIRST edge off from the root for "
                         "the window (seconds since leg start) — the edge "
                         "rides it out on its resync FSM and re-ships its "
                         "cached summary; dedup + the committed-round "
                         "guard keep contributions exactly-once")
    p_chaos.add_argument("--partition", default="",
                         metavar="START:DURATION",
                         help="cut the server off from every client for "
                         "the window (seconds from world start, both "
                         "directions visible-fail); the at-least-once "
                         "layer must absorb it bitwise")
    p_chaos.add_argument("--heartbeat_s", type=float, default=0.0,
                         help="client heartbeat interval for the soak "
                         "(0 = auto: on for kill legs, off otherwise)")
    p_chaos.add_argument("--trace_dir", default="",
                         help="distributed-tracing span/flight dir for the "
                         "faulty legs (kill-phase legs default to "
                         "WORKDIR/trace and verify the pre-SIGKILL "
                         "post-mortem + orphan-free merge)")
    # internal: run ONE chaos leg in this process (the orchestrator's child)
    p_chaos.add_argument("--worker", action="store_true",
                         help=argparse.SUPPRESS)
    # internal: the crash-failover flow's server-only worker — the
    # orchestrator owns the client processes so they survive the kill
    p_chaos.add_argument("--server-only", dest="server_only",
                         action="store_true", help=argparse.SUPPRESS)
    p_chaos.add_argument("--out", default="", help=argparse.SUPPRESS)
    p_chaos.add_argument("--checkpoint_dir", default="",
                         help=argparse.SUPPRESS)
    # internal: run ONE real gRPC client in this process (spawned by the
    # chaos worker's ProcSpawner for the multiprocess transport leg)
    p_chaos.add_argument("--client", action="store_true",
                         help=argparse.SUPPRESS)
    p_chaos.add_argument("--client_rank", type=int, default=0,
                         help=argparse.SUPPRESS)
    p_chaos.add_argument("--port", type=int, default=0,
                         help=argparse.SUPPRESS)

    p_swarm = sub.add_parser(
        "swarm",
        help="client-swarm traffic soak against the async (FedBuff-style) "
        "server: seeded arrival/dropout, admission control, p99 "
        "dispatch→ready report",
    )
    p_swarm.add_argument("--clients", type=int, default=200,
                         help="concurrent simulated devices")
    p_swarm.add_argument("--steps", type=int, default=20,
                         help="server steps (model versions) to run")
    p_swarm.add_argument("--buffer", type=int, default=0,
                         help="async buffer size K (0 = min(10, clients))")
    p_swarm.add_argument("--staleness_alpha", type=float, default=0.5,
                         help="staleness decay exponent (1+s)^-alpha")
    p_swarm.add_argument("--max_staleness", type=int, default=0,
                         help="drop updates staler than this (0 = never)")
    p_swarm.add_argument("--flush_s", type=float, default=5.0,
                         help="flush a partial buffer after this stall")
    p_swarm.add_argument("--admit_rate", type=float, default=0.0,
                         help="token-bucket admission rate, updates/s "
                         "(0 = unlimited)")
    p_swarm.add_argument("--admit_burst", type=int, default=0,
                         help="token-bucket burst (0 = 2x buffer)")
    p_swarm.add_argument("--queue_limit", type=int, default=0,
                         help="bounded fold-queue depth (0 = 4x buffer)")
    p_swarm.add_argument("--think_s", type=float, default=0.2,
                         help="mean device think time, seconds "
                         "(exponential — Poisson arrivals at the server)")
    p_swarm.add_argument("--dropout", type=float, default=0.0,
                         help="per-dispatch device dropout probability")
    p_swarm.add_argument("--seed", type=int, default=7)
    p_swarm.add_argument("--tiers", type=int, default=1,
                         help="aggregation tiers: 2 inserts an edge-"
                         "aggregator tier between devices and root "
                         "(~1 edge per 100 devices unless --edges is "
                         "given); root then folds E pre-folded summaries "
                         "per bump instead of N raw updates")
    p_swarm.add_argument("--edges", type=int, default=0, metavar="E",
                         help="explicit edge-aggregator count for the "
                         "tiered soak (implies --tiers 2)")
    p_swarm.add_argument("--backend", choices=("loopback", "grpc"),
                         default="loopback")
    p_swarm.add_argument("--procs", type=int, default=2,
                         help="device-host processes (grpc backend)")
    p_swarm.add_argument("--ranks_per_port", type=int, default=0,
                         help="gRPC rank→port multiplexing: N device ranks "
                         "share one port/server (0 = auto: one port per "
                         "device-host process; 1 = legacy port-per-rank)")
    p_swarm.add_argument("--port", type=int, default=18950,
                         help="gRPC base port")
    p_swarm.add_argument("--s2c_delta", choices=("auto", "off"),
                         default="off",
                         help="S2C delta plane for the soak: auto makes "
                         "devices delta-capable (ACK + base store + frame "
                         "decode) so dispatches ship delta frames; off "
                         "keeps the legacy full-frame soak")
    p_swarm.add_argument("--wire_path", choices=("host", "device", "auto"),
                         default="auto",
                         help="delta codec implementation for the soak: "
                         "device forces the jit'd kernels (byte-identical "
                         "frames), host the numpy reference, auto picks "
                         "device only on a real accelerator")
    p_swarm.add_argument("--timeout", type=float, default=300.0)
    p_swarm.add_argument("--run_id", default="swarm")
    p_swarm.add_argument("--trace", action="store_true",
                         help="distributed tracing for the soak: every "
                         "process records causal spans, and the report "
                         "gains trace_spans / critical_path_segments plus "
                         "the traced dispatch→ready sum (reconciles with "
                         "the traffic.dispatch_ready_s histogram)")
    p_swarm.add_argument("--trace_sample", type=float, default=1.0,
                         metavar="P",
                         help="fraction of rounds traced (deterministic "
                         "per-round hash; 1.0 = every round)")
    p_swarm.add_argument("--trace_dir", default="",
                         help="span/flight dir (default: "
                         ".fedml_tpu_runs/trace_RUN_ID)")
    p_swarm.add_argument("--leak_check", action="store_true",
                         help="memory-leak witness (graftmem's runtime "
                         "half): sample VmRSS across the soak, fail on a "
                         "positive steady-state slope, and report the "
                         "mem.* per-container occupancy gauges")
    p_swarm.add_argument("--leak_interval", type=float, default=0.2,
                         metavar="S",
                         help="RSS sampling period in seconds")
    p_swarm.add_argument("--leak_slope_mb_s", type=float, default=1.0,
                         metavar="MB",
                         help="max tolerated steady-state RSS slope "
                         "(MB/s over the soak's second half)")
    # internal: one gRPC device-host process (the orchestrator's child)
    p_swarm.add_argument("--worker", action="store_true",
                         help=argparse.SUPPRESS)
    p_swarm.add_argument("--rank_base", type=int, default=1,
                         help=argparse.SUPPRESS)
    p_swarm.add_argument("--count", type=int, default=0,
                         help=argparse.SUPPRESS)

    p_mh = sub.add_parser(
        "multihost", help="spawn N coordinated worker processes",
        usage="%(prog)s [-np N] [--local_devices D] script [script_args ...]",
    )
    p_mh.add_argument("-np", type=int, default=2,
                      help="number of worker processes")
    p_mh.add_argument("--local_devices", type=int, default=1,
                      help="virtual CPU devices per worker (emulation runs)")
    p_mh.add_argument("--timeout", type=float, default=600.0)
    p_mh.add_argument("script")
    p_mh.add_argument("script_args", nargs=argparse.REMAINDER)

    args = parser.parse_args(argv)
    handlers = {
        "version": cmd_version,
        "env": cmd_env,
        "status": cmd_status,
        "logs": cmd_logs,
        "top": cmd_top,
        "trace": cmd_trace,
        "build": cmd_build,
        "login": cmd_login,
        "logout": cmd_logout,
        "launch": cmd_launch,
        "agent": cmd_agent,
        "cache": cmd_cache,
        "lint": cmd_lint,
        "chaos": cmd_chaos,
        "swarm": cmd_swarm,
        "multihost": cmd_multihost,
    }
    if args.command is None:
        parser.print_help()
        return 1
    return handlers[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
