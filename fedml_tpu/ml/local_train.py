"""The functional core of local client training.

Replaces the reference's per-client Python epoch/batch loops
(``ml/trainer/my_model_trainer_classification.py:15-100``: for epoch → for
batch → loss.backward → optimizer.step) with one pure, jit-compatible
function per model:

    local_train(global_params, x, y, n, rng) -> (new_params, metrics)

- batches are a static grid over the packed capacity; a per-epoch
  ``jax.random.permutation`` provides shuffling; padding is masked out
- epochs × batches run under ``lax.scan`` (one XLA while loop, no unrolling)
- the whole function ``vmap``s over a cohort axis — a round of K clients is a
  single fused device program instead of K sequential torch loops
- FedProx's proximal term (reference ``simulation/mpi/fedprox``) is a flag

This is the kernel both simulators (sp/mesh) and cross-silo trainers share.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from .losses import get_loss_fn
from .optimizer import create_client_optimizer

PyTree = Any
LocalTrainFn = Callable[..., Tuple[PyTree, Dict[str, jnp.ndarray]]]


def make_local_train_fn(
    bundle,
    args,
    cap: int,
    scaffold: bool = False,
) -> LocalTrainFn:
    """Build the pure local-training function for one client shard.

    ``cap`` is the packed per-client capacity; batch grid = cap // batch_size
    (the data layer pads cap to a batch multiple). With ``scaffold=True`` the
    signature grows control variates: ``local_train(params, x, y, n, rng,
    c_global, c_local)`` (SCAFFOLD: stochastic controlled averaging).
    """
    batch_size = int(args.batch_size)
    epochs = int(args.epochs)
    num_batches = max(cap // batch_size, 1)
    loss_fn_raw = get_loss_fn(bundle.task)
    opt = create_client_optimizer(args)
    fedprox_mu = (
        float(getattr(args, "fedprox_mu", 0.0))
        if str(getattr(args, "federated_optimizer", "")).lower() == "fedprox"
        else 0.0
    )
    # bf16 compute (MXU-native) with fp32 master weights: forward/backward in
    # bfloat16, gradients cast back for the fp32 optimizer update. Default
    # fp32 keeps exactness for the parity tests; bench configs turn this on.
    bf16 = str(getattr(args, "train_dtype", "fp32")).lower() in (
        "bf16", "bfloat16"
    )

    def loss_fn(params, bx, by, bmask, rng, global_params):
        if bf16:
            params = jax.tree.map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p,
                params,
            )
            if jnp.issubdtype(bx.dtype, jnp.floating):
                bx = bx.astype(jnp.bfloat16)
        logits = bundle.apply(params, bx, train=True, rngs={"dropout": rng})
        logits = logits.astype(jnp.float32)
        loss, metrics = loss_fn_raw(logits, by, bmask)
        if fedprox_mu > 0.0:
            sq = sum(
                jnp.sum((p - g) ** 2)
                for p, g in zip(jax.tree.leaves(params), jax.tree.leaves(global_params))
            )
            loss = loss + 0.5 * fedprox_mu * sq
        return loss, metrics

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def local_train(global_params, x, y, n, rng, c_global=None, c_local=None):
        """x [cap, ...], y [cap, ...], n = true sample count (scalar)."""
        opt_state = opt.init(global_params)
        nf = n.astype(jnp.float32)

        def epoch_body(carry, erng):
            params, opt_state = carry
            # key discipline (graftrep D001): the epoch key fans out into a
            # shuffle key and a per-batch base BEFORE anything samples —
            # a consumed key is never reused as a fold_in base
            perm_rng, step_rng = jax.random.split(erng)
            perm = jax.random.permutation(perm_rng, cap)

            def batch_body(carry, i):
                params, opt_state = carry
                idx = jax.lax.dynamic_slice(perm, (i * batch_size,), (batch_size,))
                bx = jnp.take(x, idx, axis=0)
                by = jnp.take(y, idx, axis=0)
                bmask = (idx < n).astype(jnp.float32)
                brng = jax.random.fold_in(step_rng, i)
                (loss, _), grads = grad_fn(
                    params, bx, by, bmask, brng, global_params
                )
                if scaffold:
                    grads = jax.tree.map(
                        lambda g, cg, cl: g + cg - cl, grads, c_global, c_local
                    )
                # guard fully-padded batches: freeze params there
                has_data = (bmask.sum() > 0).astype(jnp.float32)
                grads = jax.tree.map(lambda g: g * has_data, grads)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                batch_body, (params, opt_state), jnp.arange(num_batches)
            )
            return (params, opt_state), losses.mean()

        erngs = jax.random.split(rng, epochs)
        (params, opt_state), epoch_losses = jax.lax.scan(
            epoch_body, (global_params, opt_state), erngs
        )
        # actual optimizer steps taken on real data (for FedNova tau)
        steps_per_epoch = jnp.ceil(nf / batch_size)
        tau = jnp.maximum(steps_per_epoch * epochs, 1.0)
        metrics = {"train_loss": epoch_losses.mean(), "num_samples": nf, "tau": tau}
        if scaffold:
            # c_local' = c_local - c_global + (global - local)/(tau * lr)
            lr = float(getattr(args, "learning_rate", 0.03))
            new_c = jax.tree.map(
                lambda cl, cg, gp, p: cl - cg + (gp - p) / (tau * lr),
                c_local, c_global, global_params, params,
            )
            return params, metrics, new_c
        return params, metrics

    return local_train


def make_grad_fn(bundle, args, cap: int):
    """One full-batch gradient over a client shard (FedSGD: the reference's
    gradient-level averaging, ``simulation/sp/fedsgd/fedsgd_api.py``)."""
    loss_fn_raw = get_loss_fn(bundle.task)

    def loss_fn(params, x, y, mask, rng):
        logits = bundle.apply(params, x, train=True, rngs={"dropout": rng})
        loss, _ = loss_fn_raw(logits, y, mask)
        return loss

    grad = jax.value_and_grad(loss_fn)

    def client_grad(global_params, x, y, n, rng):
        mask = (jnp.arange(cap) < n).astype(jnp.float32)
        loss, g = grad(global_params, x, y, mask, rng)
        return g, {
            "train_loss": loss,
            "num_samples": n.astype(jnp.float32),
        }

    return client_grad
