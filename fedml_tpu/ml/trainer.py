"""Concrete model trainers + factory.

reference: ``python/fedml/ml/trainer/`` — per-task trainers
(my_model_trainer_classification.py, *_nwp.py, *_tag_prediction.py) and
``trainer_creator.py:6-13``. One JAX trainer covers all tasks (the task enters
through the loss fn); it exposes both the imperative ``train`` contract (for
message-driven runtimes) and the pure ``local_train_fn`` (for SPMD runtimes).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..core.alg_frame import ClientTrainer
from .local_train import make_local_train_fn

PyTree = Any


class ModelTrainer(ClientTrainer):
    """Default trainer: jit'd masked mini-batch SGD over the packed shard."""

    def __init__(self, model, args=None):
        super().__init__(model, args)
        self._jitted = {}

    def _get_fn(self, cap: int):
        if cap not in self._jitted:
            self._jitted[cap] = jax.jit(
                make_local_train_fn(self.model, self.args, cap)
            )
        return self._jitted[cap]

    def train(self, train_data, device, args) -> Dict[str, Any]:
        """train_data = (x [cap, ...], y [cap, ...], n) for this client."""
        x, y, n = train_data
        cap = int(x.shape[0])
        rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0))),
            int(getattr(args, "round_idx", 0)) * 100003 + self.id,
        )
        fn = self._get_fn(cap)
        params, metrics = fn(
            self.model_params, jnp.asarray(x), jnp.asarray(y), jnp.asarray(n), rng
        )
        self.model_params = params
        return {k: float(v) for k, v in metrics.items()}

def create_model_trainer(model, args) -> ClientTrainer:
    """reference: trainer_creator.py:6-13 — dispatch on dataset/task; the
    single JAX trainer already routes by ``model.task``. The Cheetah
    transformer bundle routes to the FedLLM trainer, whose local steps run
    sharded over the silo's mesh."""
    from ..models.transformer_lm import TransformerBundle

    if isinstance(model, TransformerBundle):
        from ..cross_silo.fedllm import CheetahClientTrainer

        return CheetahClientTrainer(model, args)
    return ModelTrainer(model, args)
