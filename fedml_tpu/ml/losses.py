"""Per-task losses and metrics, all mask-aware.

The reference splits these across per-task trainers
(``python/fedml/ml/trainer/my_model_trainer_classification.py`` CE loss,
``my_model_trainer_nwp.py`` next-word CE ignoring pad id 0,
``my_model_trainer_tag_prediction.py`` multilabel BCE). Here they are pure
functions over logits so one jit'd trainer serves every task; masks carry the
padded-cohort semantics (SURVEY.md §7 "Dynamic shapes vs jit").
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax

Metrics = Dict[str, jnp.ndarray]

PAD_TOKEN = 0  # nwp pad id (reference masks token 0 in NWP accuracy)


def classification_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Masked softmax cross-entropy. logits [B, C], y [B], mask [B]."""
    per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    loss = (per * sample_mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * sample_mask).sum()
    return loss, {"loss_sum": per * sample_mask, "correct": correct, "count": sample_mask.sum()}


def nwp_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Next-word CE. logits [B, L, V], y [B, L]; pad targets (id 0) ignored."""
    tok_mask = (y != PAD_TOKEN).astype(jnp.float32) * sample_mask[:, None]
    per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    denom = jnp.maximum(tok_mask.sum(), 1.0)
    loss = (per * tok_mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * tok_mask).sum()
    return loss, {"loss_sum": per * tok_mask, "correct": correct, "count": tok_mask.sum()}


def tagpred_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Multilabel sigmoid BCE. logits [B, C], y [B, C] in {0,1}."""
    per = optax.sigmoid_binary_cross_entropy(logits, y).mean(-1)
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    loss = (per * sample_mask).sum() / denom
    pred = (logits > 0).astype(jnp.float32)
    tp = (pred * y).sum(-1)
    precision = tp / jnp.maximum(pred.sum(-1), 1.0)
    recall = tp / jnp.maximum(y.sum(-1), 1.0)
    correct = (2 * precision * recall / jnp.maximum(precision + recall, 1e-8)
               * sample_mask).sum()  # summed F1, "correct" for uniform metrics
    return loss, {"loss_sum": per * sample_mask, "correct": correct, "count": sample_mask.sum()}


def segmentation_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Class-balanced per-pixel CE. logits [B, H, W, C], y [B, H, W] ints.

    reference: ``simulation/mpi/fedseg/utils.py`` SegmentationLosses (CE /
    focal modes with class weighting) + pixel-accuracy Evaluator; mIoU is
    computed by the FedSeg eval pass. Weighting is inverse batch frequency:
    background dominates segmentation labels, and plain CE converges to the
    all-background predictor (high pixel acc, mIoU ≈ bg-IoU/C); weighting
    keeps every present class in the gradient.
    """
    c = logits.shape[-1]
    per_px = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    px_mask = sample_mask[:, None, None] * jnp.ones_like(per_px)
    counts = (jax.nn.one_hot(y, c) * px_mask[..., None]).sum((0, 1, 2))
    present = (counts > 0).astype(jnp.float32)
    inv = jnp.where(counts > 0, 1.0 / jnp.maximum(counts, 1.0), 0.0)
    class_w = inv / jnp.maximum(
        (inv * present).sum(), 1e-12
    ) * jnp.maximum(present.sum(), 1.0)  # mean weight over present classes = 1
    w_px = class_w[y]
    denom = jnp.maximum((w_px * px_mask).sum(), 1.0)
    loss = (per_px * w_px * px_mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * px_mask).sum()
    return loss, {
        "loss_sum": (per_px * px_mask).sum((1, 2)),
        "correct": correct,
        "count": px_mask.sum(),
    }


def regression_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """MSE. logits [B, 1] (or [B]), y [B] float targets.

    reference: app/fedgraphnn/moleculenet_graph_reg trainers (MSE/RMSE).
    "correct" counts predictions within 0.5 of the target so the uniform
    accuracy plumbing still reads as a hit-rate.
    """
    pred = logits.reshape(y.shape)
    per = (pred - y) ** 2
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    loss = (per * sample_mask).sum() / denom
    correct = ((jnp.abs(pred - y) < 0.5) * sample_mask).sum()
    return loss, {"loss_sum": per * sample_mask, "correct": correct,
                  "count": sample_mask.sum()}


def node_clf_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Per-node CE. logits [B, N, C], y [B, N] int labels, padding = -1.

    reference: app/fedgraphnn/ego_networks_node_clf trainers (masked CE over
    ego-network nodes).
    """
    node_mask = (y >= 0).astype(jnp.float32) * sample_mask[:, None]
    y_safe = jnp.maximum(y, 0)
    per = optax.softmax_cross_entropy_with_integer_labels(logits, y_safe)
    denom = jnp.maximum(node_mask.sum(), 1.0)
    loss = (per * node_mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y_safe) * node_mask).sum()
    return loss, {"loss_sum": per * node_mask, "correct": correct,
                  "count": node_mask.sum()}


def link_pred_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Edge-reconstruction BCE. logits [B, N, N] pair scores; y [B, N, N+1]
    = full target adjacency ++ node-mask column (data/graphs.py layout).

    reference: app/fedgraphnn/ego_networks_link_pred trainers (BCE over
    candidate edges). Positives are up-weighted by the observed sparsity so
    the all-zeros predictor is never a minimum.
    """
    n = logits.shape[-1]
    adj = y[..., :n]
    node_mask = y[..., -1]
    pair = node_mask[:, :, None] * node_mask[:, None, :]
    pair = pair * (1.0 - jnp.eye(n)[None])  # self-pairs carry no signal
    pair = pair * sample_mask[:, None, None]
    pos_frac = (adj * pair).sum() / jnp.maximum(pair.sum(), 1.0)
    w = jnp.where(adj > 0, 1.0 / jnp.maximum(pos_frac, 1e-3), 1.0)
    per = optax.sigmoid_binary_cross_entropy(logits, adj) * w
    denom = jnp.maximum((pair * w).sum(), 1.0)
    loss = (per * pair).sum() / denom
    correct = (((logits > 0) == (adj > 0)) * pair).sum()
    return loss, {"loss_sum": (per * pair).sum((1, 2)), "correct": correct,
                  "count": pair.sum()}


def span_extraction_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Start/end pointer CE. logits [B, L, 2], y [B, 2] = (start, end).

    reference: app/fednlp/span_extraction trainers (SQuAD-style QA heads).
    "correct" counts exact-match spans.
    """
    start_logits, end_logits = logits[..., 0], logits[..., 1]
    per = (optax.softmax_cross_entropy_with_integer_labels(
               start_logits, y[:, 0]) +
           optax.softmax_cross_entropy_with_integer_labels(
               end_logits, y[:, 1]))
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    loss = (per * sample_mask).sum() / denom
    hit = ((jnp.argmax(start_logits, -1) == y[:, 0]) &
           (jnp.argmax(end_logits, -1) == y[:, 1]))
    correct = (hit * sample_mask).sum()
    return loss, {"loss_sum": per * sample_mask, "correct": correct,
                  "count": sample_mask.sum()}


def detection_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Dense anchor-free detection. logits [B, H, W, C+2] (class heatmap ++
    size); y [B, H, W, C+3] (one-hot heatmap ++ size ++ center mask).

    reference: app/fedcv/object_detection (YOLOv5 obj/cls/box terms) —
    re-shaped to the CenterNet-style dense target (models/detection.py):
    BCE on the heatmap everywhere, L1 on sizes at real centers. "correct"
    counts centers whose argmax class is right.
    """
    c = logits.shape[-1] - 2
    cls_logits, size_pred = logits[..., :c], logits[..., c:]
    heat, size_t, center = y[..., :c], y[..., c:c + 2], y[..., -1]
    sm = sample_mask[:, None, None]
    # heatmap: per-cell BCE, positives up-weighted (centers are rare)
    w = jnp.where(heat > 0, 20.0, 1.0)
    bce = (optax.sigmoid_binary_cross_entropy(cls_logits, heat) * w).sum(-1)
    heat_denom = jnp.maximum((jnp.ones_like(bce) * sm).sum(), 1.0)
    heat_loss = (bce * sm).sum() / heat_denom
    # sizes: L1 at centers only
    l1 = jnp.abs(size_pred - size_t).sum(-1) * center
    size_loss = (l1 * sm).sum() / jnp.maximum((center * sm).sum(), 1.0)
    loss = heat_loss + 0.1 * size_loss
    hit = (jnp.argmax(cls_logits, -1) == jnp.argmax(heat, -1)) * center
    correct = (hit * sm).sum()
    # evaluate() divides Σloss_sum and Σcorrect by ONE Σcount — unit here is
    # the center: count is the raw center total (evaluate clamps the final
    # denominator, so all-padding batches add nothing), and loss_sum is each
    # sample's training-objective value scaled by its center count, so
    # test_loss is the center-weighted mean of the objective being trained
    centers_i = (center * sm).sum((1, 2))
    per_sample = (bce * sm).mean((1, 2)) + 0.1 * (
        (l1 * sm).sum((1, 2)) / jnp.maximum(centers_i, 1.0)
    )
    return loss, {"loss_sum": per_sample * centers_i, "correct": correct,
                  "count": (center * sm).sum()}


LOSSES = {
    "classification": classification_loss,
    "nwp": nwp_loss,
    "tagpred": tagpred_loss,
    "segmentation": segmentation_loss,
    "regression": regression_loss,
    "node_clf": node_clf_loss,
    "link_pred": link_pred_loss,
    # per-token CE with -1 padding is structurally the node task
    # (reference: app/fednlp/seq_tagging)
    "seq_tagging": node_clf_loss,
    "span_extraction": span_extraction_loss,
    "detection": detection_loss,
}


def get_loss_fn(task: str):
    if task not in LOSSES:
        raise ValueError(f"unknown task {task!r}; known: {sorted(LOSSES)}")
    return LOSSES[task]
