"""Per-task losses and metrics, all mask-aware.

The reference splits these across per-task trainers
(``python/fedml/ml/trainer/my_model_trainer_classification.py`` CE loss,
``my_model_trainer_nwp.py`` next-word CE ignoring pad id 0,
``my_model_trainer_tag_prediction.py`` multilabel BCE). Here they are pure
functions over logits so one jit'd trainer serves every task; masks carry the
padded-cohort semantics (SURVEY.md §7 "Dynamic shapes vs jit").
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import optax

Metrics = Dict[str, jnp.ndarray]

PAD_TOKEN = 0  # nwp pad id (reference masks token 0 in NWP accuracy)


def classification_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Masked softmax cross-entropy. logits [B, C], y [B], mask [B]."""
    per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    loss = (per * sample_mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * sample_mask).sum()
    return loss, {"loss_sum": per * sample_mask, "correct": correct, "count": sample_mask.sum()}


def nwp_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Next-word CE. logits [B, L, V], y [B, L]; pad targets (id 0) ignored."""
    tok_mask = (y != PAD_TOKEN).astype(jnp.float32) * sample_mask[:, None]
    per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    denom = jnp.maximum(tok_mask.sum(), 1.0)
    loss = (per * tok_mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * tok_mask).sum()
    return loss, {"loss_sum": per * tok_mask, "correct": correct, "count": tok_mask.sum()}


def tagpred_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Multilabel sigmoid BCE. logits [B, C], y [B, C] in {0,1}."""
    per = optax.sigmoid_binary_cross_entropy(logits, y).mean(-1)
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    loss = (per * sample_mask).sum() / denom
    pred = (logits > 0).astype(jnp.float32)
    tp = (pred * y).sum(-1)
    precision = tp / jnp.maximum(pred.sum(-1), 1.0)
    recall = tp / jnp.maximum(y.sum(-1), 1.0)
    correct = (2 * precision * recall / jnp.maximum(precision + recall, 1e-8)
               * sample_mask).sum()  # summed F1, "correct" for uniform metrics
    return loss, {"loss_sum": per * sample_mask, "correct": correct, "count": sample_mask.sum()}


def segmentation_loss(logits, y, sample_mask) -> Tuple[jnp.ndarray, Metrics]:
    """Per-pixel CE. logits [B, H, W, C], y [B, H, W] int labels.

    reference: ``simulation/mpi/fedseg/utils.py`` SegmentationLosses (CE mode)
    + pixel-accuracy Evaluator; mIoU is computed by the FedSeg eval pass.
    """
    per_px = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    px_mask = sample_mask[:, None, None] * jnp.ones_like(per_px)
    denom = jnp.maximum(px_mask.sum(), 1.0)
    loss = (per_px * px_mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * px_mask).sum()
    return loss, {
        "loss_sum": (per_px * px_mask).sum((1, 2)),
        "correct": correct,
        "count": px_mask.sum(),
    }


LOSSES = {
    "classification": classification_loss,
    "nwp": nwp_loss,
    "tagpred": tagpred_loss,
    "segmentation": segmentation_loss,
}


def get_loss_fn(task: str):
    if task not in LOSSES:
        raise ValueError(f"unknown task {task!r}; known: {sorted(LOSSES)}")
    return LOSSES[task]
