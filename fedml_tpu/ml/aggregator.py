"""Concrete server aggregators + factory.

reference: ``python/fedml/ml/aggregator/`` — DefaultServerAggregator and
per-task variants (``my_server_aggregator_nwp.py`` etc.), factory at
``aggregator_creator.py:6-14``. Aggregation itself is the jit'd kernel in
``core/aggregate.py``; this class adds the test logic + hook points that the
attack/defense layer intercepts.
"""

from __future__ import annotations

from ..core.alg_frame import ServerAggregator
from .evaluate import make_eval_fn


class DefaultServerAggregator(ServerAggregator):
    def __init__(self, model, args=None):
        super().__init__(model, args)
        self._eval = make_eval_fn(model)

    def test(self, test_data, device, args):
        x, y = test_data
        return self._eval(self.model_params, x, y)


def create_server_aggregator(model, args) -> DefaultServerAggregator:
    return DefaultServerAggregator(model, args)
