"""Optax optimizer factories for client and server sides.

Client side replaces the reference's per-trainer torch.optim construction
(``ml/trainer/my_model_trainer_classification.py:30-45``: SGD or Adam + weight
decay). Server side replaces the reflection-based ``optrepo``
(``simulation/sp/fedopt/optrepo.py``) with an explicit registry — the
FedOpt-family server optimizer steps on the *pseudo-gradient*
w_global − avg(w_clients) (SURVEY.md §7 "Optimizer-state semantics").
"""

from __future__ import annotations

import optax


def create_client_optimizer(args) -> optax.GradientTransformation:
    name = str(getattr(args, "client_optimizer", "sgd")).lower()
    lr = float(getattr(args, "learning_rate", 0.03))
    wd = float(getattr(args, "weight_decay", 0.0))
    momentum = float(getattr(args, "momentum", 0.0))
    clip = float(getattr(args, "clip_grad", 0.0))

    if name == "sgd":
        tx = optax.sgd(lr, momentum=momentum if momentum > 0 else None)
    elif name == "adam":
        tx = optax.adam(lr)
    elif name == "adamw":
        tx = optax.adamw(lr, weight_decay=wd)
        wd = 0.0
    else:
        raise ValueError(f"unknown client_optimizer {name!r}")

    chain = []
    if clip > 0:
        chain.append(optax.clip_by_global_norm(clip))
    if wd > 0 and name != "adamw":
        chain.append(optax.add_decayed_weights(wd))
    chain.append(tx)
    return optax.chain(*chain) if len(chain) > 1 else tx


SERVER_OPTIMIZERS = ("sgd", "adam", "adagrad", "yogi")


def create_server_optimizer(args) -> optax.GradientTransformation:
    """Server optimizer applied to the pseudo-gradient (FedOpt family,
    Adaptive Federated Optimization: FedAdam / FedAdagrad / FedYogi)."""
    name = str(getattr(args, "server_optimizer", "sgd")).lower()
    lr = float(getattr(args, "server_lr", 1.0))
    momentum = float(getattr(args, "server_momentum", 0.0))
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum if momentum > 0 else None)
    if name == "adam":
        return optax.adam(lr, b1=0.9, b2=0.99, eps=1e-3)
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "yogi":
        return optax.yogi(lr)
    raise ValueError(
        f"unknown server_optimizer {name!r}; known: {SERVER_OPTIMIZERS}"
    )
