"""Host-side detection decode + mAP@0.5.

reference: ``python/app/fedcv/object_detection/model/yolov5/val.py`` (its
``ap_per_class``/``box_iou`` machinery — VOC-style all-point-interpolated AP
with greedy IoU matching). Re-grounded for the dense CenterNet-style head
(``models/detection.py``): decoding is a 3x3 peak-NMS over the sigmoid
heatmap followed by top-k, runs on HOST numpy after eval, and never enters
jit (ragged box lists are hostile to XLA — the jit side stays dense).

Both predictions and ground truth decode from the SAME dense grid layout
(``[H/s, W/s, C+2]`` logits / ``[H/s, W/s, C+3]`` targets), so the metric
needs no side-channel annotation plumbing: any detection dataset in the
registry (synthetic or the COCO-format reader) is mAP-evaluable.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

Box = Tuple[float, float, float, float]  # (y0, x0, y1, x1), normalized


def _sigmoid(z):
    return 1.0 / (1.0 + np.exp(-z))


def decode_predictions(logits: np.ndarray, topk: int = 50,
                       score_thresh: float = 0.05,
                       ) -> List[Tuple[float, int, Box]]:
    """Dense head output [Hs, Ws, C+2] → [(score, class, box), ...].

    CenterNet decode: sigmoid the class heatmap, keep 3x3 local maxima
    (the pooled-peak NMS of the CenterNet paper — no box NMS needed),
    take the global top-k above ``score_thresh``; each peak's box comes
    from the (h, w) size regression at that cell."""
    Hs, Ws, cc = logits.shape
    C = cc - 2
    heat = _sigmoid(np.asarray(logits[..., :C], np.float32))
    size = np.asarray(logits[..., C:], np.float32)
    # 3x3 max-pool via padded shifted maximum
    pad = np.pad(heat, ((1, 1), (1, 1), (0, 0)), constant_values=-1.0)
    pooled = heat.copy()
    for dy in (0, 1, 2):
        for dx in (0, 1, 2):
            pooled = np.maximum(pooled, pad[dy:dy + Hs, dx:dx + Ws])
    peak = heat * (heat >= pooled)
    flat = peak.ravel()
    k = min(topk, flat.size)
    order = np.argpartition(-flat, k - 1)[:k]
    out: List[Tuple[float, int, Box]] = []
    for idx in order[np.argsort(-flat[order])]:
        score = float(flat[idx])
        if score < score_thresh:
            break
        cy, cx, c = np.unravel_index(idx, peak.shape)
        h = float(np.clip(size[cy, cx, 0], 0.0, 1.0))
        w = float(np.clip(size[cy, cx, 1], 0.0, 1.0))
        yc, xc = (cy + 0.5) / Hs, (cx + 0.5) / Ws
        out.append((score, int(c),
                    (yc - h / 2, xc - w / 2, yc + h / 2, xc + w / 2)))
    return out


def decode_ground_truth(target: np.ndarray) -> List[Tuple[int, Box]]:
    """Dense target [Hs, Ws, C+3] → [(class, box), ...] from center cells."""
    Hs, Ws, cc = target.shape
    C = cc - 3
    out: List[Tuple[int, Box]] = []
    for cy, cx in zip(*np.nonzero(target[..., -1] > 0.5)):
        c = int(np.argmax(target[cy, cx, :C]))
        h, w = float(target[cy, cx, C]), float(target[cy, cx, C + 1])
        yc, xc = (cy + 0.5) / Hs, (cx + 0.5) / Ws
        out.append((c, (yc - h / 2, xc - w / 2, yc + h / 2, xc + w / 2)))
    return out


def _iou(a: Box, b: Box) -> float:
    y0 = max(a[0], b[0])
    x0 = max(a[1], b[1])
    y1 = min(a[2], b[2])
    x1 = min(a[3], b[3])
    inter = max(y1 - y0, 0.0) * max(x1 - x0, 0.0)
    area_a = max(a[2] - a[0], 0.0) * max(a[3] - a[1], 0.0)
    area_b = max(b[2] - b[0], 0.0) * max(b[3] - b[1], 0.0)
    union = area_a + area_b - inter
    return inter / union if union > 0 else 0.0


def _average_precision(recall: np.ndarray, precision: np.ndarray) -> float:
    """All-point interpolated AP (the reference's compute_ap with
    method != 'interp' — precision envelope integrated over recall)."""
    r = np.concatenate(([0.0], recall, [1.0]))
    p = np.concatenate(([1.0], precision, [0.0]))
    p = np.maximum.accumulate(p[::-1])[::-1]
    idx = np.nonzero(r[1:] != r[:-1])[0]
    return float(np.sum((r[idx + 1] - r[idx]) * p[idx + 1]))


def map_at_50(pred_logits: Sequence[np.ndarray],
              targets: Sequence[np.ndarray],
              iou_thresh: float = 0.5, topk: int = 50,
              score_thresh: float = 0.05) -> Dict[str, float]:
    """mAP@IoU over a test set of dense logits/targets.

    Per class: rank all detections by score across images, greedily match
    each to the best unmatched GT of the same class+image at IoU >=
    ``iou_thresh``, accumulate the PR curve, integrate AP; mAP averages the
    classes that have ground truth (reference ``ap_per_class`` semantics).
    """
    dets: Dict[int, List[Tuple[float, int, Box]]] = {}
    gts: Dict[Tuple[int, int], List[Box]] = {}
    n_gt: Dict[int, int] = {}
    for i, (pl, tg) in enumerate(zip(pred_logits, targets)):
        for score, c, box in decode_predictions(pl, topk, score_thresh):
            dets.setdefault(c, []).append((score, i, box))
        for c, box in decode_ground_truth(tg):
            gts.setdefault((c, i), []).append(box)
            n_gt[c] = n_gt.get(c, 0) + 1
    aps = []
    for c, total in sorted(n_gt.items()):
        ds = sorted(dets.get(c, []), key=lambda d: -d[0])
        matched: Dict[int, List[bool]] = {}
        tp = np.zeros(len(ds))
        fp = np.zeros(len(ds))
        for j, (_score, img, box) in enumerate(ds):
            cand = gts.get((c, img), [])
            used = matched.setdefault(img, [False] * len(cand))
            best, best_iou = -1, iou_thresh
            for gi, gbox in enumerate(cand):
                if used[gi]:
                    continue
                iou = _iou(box, gbox)
                if iou >= best_iou:
                    best, best_iou = gi, iou
            if best >= 0:
                used[best] = True
                tp[j] = 1.0
            else:
                fp[j] = 1.0
        ctp = np.cumsum(tp)
        recall = ctp / max(total, 1)
        precision = ctp / np.maximum(ctp + np.cumsum(fp), 1e-9)
        aps.append(_average_precision(recall, precision))
    return {
        "map50": float(np.mean(aps)) if aps else 0.0,
        "classes_evaluated": float(len(aps)),
        "total_gt": float(sum(n_gt.values())),
    }


def collect_detection_logits(bundle, params, test_x,
                             batch_size: int = 8) -> List[np.ndarray]:
    """One dense forward over the test set (jit-sized batches, device);
    callers score the SAME logits at any number of IoU thresholds without
    re-running the conv stack (minutes at 224px on CPU)."""
    import jax
    import jax.numpy as jnp

    # cache the jitted forward on the bundle: re-jitting a fresh lambda per
    # call would recompile the conv stack every eval
    apply = getattr(bundle, "_map50_apply", None)
    if apply is None:
        apply = jax.jit(lambda p, bx: bundle.apply(p, bx, train=False))
        bundle._map50_apply = apply
    logits: List[np.ndarray] = []
    n = test_x.shape[0]
    for i in range(0, n, batch_size):
        bx = jnp.asarray(np.asarray(test_x[i:i + batch_size], np.float32))
        logits.extend(np.asarray(apply(params, bx), np.float32))
    return logits


def evaluate_map50(bundle, params, test_x, test_y, batch_size: int = 8,
                   **decode_kw) -> Dict[str, float]:
    """mAP@0.5 of a detection bundle over a test set.

    Runs the dense forward in jit-sized batches (device), then decodes and
    matches host-side — the federated analog of the reference's
    ``yolov5/val.py`` end-of-training eval."""
    logits = collect_detection_logits(bundle, params, test_x, batch_size)
    return map_at_50(logits, [np.asarray(t, np.float32) for t in test_y],
                     **decode_kw)
