"""Generation metrics for the seq2seq task: greedy decode + ROUGE-L + BLEU.

reference: ``python/app/fednlp/seq2seq/trainer/seq2seq_trainer.py`` evaluates
with generation metrics (rouge via the ``rouge_score`` package) rather than
per-token accuracy. Same math here over token ids (our corpora are packed
token streams; on a word-tokenized corpus ids are words, so the scores
coincide with the text-level ones):

- ROUGE-L: LCS-based F-measure per (hypothesis, reference) pair, averaged;
- BLEU: corpus-level modified n-gram precision (n<=4, add-0 counting with
  the standard brevity penalty — Papineni et al.);
- exact match rides along (the old test_acc's sequence-level analog).

Decoding is true autoregressive greedy generation on the prefix-LM: the
prompt is ``[src ; SEP]``, one forward per generated token (the causal mask
makes right-padding invisible), argmax over the vocab. Host-driven loop, one
jitted forward reused across steps — eval-sized work, never in train jit.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Sequence

import numpy as np


def _lcs_len(a: Sequence[int], b: Sequence[int]) -> int:
    """Classic O(len(a)*len(b)) LCS table, iterative."""
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def rouge_l(hyp: Sequence[int], ref: Sequence[int]) -> float:
    """ROUGE-L F1 of one pair (beta=1; the reference's rouge_score default
    weights recall via beta=1.2^2 — F1 is the common reporting choice)."""
    lcs = _lcs_len(list(hyp), list(ref))
    if lcs == 0:
        return 0.0
    p = lcs / len(hyp)
    r = lcs / len(ref)
    return 2 * p * r / (p + r)


def corpus_bleu(hyps: Sequence[Sequence[int]],
                refs: Sequence[Sequence[int]], max_n: int = 4) -> float:
    """Corpus BLEU over token ids (modified n-gram precision + brevity
    penalty; single reference per hypothesis)."""
    match = [0] * max_n
    total = [0] * max_n
    hyp_len = ref_len = 0
    for hyp, ref in zip(hyps, refs):
        hyp, ref = list(hyp), list(ref)
        hyp_len += len(hyp)
        ref_len += len(ref)
        for n in range(1, max_n + 1):
            h_ngrams = Counter(
                tuple(hyp[i:i + n]) for i in range(len(hyp) - n + 1)
            )
            r_ngrams = Counter(
                tuple(ref[i:i + n]) for i in range(len(ref) - n + 1)
            )
            total[n - 1] += max(len(hyp) - n + 1, 0)
            match[n - 1] += sum(
                min(c, r_ngrams[g]) for g, c in h_ngrams.items()
            )
    if hyp_len == 0 or all(m == 0 for m in match):
        return 0.0
    # orders with no candidates at all (every hypothesis shorter than n)
    # drop out of the geometric mean rather than zeroing it — a perfect
    # 2-token corpus must not score 0 for lacking 4-grams
    orders = [(m, t) for m, t in zip(match, total) if t > 0]
    # smoothing (Chen & Cherry method 1): zero n-gram matches count as a
    # small epsilon instead of zeroing the whole geometric mean — short
    # sequences would otherwise report BLEU=0 despite real overlap
    log_p = sum(
        np.log((m if m > 0 else 0.5 / t) / t) for m, t in orders
    ) / len(orders)
    bp = 1.0 if hyp_len > ref_len else float(np.exp(1 - ref_len / hyp_len))
    return float(bp * np.exp(log_p))


def greedy_decode(bundle, params, prompts: np.ndarray, prompt_len: int,
                  max_new: int) -> np.ndarray:
    """Autoregressive greedy generation on a prefix-LM bundle.

    ``prompts`` [B, L] carries the prompt in positions < prompt_len (the
    rest is pad); position ``prompt_len - 1`` (the SEP) predicts the first
    generated token. Returns [B, max_new] generated ids."""
    import jax
    import jax.numpy as jnp

    apply = getattr(bundle, "_gen_apply", None)
    if apply is None:
        apply = jax.jit(lambda p, x: bundle.apply(p, x, train=False))
        bundle._gen_apply = apply
    x = np.asarray(prompts, np.int32).copy()
    out = np.zeros((x.shape[0], max_new), np.int32)
    for k in range(max_new):
        pos = prompt_len - 1 + k
        logits = np.asarray(apply(params, jnp.asarray(x)))
        nxt = logits[:, pos].argmax(-1).astype(np.int32)
        out[:, k] = nxt
        if pos + 1 < x.shape[1]:
            x[:, pos + 1] = nxt
    return out


def evaluate_generation(bundle, params, test_x: np.ndarray,
                        test_y: np.ndarray, prompt_len: int,
                        tgt_len: int) -> Dict[str, float]:
    """Greedy-decode the test prompts and score ROUGE-L / BLEU / exact match
    against the reference targets (``test_y``'s supervised region)."""
    x = np.asarray(test_x, np.int32)
    prompts = x.copy()
    prompts[:, prompt_len:] = 0  # hide the gold continuation
    gen = greedy_decode(bundle, params, prompts, prompt_len, tgt_len)
    refs: List[List[int]] = [
        [int(t) for t in row[prompt_len - 1: prompt_len - 1 + tgt_len]
         if t != 0]
        for row in np.asarray(test_y, np.int32)
    ]
    hyps: List[List[int]] = [
        [int(t) for t in g[:len(r)]] for g, r in zip(gen, refs)
    ]
    pairs = [(h, r) for h, r in zip(hyps, refs) if r]
    if not pairs:
        return {"rouge_l": 0.0, "bleu": 0.0, "exact_match": 0.0,
                "n_eval": 0.0}
    rl = float(np.mean([rouge_l(h, r) for h, r in pairs]))
    bl = corpus_bleu([h for h, _ in pairs], [r for _, r in pairs])
    em = float(np.mean([h == r for h, r in pairs]))
    return {"rouge_l": rl, "bleu": bl, "exact_match": em,
            "n_eval": float(len(pairs))}
