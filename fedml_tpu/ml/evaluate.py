"""Sharded model evaluation.

Replaces the reference's ``_local_test_on_all_clients``
(``simulation/sp/fedavg/fedavg_api.py:174-232``) central torch eval loops with
one jit'd batched pass; metric definitions preserved (accuracy = correct/total,
NWP accuracy ignores pad tokens, tagpred reports mean F1) so the §6 baseline
numbers are comparable.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .losses import get_loss_fn


def make_eval_fn(bundle, batch_size: int = 256):
    loss_fn_raw = get_loss_fn(bundle.task)

    @partial(jax.jit, static_argnums=())
    def eval_batch(params, bx, by, bmask):
        logits = bundle.apply(params, bx, train=False)
        loss, metrics = loss_fn_raw(logits, by, bmask)
        return (
            (metrics["loss_sum"]).sum(),
            metrics["correct"],
            metrics["count"],
        )

    def evaluate(params, test_x, test_y) -> Dict[str, float]:
        n = test_x.shape[0]
        pad = (-n) % batch_size
        if pad:
            test_x = np.concatenate([test_x, np.zeros((pad,) + test_x.shape[1:], test_x.dtype)])
            test_y = np.concatenate([test_y, np.zeros((pad,) + test_y.shape[1:], test_y.dtype)])
        mask_full = (np.arange(test_x.shape[0]) < n).astype(np.float32)
        tot_loss = tot_correct = tot_count = 0.0
        for i in range(0, test_x.shape[0], batch_size):
            ls, c, cnt = eval_batch(
                params,
                jnp.asarray(test_x[i : i + batch_size]),
                jnp.asarray(test_y[i : i + batch_size]),
                jnp.asarray(mask_full[i : i + batch_size]),
            )
            tot_loss += float(ls)
            tot_correct += float(c)
            tot_count += float(cnt)
        return {
            "test_loss": tot_loss / max(tot_count, 1.0),
            "test_acc": tot_correct / max(tot_count, 1.0),
            "test_total": tot_count,
        }

    return evaluate
