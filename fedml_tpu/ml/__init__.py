"""``fedml_tpu.ml`` — trainers, aggregators, losses, optimizers, eval."""

from .aggregator import DefaultServerAggregator, create_server_aggregator
from .evaluate import make_eval_fn
from .local_train import make_grad_fn, make_local_train_fn
from .losses import get_loss_fn
from .optimizer import create_client_optimizer, create_server_optimizer
from .trainer import ModelTrainer, create_model_trainer

__all__ = [
    "DefaultServerAggregator",
    "create_server_aggregator",
    "make_eval_fn",
    "make_grad_fn",
    "make_local_train_fn",
    "get_loss_fn",
    "create_client_optimizer",
    "create_server_optimizer",
    "ModelTrainer",
    "create_model_trainer",
]
