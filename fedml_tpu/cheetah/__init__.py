"""``fedml_tpu.cheetah`` — the distributed-training pillar.

In the reference this pillar is an EMPTY STUB (``python/fedml/distributed/``
contains one empty ``__init__.py``; ``constants.py:5`` names the platform but
``runner.py:29-38`` has no branch for it — SURVEY.md intro). Here it is real:
LLM pretraining over an N-D device mesh (data/fsdp/tensor/sequence axes),
built on ``fedml_tpu.parallel``.
"""

from .runner import CheetahRunner

__all__ = ["CheetahRunner"]
