"""Cheetah runner: config → mesh → sharded pretraining loop.

The ``training_type: distributed`` branch of FedMLRunner (absent in the
reference — ``runner.py:29-38`` handles only simulation/cross_silo/
cross_device). Consumes the packed FedDataset (token streams) or a synthetic
stream, builds the mesh from ``args.mesh_shape``, and drives
``parallel.CheetahTrainer`` with optional per-step logging + checkpointing.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.mlops import telemetry
from ..parallel.sharding import make_mesh
from ..parallel.train_step import CheetahTrainer, make_optimizer
from ..parallel.transformer import TransformerConfig

logger = logging.getLogger(__name__)


def _parse_bool(v) -> bool:
    """YAML-robust bool: unregistered keys reach us as raw strings, and
    bool(\"false\") would silently mean True."""
    if isinstance(v, str):
        lowered = v.strip().lower()
        if lowered in ("1", "true", "yes", "on"):
            return True
        if lowered in ("0", "false", "no", "off", ""):
            return False
        raise ValueError(f"not a boolean: {v!r}")
    return bool(v)


def config_from_args(args) -> TransformerConfig:
    size = str(getattr(args, "model_size", "tiny")).lower()
    if size in ("7b", "llama2_7b"):
        cfg = TransformerConfig.llama2_7b()
    elif size == "tiny":
        cfg = TransformerConfig.tiny(
            vocab_size=int(getattr(args, "vocab_size", 256))
        )
    else:
        cfg = TransformerConfig(
            vocab_size=int(getattr(args, "vocab_size", 32000)),
            d_model=int(getattr(args, "d_model", 1024)),
            n_layers=int(getattr(args, "n_layers", 8)),
            n_heads=int(getattr(args, "n_heads", 8)),
            n_kv_heads=int(getattr(args, "n_kv_heads", 8)),
            d_ff=int(getattr(args, "d_ff", 2816)),
            max_seq_len=int(getattr(args, "seq_len", 1024)),
        )
    # knobs beyond the shape: splash kernel blocks (the hd128 MFU lever —
    # tools/mfu_sweep.py), MoE routing, remat, positional scheme — all
    # YAML-reachable, applied to EVERY size (the one place the args→config
    # mapping lives; bundle factories must not re-plumb knobs). Only keys
    # the config actually carries are passed through, so the
    # TransformerConfig dataclass defaults stay the single source of truth.
    import dataclasses as _dc

    extra = {}
    for name, cast in (("attn_block_q", int), ("attn_block_kv", int),
                       ("moe_experts", int), ("moe_top_k", int),
                       ("moe_capacity_factor", float),
                       ("remat", _parse_bool), ("remat_policy", str),
                       ("pos_emb", str)):
        if getattr(args, name, None) is not None:
            extra[name] = cast(getattr(args, name))
    return _dc.replace(cfg, **extra) if extra else cfg


class CheetahRunner:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        self.cfg = config_from_args(args)
        mesh_shape = args.parse_mesh_shape() or None
        self.mesh = make_mesh(mesh_shape)
        self.batch_size = int(getattr(args, "batch_size", 8))
        self.seq_len = int(getattr(args, "seq_len", 128))
        self.total_steps = int(getattr(args, "total_steps", 10))
        self.accum_steps = int(getattr(args, "accum_steps", 1))
        self.trainer = CheetahTrainer(
            self.cfg,
            self.mesh,
            optimizer=make_optimizer(
                learning_rate=float(getattr(args, "learning_rate", 3e-4)),
                warmup_steps=int(getattr(args, "warmup_steps", 10)),
                total_steps=self.total_steps,
            ),
            accum_steps=self.accum_steps,
        )
        self.dataset = dataset
        self.checkpoint_dir = str(getattr(args, "checkpoint_dir", "") or "")

    def _token_stream(self) -> Optional[np.ndarray]:
        """The packed dataset's tokens as one contiguous stream, or None.

        The data layer packs NWP datasets as [clients, cap, seq] int token
        windows; pretraining doesn't care about client boundaries, so the
        whole corpus flattens into a single stream that random seq_len
        windows are drawn from. Token ids are clipped into the model's
        vocab (a staged corpus may use a smaller alphabet — fine; a larger
        one would silently alias, so clip and warn once).
        """
        ds = self.dataset
        if ds is None or getattr(ds, "task", "") != "nwp":
            return None
        # only each client's REAL rows — the packed layout zero-pads beyond
        # train_counts[c], and training on runs of pad token 0 poisons loss
        tx = np.asarray(ds.train_x)
        counts = np.asarray(ds.train_counts)
        parts = [
            tx[c, : int(counts[c])].reshape(-1)
            for c in range(tx.shape[0])
            if int(counts[c]) > 0
        ]
        if not parts:
            return None
        stream = np.concatenate(parts).astype(np.int32)
        if stream.size < (self.seq_len + 1) * 2:
            return None
        vmax = int(stream.max())
        if vmax >= self.cfg.vocab_size:
            logger.warning(
                "cheetah: corpus vocab %d exceeds model vocab %d; clipping",
                vmax + 1, self.cfg.vocab_size,
            )
            stream = np.minimum(stream, self.cfg.vocab_size - 1)
        return stream

    def _batches(self, rng: np.random.RandomState):
        """Token batches from the dataset's packed stream, else synthetic."""
        V = self.cfg.vocab_size
        shape = (self.batch_size, self.seq_len)
        if self.accum_steps > 1:
            shape = (self.accum_steps,) + shape
        stream = self._token_stream()
        if stream is None:
            while True:
                yield rng.randint(0, V, shape).astype(np.int32)
        from .. import native

        n_rows = int(np.prod(shape[:-1]))
        while True:
            starts = rng.randint(0, stream.size - self.seq_len, size=n_rows)
            # threaded C++ window gather: this slice runs on the host
            # critical path between device steps
            rows = native.gather_windows(stream, starts, self.seq_len)
            yield rows.reshape(shape)

    def run(self) -> dict:
        state = self.trainer.init_state(
            jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0)))
        )
        start_step = 0
        guard = None
        if self.checkpoint_dir:
            from ..checkpoint import CheckpointManager
            from ..core import runstate

            ckpt = CheckpointManager(self.checkpoint_dir)
            restored = ckpt.restore_latest(state)
            if restored is not None:
                state = restored
                start_step = int(state.step)
                logger.info("cheetah: resumed from step %d", start_step)
            # step-granular preemption drain (docs/robustness.md): SIGTERM
            # during a long pretrain exits within ONE step's latency with
            # the state checkpointed at the step boundary it latched on
            guard = runstate.preemption_guard()
            if bool(getattr(self.args, "preempt_signals", True)):
                guard.install()
            guard.reset()
        rng = np.random.RandomState(int(getattr(self.args, "random_seed", 0)))
        gen = self._batches(rng)
        losses = []
        t0 = time.perf_counter()
        tokens_done = 0
        every = int(getattr(self.args, "checkpoint_every_rounds", 0) or 0)
        # per-step telemetry denominators (the Cheetah "round" is a step):
        # model FLOPs/token for the live MFU gauge, chip peak by device kind
        n_params = sum(int(p.size) for p in jax.tree.leaves(state.params))
        flops_tok = telemetry.flops_per_token(
            n_params, self.seq_len, self.cfg.n_layers, self.cfg.d_model
        )
        device_kind = str(getattr(jax.devices()[0], "device_kind", "?"))
        n_chips = jax.device_count()
        for step in range(start_step, self.total_steps):
            telemetry.on_round_start(step)
            rec = telemetry.begin_round(step)
            with telemetry.phase("data"):
                tokens = next(gen)
                mask = np.ones_like(tokens)
            with telemetry.phase("step"):
                state, metrics = self.trainer.train_step(
                    state, jnp.asarray(tokens), jnp.asarray(mask)
                )
            with telemetry.phase("loss_sync"):
                losses.append(float(metrics["loss"]))
            tokens_done += tokens.size
            if rec is not None:
                rec.lazy["examples"] = tokens.size
            telemetry.end_round(rec, train_loss=losses[-1])
            if rec is not None and rec.wall_s > 0:
                tps = tokens.size / rec.wall_s
                telemetry.gauge_set("cheetah.tokens_per_sec", tps)
                mfu = telemetry.mfu_estimate(tps, flops_tok, device_kind,
                                             n_chips)
                if mfu is not None:
                    telemetry.gauge_set("cheetah.mfu_estimate", mfu)
            telemetry.on_round_end(step)
            if every and (step + 1) % every == 0 and self.checkpoint_dir:
                ckpt.save(state)
            if guard is not None and guard.requested() \
                    and step + 1 < self.total_steps:
                from ..core.runstate import PreemptionError

                # drain commit: this step completed — persist it NOW (even
                # off the checkpoint cadence) so the restart resumes at
                # exactly step + 1 instead of re-training the window
                if ckpt.latest_step() != int(state.step):
                    ckpt.save(state)
                ckpt.close()
                telemetry.counter_inc("run.preemptions")
                raise PreemptionError(step)
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
        tps = tokens_done / max(dt, 1e-9)
        result = {
            "final_loss": losses[-1] if losses else float("nan"),
            "steps": self.total_steps - start_step,
            "tokens_per_sec": tps,
        }
        mfu = telemetry.mfu_estimate(tps, flops_tok, device_kind, n_chips)
        if mfu is not None:
            result["mfu_estimate"] = round(mfu, 4)
        if self.checkpoint_dir:
            ckpt.save(state)
            ckpt.close()  # release orbax worker threads with the run
        logger.info("cheetah: %s", result)
        return result
