"""Edge/server agent daemons — the deployment plane.

reference: ``cli/edge_deployment/client_runner.py`` (879 LoC) +
``client_daemon.py`` / ``server_deployment/`` — ``fedml login`` binds the
device to an account and starts a daemon that receives run requests from the
MLOps platform (MQTT), downloads the training package, unpacks it, launches
the user's entry point as a subprocess, and reports status transitions
(IDLE → UPGRADING → INITIALIZING → TRAINING → FINISHED/FAILED,
``client_constants.py:15-23``; server mirror at ``:25-31``).

TPU re-grounding: pods receive work through shared storage, not a SaaS MQTT
broker, so the job plane here is a *directory queue* on a filesystem both
submitter and agent can see (NFS/GCS-fuse on a real pod; tmpdir in tests):

- ``submit_job(package_zip, jobs_dir)`` drops the package built by
  ``fedml_tpu build`` plus a JSON descriptor into the queue (the analog of
  the platform's run-start MQTT message);
- ``Agent.run_once()`` claims the oldest pending descriptor by atomic
  rename (safe with many agents on one queue), unpacks the package, runs
  its manifest entry point as a subprocess, and appends every status
  transition to ``status.jsonl`` — the same observable FSM the reference
  reports over MQTT;
- a ``stop`` file next to the job descriptor is the kill switch (the
  analog of the platform's stop-run message, client_runner's
  cleanup_run_when_stopped).

Login/logout keep their reference meaning — bind/unbind this host as a
named edge device — but write a local state file instead of calling
open.fedml.ai.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import sys
import time
import uuid
import zipfile
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

logger = logging.getLogger("fedml_tpu.agent")

# reference: client_constants.py:15-23 / :25-31 (shared transition names)
STATUS_IDLE = "IDLE"
STATUS_UPGRADING = "UPGRADING"          # unpacking the package
STATUS_INITIALIZING = "INITIALIZING"    # entry process starting
STATUS_RUNNING = "RUNNING"              # reference: TRAINING / RUNNING
STATUS_STOPPING = "STOPPING"
STATUS_FINISHED = "FINISHED"
STATUS_FAILED = "FAILED"

STATE_FILE = "agent_state.json"
PENDING_SUFFIX = ".job.json"
CLAIMED_SUFFIX = ".job.claimed"
STOP_SUFFIX = ".stop"


# ---------------------------------------------------------------------------
# login / logout (reference: fedml login <account> -c|-s, fedml logout)
# ---------------------------------------------------------------------------


def login(account_id: str, role: str = "client",
          state_dir: str = ".fedml_tpu_agent") -> Dict[str, Any]:
    """Bind this host as an edge device (reference: client_login.py)."""
    if role not in ("client", "server"):
        raise ValueError(f"role must be client|server, got {role!r}")
    os.makedirs(state_dir, exist_ok=True)
    state = {
        "account_id": str(account_id),
        "role": role,
        "device_id": f"{role}-{uuid.uuid4().hex[:12]}",
        "bound_at": time.time(),
    }
    with open(os.path.join(state_dir, STATE_FILE), "w") as f:
        json.dump(state, f, indent=2)
    return state


def logout(state_dir: str = ".fedml_tpu_agent") -> bool:
    path = os.path.join(state_dir, STATE_FILE)
    if os.path.exists(path):
        os.remove(path)
        return True
    return False


def agent_state(state_dir: str = ".fedml_tpu_agent") -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(state_dir, STATE_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# job submission (the analog of the platform's run-start message)
# ---------------------------------------------------------------------------


def submit_job(package_zip: str, jobs_dir: str,
               job_id: Optional[str] = None,
               run_args: Optional[List[str]] = None) -> str:
    """Queue a package built by ``fedml_tpu build`` for an agent to run."""
    if not zipfile.is_zipfile(package_zip):
        raise ValueError(f"{package_zip} is not a package zip")
    os.makedirs(jobs_dir, exist_ok=True)
    job_id = job_id or f"job-{uuid.uuid4().hex[:12]}"
    pkg_dest = os.path.join(jobs_dir, f"{job_id}.zip")
    shutil.copyfile(package_zip, pkg_dest)
    desc = {
        "job_id": job_id,
        "package": os.path.basename(pkg_dest),
        "run_args": run_args or [],
        "submitted_at": time.time(),
    }
    tmp = os.path.join(jobs_dir, f".{job_id}.tmp")
    with open(tmp, "w") as f:
        json.dump(desc, f)
    # atomic publish: the descriptor appears only when fully written
    os.replace(tmp, os.path.join(jobs_dir, f"{job_id}{PENDING_SUFFIX}"))
    return job_id


def request_stop(job_id: str, jobs_dir: str) -> None:
    """Drop the stop file (analog of the platform's stop-run message)."""
    with open(os.path.join(jobs_dir, f"{job_id}{STOP_SUFFIX}"), "w") as f:
        f.write(str(time.time()))


# ---------------------------------------------------------------------------
# the agent
# ---------------------------------------------------------------------------


@dataclass
class JobResult:
    job_id: str
    status: str
    returncode: Optional[int]
    run_dir: str


class Agent:
    """Directory-queue job runner (reference: FedMLClientRunner FSM,
    client_runner.py — download → unzip → bootstrap → launch → report)."""

    def __init__(self, jobs_dir: str, work_dir: str, role: str = "client",
                 python_exe: Optional[str] = None,
                 poll_interval_s: float = 1.0,
                 stale_claim_s: float = 3600.0):
        self.jobs_dir = jobs_dir
        self.work_dir = work_dir
        self.role = role
        # claims are renamed to an agent-unique filename: success of any later
        # operation on OUR claim path then proves ownership (a same-named path
        # recreated by a peer after a steal cannot alias ours)
        self.agent_id = uuid.uuid4().hex[:8]
        self.python_exe = python_exe or sys.executable
        self.poll_interval_s = poll_interval_s
        self.stale_claim_s = stale_claim_s
        os.makedirs(jobs_dir, exist_ok=True)
        os.makedirs(work_dir, exist_ok=True)
        self.status_path = os.path.join(work_dir, "status.jsonl")

    # -- status reporting (reference: mlops_metrics report_*_status) --------

    def _report(self, job_id: str, status: str, **extra) -> None:
        rec = {"job_id": job_id, "status": status, "role": self.role,
               "time": time.time(), **extra}
        with open(self.status_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        logger.info("agent %s: %s -> %s", self.role, job_id, status)

    def job_statuses(self, job_id: str) -> List[str]:
        if not os.path.exists(self.status_path):
            return []
        out = []
        with open(self.status_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("job_id") == job_id:
                    out.append(rec["status"])
        return out

    # -- queue claim --------------------------------------------------------

    def _requeue_stale_claims(self) -> None:
        """A claim whose agent died mid-run must not strand the job: when a
        ``.job.claimed`` file's mtime exceeds ``stale_claim_s``, rename it
        back to pending (atomic; at most one reviver wins). The analog of
        the reference daemon's restart-and-rerun loop (client_daemon.py)."""
        now = time.time()
        for fn in os.listdir(self.jobs_dir):
            if CLAIMED_SUFFIX not in fn:
                continue
            path = os.path.join(self.jobs_dir, fn)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue  # finished and removed under us
            if age < self.stale_claim_s:
                continue
            pending = (
                path[: path.index(CLAIMED_SUFFIX)] + PENDING_SUFFIX
            )
            try:
                os.rename(path, pending)
                logger.warning("requeued stale claim %s (%.0fs old)", fn, age)
            except OSError:
                pass

    def _claim_next(self) -> Optional[Dict[str, Any]]:
        self._requeue_stale_claims()
        pending = sorted(
            fn for fn in os.listdir(self.jobs_dir)
            if fn.endswith(PENDING_SUFFIX)
        )
        for fn in pending:
            src = os.path.join(self.jobs_dir, fn)
            dst = (src[: -len(PENDING_SUFFIX)] + CLAIMED_SUFFIX
                   + "." + self.agent_id)
            try:
                os.rename(src, dst)  # atomic: exactly one agent wins
            except OSError:
                continue
            try:
                # rename preserves the descriptor's submit-time mtime; stamp
                # the claim NOW so a peer's stale-claim reviver measures age
                # from claim time, not from however long the job queued.
                # Failure means a reviver stole the claim back in the
                # rename→utime window — and because dst embeds OUR agent_id,
                # a peer re-claiming the job can never recreate this path,
                # so failure here is a definitive lost-claim signal.
                os.utime(dst)
                with open(dst) as f:
                    desc = json.load(f)
                desc["_claim_path"] = dst
                return desc
            except OSError:
                continue
        return None

    # -- one job ------------------------------------------------------------

    def _unpack(self, desc: Dict[str, Any]) -> str:
        pkg = os.path.join(self.jobs_dir, desc["package"])
        run_dir = os.path.join(self.work_dir, desc["job_id"])
        os.makedirs(run_dir, exist_ok=True)
        with zipfile.ZipFile(pkg) as z:
            base = os.path.realpath(run_dir)
            for info in z.infolist():
                target = os.path.realpath(os.path.join(run_dir, info.filename))
                if not target.startswith(base + os.sep) and target != base:
                    raise ValueError(
                        f"package entry escapes run dir: {info.filename}"
                    )
            z.extractall(run_dir)
        return run_dir

    def _run_job(self, desc: Dict[str, Any]) -> JobResult:
        job_id = desc["job_id"]
        self._report(job_id, STATUS_UPGRADING)
        try:
            run_dir = self._unpack(desc)
            manifest_path = os.path.join(run_dir, "fedml_package.json")
            with open(manifest_path) as f:
                manifest = json.load(f)
            entry = manifest.get("entry_point", "main.py")
        except Exception as e:
            self._report(job_id, STATUS_FAILED, error=str(e))
            return JobResult(job_id, STATUS_FAILED, None, "")

        self._report(job_id, STATUS_INITIALIZING, entry_point=entry)
        stop_file = os.path.join(self.jobs_dir, f"{job_id}{STOP_SUFFIX}")
        claim_path = desc.get("_claim_path")
        log_path = os.path.join(run_dir, "job.log")
        last_heartbeat = time.time()
        claim_lost = False
        with open(log_path, "w") as log_f:
            proc = subprocess.Popen(
                [self.python_exe, entry, *desc.get("run_args", [])],
                cwd=run_dir, stdout=log_f, stderr=subprocess.STDOUT,
            )
            self._report(job_id, STATUS_RUNNING, pid=proc.pid)
            while proc.poll() is None:
                if os.path.exists(stop_file):
                    self._report(job_id, STATUS_STOPPING)
                    proc.terminate()
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                    break
                now = time.time()
                if now - last_heartbeat > 30.0:
                    last_heartbeat = now
                    try:  # keep the claim fresh so peers don't steal it
                        if claim_path is not None:
                            os.utime(claim_path)
                    except OSError:
                        # our agent-unique claim file is gone: a reviver
                        # re-pended the job (we stalled past stale_claim_s)
                        # and a peer may be re-running it — kill our copy
                        # rather than double-execute
                        claim_lost = True
                        proc.terminate()
                        try:
                            proc.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            proc.kill()
                        break
                time.sleep(0.1)
            rc = proc.wait()
        if claim_lost:
            self._report(job_id, STATUS_FAILED, error="claim lost to reviver")
            return JobResult(job_id, STATUS_FAILED, rc, run_dir)
        status = STATUS_FINISHED if rc == 0 else STATUS_FAILED
        self._report(job_id, status, returncode=rc)
        return JobResult(job_id, status, rc, run_dir)

    # -- daemon loop --------------------------------------------------------

    def run_once(self) -> Optional[JobResult]:
        """Claim and run at most one pending job (test/cron entry)."""
        desc = self._claim_next()
        if desc is None:
            return None
        result = self._run_job(desc)
        # drop our claim (stop it looking stale); only if that succeeds —
        # ownership proof — also clear the stop file, so a resubmitted job_id
        # isn't killed at startup by a stale kill switch. A zombie agent whose
        # claim was stolen must NOT delete a stop aimed at the peer's re-run.
        owned = True
        claim = desc.get("_claim_path")
        if claim is not None:
            try:
                os.remove(claim)
            except OSError:
                owned = False
        if owned:
            try:
                os.remove(os.path.join(
                    self.jobs_dir, f"{desc['job_id']}{STOP_SUFFIX}"))
            except OSError:
                pass
        return result

    def run_forever(self, max_jobs: Optional[int] = None) -> None:
        """The daemon loop (reference: client_daemon.py restart loop)."""
        self._report("-", STATUS_IDLE)
        done = 0
        while max_jobs is None or done < max_jobs:
            result = self.run_once()
            if result is None:
                time.sleep(self.poll_interval_s)
                continue
            done += 1
