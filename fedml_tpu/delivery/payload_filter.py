"""Adapter-only payload filter: regex over named pytree leaves.

reference: LoRA (Hu et al., 2021) and the adapter-FL line — in the federated
7B scenario clients fine-tune a small set of adapter/head parameters and the
backbone stays frozen, so only ~0.1% of the weights ever need to cross the
wire. The reference framework ships the full state dict regardless.

The filter reuses the ``scale/partition_rules`` leaf-naming convention
(``a/b/c`` paths via :func:`named_tree_paths`, ``re.search`` semantics):
``--payload_filter "adapter|lora_|head"`` selects the leaves that ride the
C2S update; the server merges them into its *current* global for
aggregation, so unselected leaves are exactly frozen — every buffer entry
carries the head's values for them and their weighted average is the head
itself. The S2C direction needs no filter: frozen leaves are bit-identical
between versions, so the lossless sparse delta frame
(:mod:`~fedml_tpu.delivery.delta_codec`) prices them at ~zero bytes.

Both ends construct the filter from the SAME ``args.payload_filter`` over
the SAME model skeleton, so the selected index set is identical by
construction; the C2S message additionally carries the pattern
(:data:`FILTER_KEY`) and the receiver refuses a mismatch loudly instead of
mis-merging leaves.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Sequence

import numpy as np

from ..scale.partition_rules import named_tree_paths

# message param announcing a filtered payload (absent = full leaf list)
FILTER_KEY = "__payload_filter__"

PyTree = Any


class PayloadFilter:
    """Select/merge a fixed subset of a pytree's leaves by leaf name."""

    def __init__(self, pattern: str, template_tree: PyTree):
        self.pattern = str(pattern)
        try:
            rx = re.compile(self.pattern)
        except re.error as e:
            raise ValueError(
                f"bad payload_filter pattern {pattern!r}: {e}") from None
        named = named_tree_paths(template_tree)
        self.names = [name for name, _ in named]
        self.indices = [i for i, (name, _) in enumerate(named)
                        if rx.search(name) is not None]
        # per-leaf (shape, dtype, flat offset) over the CANONICAL flatten
        # order (delivery.flatten_leaves): selected leaves are fixed slices
        # of the flat model vector, so codec paths can slice base vectors
        # directly instead of round-tripping the whole model through a
        # pytree (attrs only — no host copy of a possibly-on-device leaf)
        self._shapes, self._dtypes, self._offsets = [], [], []
        off = 0
        for _, leaf in named:
            shape = tuple(getattr(leaf, "shape", ()))
            size = 1
            for s in shape:
                size *= int(s)
            self._shapes.append(shape)
            self._dtypes.append(np.dtype(getattr(leaf, "dtype", np.float32)))
            self._offsets.append(off)
            off += size
        self.total_size = off
        if not self.indices:
            raise ValueError(
                f"payload_filter {pattern!r} matches no leaf of the model "
                f"(leaves: {self.names})"
            )
        if len(self.indices) == len(named):
            raise ValueError(
                f"payload_filter {pattern!r} matches EVERY leaf — drop the "
                "filter instead of shipping a filtered full model"
            )
        self.selected_names = [self.names[i] for i in self.indices]

    def select(self, leaves: Sequence[Any]) -> List[Any]:
        """The filtered sub-list, in canonical leaf order."""
        self._check_arity(leaves)
        return [leaves[i] for i in self.indices]

    def merge(self, full_leaves: Sequence[Any],
              sub_leaves: Sequence[Any]) -> List[Any]:
        """Replace the selected positions of ``full_leaves`` with
        ``sub_leaves`` (a fresh list; inputs untouched)."""
        self._check_arity(full_leaves)
        if len(sub_leaves) != len(self.indices):
            raise ValueError(
                f"filtered payload carries {len(sub_leaves)} leaves, filter "
                f"selects {len(self.indices)}"
            )
        out = list(full_leaves)
        for pos, leaf in zip(self.indices, sub_leaves):
            out[pos] = leaf
        return out

    def select_vector(self, leaves: Sequence[Any]) -> np.ndarray:
        """Flat vector of the selected leaves (the codec substrate when
        C2S compression composes with the filter)."""
        sub = self.select(leaves)
        return np.concatenate([np.ravel(np.asarray(l)) for l in sub])

    def select_from_vector(self, vec: np.ndarray) -> np.ndarray:
        """:meth:`select_vector` over an already-FLAT model vector (the
        version store's format): the selected leaves are fixed slices, so
        no pytree — and no device round-trip — is ever materialized."""
        vec = np.asarray(vec)
        if vec.size != self.total_size:
            raise ValueError(
                f"model vector length {vec.size} does not match the "
                f"filter's template ({self.total_size})"
            )
        parts = []
        for i in self.indices:
            off = self._offsets[i]
            size = int(np.prod(self._shapes[i])) if self._shapes[i] else 1
            parts.append(vec[off:off + size])
        return np.concatenate(parts)

    def split_vector(self, vec: np.ndarray) -> List[np.ndarray]:
        """Inverse of :meth:`select_vector`: slice a filtered flat vector
        back into the selected leaves' shapes/dtypes (from the template
        the filter was built over)."""
        out: List[np.ndarray] = []
        off = 0
        vec = np.asarray(vec)
        for i in self.indices:
            shape, dtype = self._shapes[i], self._dtypes[i]
            size = int(np.prod(shape)) if shape else 1
            out.append(vec[off:off + size].reshape(shape).astype(
                dtype, copy=False))
            off += size
        if off != vec.size:
            raise ValueError(
                f"filtered vector length {vec.size} does not match the "
                f"selected leaves' total size {off}"
            )
        return out

    def meta(self) -> Dict:
        """What the C2S message announces about its filtered payload."""
        return {"pattern": self.pattern, "n_selected": len(self.indices)}

    def _check_arity(self, leaves: Sequence[Any]) -> None:
        if len(leaves) != len(self.names):
            raise ValueError(
                f"payload filter built over {len(self.names)} leaves, got "
                f"{len(leaves)}"
            )


def filter_from_args(args, template_tree: PyTree):
    """The configured filter, or None. One parser for both wire ends."""
    pattern = str(getattr(args, "payload_filter", "") or "")
    if not pattern:
        return None
    return PayloadFilter(pattern, template_tree)
