"""Device-direct S2C delta codec: jit'd kernels + the wire-path facade.

The host :class:`~fedml_tpu.delivery.delta_codec.DeltaCodec` is the
reference implementation; this module moves its hot arithmetic on-device
(ROADMAP "Device-direct wire path"):

- **raw-bit compare / count / last-index** — one fused pass via
  ``lax.bitcast_convert_type`` instead of numpy's compare → nonzero →
  index chain (three full sweeps plus a bool temporary);
- **sparse-exact compaction** — ``jnp.nonzero(mask, size=N)`` with
  power-of-two ``N`` buckets (recompiles are bounded by log2(dim)), values
  gathered in the *bit domain* so NaN payloads and ``-0.0`` survive XLA
  untouched;
- **XOR substrate** for ``xorz`` — computed on device; **zlib stays
  host-side** (DEFLATE is branchy byte-serial Huffman coding, there is no
  XLA story for it) and reads the XORed bits through the buffer protocol;
- **scatter / XOR-apply decode** — ``.at[idx].set()`` on the bitcast view.

Scheme *choice* is delegated to the host codec's
:func:`~fedml_tpu.delivery.delta_codec.plan_frame` over identically-derived
costs, so device frames are **byte-identical** to host frames — every
bitwise trajectory pin and chaos parity leg holds unchanged whichever path
a deployment picks.

Emission is zero-copy: device buffers cross to the frame writer as dlpack
views (``np.from_dlpack``), which the raw-frame writer (``tensor_transport``)
wraps in memoryviews — bytes are touched once, by the final socket write.

Host fallback rules (per encode/decode, accounted as
``comm.wire.host_fallbacks``):

- JAX absent or import-gated → host path for everything;
- 8-byte dtypes → host (x64 is disabled by default; ``uint64`` bitcast is
  unavailable on the device path);
- ``dim == 0`` or ``dim >= 2^31`` → host (the latter also preserves the
  int32 index-overflow guard byte-for-byte: the device path never sees a
  vector it couldn't address).

The :class:`WireCodec` facade owns the knob (``--wire_path host|device|
auto``), the fallback decisions, and the ``comm.wire.*`` telemetry family.
"""

from __future__ import annotations

import time
import zlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..core.mlops import telemetry
from .delta_codec import _BIT_VIEWS, DeltaCodec, _as_host, plan_frame

try:  # pragma: no cover - exercised implicitly by every import site
    import jax
    import jax.numpy as jnp
    from jax import lax

    _HAS_JAX = True
except Exception:  # jax is baked into the image, but stay import-safe
    jax = None  # type: ignore[assignment]
    jnp = None  # type: ignore[assignment]
    lax = None  # type: ignore[assignment]
    _HAS_JAX = False

# itemsize -> device bit dtype; 8 is absent on purpose (x64 disabled)
_DEV_BITS = {1: "uint8", 2: "uint16", 4: "uint32"}


def device_available() -> bool:
    """Whether the device wire path can run at all in this process."""
    return _HAS_JAX


def _accelerator_present() -> bool:
    """A real accelerator backs the default JAX device. On the CPU backend
    the 'device' kernels are an XLA-CPU stand-in that LOSES to the numpy
    reference (its nonzero/scatter lower serially), so ``auto`` only picks
    the device path when the kernels actually run off-host."""
    if not _HAS_JAX:
        return False
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def device_supported(dtype, dim: int) -> bool:
    """Whether (dtype, dim) is addressable by the device kernels."""
    return (_HAS_JAX and 0 < int(dim) < (1 << 31)
            and np.dtype(dtype).itemsize in _DEV_BITS)


def resolve_wire_path(requested: str) -> str:
    """``auto`` resolves to ``device`` only when a real accelerator backs
    JAX (see :func:`_accelerator_present`), else ``host``. An explicit
    ``device`` request always gets the kernels when JAX is importable —
    even on the CPU backend (tests, smoke legs, benches) — and degrades
    loudly (counter) rather than crashing a JAX-less process."""
    requested = str(requested or "auto")
    if requested == "host":
        return "host"
    if requested == "device":
        if not _HAS_JAX:
            telemetry.counter_inc("comm.wire.host_fallbacks")
            return "host"
        return "device"
    return "device" if _accelerator_present() else "host"


def _bits_of(vec):
    """Device bitcast of ``vec`` to the unsigned type of its itemsize."""
    bits = _DEV_BITS[vec.dtype.itemsize]
    if str(vec.dtype) == bits:
        return vec
    return lax.bitcast_convert_type(vec, jnp.dtype(bits))


def _from_bits(bits_vec, dtype):
    if str(bits_vec.dtype) == str(np.dtype(dtype)):
        return bits_vec
    return lax.bitcast_convert_type(bits_vec, jnp.dtype(dtype))


# -- jit'd kernels -----------------------------------------------------------
# All arithmetic happens in the bit domain: gathers/scatters/XORs on uintN
# are exact, so no XLA canonicalization can perturb NaN payloads or -0.0.

def _stats_kernel(base, new):
    """(count, last_changed) of raw-bit-differing entries, one fused pass."""
    mask = _bits_of(base) != _bits_of(new)
    count = jnp.sum(mask, dtype=jnp.int32)
    idx = jnp.arange(mask.shape[0], dtype=jnp.int32)
    last = jnp.max(jnp.where(mask, idx, jnp.int32(-1)))
    return jnp.stack([count, last])


def _xor_kernel(base, new):
    """XOR of the two vectors' raw bits (the ``xorz`` substrate)."""
    return _bits_of(base) ^ _bits_of(new)


def _compact_kernel(base, new, size: int):
    """Sparse-exact compaction: (int32 indices, changed bits) padded to the
    static ``size`` bucket (slice ``[:count]`` host-side)."""
    new_bits = _bits_of(new)
    mask = _bits_of(base) != new_bits
    idx = jnp.nonzero(mask, size=size, fill_value=0)[0].astype(jnp.int32)
    return idx, jnp.take(new_bits, idx)


def _scatter_kernel(base, idx, val_bits):
    """Sparse decode: scatter changed bits into the base, bit-exact."""
    out_bits = _bits_of(base).at[idx].set(val_bits)
    return _from_bits(out_bits, base.dtype)


def _xor_apply_kernel(base, xor_bits):
    """``xorz`` decode: XOR the base's bits with the decompressed mask."""
    return _from_bits(_bits_of(base) ^ xor_bits, base.dtype)


if _HAS_JAX:
    _stats_jit = jax.jit(_stats_kernel)
    # vmap over the stacked-base axis: one dispatch covers E distinct ACKed
    # bases against the same new global (per-cohort fan-out, pull batches)
    _stats_batch_jit = jax.jit(jax.vmap(_stats_kernel, in_axes=(0, None)))
    _xor_jit = jax.jit(_xor_kernel)
    _xor_batch_jit = jax.jit(jax.vmap(_xor_kernel, in_axes=(0, None)))
    _compact_jit = jax.jit(_compact_kernel, static_argnums=2)
    _compact_batch_jit = jax.jit(
        jax.vmap(_compact_kernel, in_axes=(0, None, None)), static_argnums=2)
    _scatter_jit = jax.jit(_scatter_kernel)
    _xor_apply_jit = jax.jit(_xor_apply_kernel)


def host_view(x, scoped=None) -> np.ndarray:
    """Zero-copy host view of a device buffer via dlpack; falls back to a
    materializing transfer (accounted) when the exporter refuses.

    ``scoped`` is a :class:`TelemetryScope`; serving-plane callers pass
    their ``world.telemetry`` so the copy counter lands in the tenant's
    registry (graftiso I002), library callers omit it for the process
    default."""
    if isinstance(x, np.ndarray):
        return x
    try:
        return np.from_dlpack(x)
    except Exception:
        out = np.asarray(x)
        scope = scoped if scoped is not None else telemetry
        scope.counter_inc("comm.wire.host_bytes_copied", float(out.nbytes))
        return out


def _bucket(count: int, dim: int) -> int:
    """Static nonzero size: next power of two ≥ count, capped at dim —
    bounds jit recompiles to log2(dim) shape variants."""
    return min(1 << max(int(count) - 1, 0).bit_length(), int(dim))


class DeviceDeltaCodec:
    """Device-kernel twin of :class:`DeltaCodec` — same frames, same bytes.

    Inputs are device (or device-uploadable) 1-D vectors; outputs are host
    views suitable for the raw-frame writer. ``decode`` returns a DEVICE
    array — the S2C install path feeds it straight to
    ``tree_unflatten_from_vector`` without a host round-trip.
    """

    @staticmethod
    def encode(base_dev, new_dev,
               level: int = 1) -> Tuple[List[np.ndarray], Dict]:
        base = jnp.asarray(base_dev)
        new = jnp.asarray(new_dev)
        if base.shape != new.shape or base.dtype != new.dtype:
            raise ValueError(
                f"device delta codec: base {base.dtype}{base.shape} and new "
                f"{new.dtype}{new.shape} frames disagree"
            )
        dim = int(new.shape[0])
        dtype = np.dtype(str(new.dtype))
        meta: Dict = {"dim": dim, "dtype": dtype.str}
        count, last = (int(v) for v in np.asarray(_stats_jit(base, new)))
        raw_cost = dim * dtype.itemsize
        scheme, xor_comp = plan_frame(
            raw_cost, dtype.itemsize, count, max(last, 0),
            lambda: zlib.compress(host_view(_xor_jit(base, new)), level))
        meta["scheme"] = scheme
        if scheme == "sparse":
            if count == 0:
                return [np.empty(0, np.int32), np.empty(0, dtype)], meta
            idx_d, bits_d = _compact_jit(base, new, _bucket(count, dim))
            return [host_view(idx_d)[:count],
                    host_view(bits_d)[:count].view(dtype)], meta
        if scheme == "xorz":
            return [np.frombuffer(xor_comp, dtype=np.uint8)], meta
        return [host_view(new)], meta

    @staticmethod
    def encode_batch(bases_dev, new_dev,
                     level: int = 1) -> List[Tuple[List[np.ndarray], Dict]]:
        """Encode the same ``new`` against E stacked bases in batched
        dispatches (vmap over the base axis) — one stats launch and one
        compaction launch for the whole cohort instead of E host loops.
        Frames are identical to E sequential :meth:`encode` calls."""
        new = jnp.asarray(new_dev)
        bases = jnp.stack([jnp.asarray(b) for b in bases_dev])
        n_bases = int(bases.shape[0])
        dim = int(new.shape[0])
        dtype = np.dtype(str(new.dtype))
        stats = np.asarray(_stats_batch_jit(bases, new))
        counts = [int(c) for c in stats[:, 0]]
        lasts = [int(v) for v in stats[:, 1]]
        raw_cost = dim * dtype.itemsize

        # one vmapped compaction dispatch sized for the widest sparse frame
        need_compact = [i for i, c in enumerate(counts)
                        if 0 < c * (4 + dtype.itemsize) < raw_cost]
        idx_b = bits_b = None
        if need_compact:
            size = _bucket(max(counts[i] for i in need_compact), dim)
            idx_b, bits_b = _compact_batch_jit(bases, new, size)
        xor_b = None

        out: List[Tuple[List[np.ndarray], Dict]] = []
        for i in range(n_bases):
            count = counts[i]

            def make_xor(i=i):
                nonlocal xor_b
                if xor_b is None:
                    xor_b = _xor_batch_jit(bases, new)
                return zlib.compress(host_view(xor_b[i]), level)

            scheme, xor_comp = plan_frame(
                raw_cost, dtype.itemsize, count, max(lasts[i], 0), make_xor)
            meta = {"dim": dim, "dtype": dtype.str, "scheme": scheme}
            if scheme == "sparse":
                if count == 0:
                    arrays = [np.empty(0, np.int32), np.empty(0, dtype)]
                else:
                    arrays = [host_view(idx_b[i])[:count],
                              host_view(bits_b[i])[:count].view(dtype)]
            elif scheme == "xorz":
                arrays = [np.frombuffer(xor_comp, dtype=np.uint8)]
            else:
                arrays = [host_view(new)]
            out.append((arrays, meta))
        return out

    @staticmethod
    def decode(base_dev, arrays: Sequence[np.ndarray], meta: Dict):
        base = jnp.asarray(base_dev)
        dim = int(meta["dim"])
        dtype = np.dtype(meta["dtype"])
        if base.shape != (dim,) or str(base.dtype) != str(dtype):
            raise ValueError(
                f"device delta codec: base {base.dtype}{base.shape} does not "
                f"match frame ({dtype}, dim {dim})"
            )
        scheme = meta.get("scheme")
        if scheme == "sparse":
            # the uploads ARE the (unavoidable) wire→device crossing; the
            # scatter itself happens in the bit domain on device
            idx = jnp.asarray(_as_host(arrays[0]))
            vals = jnp.asarray(_as_host(arrays[1]))
            return _scatter_jit(base, idx, _bits_of(vals))
        if scheme == "xorz":
            xor = np.frombuffer(zlib.decompress(_as_host(arrays[0])),
                                dtype=_BIT_VIEWS[dtype.itemsize])
            return _xor_apply_jit(base, jnp.asarray(xor))
        if scheme == "raw":
            return jnp.asarray(_as_host(arrays[0]))
        raise ValueError(f"device delta codec: unknown scheme {scheme!r}")


class WireCodec:
    """The wire-path facade every encode/decode call site goes through.

    Owns the resolved ``--wire_path`` choice, the per-call host-fallback
    rules, and the ``comm.wire.*`` telemetry family:

    - ``comm.wire.encode_s`` / ``comm.wire.decode_s`` — per-call histograms;
    - ``comm.wire.device_encodes`` / ``comm.wire.device_decodes`` — calls
      served by the device kernels;
    - ``comm.wire.host_fallbacks`` — device-path calls that had to degrade
      (unsupported dtype/dim, JAX-less process);
    - ``comm.wire.host_bytes_copied`` — bytes materialized by non-dlpack
      transfers (zero on the healthy path).

    Frames out of ``encode`` are byte-identical whichever path serves the
    call — the path knob is a performance choice, never a protocol one
    (``delivery_identity`` excludes it on purpose).
    """

    def __init__(self, path: str = "auto", scoped=None):
        self.requested = str(path or "auto")
        self.path = resolve_wire_path(self.requested)
        # ONE metrics sink: the world-scoped telemetry when a serving-plane
        # owner hands one in (graftiso I002), else the process default —
        # never both (the default scope wraps the same global registry;
        # double-emitting would double-count loopback worlds)
        self._scoped = scoped

    # -- helpers -------------------------------------------------------------

    def _emit(self, name: str, value: float, kind: str = "counter") -> None:
        sink = self._scoped if self._scoped is not None else telemetry
        if kind == "observe":
            sink.observe(name, value)
        else:
            sink.counter_inc(name, value)

    def _use_device(self, dtype, dim: int) -> bool:
        if self.path != "device":
            return False
        if device_supported(dtype, dim):
            return True
        self._emit("comm.wire.host_fallbacks", 1.0)
        return False

    # -- codec surface -------------------------------------------------------

    def encode(self, base_vec, new_vec,
               level: int = 1) -> Tuple[List[np.ndarray], Dict]:
        dim = int(getattr(new_vec, "shape", (len(new_vec),))[0])
        dtype = getattr(new_vec, "dtype", np.dtype(np.float32))
        t0 = time.perf_counter()
        if self._use_device(dtype, dim):
            out = DeviceDeltaCodec.encode(base_vec, new_vec, level=level)
            self._emit("comm.wire.device_encodes", 1.0)
        else:
            out = DeltaCodec.encode(base_vec, new_vec, level=level)
        self._emit("comm.wire.encode_s", time.perf_counter() - t0, "observe")
        return out

    def encode_batch(self, bases, new_vec,
                     level: int = 1) -> List[Tuple[List[np.ndarray], Dict]]:
        """Batched per-cohort encode over distinct ACKed bases. Falls back
        to sequential host encodes off the device path."""
        bases = list(bases)
        if not bases:
            return []
        dim = int(getattr(new_vec, "shape", (len(new_vec),))[0])
        dtype = getattr(new_vec, "dtype", np.dtype(np.float32))
        t0 = time.perf_counter()
        if len(bases) > 1 and self._use_device(dtype, dim):
            out = DeviceDeltaCodec.encode_batch(bases, new_vec, level=level)
            self._emit("comm.wire.device_encodes", float(len(bases)))
        elif self._use_device(dtype, dim):
            out = [DeviceDeltaCodec.encode(bases[0], new_vec, level=level)]
            self._emit("comm.wire.device_encodes", 1.0)
        else:
            out = [DeltaCodec.encode(b, new_vec, level=level) for b in bases]
        self._emit("comm.wire.encode_s", time.perf_counter() - t0, "observe")
        return out

    def decode(self, base_vec, arrays: Sequence[np.ndarray], meta: Dict):
        """Reconstruct the new vector. On the device path the result is a
        DEVICE array (ready for ``tree_unflatten_from_vector``); host path
        returns numpy — both bitwise-identical to the encoded vector."""
        dim = int(meta["dim"])
        dtype = np.dtype(meta["dtype"])
        t0 = time.perf_counter()
        if self._use_device(dtype, dim):
            out = DeviceDeltaCodec.decode(base_vec, arrays, meta)
            self._emit("comm.wire.device_decodes", 1.0)
        else:
            out = DeltaCodec.decode(host_view(base_vec) if not isinstance(
                base_vec, np.ndarray) else base_vec, arrays, meta)
        self._emit("comm.wire.decode_s", time.perf_counter() - t0, "observe")
        return out
