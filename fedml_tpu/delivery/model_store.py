"""Version-indexed reference store for global model vectors.

reference: the FedBuff line of work (Nguyen et al., AISTATS 2022) assumes a
server that can reconstruct "the global the client trained from" for any
update it is still willing to fold — without that, update compression and
asynchrony are mutually exclusive (a delta only decodes against its exact
base). The reference FedML framework keeps exactly one global in memory and
therefore refuses the combination; so did this repo's server until ISSUE 9
(``cross_silo/server_manager.py`` raised on ``async`` × ``--compression``).

:class:`VersionedModelStore` is that reconstruction capability as a small,
thread-safe object: a bounded ring of the last ``capacity`` committed global
vectors keyed by **server version** (= the round index every dispatch is
already tagged with), each entry carrying a content digest. Both wire ends
hold one — the server for decoding C2S update deltas against the client's
tagged base, the client for decoding S2C sync deltas against the global it
last acknowledged. Eviction is oldest-version-first and *accounted*
(``comm.delta.store_evictions``): an evicted base is a loud full-frame
fallback on the S2C side and a drop-with-resync on the C2S side, never a
silent corruption.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.mlops import telemetry


def vector_digest(vec: np.ndarray) -> str:
    """Content digest of a stored vector (dtype + bytes, sha256[:16])."""
    h = hashlib.sha256()
    h.update(str(vec.dtype.str).encode())
    h.update(np.ascontiguousarray(vec).tobytes())
    return h.hexdigest()[:16]


class VersionedModelStore:
    """Bounded ring of global model vectors keyed by server version.

    ``put`` is idempotent per version (re-dispatching a version after a
    resume re-stores the same bytes); capacity overflow evicts the OLDEST
    versions — deltas are only ever requested against recent history, and
    an evicted base must surface as an accounted fallback, not unbounded
    memory. ``get`` counts hits/misses so the delta hit rate is readable
    from telemetry alone (``fedml_tpu top``).

    ``metric_prefix`` namespaces the counters per wire end
    (``comm.delta.server_store.*`` vs ``comm.delta.client_store.*``): in
    loopback worlds both ends share one process-wide registry.

    The device wire path (``delivery/device_codec.py``) additionally keeps a
    **device-resident copy of ring heads**: :meth:`get_device` uploads a
    version's vector at most once and every subsequent encode against that
    base reads the cached device buffer — bases never re-upload per fan-out.
    ``put(..., device=...)`` seeds the cache directly with a buffer the
    caller already holds on device (the server stores the global it just
    encoded with). Eviction drops the device copy with the host entry.
    """

    def __init__(self, capacity: int = 8,
                 metric_prefix: str = "comm.delta.store"):
        if int(capacity) < 1:
            raise ValueError(
                f"delta_store_versions must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.metric_prefix = str(metric_prefix)
        self._lock = threading.Lock()
        self._entries: Dict[int, Tuple[np.ndarray, str]] = {}
        self._device: Dict[int, object] = {}
        self._evictions = 0

    # -- write side ---------------------------------------------------------

    def put(self, version: int, vec, device=None) -> str:
        """Store ``vec`` under ``version``; returns the content digest.
        Oldest versions beyond ``capacity`` are evicted and counted.
        ``device`` optionally seeds the device-resident cache with an
        already-uploaded copy of the same vector."""
        version = int(version)
        vec = np.array(np.asarray(vec), copy=True)  # detach from wire views
        digest = vector_digest(vec)
        evicted = 0
        with self._lock:
            self._entries[version] = (vec, digest)
            if device is not None:
                self._device[version] = device
            while len(self._entries) > self.capacity:
                oldest = min(self._entries)
                del self._entries[oldest]
                self._device.pop(oldest, None)
                evicted += 1
            self._evictions += evicted
            occupancy = len(self._entries)
        telemetry.counter_inc(f"{self.metric_prefix}.puts")
        if evicted:
            telemetry.counter_inc(f"{self.metric_prefix}.evictions", evicted)
        telemetry.gauge_set(f"{self.metric_prefix}.occupancy",
                            float(occupancy))
        return digest

    # -- read side ----------------------------------------------------------

    def get(self, version) -> Optional[np.ndarray]:
        """The stored vector for ``version`` (or None), counting the
        hit/miss. The array is the stored instance — READ-ONLY by contract
        (decoders copy before mutating)."""
        if version is None:
            telemetry.counter_inc(f"{self.metric_prefix}.misses")
            return None
        with self._lock:
            entry = self._entries.get(int(version))
        if entry is None:
            telemetry.counter_inc(f"{self.metric_prefix}.misses")
            return None
        telemetry.counter_inc(f"{self.metric_prefix}.hits")
        return entry[0]

    def get_device(self, version):
        """Device-resident copy of the stored vector for ``version`` (or
        None) — uploaded AT MOST ONCE per version, then served from the
        cache so encode bases never re-cross the host/device boundary.
        Same READ-ONLY contract (and hit/miss accounting) as :meth:`get`.
        Falls back to the host array when JAX is unavailable."""
        if version is None:
            telemetry.counter_inc(f"{self.metric_prefix}.misses")
            return None
        version = int(version)
        with self._lock:
            dev = self._device.get(version)
            entry = self._entries.get(version)
        if dev is not None:
            telemetry.counter_inc(f"{self.metric_prefix}.hits")
            return dev
        if entry is None:
            telemetry.counter_inc(f"{self.metric_prefix}.misses")
            return None
        try:
            import jax.numpy as jnp
            dev = jnp.asarray(entry[0])
            telemetry.counter_inc(f"{self.metric_prefix}.device_uploads")
        except Exception:
            dev = entry[0]
        with self._lock:
            # only cache if the version is still resident (racing eviction)
            if version in self._entries:
                self._device[version] = dev
        telemetry.counter_inc(f"{self.metric_prefix}.hits")
        return dev

    def has(self, version) -> bool:
        with self._lock:
            return int(version) in self._entries if version is not None \
                else False

    def digest(self, version: int) -> Optional[str]:
        with self._lock:
            entry = self._entries.get(int(version))
        return None if entry is None else entry[1]

    def versions(self) -> List[int]:
        with self._lock:
            return sorted(self._entries)

    def latest(self) -> Optional[int]:
        with self._lock:
            return max(self._entries) if self._entries else None

    def occupancy(self) -> int:
        with self._lock:
            return len(self._entries)

    def evictions(self) -> int:
        with self._lock:
            return self._evictions
