"""Lossless S2C delta wire codec.

The C2S direction already had a (lossy) update codec
(``core/compression.UpdateCodec``) — clients ship sparse/quantized deltas of
what they *trained*. The S2C direction is different: the broadcast global is
**shared reference state**. A lossy sync would make every client hold a
slightly different "version r", and every subsequent C2S delta would decode
against a base the server doesn't have. So the S2C codec here is lossless
*by construction* — ``decode(base, encode(base, new)) == new`` bit for bit —
which is also what keeps delta shipping on by default without perturbing any
bitwise trajectory pin.

Two frame schemes, chosen per message by measured size:

- ``sparse`` — int32 indices + exact values of the entries whose RAW BITS
  changed (bit comparison, so ``-0.0`` vs ``0.0`` and NaN payloads survive).
  When the C2S direction runs top-k compression, the aggregated global delta
  has support bounded by (cohort × k) — the S2C delta is then *exactly*
  sparse and this frame is an order of magnitude smaller than the vector.
- ``xorz`` — zlib over the XOR of the two vectors' raw bits. Dense updates
  still compress (unchanged exponent/sign bytes XOR to zero runs).

Whichever is smaller wins; if neither beats the raw vector the codec
returns a ``raw`` frame (the full new vector) — never larger than the
full-model message it replaces, modulo a few header bytes.

NOTE (ROADMAP device-direct wire path): this codec runs on HOST — every
``np.asarray`` below is a device→host materialization that graftshard
S004's delivery-plane prong flags. The sparse-exact scatter and XOR paths
are elementwise and trivially jit-able; until they move on-device the
host sites carry per-line ``graftshard: disable=S004`` allowances so the
round-trip inventory stays visible in the source without blocking tier-1.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

# message param carrying the delta frame description (base version etc.);
# absent = a plain full-model frame
DELTA_KEY = "__s2c_delta__"

_BIT_VIEWS = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _bits(vec: np.ndarray) -> np.ndarray:
    """The vector's raw bits as an unsigned-int view (exact comparison /
    XOR substrate; float equality would merge -0.0/0.0 and break NaN)."""
    view = _BIT_VIEWS.get(vec.dtype.itemsize)
    if view is None:
        raise ValueError(
            f"delta codec: unsupported itemsize {vec.dtype.itemsize} "
            f"({vec.dtype})"
        )
    return np.ascontiguousarray(vec).view(view)


def payload_nbytes(arrays: Sequence[np.ndarray]) -> int:
    return int(sum(int(np.asarray(a).nbytes) for a in arrays))


class DeltaCodec:
    """Stateless lossless delta encode/decode over flat model vectors."""

    @staticmethod
    def encode(base_vec, new_vec,
               level: int = 1) -> Tuple[List[np.ndarray], Dict]:
        """``(base, new) -> (arrays, meta)``; reconstruction is bitwise."""
        base = np.asarray(base_vec)  # graftshard: disable=S004 (host codec until device-direct)
        new = np.asarray(new_vec)  # graftshard: disable=S004 (host codec until device-direct)
        if base.shape != new.shape or base.dtype != new.dtype:
            raise ValueError(
                f"delta codec: base {base.dtype}{base.shape} and new "
                f"{new.dtype}{new.shape} frames disagree"
            )
        meta: Dict = {"dim": int(new.shape[0]), "dtype": new.dtype.str}
        base_bits = _bits(base)
        new_bits = _bits(new)
        changed = np.nonzero(base_bits != new_bits)[0]
        raw_cost = int(new.nbytes)
        sparse_cost = int(changed.size) * (4 + new.dtype.itemsize)
        if changed.size and changed[-1] >= (1 << 31):
            sparse_cost = raw_cost + 1  # int32 indices can't address it
        xor_comp = None
        if sparse_cost >= raw_cost // 2:
            # dense-ish delta: XOR bits + zlib (zero runs where bytes agree)
            xor_comp = zlib.compress(
                (base_bits ^ new_bits).tobytes(), level)
        if sparse_cost < raw_cost and (
                xor_comp is None or sparse_cost <= len(xor_comp)):
            meta["scheme"] = "sparse"
            return [changed.astype(np.int32),
                    np.ascontiguousarray(new[changed])], meta
        if xor_comp is not None and len(xor_comp) < raw_cost:
            meta["scheme"] = "xorz"
            return [np.frombuffer(xor_comp, dtype=np.uint8)], meta
        meta["scheme"] = "raw"
        return [np.ascontiguousarray(new)], meta

    @staticmethod
    def decode(base_vec, arrays: Sequence[np.ndarray],
               meta: Dict) -> np.ndarray:
        """Reconstruct the new vector — bitwise — from ``base`` + frame."""
        base = np.asarray(base_vec)  # graftshard: disable=S004 (host codec until device-direct)
        dim = int(meta["dim"])
        dtype = np.dtype(meta["dtype"])
        if base.shape != (dim,) or base.dtype != dtype:
            raise ValueError(
                f"delta codec: base {base.dtype}{base.shape} does not match "
                f"frame ({dtype}, dim {dim})"
            )
        scheme = meta.get("scheme")
        if scheme == "sparse":
            out = np.array(base, copy=True)
            idx = np.asarray(arrays[0])  # graftshard: disable=S004 (host codec until device-direct)
            out[idx] = np.asarray(arrays[1])  # graftshard: disable=S004 (host codec until device-direct)
            return out
        if scheme == "xorz":
            frame = np.asarray(arrays[0])  # graftshard: disable=S004 (host codec until device-direct)
            comp = np.ascontiguousarray(frame).tobytes()
            xor = np.frombuffer(zlib.decompress(comp),
                                dtype=_BIT_VIEWS[dtype.itemsize])
            return (_bits(base) ^ xor).view(dtype)
        if scheme == "raw":
            out = np.asarray(arrays[0])  # graftshard: disable=S004 (host codec until device-direct)
            return np.array(out, copy=True)
        raise ValueError(f"delta codec: unknown scheme {scheme!r}")
