"""Lossless S2C delta wire codec (host reference implementation).

The C2S direction already had a (lossy) update codec
(``core/compression.UpdateCodec``) — clients ship sparse/quantized deltas of
what they *trained*. The S2C direction is different: the broadcast global is
**shared reference state**. A lossy sync would make every client hold a
slightly different "version r", and every subsequent C2S delta would decode
against a base the server doesn't have. So the S2C codec here is lossless
*by construction* — ``decode(base, encode(base, new)) == new`` bit for bit —
which is also what keeps delta shipping on by default without perturbing any
bitwise trajectory pin.

Two frame schemes, chosen per message by measured size:

- ``sparse`` — int32 indices + exact values of the entries whose RAW BITS
  changed (bit comparison, so ``-0.0`` vs ``0.0`` and NaN payloads survive).
  When the C2S direction runs top-k compression, the aggregated global delta
  has support bounded by (cohort × k) — the S2C delta is then *exactly*
  sparse and this frame is an order of magnitude smaller than the vector.
- ``xorz`` — zlib over the XOR of the two vectors' raw bits. Dense updates
  still compress (unchanged exponent/sign bytes XOR to zero runs).

Whichever is smaller wins; if neither beats the raw vector the codec
returns a ``raw`` frame (the full new vector) — never larger than the
full-model message it replaces, modulo a few header bytes.

The scheme decision itself lives in :func:`plan_frame` so the device codec
(``delivery/device_codec.py``) picks the SAME scheme from the SAME measured
costs — frames are byte-identical across wire paths by construction, not by
testing luck. This module stays pure-numpy: it is the reference
implementation, the fallback for dtypes/dims the device path can't address
(8-byte scalars without x64, dim ≥ 2^31), and the only path when JAX is
absent. Every conversion funnels through :func:`_as_host`, which is a
zero-copy no-op for the C-contiguous host vectors the call sites hand in —
there are no hidden device→host round-trips left here (graftshard S004's
delivery prong verifies that; this file carries no allowances).
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# message param carrying the delta frame description (base version etc.);
# absent = a plain full-model frame
DELTA_KEY = "__s2c_delta__"

_BIT_VIEWS = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _as_host(a) -> np.ndarray:
    """Zero-copy host view of ``a``.

    Call-site contract: encode/decode inputs are already C-contiguous host
    vectors (flatten_leaves output, store rings, decoded wire frames), so
    for the hot path this returns its argument unchanged. Anything else
    (lists in tests, CPU-backed jax arrays) falls through numpy's
    buffer-protocol conversion, which only copies when it must.
    """
    if isinstance(a, np.ndarray) and a.flags.c_contiguous:
        return a
    return np.ascontiguousarray(a)


def _bits(vec: np.ndarray) -> np.ndarray:
    """The vector's raw bits as an unsigned-int view (exact comparison /
    XOR substrate; float equality would merge -0.0/0.0 and break NaN)."""
    view = _BIT_VIEWS.get(vec.dtype.itemsize)
    if view is None:
        raise ValueError(
            f"delta codec: unsupported itemsize {vec.dtype.itemsize} "
            f"({vec.dtype})"
        )
    return _as_host(vec).view(view)


def payload_nbytes(arrays: Sequence) -> int:
    """Total wire bytes of a frame list, computed from shape/dtype metadata
    only — runs per frame on the hot path, so it must not touch (let alone
    materialize) array data."""
    total = 0
    for a in arrays:
        nbytes = getattr(a, "nbytes", None)
        total += int(nbytes) if nbytes is not None else len(a)
    return total


def plan_frame(raw_cost: int, itemsize: int, count: int, last_changed: int,
               make_xor_comp: Callable[[], bytes],
               ) -> Tuple[str, Optional[bytes]]:
    """The codec's scheme decision, shared verbatim by host and device paths.

    ``count`` is the number of raw-bit-changed entries, ``last_changed`` the
    highest changed index (ignored when count == 0). ``make_xor_comp`` lazily
    produces the zlib-compressed XOR payload — only invoked when the sparse
    frame isn't already a clear win, exactly mirroring the historical host
    control flow so the chosen scheme (and bytes) never shifts.

    Returns ``(scheme, xor_comp)`` with ``xor_comp`` the compressed payload
    when scheme == "xorz" (and possibly-populated scratch otherwise).
    """
    sparse_cost = count * (4 + itemsize)
    if count and last_changed >= (1 << 31):
        sparse_cost = raw_cost + 1  # int32 indices can't address it
    xor_comp = None
    if sparse_cost >= raw_cost // 2:
        # dense-ish delta: XOR bits + zlib (zero runs where bytes agree)
        xor_comp = make_xor_comp()
    if sparse_cost < raw_cost and (
            xor_comp is None or sparse_cost <= len(xor_comp)):
        return "sparse", xor_comp
    if xor_comp is not None and len(xor_comp) < raw_cost:
        return "xorz", xor_comp
    return "raw", xor_comp


class DeltaCodec:
    """Stateless lossless delta encode/decode over flat model vectors."""

    @staticmethod
    def encode(base_vec, new_vec,
               level: int = 1) -> Tuple[List[np.ndarray], Dict]:
        """``(base, new) -> (arrays, meta)``; reconstruction is bitwise."""
        base = _as_host(base_vec)
        new = _as_host(new_vec)
        if base.shape != new.shape or base.dtype != new.dtype:
            raise ValueError(
                f"delta codec: base {base.dtype}{base.shape} and new "
                f"{new.dtype}{new.shape} frames disagree"
            )
        meta: Dict = {"dim": int(new.shape[0]), "dtype": new.dtype.str}
        base_bits = _bits(base)
        new_bits = _bits(new)
        changed = np.nonzero(base_bits != new_bits)[0]
        raw_cost = int(new.nbytes)
        last = int(changed[-1]) if changed.size else 0
        scheme, xor_comp = plan_frame(
            raw_cost, new.dtype.itemsize, int(changed.size), last,
            # zlib takes the XOR array via the buffer protocol — the bytes
            # out are identical to compressing a materialized copy
            lambda: zlib.compress(base_bits ^ new_bits, level))
        meta["scheme"] = scheme
        if scheme == "sparse":
            # fancy indexing already yields fresh C-contiguous arrays
            return [changed.astype(np.int32), new[changed]], meta
        if scheme == "xorz":
            return [np.frombuffer(xor_comp, dtype=np.uint8)], meta
        return [new], meta

    @staticmethod
    def decode(base_vec, arrays: Sequence[np.ndarray],
               meta: Dict) -> np.ndarray:
        """Reconstruct the new vector — bitwise — from ``base`` + frame."""
        base = _as_host(base_vec)
        dim = int(meta["dim"])
        dtype = np.dtype(meta["dtype"])
        if base.shape != (dim,) or base.dtype != dtype:
            raise ValueError(
                f"delta codec: base {base.dtype}{base.shape} does not match "
                f"frame ({dtype}, dim {dim})"
            )
        scheme = meta.get("scheme")
        if scheme == "sparse":
            out = base.copy()
            out[_as_host(arrays[0])] = _as_host(arrays[1])
            return out
        if scheme == "xorz":
            # zlib.decompress reads the (uint8, always-aligned) frame view
            # through the buffer protocol — no intermediate bytes object
            xor = np.frombuffer(zlib.decompress(_as_host(arrays[0])),
                                dtype=_BIT_VIEWS[dtype.itemsize])
            return (_bits(base) ^ xor).view(dtype)
        if scheme == "raw":
            out = _as_host(arrays[0])
            if out.base is None and out.flags.writeable:
                return out  # frame owns its buffer: adopt it, no copy
            return out.copy()
        raise ValueError(f"delta codec: unknown scheme {scheme!r}")
