"""Delta delivery plane: version-indexed model store + bidirectional delta
shipping (ISSUE 9 tentpole — docs/delivery.md).

Round traffic before this package was full model pytrees in both directions.
The pieces here make the wire carry *changes* instead:

- :class:`~fedml_tpu.delivery.model_store.VersionedModelStore` — a bounded
  ring of the last V committed global vectors keyed by server version
  (= round index, version-tagged on every dispatch since the async traffic
  plane), with content digests and eviction accounting. Both ends of the
  wire hold one: the server decodes compressed C2S deltas against the
  version the client actually trained from (closing the async×compression
  refusal), and the client decodes S2C delta frames against the global it
  last acknowledged.
- :class:`~fedml_tpu.delivery.delta_codec.DeltaCodec` — the S2C delta wire
  format. LOSSLESS by construction (sparse-exact scatter or XOR+zlib over
  the raw bits), so a delta-shipped sync is bitwise-identical to a full
  broadcast — which is what lets delta shipping default on without touching
  any trajectory pin.
- :class:`~fedml_tpu.delivery.device_codec.WireCodec` — the wire-path
  facade over the host codec and its jit'd device twin
  (:class:`~fedml_tpu.delivery.device_codec.DeviceDeltaCodec`). The
  ``--wire_path host|device|auto`` knob is a PERFORMANCE choice only:
  device frames are byte-identical to host frames (shared
  :func:`~fedml_tpu.delivery.delta_codec.plan_frame` scheme decision), so
  the knob is deliberately excluded from :func:`delivery_identity`.
- :class:`~fedml_tpu.delivery.payload_filter.PayloadFilter` — adapter-only
  payloads: a regex over named pytree leaves (the
  ``scale/partition_rules`` naming) selects which leaves ride the C2S wire;
  everything else is frozen at the server's global. LoRA/adapter FedLLM
  rounds ship ~0.1% of weights this way.

Telemetry rides the ``comm.delta.*`` family (docs/telemetry.md); the store
and codec configuration are run-ledger ``run_meta`` identity
(:func:`delivery_identity`), so resuming a federation under a different
delivery configuration is refused.
"""

from __future__ import annotations

from .delta_codec import DeltaCodec
from .device_codec import DeviceDeltaCodec, WireCodec, resolve_wire_path
from .model_store import VersionedModelStore
from .payload_filter import PayloadFilter

__all__ = [
    "DeltaCodec",
    "DeviceDeltaCodec",
    "PayloadFilter",
    "VersionedModelStore",
    "WireCodec",
    "delivery_identity",
    "flatten_leaves",
    "resolve_wire_path",
]


def flatten_leaves(leaves):
    """Host-side flatten of pytree leaves into ONE numpy vector (canonical
    leaf order). The wire plane's counterpart of
    ``utils.tree.tree_flatten_to_vector`` — deliberately numpy, so
    serializing a model for dispatch never round-trips it through a
    device buffer. The single definition every store put and delta encode
    uses: server and client vectors can only agree if they flatten the
    same way."""
    import numpy as np

    arrs = [np.ravel(np.asarray(l)) for l in leaves]
    if not arrs:
        return np.zeros((0,), np.float32)
    if len(arrs) == 1:
        # single-leaf models: ravel is already a view — skip the
        # concatenate, which would copy the whole vector unconditionally
        return np.ascontiguousarray(arrs[0])
    return np.concatenate(arrs)


def delivery_identity(args):
    """The trajectory-affecting delivery configuration, as run-ledger
    ``run_meta`` identity — or None when the delivery plane runs in its
    default lossless shape (plain worlds keep the pre-delta ledger format,
    so old checkpoints keep resuming).

    Lossy C2S compression and the adapter filter change what the
    aggregation ever sees, and the store depth decides which stale deltas
    are even decodable — resuming a checkpoint under a different value of
    any of these is a different federation.
    """
    scheme = str(getattr(args, "compression", "") or "").lower()
    pattern = str(getattr(args, "payload_filter", "") or "")
    if not scheme and not pattern:
        return None
    ident = {
        "store_versions": int(getattr(args, "delta_store_versions", 8) or 8),
    }
    if scheme:
        ident["compression"] = scheme
        ident["compression_ratio"] = float(
            getattr(args, "compression_ratio", 0.1))
        if scheme == "quantize":
            ident["quantize_bits"] = int(getattr(args, "quantize_bits", 8))
        if scheme == "qsgd":
            ident["qsgd_levels"] = int(getattr(args, "qsgd_levels", 256))
    if pattern:
        ident["payload_filter"] = pattern
    return ident
