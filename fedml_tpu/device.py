"""Device & mesh discovery — the TPU-native replacement for the reference's
``python/fedml/device/device.py:51-166`` (process→GPU mapping via yaml files).

On TPU there is no per-process GPU mapping to manage: JAX exposes all local
chips, and parallelism is expressed as a `jax.sharding.Mesh` over them. This
module is the single place that builds meshes for the three runtimes:

- simulation "sp": a trivial 1-device context (reference: device.py:52-60)
- simulation "mesh": a 1-D ``clients`` mesh over all chips (replaces
  gpu_mapping_mpi.py — FL clients become shards of a mesh axis)
- distributed "Cheetah": an N-D mesh (data/fsdp/tensor/sequence/...) built from
  ``args.mesh_shape``
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from . import constants

logger = logging.getLogger(__name__)


def device_kind() -> str:
    return jax.devices()[0].platform


def get_device(args=None):
    """Return the default device (reference API: ``fedml.device.get_device``).

    Honors ``args.device_type`` ("auto" | "tpu" | "cpu"): a non-auto value
    selects that JAX platform explicitly (reference analog: device.py:52-60's
    cpu/gpu/mps dispatch).
    """
    device_type = getattr(args, "device_type", "auto") if args is not None else "auto"
    if device_type and device_type != "auto":
        return jax.devices(device_type)[0]
    return jax.devices()[0]


def build_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh from ``{axis_name: size}``.

    If ``axis_sizes`` is empty/None, builds a 1-D ``clients`` mesh over all
    devices. Sizes may include one ``-1`` entry meaning "all remaining devices".
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {constants.MESH_AXIS_CLIENTS: n}
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one -1 axis size allowed")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known != 0:
            raise ValueError(f"cannot infer -1 axis: {n} devices, known product {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def get_mesh(args) -> Mesh:
    """Mesh for a config namespace (replaces device.py:51-166 dispatch)."""
    axis_sizes = args.parse_mesh_shape() if args is not None else {}
    mesh = build_mesh(axis_sizes)
    logger.info(
        "mesh: %s over %d %s device(s)",
        dict(zip(mesh.axis_names, mesh.devices.shape)),
        mesh.devices.size,
        device_kind(),
    )
    return mesh
