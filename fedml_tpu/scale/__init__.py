"""``fedml_tpu.scale`` — the million-client cohort substrate.

Decouples federated population size N from device memory (ROADMAP
"Million-client simulation substrate"): a compact packed client registry
with on-device seeded K-of-N sampling (``registry.py``), a double-buffered
host→HBM shard prefetcher that streams only the sampled cohort's data
(``prefetch.py``), regex-over-named-pytree partition rules generalizing
the mesh path's sharding (``partition_rules.py``), and the engine gluing
them into the sp/mesh FedAvg loops (``cohort_engine.py``).

Enable with ``--client_registry N`` (or a saved registry path) and
``--cohort_size K``; see ``docs/scale.md``.
"""

from .cohort_engine import CohortEngine, build_cohort_engine
from .partition_rules import (
    DEFAULT_COHORT_RULES,
    DEFAULT_STATE_RULES,
    make_shardings,
    match_partition_rules,
    named_tree_map,
    named_tree_paths,
    parse_partition_rules,
)
from .prefetch import ShardPrefetcher, cohort_key
from .registry import ClientRegistry

__all__ = [
    "ClientRegistry",
    "CohortEngine",
    "ShardPrefetcher",
    "build_cohort_engine",
    "cohort_key",
    "DEFAULT_COHORT_RULES",
    "DEFAULT_STATE_RULES",
    "make_shardings",
    "match_partition_rules",
    "named_tree_map",
    "named_tree_paths",
    "parse_partition_rules",
]
