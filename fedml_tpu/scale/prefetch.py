"""Double-buffered host→HBM shard prefetcher for streamed cohorts.

Registry-scale federations (``registry.py``) never hold the population's
data resident: each round materializes ONLY the sampled cohort's shards.
Done naively that serializes ``host gather → device_put → train`` every
round and the accelerator idles through the I/O. This prefetcher overlaps
them: while round *r* trains on device, a worker thread gathers and places
round *r+1*'s shards, so a steady-state round finds its inputs already in
HBM — the classic double-buffered input pipeline, applied to FL cohorts.

Contract (pinned by ``tests/test_scale.py``):

- **Never blocks the round beyond its own data.** ``schedule`` is
  non-blocking; ``take`` waits only for the buffer it asked for (and
  gathers synchronously on a miss — a cold start costs one gather, never a
  deadlock).
- **Never serves a stale shard.** Buffers are keyed by a digest of the
  exact cohort row indices; ``take`` with a different cohort than what was
  scheduled is a counted miss + fresh gather, not a wrong answer.
- **Bounded memory.** At most ``depth`` prefetched cohorts are in flight
  or parked; older unclaimed buffers are evicted (counted).

Telemetry (all under the ``io.`` family, zero-cost when the registry/
prefetcher is off):

    io.prefetch_requests / hits / misses / stale_drops / errors
    io.prefetch_bytes      bytes placed ahead of demand
    io.prefetch_gather_s   seconds the worker spent gathering+placing
    io.prefetch_wait_s     seconds ``take`` blocked on an unfinished buffer

Overlap fraction = 1 - wait/gather (see :meth:`ShardPrefetcher.stats`):
1.0 means every gather fully hid behind device compute; 0 means the
pipeline is I/O-bound end-to-end. The million-client bench leg reports it.
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..core.mlops import telemetry

logger = logging.getLogger(__name__)

GatherFn = Callable[[], Any]


def cohort_key(cohort: np.ndarray) -> str:
    """Digest of the exact cohort rows — the staleness-proof buffer key."""
    a = np.ascontiguousarray(np.asarray(cohort, np.int64))
    return hashlib.sha256(a.tobytes()).hexdigest()[:24]


class ShardPrefetcher:
    """Background gather of the next cohort's shards into device memory.

    ``depth`` bounds the number of prefetched cohorts held at once
    (1 = classic double buffering). ``depth=0`` disables the thread
    entirely — ``take`` degrades to synchronous gathering with the same
    API, so callers never branch.
    """

    def __init__(self, depth: int = 1, name: str = "cohort"):
        self.depth = max(int(depth), 0)
        self.name = str(name)
        # all cross-thread state lives behind this Condition (its lock):
        # _slots maps key -> ("pending" | "ready" | "error", value, order)
        self._lock = threading.Condition()
        self._slots: Dict[str, Tuple[str, Any, int]] = {}
        self._order = 0
        self._work: "queue.Queue[Optional[Tuple[str, GatherFn]]]" = \
            queue.Queue()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gather_s = 0.0  # guarded by _lock
        self._wait_s = 0.0    # guarded by _lock

    # -- worker --------------------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None or self.depth == 0:
                return
            self._thread = threading.Thread(
                target=self._run, name=f"prefetch-{self.name}", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            item = self._work.get()
            if item is None:
                break
            key, gather = item
            t0 = time.perf_counter()
            try:
                value = gather()
                status = "ready"
            except Exception as e:  # served as a counted miss by take()
                telemetry.counter_inc("io.prefetch_errors")
                logger.warning("prefetch %s: gather failed: %s", key, e)
                value, status = e, "error"
            dt = time.perf_counter() - t0
            with self._lock:
                if status == "ready":
                    # errored gathers must not count as hidden I/O: the
                    # take() fallback will do (and account) the real work,
                    # so crediting the failed attempt would inflate the
                    # overlap fraction
                    self._gather_s += dt
                if key in self._slots:  # not evicted while gathering
                    self._slots[key] = (status, value, self._slots[key][2])
                self._lock.notify_all()
            if status == "ready":
                telemetry.counter_inc("io.prefetch_gather_s", dt)
                telemetry.counter_inc(
                    "io.prefetch_bytes", _nbytes(value)
                )

    # -- API -----------------------------------------------------------------

    def schedule(self, key: str, gather: GatherFn) -> bool:
        """Queue a background gather for ``key``. Returns False when the
        prefetcher is off, the key is already in flight/ready, or the
        buffer budget is full after eviction of the oldest unclaimed
        entry."""
        if self.depth == 0 or self._stop_evt.is_set():
            return False
        self._ensure_thread()
        with self._lock:
            if key in self._slots:
                return False
            while len(self._slots) >= self.depth:
                oldest = min(self._slots, key=lambda k: self._slots[k][2])
                if self._slots[oldest][0] == "pending":
                    # never race the worker for an in-flight gather; the
                    # caller retries next round
                    return False
                del self._slots[oldest]
                telemetry.counter_inc("io.prefetch_stale_drops")
            self._order += 1
            self._slots[key] = ("pending", None, self._order)
        self._work.put((key, gather))
        telemetry.counter_inc("io.prefetch_requests")
        return True

    def take(self, key: str, gather: GatherFn) -> Any:
        """The shards for ``key``: the prefetched buffer when one matches
        (waiting out an in-flight gather), else a synchronous gather."""
        telemetry.counter_inc("io.prefetch_takes")
        if self.depth == 0:
            return self._sync_gather(gather)
        with self._lock:
            entry = self._slots.get(key)
            if entry is None:
                telemetry.counter_inc("io.prefetch_misses")
            else:
                t0 = time.perf_counter()
                while self._slots.get(key, ("gone",))[0] == "pending":
                    self._lock.wait(timeout=0.5)
                    if self._stop_evt.is_set():
                        break
                waited = time.perf_counter() - t0
                self._wait_s += waited
                if waited > 1e-9:
                    telemetry.counter_inc("io.prefetch_wait_s", waited)
                entry = self._slots.pop(key, None)
                if entry is not None and entry[0] == "ready":
                    telemetry.counter_inc("io.prefetch_hits")
                    return entry[1]
                telemetry.counter_inc("io.prefetch_misses")
        return self._sync_gather(gather)

    def _sync_gather(self, gather: GatherFn) -> Any:
        """On-demand gather: its full latency is exposed (counts as wait)."""
        t0 = time.perf_counter()
        value = gather()
        dt = time.perf_counter() - t0
        with self._lock:
            self._gather_s += dt
            self._wait_s += dt
        telemetry.counter_inc("io.prefetch_gather_s", dt)
        telemetry.counter_inc("io.prefetch_wait_s", dt)
        return value

    def stats(self) -> Dict[str, float]:
        """Lifetime gather/wait seconds and the overlap fraction
        (``1 - wait/gather``: the share of I/O hidden behind compute)."""
        with self._lock:
            gather_s, wait_s = self._gather_s, self._wait_s
        overlap = 0.0
        if gather_s > 1e-12:
            overlap = max(0.0, min(1.0, 1.0 - wait_s / gather_s))
        return {"gather_s": gather_s, "wait_s": wait_s,
                "overlap_fraction": overlap}

    def stop(self) -> None:
        """Stop the worker and drop all buffers (idempotent)."""
        self._stop_evt.set()
        self._work.put(None)
        t = None
        with self._lock:
            t = self._thread
            self._thread = None
            self._slots.clear()
            self._lock.notify_all()
        if t is not None:
            t.join(timeout=5.0)


def _nbytes(value: Any) -> int:
    total = 0
    try:
        import jax

        for leaf in jax.tree.leaves(value):
            total += int(getattr(leaf, "nbytes", 0) or 0)
    except Exception:
        pass
    return total
