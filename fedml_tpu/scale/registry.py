"""Packed client registry: population size N decoupled from device memory.

The sp/mesh simulators keep the whole client population's DATA resident
(HBM or host RAM) and sample cohorts by indexing it — which caps N at
whatever the packed ``[clients, cap, ...]`` arrays fit, ~100 clients for
real shapes. Production FL populations are millions of devices of which a
cohort of thousands participates per round (Bonawitz et al., MLSys 2019;
Papaya/FedBuff-style async serving), and large-population benchmarking
(FedScale) works the same way: a compact per-client RECORD array scales to
N, the data plane only ever materializes the sampled cohort.

This module is that record array. Four packed columns over N registered
clients (ids are implicit ``0..N-1``):

    weight        f32[N]  sampling weight (participation propensity)
    shard_ptr     i32[N]  row of the backing :class:`~..data.FedDataset`
                          holding this client's data shard
    participation i32[N]  rounds this client was sampled into (counter)
    staleness     i32[N]  rounds since last sampled (∞-ish until first)

At N = 1,000,000 the registry is 16 MB — it lives comfortably on device,
so cohort sampling is ONE jit'd program: seeded Gumbel-top-K over the
weights (weighted K-of-N without replacement), keyed by
``fold_in(PRNGKey(seed), round_idx)``. The same program serves the
host-driven per-round path and the ``lax.scan`` superround body
(round_engine), so both paths sample IDENTICAL cohorts for a given seed.
K and N are static per registry — cohort sampling can never trigger a
recompile.

``shard_ptr`` is the level of indirection that lets a million registered
clients share a bounded backing dataset: many virtual clients may point at
the same (or overlapping) data shards, exactly like FedScale replays a
bounded trace over a large population. With real per-client data, the
pointer is the identity map.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

# staleness value meaning "never sampled yet" — large but far from i32 wrap
# even after adding the per-round +1 for billions of rounds is impossible,
# so clamp growth at this ceiling
NEVER_SAMPLED = np.int32(1 << 28)


class ClientRegistry:
    """Packed per-client record array with on-device seeded K-of-N sampling.

    Construction is host-side numpy; :meth:`sample` and
    :meth:`note_participation` run as jit'd device programs over the
    device-resident columns. The host copies are kept authoritative for
    save/identity; counters are pulled back lazily via :meth:`counters`.
    """

    def __init__(
        self,
        weights: np.ndarray,
        shard_ptrs: np.ndarray,
        seed: int = 0,
        participation: Optional[np.ndarray] = None,
        staleness: Optional[np.ndarray] = None,
    ):
        weights = np.asarray(weights, np.float32).reshape(-1)
        shard_ptrs = np.asarray(shard_ptrs, np.int32).reshape(-1)
        if weights.shape != shard_ptrs.shape:
            raise ValueError(
                f"registry columns disagree: {weights.shape[0]} weights vs "
                f"{shard_ptrs.shape[0]} shard pointers"
            )
        if weights.size == 0:
            raise ValueError("registry must hold at least one client")
        if not np.all(weights > 0):
            raise ValueError("registry weights must be strictly positive")
        self.weights = weights
        self.shard_ptrs = shard_ptrs
        self.seed = int(seed)
        n = weights.shape[0]
        if np.any(shard_ptrs < 0):
            raise ValueError(
                "registry shard pointers must be non-negative (negative "
                "values would silently gather the wrong client's shard "
                "via numpy wraparound indexing)"
            )
        self.participation = (
            np.zeros(n, np.int32) if participation is None
            else np.asarray(participation, np.int32).reshape(-1)
        )
        self.staleness = (
            np.full(n, NEVER_SAMPLED, np.int32) if staleness is None
            else np.asarray(staleness, np.int32).reshape(-1)
        )
        for name, col in (("participation", self.participation),
                          ("staleness", self.staleness)):
            if col.shape != weights.shape:
                raise ValueError(
                    f"registry column {name!r} has {col.shape[0]} entries "
                    f"for {n} clients"
                )
        self._root = jax.random.PRNGKey(self.seed)
        # device mirrors, built lazily on first sample (a registry used only
        # for identity/save never touches the device)
        self._dev: Optional[Dict[str, jax.Array]] = None
        self._sample_fn: Dict[int, Any] = {}
        self._note_fn = None

    # -- construction --------------------------------------------------------

    @classmethod
    def synthetic(cls, n: int, backing_shards: int, seed: int = 0,
                  weight_concentration: float = 0.0) -> "ClientRegistry":
        """A population of ``n`` virtual clients over ``backing_shards`` data
        rows. ``weight_concentration > 0`` draws heterogeneous sampling
        weights from ``Gamma(k)`` (device-churn-like skew); 0 = uniform."""
        n = int(n)
        backing = int(backing_shards)
        if n <= 0 or backing <= 0:
            raise ValueError("n and backing_shards must be positive")
        rs = np.random.RandomState(seed)
        # permuted modular map: virtual clients spread over the backing rows
        # in a seed-stable shuffle (not blocks, so any cohort mixes shards)
        ptrs = rs.permutation(n).astype(np.int64) % backing
        if weight_concentration > 0:
            w = rs.gamma(weight_concentration, 1.0, n).astype(np.float32)
            w = np.maximum(w, 1e-6)
        else:
            w = np.ones(n, np.float32)
        return cls(w, ptrs.astype(np.int32), seed=seed)

    @classmethod
    def from_dataset(cls, ds, seed: int = 0) -> "ClientRegistry":
        """Identity registry: one registered client per backing data shard."""
        n = int(ds.client_num)
        return cls(np.ones(n, np.float32), np.arange(n, dtype=np.int32),
                   seed=seed)

    # -- basics --------------------------------------------------------------

    @property
    def num_clients(self) -> int:
        return int(self.weights.shape[0])

    def __len__(self) -> int:
        return self.num_clients

    def shard_rows(self, client_ids: np.ndarray) -> np.ndarray:
        """Registry client ids → backing dataset rows."""
        return self.shard_ptrs[np.asarray(client_ids)]

    def injective_shards(self) -> bool:
        """True when no two clients share a backing shard — the invariant
        per-client state (SCAFFOLD control variates) needs: with aliased
        pointers a cohort holds duplicate rows and a per-row scatter
        becomes order-dependent."""
        return (len(np.unique(self.shard_ptrs)) == self.num_clients)

    # -- on-device sampling --------------------------------------------------

    def _ensure_device(self) -> None:
        if self._dev is not None:
            return
        self._dev = {
            "log_w": jnp.log(jnp.asarray(self.weights)),
            "ptrs": jnp.asarray(self.shard_ptrs),
            "participation": jnp.asarray(self.participation),
            "staleness": jnp.asarray(self.staleness),
        }

    def device_sampler(self, k: int):
        """``sample(round_idx) -> i32[k]`` registry client ids — ONE jit'd
        program, weighted K-of-N without replacement via Gumbel-top-K.

        ``round_idx`` is a traced scalar: every round runs the same compiled
        program (N and K are the only static shapes), so population-scale
        sampling can never be a recompile source. Deterministic given
        (seed, round_idx) — the superround scan body and the host-driven
        path call this same function and agree on every cohort.
        """
        k = int(k)
        if not 0 < k <= self.num_clients:
            raise ValueError(
                f"cohort size {k} must be in [1, {self.num_clients}]"
            )
        self._ensure_device()
        log_w = self._dev["log_w"]
        root = self._root

        def sample(round_idx):
            key = jax.random.fold_in(root, round_idx)
            g = jax.random.gumbel(key, log_w.shape, log_w.dtype)
            _, ids = jax.lax.top_k(log_w + g, k)
            return ids.astype(jnp.int32)

        fn = self._sample_fn.get(k)
        if fn is None:
            fn = jax.jit(sample)
            self._sample_fn[k] = fn
        return fn

    def sample(self, round_idx: int, k: int) -> np.ndarray:
        """Host-side view of :meth:`device_sampler` (np.ndarray out)."""
        return np.asarray(self.device_sampler(k)(jnp.int32(round_idx)))

    def device_shard_ptrs(self) -> jax.Array:
        """The shard-pointer column on device (superround gathers need it)."""
        self._ensure_device()
        return self._dev["ptrs"]

    def note_participation(self, cohort_ids: np.ndarray) -> None:
        """Fold one sampled cohort into the participation/staleness counters
        (device-side scatter; the donated update keeps one live copy)."""
        self._ensure_device()

        if self._note_fn is None:
            def note(part, stale, ids):
                part = part.at[ids].add(1)
                stale = jnp.minimum(stale + 1, NEVER_SAMPLED)
                stale = stale.at[ids].set(0)
                return part, stale

            self._note_fn = jax.jit(note, donate_argnums=(0, 1))
        part, stale = self._note_fn(
            self._dev["participation"], self._dev["staleness"],
            jnp.asarray(cohort_ids, jnp.int32),
        )
        self._dev["participation"] = part
        self._dev["staleness"] = stale

    def counters(self) -> Dict[str, np.ndarray]:
        """Pull the participation/staleness counters back to host."""
        if self._dev is not None:
            self.participation = np.asarray(self._dev["participation"])
            self.staleness = np.asarray(self._dev["staleness"])
        return {"participation": self.participation,
                "staleness": self.staleness}

    # -- identity / persistence ---------------------------------------------

    def identity(self) -> Dict[str, Any]:
        """Run-identity fields for the run ledger: a resumed run against a
        DIFFERENT registry (size, seed, weights or shard map) would silently
        change every remaining cohort, so the ledger pins a digest of the
        sampling-relevant columns and ``RunLedger.ensure_meta`` turns any
        mismatch into a loud error."""
        h = hashlib.sha256()
        h.update(self.weights.tobytes())
        h.update(self.shard_ptrs.tobytes())
        return {
            "num_clients": self.num_clients,
            "seed": self.seed,
            "columns_sha256": h.hexdigest()[:16],
        }

    def save(self, path: str) -> None:
        self.counters()  # fold device-side counters into the host copies
        np.savez(
            path, weights=self.weights, shard_ptrs=self.shard_ptrs,
            participation=self.participation, staleness=self.staleness,
            seed=np.int64(self.seed),
        )

    @classmethod
    def load(cls, path: str) -> "ClientRegistry":
        with np.load(path) as z:
            return cls(
                z["weights"], z["shard_ptrs"], seed=int(z["seed"]),
                participation=z["participation"], staleness=z["staleness"],
            )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"ClientRegistry(n={self.num_clients}, seed={self.seed}, "
            f"backing={int(self.shard_ptrs.max()) + 1})"
        )
