"""Regex-over-named-pytree partition rules → ``NamedSharding`` specs.

``mesh_api`` shipped with exactly one sharding idea: every cohort array is
split on its first axis over the ``clients`` mesh axis, everything else is
replicated (two hard-coded ``NamedSharding`` objects). That is the right
default — and a dead end the moment a model wants its embedding sharded,
a mesh grows a second axis, or the cohort arrays stop being a fixed
3-tuple. The large-model JAX ecosystem converged on a better shape for
this decision (the ``match_partition_rules`` pattern, SNIPPETS.md [2]/[3]):
name every leaf of a pytree, walk an ordered list of ``(regex,
PartitionSpec)`` rules, first match wins, scalars never partition.

This module is that pattern for the FL cohort plane:

- :func:`named_tree_paths` / :func:`named_tree_map` — canonical
  ``a/b/c``-style leaf names for any pytree (dicts, dataclass pytrees,
  lists).
- :func:`match_partition_rules` — rules → pytree of ``PartitionSpec``;
  0-d/size-1 leaves get ``P()`` regardless (don't partition scalars);
  unmatched leaves take ``fallback`` (or raise when ``fallback=None``).
- :func:`make_shardings` — spec pytree → ``NamedSharding`` pytree over a
  mesh, validating that every named axis exists on the mesh.
- :func:`parse_partition_rules` — the CLI/YAML surface
  (``--mesh_partition_rules``): ``"pattern=axis,axis;pattern2="`` with
  ``+`` for multi-axis dims.

``DEFAULT_COHORT_RULES`` / ``DEFAULT_STATE_RULES`` reproduce the legacy
first-axis behavior exactly — the mesh parity test in
``tests/test_scale.py`` pins rule-driven sharding bitwise-equal to the
hard-coded original over the model zoo.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path, tree_unflatten

from .. import constants

PyTree = Any
Rules = Sequence[Tuple[str, P]]

# cohort-plane arrays carry clients on the leading axis; state (params,
# optimizer, control variates) is replicated — byte-for-byte the legacy
# mesh_api behavior
DEFAULT_COHORT_RULES: Tuple[Tuple[str, P], ...] = (
    (r".*", P(constants.MESH_AXIS_CLIENTS)),
)
DEFAULT_STATE_RULES: Tuple[Tuple[str, P], ...] = (
    (r".*", P()),
)


def _key_name(entry) -> str:
    """One path entry → its plain name (DictKey('a') → 'a', [3] → '3')."""
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def named_tree_paths(tree: PyTree, sep: str = "/") -> List[Tuple[str, Any]]:
    """Flatten ``tree`` to ``[(name, leaf), ...]`` with ``a/b/c`` names."""
    flat, _ = tree_flatten_with_path(tree)
    return [(sep.join(_key_name(k) for k in path) or sep, leaf)
            for path, leaf in flat]


def named_tree_map(fn, tree: PyTree, sep: str = "/") -> PyTree:
    """``fn(name, leaf)`` over every leaf, preserving structure."""
    flat, treedef = tree_flatten_with_path(tree)
    out = [fn(sep.join(_key_name(k) for k in path) or sep, leaf)
           for path, leaf in flat]
    return tree_unflatten(treedef, out)


def is_scalar_leaf(leaf: Any) -> bool:
    """True for leaves that never partition (0-d / single-element) — the
    one predicate shared by rule matching and any cache keyed on it."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return True
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(
    rules: Rules, tree: PyTree, fallback: Optional[P] = P(),
    sep: str = "/",
) -> PyTree:
    """Resolve ordered ``(regex, PartitionSpec)`` rules over a named pytree.

    First matching rule wins (``re.search`` semantics — anchor with ``^``/
    ``$`` for exact names). Scalar / single-element leaves always resolve
    to ``P()``. A leaf no rule matches takes ``fallback``; with
    ``fallback=None`` it raises instead — use that in tests/CI to prove a
    rule set covers a model.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def resolve(name: str, leaf: Any) -> P:
        if is_scalar_leaf(leaf):
            return P()
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec
        if fallback is None:
            raise ValueError(
                f"no partition rule matches leaf {name!r} "
                f"(shape={getattr(leaf, 'shape', None)}); add a rule or "
                "pass an explicit fallback"
            )
        return fallback

    return named_tree_map(resolve, tree, sep=sep)


def make_shardings(mesh: Mesh, specs: PyTree) -> PyTree:
    """Spec pytree → ``NamedSharding`` pytree, validating axis names."""
    names = set(mesh.axis_names)

    def to_sharding(spec: P) -> NamedSharding:
        for dim in spec:
            for ax in (dim if isinstance(dim, tuple) else (dim,)):
                if ax is not None and ax not in names:
                    raise ValueError(
                        f"partition spec {spec} names axis {ax!r} but the "
                        f"mesh has {sorted(names)}"
                    )
        return NamedSharding(mesh, spec)

    import jax

    return jax.tree.map(to_sharding, specs,
                        is_leaf=lambda x: isinstance(x, P))


def parse_partition_rules(text: Optional[str]) -> List[Tuple[str, P]]:
    """Parse the CLI/YAML rule syntax into ``[(regex, PartitionSpec)]``.

    ``"rule;rule;..."`` where each rule is ``pattern=dims`` and ``dims`` is
    a comma-separated dim list: an axis name shards that dim, an empty
    token (or ``-``) replicates it, ``a+b`` shards one dim over two axes.
    ``pattern=`` (empty dims) means fully replicated. Examples::

        cohort/.*=clients            # first axis over 'clients'
        embedding=clients,tensor     # dim0 over clients, dim1 over tensor
        .*=                          # replicate everything else

    Returns ``[]`` for empty/None input (callers substitute defaults).
    """
    out: List[Tuple[str, P]] = []
    if not text:
        return out
    for raw in str(text).split(";"):
        raw = raw.strip()
        if not raw:
            continue
        pattern, eq, dims_text = raw.partition("=")
        pattern = pattern.strip()
        if not pattern or not eq:
            raise ValueError(
                f"bad partition rule {raw!r}: expected 'pattern=dims'"
            )
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(
                f"bad partition rule pattern {pattern!r}: {e}"
            ) from None
        dims: List[Any] = []
        if dims_text.strip():
            for tok in dims_text.split(","):
                tok = tok.strip()
                if tok in ("", "-", "None", "none"):
                    dims.append(None)
                elif "+" in tok:
                    dims.append(tuple(t.strip() for t in tok.split("+")
                                      if t.strip()))
                else:
                    dims.append(tok)
        out.append((pattern, P(*dims)))
    return out
