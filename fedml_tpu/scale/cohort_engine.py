"""Cohort engine: registry-backed sampling + streamed shard prefetch.

The glue that turns the sp/mesh FedAvg engines into million-client
federations without touching their round math:

- **Sampling** (`data_cohort`): the round's cohort is drawn from the
  :class:`~.registry.ClientRegistry` — seeded, weighted, K-of-N,
  on-device (one jit'd program, never a recompile source) — and mapped
  through the registry's shard pointers to backing dataset rows. The
  FedAvg engines keep operating on dataset rows exactly as before; only
  WHO participates each round now comes from a population of N ≥ 1M.
- **Streaming** (`gather`): cohort shards are gathered host-side and
  placed on device by a :class:`~.prefetch.ShardPrefetcher`; serving
  round *r* schedules round *r+1*'s gather in the background, so
  steady-state rounds find their data already in HBM. Placement is a
  callable supplied per call — the sp path places plain device arrays,
  the mesh path places rule-driven ``NamedSharding`` arrays
  (`partition_rules.py`) — the engine never needs to know.
- **Accounting**: participation/staleness counters fold in per sampled
  cohort; the registry identity (size, seed, column digest) extends the
  run ledger's ``run_meta`` so ``--resume`` against a different registry
  fails loudly instead of silently resampling every remaining round.

Determinism: cohorts depend only on (registry seed, round index), so a
resumed run samples the exact cohorts the dead run would have — the same
property host-side ``np.random.RandomState(round_idx)`` sampling gave the
small-N path, now at population scale.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .prefetch import ShardPrefetcher, cohort_key
from .registry import ClientRegistry

logger = logging.getLogger(__name__)

HostGatherFn = Callable[[np.ndarray], Any]
PlaceFn = Callable[[Any], Any]

# rounds of sampled-cohort cache kept for ledger replay / prefetch keying
_COHORT_CACHE_ROUNDS = 8


def build_cohort_engine(args, ds) -> Optional["CohortEngine"]:
    """Construct the engine from ``--client_registry`` / ``--cohort_size``
    (None when no registry is configured). ``client_registry`` is either a
    client count (synthetic population over the dataset's shards) or a path
    to a registry saved with :meth:`ClientRegistry.save`."""
    spec = str(getattr(args, "client_registry", "") or "").strip()
    if not spec:
        return None
    seed = int(getattr(args, "random_seed", 0))
    try:
        n = int(spec)  # accepts "1_000_000" spellings too
    except ValueError:
        n = None
    if n is not None:
        if n <= 0:
            raise ValueError(
                f"client_registry count must be positive, got {n}"
            )
        registry = ClientRegistry.synthetic(
            n, backing_shards=ds.client_num, seed=seed,
            weight_concentration=float(
                getattr(args, "registry_weight_concentration", 0.0) or 0.0
            ),
        )
    elif os.path.exists(spec):
        registry = ClientRegistry.load(spec)
        if int(registry.shard_ptrs.max()) >= ds.client_num:
            raise ValueError(
                f"registry {spec} points at shard "
                f"{int(registry.shard_ptrs.max())} but the dataset has only "
                f"{ds.client_num} client shards"
            )
    else:
        raise ValueError(
            "client_registry must be a client count or a path to a saved "
            f"registry npz, got {spec!r} (no such file)"
        )
    k = int(getattr(args, "cohort_size", 0) or 0)
    if k <= 0:
        k = min(int(args.client_num_per_round), registry.num_clients)
    depth = int(getattr(args, "cohort_prefetch", 1) or 0)
    from ..core.mlops import telemetry

    telemetry.gauge_set("scale.registry_clients", registry.num_clients)
    telemetry.gauge_set("scale.cohort_size", k)
    return CohortEngine(registry, cohort_size=k, prefetch_depth=depth,
                        total_rounds=int(getattr(args, "comm_round", 0)
                                         or 0))


class CohortEngine:
    """Per-run orchestration of one registry + one prefetcher."""

    def __init__(self, registry: ClientRegistry, cohort_size: int,
                 prefetch_depth: int = 1, total_rounds: int = 0):
        self.registry = registry
        self.cohort_size = int(cohort_size)
        # when > 0, no prefetch is scheduled past the last round — the
        # final round must not pay for a cohort nothing will consume
        self.total_rounds = int(total_rounds)
        if not 0 < self.cohort_size <= registry.num_clients:
            raise ValueError(
                f"cohort_size {cohort_size} must be in "
                f"[1, {registry.num_clients}]"
            )
        self.prefetcher = ShardPrefetcher(depth=prefetch_depth)
        self._sampler = registry.device_sampler(self.cohort_size)
        # round -> (registry ids, dataset rows); bounded LRU-ish cache so
        # the ledger's post-round replay of _client_sampling is free
        self._cohorts: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self._round_of_key: Dict[str, int] = {}
        # rounds whose cohort was folded into the participation/staleness
        # counters — sampling alone must NOT count (the prefetcher samples
        # round r+1 ahead of time, and r+1 may never run)
        self._noted: set = set()
        self._host_gather: Optional[HostGatherFn] = None
        # maps sampled rows → the rows the engine will actually be asked to
        # gather (the mesh path pads cohorts to an axis multiple; prefetch
        # keys must match the padded request or every take would miss)
        self._transform: Callable[[np.ndarray], np.ndarray] = lambda r: r

    # -- sampling ------------------------------------------------------------

    def data_cohort(self, round_idx: int) -> np.ndarray:
        """Dataset rows for round ``round_idx``'s cohort (deterministic)."""
        return self._cohort(round_idx)[1]

    def registry_cohort(self, round_idx: int) -> np.ndarray:
        """Registry client ids for round ``round_idx``'s cohort."""
        return self._cohort(round_idx)[0]

    def _cohort(self, round_idx: int) -> Tuple[np.ndarray, np.ndarray]:
        r = int(round_idx)
        hit = self._cohorts.get(r)
        if hit is not None:
            return hit
        import jax.numpy as jnp

        ids = np.asarray(self._sampler(jnp.int32(r)))
        rows = self.registry.shard_rows(ids)
        self._cohorts[r] = (ids, rows)
        self._round_of_key[cohort_key(self._transform(rows))] = r
        while len(self._cohorts) > _COHORT_CACHE_ROUNDS:
            oldest = min(self._cohorts)
            old_rows = self._cohorts.pop(oldest)[1]
            self._round_of_key.pop(cohort_key(self._transform(old_rows)),
                                   None)
        return self._cohorts[r]

    def note_rounds(self, start_round: int, k: int) -> None:
        """Replay participation accounting for a superround scan: the scan
        body sampled rounds ``[start, start+k)`` ON DEVICE with this same
        sampler, so re-deriving the cohorts host-side folds the identical
        ids into the counters."""
        for r in range(int(start_round), int(start_round) + int(k)):
            self._note_round(r)

    def _note_round(self, r: int) -> None:
        """Fold round ``r``'s cohort into the counters exactly once, and
        only for rounds that actually TRAIN (gather/scan), never for
        lookahead sampling — the prefetcher samples r+1 speculatively and
        a preempted run may never execute it."""
        r = int(r)
        if r in self._noted:
            return
        self.registry.note_participation(self._cohort(r)[0])
        self._noted.add(r)
        if len(self._noted) > 4 * _COHORT_CACHE_ROUNDS:
            # the set only guards against double-noting recent rounds;
            # ancient entries can go (rounds never repeat going forward)
            for old in sorted(self._noted)[:_COHORT_CACHE_ROUNDS]:
                self._noted.discard(old)

    # -- streaming gather ----------------------------------------------------

    def set_host_gather(self, fn: HostGatherFn) -> None:
        """Install the host-side shard reader (rows → host arrays)."""
        self._host_gather = fn

    def set_cohort_transform(self, fn: Callable[[np.ndarray], np.ndarray]) \
            -> None:
        """Install the sampled-rows → requested-rows map (cohort padding).
        Must be set before the first round is sampled."""
        if self._cohorts:
            raise RuntimeError(
                "set_cohort_transform after cohorts were sampled would "
                "desynchronize the prefetch keys"
            )
        self._transform = fn

    def gather(self, cohort_rows: np.ndarray, place: PlaceFn) -> Any:
        """Device arrays for ``cohort_rows`` — from the prefetched buffer
        when round r-1 scheduled it, else a synchronous gather — and
        schedule the NEXT round's cohort in the background."""
        if self._host_gather is None:
            raise RuntimeError("CohortEngine.set_host_gather was never called")
        rows = np.asarray(cohort_rows)
        key = cohort_key(rows)
        host_gather = self._host_gather

        out = self.prefetcher.take(
            key, lambda: place(host_gather(rows))
        )
        r = self._round_of_key.get(key)
        if r is not None:
            self._note_round(r)  # this round really trains: count it
            if self.total_rounds <= 0 or r + 1 < self.total_rounds:
                nxt_rows = self._transform(self.data_cohort(r + 1))
                self.prefetcher.schedule(
                    cohort_key(nxt_rows),
                    lambda: place(host_gather(nxt_rows)),
                )
        return out

    # -- identity / lifecycle ------------------------------------------------

    def ledger_identity(self) -> Dict[str, Any]:
        ident = self.registry.identity()
        ident["cohort_size"] = self.cohort_size
        return ident

    def stats(self) -> Dict[str, float]:
        return self.prefetcher.stats()

    def close(self) -> None:
        self.prefetcher.stop()
