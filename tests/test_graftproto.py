"""graftproto protocol/concurrency analysis tests (tools/graftproto —
ISSUE 5).

Pins five guarantees:

1. **Per-rule fixtures**: each of P001–P009 fires on its known-bad snippet
   with exact rule ids and line numbers, and stays silent on the known-good
   twin (``tests/fixtures/graftproto/``).
2. **Suppression machinery**: inline ``# graftproto: disable=P00X`` pragmas
   (graftlint's parser under graftproto's marker) and the baseline
   round-trip.
3. **Flow-graph coverage**: every ``MSG_TYPE_*`` constant in the shipped
   tree — enumerated by an independent AST walk — is classified
   sent+handled (or explicitly baselined/pragma'd). No silent gaps.
4. **Tier-1 gate**: the shipped tree has ZERO non-baselined findings — a
   renamed MSG_TYPE, a handler on the wrong role, a send bypassing
   delivery.py, or a lock inversion fails this test.
5. **Exit codes**: 0 clean / 1 findings / 2 analyzer crash, for both
   lint suites, so CI failures are diagnosable at a glance.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftlint import baseline as baseline_mod  # noqa: E402
from tools.graftproto.analyzer import (  # noqa: E402
    analyze_paths, analyze_paths_with_model, default_baseline_path)
from tools.graftproto.model import enumerate_msg_constants  # noqa: E402

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftproto")
TREE = os.path.join(REPO_ROOT, "fedml_tpu")


def _findings(*names):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return analyze_paths(paths, repo_root=REPO_ROOT)


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


class TestRuleFixtures:
    """Exact rule ids + line numbers on known-bad, silence on known-good."""

    def test_p001_bad(self):
        fs = _findings("p001_bad.py")
        assert {f.rule for f in fs} == {"P001"}
        # 19: S2C_ORPHAN sent, never handled; 30: C2S type registered
        # only on a client-role manager (wrong role)
        assert _rule_lines(fs, "P001") == [19, 30]

    def test_p002_bad(self):
        fs = _findings("p002_bad.py")
        assert {f.rule for f in fs} == {"P002"}
        assert _rule_lines(fs, "P002") == [15]

    def test_p003_bad(self):
        fs = _findings("p003_bad.py")
        assert {f.rule for f in fs} == {"P003"}
        # 7: duplicate wire value, 8: dead constant, 18: stale attribute
        # ref, 31: raw literal shadowing the constant
        assert _rule_lines(fs, "P003") == [7, 8, 18, 31]

    def test_p004_bad(self):
        fs = _findings("p004_bad.py")
        assert {f.rule for f in fs} == {"P004"}
        assert _rule_lines(fs, "P004") == [18]

    def test_p005_bad(self):
        fs = _findings("p005_bad.py")
        assert {f.rule for f in fs} == {"P005"}
        assert _rule_lines(fs, "P005") == [13, 24]

    def test_p005_deadlock_pairing(self):
        """Terminal handler whose trigger nobody sends: the pairing check
        (P005) fires alongside the plain dead-handler check (P002)."""
        fs = _findings("p005_deadlock_bad.py")
        assert {f.rule for f in fs} == {"P002", "P005"}
        assert _rule_lines(fs, "P005") == [16]
        assert _rule_lines(fs, "P002") == [16]

    def test_p006_bad(self):
        fs = _findings("p006_bad.py")
        assert {f.rule for f in fs} == {"P006"}
        assert _rule_lines(fs, "P006") == [13]

    def test_p007_bad(self):
        fs = _findings("p007_bad.py")
        assert {f.rule for f in fs} == {"P007"}
        assert _rule_lines(fs, "P007") == [8]

    def test_p008_inversion_exact_lines(self):
        """Acceptance: the seeded A->B / B->A inversion is detected with
        exact line numbers, and both messages cross-reference the reverse
        acquisition site."""
        fs = _findings("p008_bad.py")
        assert {f.rule for f in fs} == {"P008"}
        assert _rule_lines(fs, "P008") == [16, 22]
        by_line = {f.line: f.message for f in fs}
        assert "p008_bad.py:22" in by_line[16]
        assert "p008_bad.py:16" in by_line[22]

    def test_p009_blocking_under_lock_exact_lines(self):
        """Acceptance: direct blocking calls (fsync/sleep/untimed get and
        join) and a one-hop callee block, each at its exact line."""
        fs = _findings("p009_bad.py")
        assert {f.rule for f in fs} == {"P009"}
        assert _rule_lines(fs, "P009") == [17, 18, 22, 23, 31]

    def test_p008_bare_acquire_inversion(self):
        """Satellite (ISSUE 7): lock-order analysis tracks bare
        lock.acquire()/release() windows, not only ``with`` blocks — the
        acquire(); try: ... finally: release() idiom joins the graph."""
        fs = _findings("p008_acquire_bad.py")
        assert {f.rule for f in fs} == {"P008"}
        assert _rule_lines(fs, "P008") == [14, 23]
        by_line = {f.line: f.message for f in fs}
        assert "p008_acquire_bad.py:23" in by_line[14]
        assert "p008_acquire_bad.py:14" in by_line[23]

    def test_p009_bare_acquire_blocking(self):
        """Blocking calls inside a bare acquire()/release() window fire
        P009 exactly like a ``with lock:`` block."""
        fs = _findings("p009_acquire_bad.py")
        assert {f.rule for f in fs} == {"P009"}
        assert _rule_lines(fs, "P009") == [17, 23]

    def test_p004_dataflow_round_guard(self):
        """Satellite (ISSUE 7): a guard comparing a local whose value FLOWS
        from the message's round key (no round token in the compare text)
        counts as a round guard — no pragma needed."""
        assert _findings("p004_dataflow_good.py") == []

    def test_async_handler_shape_is_clean(self):
        """The ISSUE 7 async traffic-plane handler shape — staleness/version
        guard + shed NACK via self.send_message — passes P004 and P006."""
        assert _findings("p004_async_handler_good.py") == []

    @pytest.mark.parametrize("name", [
        "p001_good.py", "p003_good.py", "p004_good.py", "p005_good.py",
        "p006_good.py", "p007_good.py", "p008_good.py", "p009_good.py",
        "p009_acquire_good.py", "p004_dataflow_good.py",
        "p004_async_handler_good.py",
    ])
    def test_good_twins_are_clean(self, name):
        assert _findings(name) == []

    def test_every_rule_has_a_firing_fixture(self):
        fixtures = {
            "P001": "p001_bad.py", "P002": "p002_bad.py",
            "P003": "p003_bad.py", "P004": "p004_bad.py",
            "P005": "p005_bad.py", "P006": "p006_bad.py",
            "P007": "p007_bad.py", "P008": "p008_bad.py",
            "P009": "p009_bad.py",
        }
        for rule, name in fixtures.items():
            assert any(f.rule == rule for f in _findings(name)), rule


class TestSuppression:
    def test_pragma_inline(self):
        fs = _findings("pragma_ok.py")
        assert _rule_lines(fs, "P009") == [14]  # line 13 suppressed

    def test_pragma_file_level(self):
        assert _findings("pragma_file.py") == []

    def test_pragma_markers_are_tool_scoped(self):
        """A graftlint pragma does not silence graftproto and vice versa."""
        from tools.graftlint.pragmas import parse_pragmas

        src = "x = 1  # graftlint: disable=G001\ny = 2  " \
              "# graftproto: disable=P009\n"
        assert parse_pragmas(src) == {1: frozenset({"G001"})}
        assert parse_pragmas(src, tool="graftproto") == {
            2: frozenset({"P009"})}

    def test_baseline_round_trip(self, tmp_path):
        fs = _findings("p009_bad.py")
        assert fs
        path = str(tmp_path / "baseline.json")
        baseline_mod.save(path, fs, tool="graftproto")
        payload = json.load(open(path))
        assert payload["comment"].startswith("graftproto baseline")
        new, old = baseline_mod.split(fs, baseline_mod.load(path))
        assert new == [] and len(old) == len(fs)
        # a NEW finding (different line text) is not swallowed
        import dataclasses

        extra = dataclasses.replace(fs[0], line=999,
                                    line_text="os.fsync(other_fd)")
        new, old = baseline_mod.split(fs + [extra], baseline_mod.load(path))
        assert [f.line for f in new] == [999]

    def test_default_baseline_is_repo_root_anchored(self):
        assert default_baseline_path(REPO_ROOT) == os.path.join(
            REPO_ROOT, "tools", "graftproto", "baseline.json")


class TestFlowGraphCoverage:
    """Acceptance: the flow graph provably covers every MSG_TYPE_* constant
    in the repo — each is sent+handled, baselined, or pragma'd."""

    def test_every_msg_type_constant_is_classified(self):
        constants = enumerate_msg_constants([TREE], REPO_ROOT)
        assert constants, "AST enumeration found no MSG_TYPE_* constants"
        _fs, model = analyze_paths_with_model([TREE], repo_root=REPO_ROOT)
        bl = baseline_mod.load(default_baseline_path(REPO_ROOT))
        gaps = []
        for c in constants:
            cls = model.classify_value(c.value)
            if cls == "sent+handled":
                continue
            baselined = any(c.value in key or c.attr in key for key in bl)
            pragmad = _has_proto_pragma(c.rel)
            if not (baselined or pragmad):
                gaps.append((c.qualname, c.value, cls))
        assert gaps == [], f"unclassified MSG_TYPE constants: {gaps}"

    def test_known_protocol_constants_are_seen(self):
        """The enumeration reaches every protocol surface the tentpole
        names: cross-silo, lightsecagg, the transport constants and the
        flow DSL."""
        constants = enumerate_msg_constants([TREE], REPO_ROOT)
        owners = {c.owner for c in constants}
        assert {"MyMessage", "LSAMessage", "CommunicationConstants",
                "FedMLAlgorithmFlow"} <= owners
        # the wire protocol is value-keyed: aliases merge
        values = {c.value for c in constants}
        assert "connection_ready" in values
        assert "c2s_send_model_to_server" in values

    def test_same_named_define_classes_stay_scoped(self):
        """Two packages may both name their define class MyMessage (the
        reference-FedML convention): each module resolves against its OWN
        class, never a bare-name merge — no phantom drift, both wire
        values classified."""
        path = os.path.join(FIXTURES, "owner_scope")
        fs, model = analyze_paths_with_model([path], repo_root=REPO_ROOT)
        assert fs == [], "\n".join(f.render() for f in fs)
        assert model.classify_value("a_go") == "sent+handled"
        assert model.classify_value("b_go") == "sent+handled"

    def test_coverage_report_shape(self):
        _fs, model = analyze_paths_with_model([TREE], repo_root=REPO_ROOT)
        cov = model.coverage()
        assert cov, "empty coverage report"
        for value, info in cov.items():
            assert info["classification"] == "sent+handled", (value, info)
            assert info["send_sites"] >= 1
            assert info["handler_sites"] >= 1


def _has_proto_pragma(rel: str) -> bool:
    with open(os.path.join(REPO_ROOT, rel)) as f:
        return "graftproto: disable=" in f.read()


class TestTreeGate:
    """The tier-1 gate: the shipped tree must be clean vs the baseline."""

    def test_fedml_tpu_clean(self):
        findings = analyze_paths([TREE], repo_root=REPO_ROOT)
        bl = baseline_mod.load(default_baseline_path(REPO_ROOT))
        new, _old = baseline_mod.split(findings, bl)
        assert new == [], "non-baselined graftproto findings:\n" + "\n".join(
            f.render() for f in new)

    def test_baseline_has_no_dead_entries(self):
        from collections import Counter

        findings = analyze_paths([TREE], repo_root=REPO_ROOT)
        bl = baseline_mod.load(default_baseline_path(REPO_ROOT))
        live = Counter(f.baseline_key() for f in findings)
        stale = {k: (n, live.get(k, 0)) for k, n in bl.items()
                 if n > live.get(k, 0)}
        assert stale == {}, f"stale baseline (key: budget vs live): {stale}"


class TestCLI:
    def _run(self, *args, module="tools.graftproto"):
        return subprocess.run(
            [sys.executable, "-m", module, *args],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )

    def test_exit_nonzero_on_bad_fixture(self):
        r = self._run("tests/fixtures/graftproto/p008_bad.py",
                      "--no-baseline")
        assert r.returncode == 1
        assert "P008" in r.stdout

    def test_exit_zero_on_tree_json(self):
        r = self._run("fedml_tpu", "--format", "json")
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert payload["findings"] == []
        assert payload["exit_code"] == 0
        assert payload["coverage"]  # machine-readable flow-graph report

    def test_json_flag_alias(self):
        r = self._run("tests/fixtures/graftproto/p009_bad.py",
                      "--no-baseline", "--json")
        assert r.returncode == 1
        payload = json.loads(r.stdout)
        assert payload["counts"] == {"P009": 5}

    def test_usage_error_is_exit_2(self):
        r = self._run("no/such/path.py")
        assert r.returncode == 2

    def test_analyzer_crash_is_exit_2(self, monkeypatch):
        """Satellite: findings (1) vs analyzer crashed (2)."""
        from tools.graftproto import cli as proto_cli

        def boom(*_a, **_k):
            raise RuntimeError("injected analyzer crash")

        monkeypatch.setattr(proto_cli, "analyze_paths_with_model", boom)
        assert proto_cli.main(["fedml_tpu"]) == 2

    def test_graftlint_crash_is_exit_2(self, monkeypatch):
        """Same contract on the sibling suite."""
        from tools.graftlint import cli as lint_cli

        def boom(*_a, **_k):
            raise RuntimeError("injected analyzer crash")

        monkeypatch.setattr(lint_cli, "analyze_paths", boom)
        assert lint_cli.main(["fedml_tpu"]) == 2

    def test_select_filter(self):
        r = self._run("tests/fixtures/graftproto/p009_bad.py",
                      "--no-baseline", "--select", "P001")
        assert r.returncode == 0

    def test_list_rules(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for rule in ("P001", "P002", "P003", "P004", "P005", "P006",
                     "P007", "P008", "P009"):
            assert rule in r.stdout

    def test_fedml_cli_lint_proto_subcommand(self):
        r = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint", "--proto"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr


class TestRealInvariantsStayFixed:
    """The real pre-existing findings fixed in this PR must stay fixed —
    these would regress silently without the gate."""

    def test_ledger_fsync_not_under_lock(self):
        fs = analyze_paths(
            [os.path.join(TREE, "core", "runstate.py")], repo_root=REPO_ROOT)
        assert [f for f in fs if f.rule == "P009"] == []

    def test_transport_literals_use_constants(self):
        fs = analyze_paths(
            [os.path.join(TREE, "core", "distributed")], repo_root=REPO_ROOT)
        assert [f for f in fs if f.rule == "P003"] == []

    def test_fsm_replay_guards_present(self):
        fs = analyze_paths([os.path.join(TREE, "cross_silo")],
                           repo_root=REPO_ROOT)
        assert [f for f in fs if f.rule == "P004"] == []


class TestFlowDSLDispatch:
    """The PR 5 residual: callbacks registered through the flow DSL
    (``add_flow``) must be first-class in the message-flow graph."""

    def test_flow_only_manager_is_clean(self):
        # sends Message(MSG_TYPE_FLOW) but registers handlers ONLY via
        # add_flow — without flow-DSL resolution this was a false P001
        assert _findings("flow_dispatch_good.py") == []

    def test_add_flow_registrations_enter_flow_graph(self):
        paths = [os.path.join(FIXTURES, "flow_dispatch_good.py")]
        _fs, model = analyze_paths_with_model(paths, repo_root=REPO_ROOT)
        regs = model.handlers.get("flow_step", [])
        assert {r.handler for r in regs} == {
            "_init_step", "_train_step", "_finish_step"}
        assert model.classify_value("flow_step") == "sent+handled"

    def test_flow_callback_round_mutation_is_p004(self):
        fs = _findings("flow_p004_bad.py")
        assert {f.rule for f in fs} == {"P004"}
        assert _rule_lines(fs, "P004") == [23]

    def test_keyword_form_add_flow_still_resolves(self, tmp_path):
        # add_flow("train", executor_task=self._fn, role=...) is legal per
        # the shipped signature — the callback must still enter the graph
        p = tmp_path / "kwflow.py"
        p.write_text(
            "class MyMessage:\n"
            "    MSG_TYPE_FLOW = \"flow_step\"\n\n\n"
            "class Message:\n"
            "    def __init__(self, t, a=0, b=0):\n"
            "        self.t = t\n\n\n"
            "class KwFlowManager:\n"
            "    def __init__(self, flow):\n"
            "        self.round_idx = 0\n"
            "        flow.add_flow(\"t\", executor_task=self._train,\n"
            "                      role=\"client\")\n\n"
            "    def _train(self, ex):\n"
            "        self.round_idx = self.round_idx + 1\n"
            "        self.finish()\n\n"
            "    def finish(self):\n"
            "        pass\n\n"
            "    def _dispatch(self):\n"
            "        return Message(MyMessage.MSG_TYPE_FLOW)\n")
        fs = analyze_paths([str(p)], repo_root=REPO_ROOT)
        assert any(f.rule == "P004" for f in fs), \
            "\n".join(f.render() for f in fs)

    def test_shipped_flow_plane_still_clean(self):
        fs = analyze_paths(
            [os.path.join(TREE, "core", "distributed", "flow.py")],
            repo_root=REPO_ROOT)
        assert fs == [], "\n".join(f.render() for f in fs)
