"""Device-direct wire path (docs/delivery.md): the jit'd device codec must
produce frames BYTE-IDENTICAL to the host ``DeltaCodec`` — same scheme
choice, same bytes — across all three schemes, including raw-bit edge
cases (−0.0, NaN payloads); batched (vmap) encodes must equal sequential
ones; and device buffers must ride the raw-frame writer zero-copy
(dlpack emission → ``decode_frames`` round-trip).

The wire path is a PERFORMANCE knob, never a protocol one: every test in
here is ultimately a restatement of that contract.
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from fedml_tpu.core.distributed.tensor_transport import (  # noqa: E402
    decode_frames,
    encode_frame_parts,
    encode_frames,
)
from fedml_tpu.core.mlops import telemetry  # noqa: E402
from fedml_tpu.delivery.delta_codec import (  # noqa: E402
    DeltaCodec,
    payload_nbytes,
    plan_frame,
)
from fedml_tpu.delivery.device_codec import (  # noqa: E402
    DeviceDeltaCodec,
    WireCodec,
    device_supported,
    host_view,
    resolve_wire_path,
)
from fedml_tpu.delivery.model_store import VersionedModelStore  # noqa: E402

RNG = np.random.default_rng(20260806)


def _nan_payload() -> np.float32:
    """A non-canonical quiet NaN — survives only if codecs stay bitwise."""
    return np.frombuffer(b"\x01\x00\xc0\x7f", dtype=np.float32)[0]


def _frames_bytes(arrays):
    return [np.asarray(a).tobytes() for a in arrays]


def _assert_byte_identical(host_out, dev_out):
    h_arrays, h_meta = host_out
    d_arrays, d_meta = dev_out
    assert h_meta == d_meta
    assert _frames_bytes(h_arrays) == _frames_bytes(d_arrays)


def _sparse_pair(dim=8192):
    base = RNG.standard_normal(dim).astype(np.float32)
    new = base.copy()
    new[3] = -0.0
    new[17] = _nan_payload()
    new[dim - 1] = 42.0
    return base, new


def _xorz_pair(dim=8192):
    base = RNG.standard_normal(dim).astype(np.float32)
    new = (base.view(np.uint32) ^ np.uint32(1)).view(np.float32).copy()
    return base, new


def _raw_pair(dim=4096):
    base = RNG.integers(0, 256, 4 * dim, dtype=np.uint8).view(
        np.float32).copy()
    new = RNG.integers(0, 256, 4 * dim, dtype=np.uint8).view(
        np.float32).copy()
    return base, new


class TestDeviceHostParity:
    """Device frames == host frames, byte for byte, scheme for scheme."""

    @pytest.mark.parametrize("pair,scheme", [
        (_sparse_pair, "sparse"),
        (_xorz_pair, "xorz"),
        (_raw_pair, "raw"),
    ])
    def test_schemes_byte_identical(self, pair, scheme):
        base, new = pair()
        host = DeltaCodec.encode(base, new)
        dev = DeviceDeltaCodec.encode(jnp.asarray(base), jnp.asarray(new))
        assert host[1]["scheme"] == scheme
        _assert_byte_identical(host, dev)

    def test_negative_zero_and_nan_survive_device_round_trip(self):
        base, new = _sparse_pair()
        arrays, meta = DeviceDeltaCodec.encode(
            jnp.asarray(base), jnp.asarray(new))
        out = np.asarray(DeviceDeltaCodec.decode(
            jnp.asarray(base), arrays, meta))
        assert out.tobytes() == new.tobytes()
        # the payload bits specifically (not just canonical NaN-ness)
        assert out[17].tobytes() == _nan_payload().tobytes()
        assert np.signbit(out[3]) and out[3] == 0.0

    def test_identical_vectors_empty_sparse(self):
        base, _ = _sparse_pair()
        host = DeltaCodec.encode(base, base.copy())
        dev = DeviceDeltaCodec.encode(jnp.asarray(base), jnp.asarray(base))
        assert host[1]["scheme"] == "sparse"
        _assert_byte_identical(host, dev)
        assert payload_nbytes(host[0]) == 0

    @pytest.mark.parametrize("dtype", [np.int32, np.uint8, np.float32])
    def test_dtype_parity(self, dtype):
        base = RNG.integers(0, 100, 2048).astype(dtype)
        new = base.copy()
        new[7] = dtype(3)
        new[99] = dtype(9)
        host = DeltaCodec.encode(base, new)
        dev = DeviceDeltaCodec.encode(jnp.asarray(base), jnp.asarray(new))
        _assert_byte_identical(host, dev)

    def test_cross_path_decode(self):
        """Host-encoded frames decode on device and vice versa — the two
        ends of a wire can run different paths."""
        for pair in (_sparse_pair, _xorz_pair, _raw_pair):
            base, new = pair()
            h_arrays, h_meta = DeltaCodec.encode(base, new)
            out_dev = np.asarray(DeviceDeltaCodec.decode(
                jnp.asarray(base), h_arrays, h_meta))
            assert out_dev.tobytes() == new.tobytes()
            d_arrays, d_meta = DeviceDeltaCodec.encode(
                jnp.asarray(base), jnp.asarray(new))
            out_host = DeltaCodec.decode(
                base, [np.asarray(a) for a in d_arrays], d_meta)
            assert out_host.tobytes() == new.tobytes()


class TestOverflowGuard:
    """int32 indices can't address ≥ 2^31 — the host codec prices sparse
    out; the device path refuses the dim outright (host fallback), so the
    guard's byte behavior is identical on both paths."""

    def test_plan_frame_prices_sparse_out(self):
        raw_cost = 4096
        scheme, comp = plan_frame(raw_cost, 4, 1, 1 << 31,
                                  lambda: b"x" * (raw_cost - 1))
        assert scheme == "xorz"
        scheme, _ = plan_frame(raw_cost, 4, 1, 1 << 31,
                               lambda: b"x" * raw_cost)
        assert scheme == "raw"
        # one index below the guard: sparse is a clear win again
        scheme, _ = plan_frame(raw_cost, 4, 1, (1 << 31) - 1, lambda: None)
        assert scheme == "sparse"

    def test_device_path_refuses_unaddressable_dims(self):
        assert not device_supported(np.float32, 1 << 31)
        assert not device_supported(np.float32, 0)
        assert not device_supported(np.float64, 128)  # x64 off: 8-byte host
        assert device_supported(np.float32, (1 << 31) - 1)

    def test_wirecodec_falls_back_for_unsupported_dtype(self):
        wire = WireCodec("device")
        before = telemetry.registry().snapshot()["counters"].get(
            "comm.wire.host_fallbacks", 0.0)
        base = RNG.standard_normal(256)  # float64
        new = base.copy()
        new[3] = 7.0
        arrays, meta = wire.encode(base, new)
        out = wire.decode(base, arrays, meta)
        assert isinstance(out, np.ndarray)  # host path served it
        assert out.tobytes() == new.tobytes()
        after = telemetry.registry().snapshot()["counters"].get(
            "comm.wire.host_fallbacks", 0.0)
        assert after > before


class TestBatchedEncode:
    """vmap'd per-cohort encode over stacked bases ≡ sequential encodes."""

    def test_batch_equals_sequential(self):
        new = RNG.standard_normal(4096).astype(np.float32)
        bases = []
        b1 = new.copy()
        b1[5] = -1.0  # sparse delta
        bases.append(b1)
        bases.append((new.view(np.uint32) ^ np.uint32(1)).view(
            np.float32).copy())  # xorz-ish delta
        bases.append(RNG.integers(0, 256, 4 * 4096, dtype=np.uint8).view(
            np.float32).copy())  # raw-ish
        bases.append(new.copy())  # identical: empty sparse
        dev_bases = [jnp.asarray(b) for b in bases]
        dev_new = jnp.asarray(new)
        seq = [DeviceDeltaCodec.encode(b, dev_new) for b in dev_bases]
        bat = DeviceDeltaCodec.encode_batch(dev_bases, dev_new)
        assert len(bat) == len(seq)
        for s, b in zip(seq, bat):
            _assert_byte_identical(s, b)

    def test_batch_matches_host(self):
        new = RNG.standard_normal(2048).astype(np.float32)
        bases = [new.copy() for _ in range(3)]
        bases[0][7] = 1.5
        bases[1][100] = _nan_payload()
        for host_base, (arrays, meta) in zip(
                bases, DeviceDeltaCodec.encode_batch(
                    [jnp.asarray(b) for b in bases], jnp.asarray(new))):
            h_arrays, h_meta = DeltaCodec.encode(host_base, new)
            assert h_meta == meta
            assert _frames_bytes(h_arrays) == _frames_bytes(arrays)

    def test_wirecodec_encode_batch_host_fallback(self):
        wire = WireCodec("host")
        new = RNG.standard_normal(512).astype(np.float32)
        b = new.copy()
        b[0] = 2.0
        out = wire.encode_batch([b], new)
        assert len(out) == 1
        assert out[0][1]["scheme"] == "sparse"


class TestDlpackEmission:
    """Device buffers ride the raw-frame writer zero-copy and round-trip
    through ``decode_frames`` bit-exactly."""

    def test_device_frames_through_raw_writer(self):
        base, new = _sparse_pair()
        arrays, meta = DeviceDeltaCodec.encode(
            jnp.asarray(base), jnp.asarray(new))
        body = encode_frames(arrays)
        back = decode_frames(body)
        assert _frames_bytes(back) == _frames_bytes(arrays)
        out = DeltaCodec.decode(base, back, meta)
        assert out.tobytes() == new.tobytes()

    def test_host_view_is_zero_copy(self):
        dev = jnp.arange(1024, dtype=jnp.float32)
        view = host_view(dev)
        assert isinstance(view, np.ndarray)
        assert view.tobytes() == np.asarray(dev).tobytes()

    def test_raw_scheme_emits_device_buffer(self):
        base, new = _raw_pair()
        arrays, meta = DeviceDeltaCodec.encode(
            jnp.asarray(base), jnp.asarray(new))
        assert meta["scheme"] == "raw"
        body = encode_frames(arrays)
        assert decode_frames(body)[0].tobytes() == new.tobytes()

    def test_encode_parts_memoryview_zero_copy(self):
        a = np.arange(256, dtype=np.float32)
        parts = encode_frame_parts([a])
        views = [p for p in parts if isinstance(p, memoryview)]
        assert views, "contiguous arrays must ride as memoryviews"
        assert b"".join(parts) == encode_frames([a])


class TestHostCodecSatellites:
    """The host-codec small fixes that rode along with the device path."""

    def test_payload_nbytes_never_touches_data(self):
        class _Exploding:
            """nbytes/shape metadata only — any data access raises."""
            nbytes = 4096

            def __array__(self, *a, **k):
                raise AssertionError("payload_nbytes touched array data")

        assert payload_nbytes([_Exploding(), np.zeros(2, np.float32)]) \
            == 4096 + 8

    def test_raw_decode_adopts_owned_buffer(self):
        base, new = _raw_pair()
        arrays, meta = DeltaCodec.encode(base, new)
        assert meta["scheme"] == "raw"
        owned = np.array(arrays[0], copy=True)
        out = DeltaCodec.decode(base, [owned], meta)
        assert out is owned  # frame owns its buffer: adopted, not copied
        ro = decode_frames(encode_frames(arrays))
        out2 = DeltaCodec.decode(base, ro, meta)
        assert out2 is not ro[0]  # read-only wire view: copied
        assert out2.tobytes() == new.tobytes()

    def test_wire_path_resolution(self):
        assert resolve_wire_path("host") == "host"
        assert resolve_wire_path("device") == "device"  # jax importable here
        # auto picks the device kernels only when a REAL accelerator backs
        # jax — on the CPU backend the XLA stand-in loses to numpy, so
        # auto degrades to host while an explicit request still forces it
        import jax as _jax

        expected = ("device" if _jax.devices()[0].platform != "cpu"
                    else "host")
        assert resolve_wire_path("auto") == expected
        assert WireCodec("host").path == "host"


class TestDeviceStoreCache:
    """Ring heads stay device-resident: one upload per version."""

    def test_get_device_uploads_once(self):
        store = VersionedModelStore(4, metric_prefix="test.wire_store")
        vec = RNG.standard_normal(512).astype(np.float32)
        store.put(3, vec)
        d1 = store.get_device(3)
        d2 = store.get_device(3)
        assert d1 is d2  # cached, not re-uploaded
        assert np.asarray(d1).tobytes() == vec.tobytes()

    def test_put_seeds_device_cache(self):
        store = VersionedModelStore(4, metric_prefix="test.wire_store")
        vec = RNG.standard_normal(128).astype(np.float32)
        dev = jnp.asarray(vec)
        store.put(1, vec, device=dev)
        assert store.get_device(1) is dev

    def test_eviction_drops_device_copy(self):
        store = VersionedModelStore(2, metric_prefix="test.wire_store")
        vecs = {v: RNG.standard_normal(64).astype(np.float32)
                for v in range(4)}
        for v in range(3):
            store.put(v, vecs[v])
            store.get_device(v)
        store.put(3, vecs[3])  # evicts 0 and 1
        assert store.get_device(0) is None
        assert store.get_device(1) is None
        got = store.get_device(2)
        assert got is not None
        assert np.asarray(got).tobytes() == vecs[2].tobytes()

    def test_missing_version_is_none(self):
        store = VersionedModelStore(2, metric_prefix="test.wire_store")
        assert store.get_device(None) is None
        assert store.get_device(99) is None


class TestWireTelemetry:
    def test_encode_decode_observed(self):
        wire = WireCodec("device")
        snap0 = telemetry.registry().snapshot()
        enc0 = (snap0["histograms"].get("comm.wire.encode_s") or
                {}).get("count", 0)
        base, new = _sparse_pair(1024)
        arrays, meta = wire.encode(jnp.asarray(base), jnp.asarray(new))
        wire.decode(jnp.asarray(base), arrays, meta)
        snap = telemetry.registry().snapshot()
        assert (snap["histograms"]["comm.wire.encode_s"]["count"]
                > enc0)
        assert snap["counters"].get("comm.wire.device_encodes", 0) > 0
        assert snap["counters"].get("comm.wire.device_decodes", 0) > 0

    def test_bucket_recompiles_bounded(self):
        """Power-of-two nonzero buckets: growing change counts reuse
        compiled kernels instead of recompiling per count."""
        from fedml_tpu.delivery.device_codec import _bucket

        dim = 1 << 20
        buckets = {_bucket(c, dim) for c in range(1, 10_000)}
        assert len(buckets) <= 15
        assert all(b >= c for c, b in
                   ((c, _bucket(c, dim)) for c in (1, 7, 100, 9999)))
