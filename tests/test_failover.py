"""Survivable serving plane (ISSUE 12): server crash-failover, client
resync FSM, deadline-based partial cohorts, and the reconstruction of the
server's hot state from durable substrate.

Four layers:

- deadline plane: ``--round_deadline_s`` partial cohorts are bitwise-equal
  to full-cohort FedAvg when nobody straggles, and a seeded straggler run
  converges with partial rounds > 0 and zero dropped contributions (late
  arrivals fold via the staleness path);
- in-process crash-failover: a server transport killed at a deterministic
  point (FaultyComm.kill right after a ledger commit), a second server
  manager resumed on the same world, heartbeat-driven client resync with
  cached-update replay — bitwise parity with an uninterrupted run;
- reconstruction units: version-store ring rebuilt from the checkpoint
  retention window (digests equal, evicted boundaries honored), re-solicited
  updates folding with the same staleness weights, run_meta identity
  refusal;
- subprocess SIGKILL matrix: ``kill_server`` at each protocol phase
  (pre_fold / mid_fold / post_commit), restart with ``--resume auto``,
  bitwise parity + exactly one ledger entry per committed round.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import chaos
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.faults import FaultPlan
from fedml_tpu.core.mlops import telemetry
from fedml_tpu.core.runstate import RunLedger
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer

HB = dict(heartbeat_s=0.2, heartbeat_miss_limit=2, resync_backoff_s=0.2,
          resync_backoff_max_s=1.0, resync_max_attempts=60)


def make_args(run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        epochs=1, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1000, random_seed=7,
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_world(run_id, n_clients=2, fault_plans=None, server_plan=None,
              **kw):
    args_s = make_args(run_id, role="server",
                       client_num_in_total=n_clients, **kw)
    if server_plan is not None:
        args_s.fault_plan = server_plan
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)
    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args(run_id, role="client", rank=rank,
                           client_num_in_total=n_clients, **kw)
        if fault_plans and rank in fault_plans:
            args_c.fault_plan = fault_plans[rank]
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    server.run()
    for t in threads:
        t.join(timeout=30)
    return server, clients


def _leaves(manager):
    import jax

    return [np.asarray(l) for l in jax.tree.leaves(manager.global_params)]


class TestPartialCohortDeadline:
    def test_deadline_unfired_is_bitwise_identical(self):
        """--round_deadline_s with nobody straggling: the deadline never
        fires and the run is BITWISE the plain full-cohort FedAvg run."""
        ref, _ = run_world("dl-ref")
        dl, _ = run_world("dl-on", round_deadline_s=30.0)
        for a, b in zip(_leaves(ref.manager), _leaves(dl.manager)):
            assert a.dtype == b.dtype and np.array_equal(a, b), \
                "an unfired deadline changed the numerics"

    def test_straggler_partial_rounds_and_late_folds(self, tmp_path):
        """A persistent straggler under --round_deadline_s: rounds close
        partially on the deadline, the straggler's late updates fold into
        the open round via the staleness path (never dropped), and the
        federation converges with every contribution counted exactly
        once."""
        reg = telemetry.registry()
        partial0 = reg.counter("traffic.partial_rounds")
        late0 = reg.counter("traffic.late_folds")
        plans = {1: FaultPlan().straggle(1, 1.0)}  # every send 1s late
        server, clients = run_world(
            "dl-straggle", fault_plans=plans, comm_round=4,
            round_deadline_s=0.6, min_clients_per_round=1,
            async_staleness_alpha=0.5,
            checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_rounds=1,
        )
        assert server.manager.round_idx == 4
        assert reg.counter("traffic.partial_rounds") > partial0
        assert reg.counter("traffic.late_folds") > late0
        # exactly-once: no contribution ever aggregated twice, and from
        # round 1 on every round folds BOTH clients (one fresh, one late)
        led = RunLedger.for_checkpoint_dir(str(tmp_path / "ckpt"))
        rounds = led.rounds()
        assert sorted(e["round"] for e in rounds) == [0, 1, 2, 3]
        for e in rounds:
            for client, count in (e.get("contrib") or {}).items():
                assert count == 1, (e["round"], client, count)
        # the straggler's work is not thrown away: its late updates fold
        # into later rounds (how many rounds close partially vs full is
        # host-timing dependent — the counters above pin that both partial
        # closes and late folds actually happened)
        assert any(1 in (e.get("cohort") or []) for e in rounds
                   if e["round"] >= 1), \
            "no straggler contribution ever folded after round 0"
        # a late-folding round records the trained-at rounds so a
        # restarted server rebuilds its committed-contribution map
        late_rounds = [e for e in rounds if e.get("client_versions")]
        assert late_rounds, "no round recorded client_versions"
        for e in late_rounds:
            assert len(e["client_versions"]) == len(e["cohort"])
            assert min(e["client_versions"]) < e["round"]
        # zero dropped contributions: every trained round of the straggler
        # short of the final one appears exactly once across the ledger
        straggler_versions = sorted(
            v for e in rounds
            for s, v in zip(e["cohort"],
                            e.get("client_versions")
                            or [e["round"]] * len(e["cohort"]))
            if s == 1
        )
        assert straggler_versions == sorted(set(straggler_versions)), \
            "a straggler update folded twice"
        assert straggler_versions[0] == 0

    def test_deadline_below_min_clients_keeps_waiting(self):
        """A deadline with fewer than min_clients models re-arms instead
        of closing an empty round."""
        plans = {1: FaultPlan().straggle(1, 0.8),
                 2: FaultPlan().straggle(2, 0.8)}
        server, _ = run_world(
            "dl-wait", fault_plans=plans, comm_round=2,
            round_deadline_s=0.3, min_clients_per_round=1,
        )
        assert server.manager.round_idx == 2  # completed, never wedged


class _Killable:
    """Find the server's FaultyComm wrapper so a test can declare it dead
    at a deterministic protocol point."""

    @staticmethod
    def kill(server):
        comm = server.manager.com_manager
        assert hasattr(comm, "kill"), "server transport is not FaultyComm"
        comm.kill()


class TestServerCrashFailover:
    def _run_crash_world(self, tmp_path, kill_after_round):
        """Run a heartbeat world, kill the server's transport right after
        the ledger commits ``kill_after_round`` (fail-stop: its queue goes
        dark), resume a second server manager on the same world, and
        return (server_b, clients)."""
        ck = str(tmp_path / "ckpt")
        run_id = f"crash-{kill_after_round}-{os.getpid()}"
        args_s = make_args(run_id, role="server", checkpoint_dir=ck,
                           checkpoint_rounds=1, **HB)
        args_s.fault_plan = FaultPlan()  # wrap only: external kill()
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        server_a = FedMLCrossSiloServer(args_s, None, ds, bundle)
        clients = [
            FedMLCrossSiloClient(
                make_args(run_id, role="client", rank=r, **HB),
                None, ds, bundle)
            for r in (1, 2)
        ]
        threads = [threading.Thread(target=c.run, daemon=True)
                   for c in clients]
        for t in threads:
            t.start()
        time.sleep(0.05)
        ta = threading.Thread(target=server_a.manager.run, daemon=True)
        ta.start()
        led = RunLedger.for_checkpoint_dir(ck)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            last = led.last_round()
            if last is not None and last >= kill_after_round:
                break
            time.sleep(0.01)
        else:
            pytest.fail("round never committed before the kill window")
        _Killable.kill(server_a)  # fail-stop: no drain, no FINISH
        ta.join(timeout=30)
        # the dead process's orbax threads die with it in real life; the
        # in-process stand-in must reap them (they would race jax tracing
        # in later tests)
        server_a.manager._ckpt.close()

        args_b = make_args(run_id, role="server", checkpoint_dir=ck,
                           checkpoint_rounds=1, **HB)
        server_b = FedMLCrossSiloServer(args_b, None, ds, bundle)
        tb = threading.Thread(target=server_b.run, daemon=True)
        tb.start()
        tb.join(timeout=120)
        for t in threads:
            t.join(timeout=30)
        return server_b, clients

    def test_kill_after_commit_resync_bitwise(self, tmp_path):
        """Server transport killed right after round 0's ledger commit;
        surviving clients heartbeat-miss, resync, and replay anything
        uncommitted; the restarted manager reconstructs from ledger +
        checkpoint and the federation finishes BITWISE equal to the
        fault-free run, with each contribution folded exactly once."""
        reg = telemetry.registry()
        resyncs0 = reg.counter("comm.resyncs")
        recoveries0 = reg.counter("run.server_recoveries")
        ref, _ = run_world(f"crash-ref-{os.getpid()}")
        ref_params = _leaves(ref.manager)

        server_b, clients = self._run_crash_world(tmp_path,
                                                  kill_after_round=0)
        assert server_b.manager.done.is_set(), "resumed server never finished"
        assert all(c.manager.done.is_set() for c in clients), \
            "a client never reached FINISH across the kill"
        for a, b in zip(ref_params, _leaves(server_b.manager)):
            assert a.dtype == b.dtype and np.array_equal(a, b), \
                "kill + resync diverged from the fault-free run"
        assert reg.counter("comm.resyncs") > resyncs0
        assert reg.counter("run.server_recoveries") > recoveries0
        # exactly one ledger entry per round, nobody counted twice
        led = RunLedger.for_checkpoint_dir(str(tmp_path / "ckpt"))
        rounds = [e["round"] for e in led.rounds()]
        assert sorted(rounds) == [0, 1, 2]
        assert len(rounds) == len(set(rounds))
        for e in led.rounds():
            for client, count in (e.get("contrib") or {}).items():
                assert count == 1, (e["round"], client, count)

    def test_resync_ack_after_finish_delivers_final_model(self, tmp_path):
        """A resync landing on a FINISHED federation gets the final model
        (S2C_FINISH) instead of silence — the late client terminates."""
        server, clients = run_world(f"finish-resync-{os.getpid()}", **HB)
        mgr = server.manager
        # drive the handler directly: done is set, a straggling resync
        # arrives from rank 1
        from fedml_tpu.core.distributed import Message
        from fedml_tpu.cross_silo.message_define import MyMessage

        sent = []
        mgr.send_message = lambda m: sent.append(m)
        resync = Message(MyMessage.MSG_TYPE_C2S_RESYNC, 1, 0)
        resync.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, 2)
        mgr._on_resync(resync)
        assert sent and sent[0].get_type() == MyMessage.MSG_TYPE_S2C_FINISH


class TestServingStateReconstruction:
    """ISSUE 12 satellite: fold-buffer and version-store reconstruction
    units — an async federation serialized mid-buffer, the server manager
    restarted, and the rebuilt state compared against the pre-kill one."""

    def _async_manager(self, tmp_path, run_id, seed=7):
        args = make_args(run_id, role="server",
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         checkpoint_rounds=1, aggregation_mode="async",
                         async_buffer_size=2, async_staleness_alpha=0.5,
                         random_seed=seed)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        return FedMLCrossSiloServer(args, None, ds, bundle).manager, bundle

    def _update_item(self, mgr, sender, client_version, n=4.0):
        import jax

        leaves = [np.asarray(l) for l in jax.tree.leaves(mgr.global_params)]
        return (time.monotonic(), sender, client_version, n, leaves,
                None, None, None)

    def test_store_ring_and_buffer_weights_survive_restart(self, tmp_path):
        """(a) the restarted store ring matches the pre-kill committed
        state digest-for-digest; (b) a re-solicited update folds with the
        SAME staleness weight it would have folded with pre-kill."""
        run_id = f"rebuild-{os.getpid()}"
        mgr_a, _ = self._async_manager(tmp_path, run_id)
        # two committed server steps: versions 1 and 2 (ckpt steps 0, 1)
        for step in range(2):
            for sender in (1, 2):
                mgr_a._async_fold(
                    self._update_item(mgr_a, sender, mgr_a.round_idx))
            assert mgr_a._async_step()
        assert mgr_a.round_idx == 2
        # one MID-BUFFER (uncommitted, in-flight) fold: stale by 1 version.
        # sender 3 has no committed contribution at version 1 — since
        # ISSUE 19 the root's committed-round guard drops a replayed
        # (sender, client_version) pair that already entered a committed
        # aggregation, so the in-flight update must come from a pair the
        # ledger does NOT cover
        mgr_a._async_fold(self._update_item(mgr_a, 3, 1))
        pre_entries = list(mgr_a.buffer._entries)
        assert len(pre_entries) == 1 and pre_entries[0].staleness == 1
        pre_weight = pre_entries[0].weight
        pre_digests = {v: mgr_a.store.digest(v)
                       for v in mgr_a.store.versions()}

        # restart: a second manager on the same checkpoint dir
        mgr_b, _ = self._async_manager(tmp_path, run_id)
        assert mgr_b.round_idx == 2
        # (a) ring contents: every version a checkpoint backs is rebuilt
        # with an identical digest; version 0 (never committed) stays out
        # — the evicted/unrecoverable boundary is honored, a delta against
        # it gets the loud fallback
        assert mgr_b.store.versions() == [1, 2]
        for v in mgr_b.store.versions():
            assert mgr_b.store.digest(v) == pre_digests[v], v
        assert not mgr_b.store.has(0)
        # the fold buffer restarts EMPTY but consistent
        assert mgr_b.buffer.occupancy() == 0
        # (b) the re-solicited update (the client replays the same vector
        # against the same version) folds with the same staleness weight
        mgr_b._async_fold(self._update_item(mgr_b, 3, 1))
        post = list(mgr_b.buffer._entries)
        assert len(post) == 1
        assert post[0].staleness == pre_entries[0].staleness
        assert post[0].weight == pre_weight
        # the committed-contribution map came back from the ledger
        assert mgr_b._committed_client_round == {1: 1, 2: 1}
        # ...and it guards the fold path: a replay of sender 1's COMMITTED
        # version-1 contribution is dropped, never double-counted
        drops0 = mgr_b.world.telemetry.counter("traffic.replay_dedup_drops")
        mgr_b._async_fold(self._update_item(mgr_b, 1, 1))
        assert len(list(mgr_b.buffer._entries)) == 1
        assert mgr_b.world.telemetry.counter(
            "traffic.replay_dedup_drops") == drops0 + 1
        mgr_a._ckpt.close()
        mgr_b._ckpt.close()

    def test_resume_refuses_mismatched_identity(self, tmp_path):
        """(c) resuming a ledger whose run_meta identity disagrees is a
        loud error, not a silent cross-federation merge."""
        run_id = f"identity-{os.getpid()}"
        mgr_a, _ = self._async_manager(tmp_path, run_id)
        for sender in (1, 2):
            mgr_a._async_fold(
                self._update_item(mgr_a, sender, mgr_a.round_idx))
        assert mgr_a._async_step()
        with pytest.raises(RuntimeError, match="run_meta mismatch"):
            self._async_manager(tmp_path, run_id, seed=8)
        mgr_a._ckpt.close()


class TestKillServerPhases:
    """The headline acceptance: SIGKILL (no drain) at each protocol phase
    + restart + client resync is BITWISE equal to the fault-free run, with
    the ledger holding exactly one entry per committed round."""

    @pytest.mark.parametrize("phase", ["pre_fold", "mid_fold",
                                       "post_commit"])
    def test_sigkill_phase_restart_bitwise(self, tmp_path, phase):
        import types

        a = types.SimpleNamespace(
            clients=2, rounds=3, epochs=1, seed=7, loss=0.0, duplicate=0.0,
            corrupt=0.0, kill_round=1, kill_phase=phase, partition="",
            heartbeat_s=0.0, checkpoint_rounds=1, workdir=str(tmp_path),
            timeout=240.0, worker=False, server_only=False, out="",
            checkpoint_dir="", transport="loopback", port=0,
        )
        ref = chaos.run_world(
            a, run_id=f"killref-{phase}-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "ref_ckpt"), faulty=False)

        out = str(tmp_path / "out")
        ckpt = str(tmp_path / "kill_ckpt")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        import subprocess

        cwd = os.path.dirname(os.path.dirname(
            os.path.abspath(chaos.__file__)))
        p1 = subprocess.run(
            chaos._worker_cmd(a, out, ckpt, a.kill_round, kill_phase=phase),
            timeout=240, env=env, cwd=cwd,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        assert p1.returncode in chaos.SIGKILL_RCS, (
            f"expected SIGKILL death, got rc={p1.returncode}:\n"
            + p1.stdout.decode(errors="replace")[-3000:])
        p2 = subprocess.run(
            chaos._worker_cmd(a, out, ckpt, -1, kill_phase=""),
            timeout=240, env=env, cwd=cwd,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        assert p2.returncode == 0, \
            p2.stdout.decode(errors="replace")[-3000:]

        with open(os.path.join(out, chaos.REPORT_FILE)) as f:
            report = json.load(f)
        assert report["preempted"] is False
        assert report["round_idx"] == a.rounds
        with np.load(os.path.join(out, chaos.FINAL_PARAMS_FILE)) as z:
            kill_params = [z[k] for k in z.files]
        assert len(kill_params) == len(ref["params"])
        for i, (x, y) in enumerate(zip(ref["params"], kill_params)):
            assert x.dtype == y.dtype and np.array_equal(x, y), \
                f"leaf {i} not bitwise equal after {phase} SIGKILL+restart"
        # SIGKILL never drains: exactly ONE ledger entry per round, every
        # contribution counted once
        led = RunLedger.for_checkpoint_dir(ckpt)
        rounds = [e["round"] for e in led.rounds()]
        assert sorted(rounds) == list(range(a.rounds))
        assert len(rounds) == len(set(rounds)), "a round committed twice"
        for e in led.rounds():
            for client, count in (e.get("contrib") or {}).items():
                assert count == 1, (e["round"], client, count)


class TestGrpcRestartedServerReconnect:
    def test_send_survives_server_restart_on_same_port(self):
        """ISSUE 12 satellite: a killed-and-restarted (multiplexed) gRPC
        server must be reachable — the client's stale channel is evicted
        on connection error and the next send re-dials."""
        import queue as queue_mod

        from fedml_tpu.core.distributed.grpc_backend import GRPCCommManager
        from fedml_tpu.core.distributed.message import Message
        from fedml_tpu.parallel.multihost import free_port

        base = free_port()
        got: "queue_mod.Queue" = queue_mod.Queue()

        class Obs:
            def receive_message(self, t, m):
                got.put((t, m.get_sender_id()))

        def serve():
            srv = GRPCCommManager("127.0.0.1", base, rank=0, world_size=2,
                                  base_port=base)
            srv.add_observer(Obs())
            th = threading.Thread(target=srv.handle_receive_message,
                                  daemon=True)
            th.start()
            return srv, th

        def drain_until(label):
            deadline = time.monotonic() + 10
            seen = []
            while time.monotonic() < deadline:
                try:
                    seen.append(got.get(timeout=0.2)[0])
                except queue_mod.Empty:
                    pass
                if label in seen:
                    return True
            return False

        srv1, th1 = serve()
        cli = GRPCCommManager(
            "127.0.0.1", base + 1, rank=1, world_size=2, base_port=base)
        msg = Message("probe", 1, 0)
        msg.set_arrays([np.arange(3, dtype=np.float32)])
        cli.send_message(msg)
        assert drain_until("probe")

        # kill the server process's stand-in: stop + release the port
        srv1.stop_receive_message()
        th1.join(timeout=10)
        # a send into the dead server exhausts the retry budget, raises,
        # and EVICTS the stale channel (the regression surface)
        import grpc

        dead = Message("probe_dead", 1, 0)
        dead.set_arrays([np.arange(3, dtype=np.float32)])
        with pytest.raises(grpc.RpcError):
            cli.send_message(dead)
        # restart on the SAME port (a new process image would do the same)
        srv2, th2 = serve()
        try:
            msg2 = Message("probe2", 1, 0)
            msg2.set_arrays([np.arange(3, dtype=np.float32)])
            cli.send_message(msg2)  # must re-dial, not die on a stale channel
            assert drain_until("probe2"), \
                "send after server restart never arrived"
        finally:
            cli.stop_receive_message()
            srv2.stop_receive_message()
            th2.join(timeout=10)


class TestStepGranularPreemption:
    def test_chunker_never_launches_scan_after_latch(self):
        """A latched PreemptionGuard forces the superround chunker to
        single rounds — the drain latency is bounded by ONE round, never
        another K-round scan program."""
        from fedml_tpu.core.runstate import preemption_guard
        from fedml_tpu.simulation.sp_api import FedAvgAPI

        overrides = dict(
            dataset="synthetic", model="lr", client_num_in_total=16,
            client_num_per_round=16, comm_round=8, epochs=1,
            batch_size=16, learning_rate=0.1, superround_k=4,
            preempt_signals=False, frequency_of_the_test=100,
        )
        args = fedml.init(Arguments(overrides=overrides),
                          should_init_logs=False)
        ds, od = data_mod.load(args)
        api = FedAvgAPI(args, fedml.get_device(args), ds,
                        model_mod.create(args, od))
        guard = preemption_guard()
        guard.reset()
        # round 4: no eval (freq 100) or checkpoint boundary strictly
        # inside the chunk — the scan is allowed
        assert api._chunk_len(4, 8, 100, 4) == 4
        guard.request()
        try:
            assert api._chunk_len(4, 8, 100, 4) == 1
            # without checkpointing (every=0) the guard is not consulted —
            # the legacy no-ckpt flow keeps its exact schedule
            assert api._chunk_len(4, 8, 100, 0) == 4
        finally:
            guard.reset()

    def test_cheetah_step_loop_drains_within_one_step(self, tmp_path):
        """SIGTERM (programmatic latch) during a cheetah pretrain exits
        after the in-flight STEP with the state checkpointed — not after
        the full step budget."""
        from collections import namedtuple

        import jax.numpy as jnp

        from fedml_tpu.cheetah.runner import CheetahRunner, config_from_args
        from fedml_tpu.core.runstate import PreemptionError, preemption_guard

        State = namedtuple("State", ["step", "params"])

        class StubTrainer:
            def init_state(self, rng):
                return State(step=0, params={"w": jnp.zeros((4,),
                                                            jnp.float32)})

            def train_step(self, state, tokens, mask):
                # the SIGTERM analog lands DURING the first step (run()
                # resets the guard at startup, as the real path does)
                preemption_guard().request()
                return (State(step=state.step + 1, params=state.params),
                        {"loss": jnp.float32(1.0)})

        args = fedml.init(Arguments(overrides=dict(
            training_type="distributed", backend="LOOPBACK",
            dataset="synthetic", total_steps=6, batch_size=2, seq_len=8,
            checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_every_rounds=100,  # cadence would only fire late
            preempt_signals=False,
        )), should_init_logs=False)
        runner = CheetahRunner.__new__(CheetahRunner)
        runner.args = args
        runner.cfg = config_from_args(args)
        runner.batch_size = 2
        runner.seq_len = 8
        runner.total_steps = 6
        runner.accum_steps = 1
        runner.trainer = StubTrainer()
        runner.dataset = None
        runner.checkpoint_dir = str(tmp_path / "ck")

        guard = preemption_guard()
        guard.reset()
        try:
            with pytest.raises(PreemptionError) as ei:
                runner.run()
        finally:
            guard.reset()
        assert ei.value.last_round == 0, \
            "drain did not stop at the first step boundary"
        from fedml_tpu.checkpoint import CheckpointManager

        ck = CheckpointManager(str(tmp_path / "ck"))
        try:
            assert ck.latest_step() == 1  # state AFTER the drained step
        finally:
            ck.close()
