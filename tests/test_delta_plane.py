"""Delta delivery plane tests (fedml_tpu/delivery/ — ISSUE 9).

Pins the tentpole's guarantees:

1. **Store**: bounded version ring, digests, eviction accounting.
2. **Codec**: the S2C delta wire format is LOSSLESS — bitwise
   reconstruction for sparse, dense, NaN/-0.0 and degenerate inputs.
3. **S2C parity**: a delta-shipped federation ends bitwise-identical to a
   full-broadcast one, with delta frames provably on the wire.
4. **async×compression**: the old refusal is gone; a STALE client's
   compressed delta decodes against its true base version and folds with
   the correct staleness weight.
5. **Eviction fallback**: evicted S2C bases fall back to full frames
   (loudly); evicted C2S bases drop the update and resync the sender.
6. **Ledger identity**: resuming under a different delivery config is
   refused.
7. **Dispatch policies**: server_push and client_pull (the new
   ``c2s_pull_request`` wire edge) both complete real federations.
8. **Adapter filter**: unselected leaves are frozen bitwise; payloads
   shrink; filter×codec compose.
9. **gRPC satellites**: rank→port multiplexing shares one server per
   port; the raw wire format is the default and corrupt raw frames are
   dropped by the digest, not crashed on.
"""

import threading
import time
import types

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.mlops import telemetry
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer
from fedml_tpu.delivery import VersionedModelStore, delivery_identity
from fedml_tpu.delivery.delta_codec import (
    DELTA_KEY,
    DeltaCodec,
    payload_nbytes,
)
from fedml_tpu.delivery.payload_filter import PayloadFilter, filter_from_args


def make_args(run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=3, client_num_per_round=3, comm_round=3,
        epochs=2, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1,
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_world(run_id, n_clients=3, **kw):
    args_s = make_args(run_id, role="server", client_num_in_total=n_clients,
                       **kw)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)
    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args(run_id, role="client", rank=rank,
                           client_num_in_total=n_clients, **kw)
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    result = server.run()
    for t in threads:
        t.join(timeout=60)
    return result, server, clients


def global_leaves(server):
    import jax

    return [np.asarray(l)
            for l in jax.tree.leaves(server.manager.global_params)]


# ---------------------------------------------------------------------------
# units: store, codec, filter
# ---------------------------------------------------------------------------


class TestVersionedModelStore:
    def test_put_get_roundtrip_and_digest(self):
        s = VersionedModelStore(4, metric_prefix="t.store.a")
        v = np.arange(8, dtype=np.float32)
        d = s.put(3, v)
        assert s.has(3) and s.digest(3) == d and len(d) == 16
        got = s.get(3)
        assert np.array_equal(got, v)
        # stored copy is detached: mutating the source never changes it
        v[0] = 99.0
        assert s.get(3)[0] == 0.0

    def test_bounded_ring_evicts_oldest(self):
        s = VersionedModelStore(2, metric_prefix="t.store.b")
        for ver in range(5):
            s.put(ver, np.full(3, float(ver), np.float32))
        assert s.versions() == [3, 4]
        assert s.occupancy() == 2
        assert s.evictions() == 3
        assert s.latest() == 4
        assert s.get(1) is None  # evicted → miss
        assert s.get(4)[0] == 4.0

    def test_capacity_validated(self):
        with pytest.raises(ValueError, match="delta_store_versions"):
            VersionedModelStore(0)

    def test_put_is_idempotent_per_version(self):
        s = VersionedModelStore(4, metric_prefix="t.store.c")
        v = np.ones(4, np.float32)
        assert s.put(1, v) == s.put(1, v)
        assert s.occupancy() == 1


class TestDeltaCodec:
    def roundtrip(self, base, new):
        arrays, meta = DeltaCodec.encode(base, new)
        out = DeltaCodec.decode(base, arrays, meta)
        assert out.dtype == new.dtype and out.shape == new.shape
        assert np.array_equal(out.view(np.uint32), new.view(np.uint32)), \
            f"scheme {meta['scheme']} not bitwise"
        return arrays, meta

    def test_sparse_bitwise_roundtrip(self):
        rng = np.random.RandomState(0)
        base = rng.randn(4096).astype(np.float32)
        new = base.copy()
        idx = rng.choice(4096, size=40, replace=False)
        new[idx] += 1.0
        arrays, meta = self.roundtrip(base, new)
        assert meta["scheme"] == "sparse"
        # 40 changed entries: ~320 payload bytes vs a 16 KB vector
        assert payload_nbytes(arrays) < base.nbytes // 10

    def test_dense_delta_still_bitwise(self):
        rng = np.random.RandomState(1)
        base = rng.randn(2048).astype(np.float32)
        new = (base + rng.randn(2048) * 1e-3).astype(np.float32)
        _, meta = self.roundtrip(base, new)
        assert meta["scheme"] in ("xorz", "raw")

    def test_identical_vectors_cost_nothing(self):
        base = np.random.RandomState(2).randn(1024).astype(np.float32)
        arrays, meta = DeltaCodec.encode(base, base.copy())
        assert meta["scheme"] == "sparse"
        assert payload_nbytes(arrays) == 0
        assert np.array_equal(DeltaCodec.decode(base, arrays, meta), base)

    def test_bit_exact_corner_cases(self):
        # -0.0 vs 0.0 and NaN payloads must survive (bit comparison, not ==)
        base = np.array([0.0, 1.0, np.nan, 3.0], np.float32)
        new = np.array([-0.0, 1.0, np.nan, 4.0], np.float32)
        arrays, meta = DeltaCodec.encode(base, new)
        out = DeltaCodec.decode(base, arrays, meta)
        assert np.array_equal(out.view(np.uint32), new.view(np.uint32))
        assert np.signbit(out[0])

    def test_mismatched_frames_refused(self):
        a = np.zeros(4, np.float32)
        with pytest.raises(ValueError, match="disagree"):
            DeltaCodec.encode(a, np.zeros(5, np.float32))
        arrays, meta = DeltaCodec.encode(a, a)
        with pytest.raises(ValueError, match="does not match"):
            DeltaCodec.decode(np.zeros(5, np.float32), arrays, meta)
        with pytest.raises(ValueError, match="scheme"):
            DeltaCodec.decode(a, arrays, {**meta, "scheme": "bogus"})


class TestPayloadFilter:
    def tree(self):
        return {"params": {"Dense_0": {"kernel": np.ones((4, 3)),
                                       "bias": np.zeros(3)},
                           "head": {"kernel": np.ones((3, 2))}}}

    def test_select_merge_roundtrip(self):
        import jax

        f = PayloadFilter("head", self.tree())
        leaves = jax.tree.leaves(self.tree())
        sub = f.select(leaves)
        assert len(sub) == 1 and sub[0].shape == (3, 2)
        merged = f.merge(leaves, [np.full((3, 2), 7.0)])
        assert merged[f.indices[0]][0, 0] == 7.0
        # unselected leaves untouched, original list untouched
        assert leaves[f.indices[0]][0, 0] == 1.0

    def test_vector_roundtrip(self):
        import jax

        from fedml_tpu.delivery import flatten_leaves

        f = PayloadFilter("kernel", self.tree())
        leaves = jax.tree.leaves(self.tree())
        vec = f.select_vector(leaves)
        assert vec.size == 4 * 3 + 3 * 2
        back = f.split_vector(vec)
        assert [b.shape for b in back] == [(4, 3), (3, 2)]
        # slicing the FLAT model vector selects the same bytes as
        # selecting leaves then flattening (the codec decode fast path)
        full = flatten_leaves(leaves)
        np.testing.assert_array_equal(f.select_from_vector(full), vec)
        with pytest.raises(ValueError, match="does not match"):
            f.select_from_vector(full[:-1])

    def test_no_match_and_match_all_refused(self):
        with pytest.raises(ValueError, match="matches no leaf"):
            PayloadFilter("nonexistent", self.tree())
        with pytest.raises(ValueError, match="EVERY leaf"):
            PayloadFilter(".*", self.tree())
        with pytest.raises(ValueError, match="bad payload_filter"):
            PayloadFilter("(", self.tree())

    def test_from_args(self):
        a = types.SimpleNamespace(payload_filter="")
        assert filter_from_args(a, self.tree()) is None
        a.payload_filter = "bias"
        assert filter_from_args(a, self.tree()).selected_names == [
            "params/Dense_0/bias"]


class TestDeliveryIdentity:
    def test_plain_world_has_no_identity(self):
        assert delivery_identity(types.SimpleNamespace()) is None

    def test_codec_and_filter_are_identity(self):
        a = types.SimpleNamespace(compression="topk", compression_ratio=0.05,
                                  payload_filter="kernel",
                                  delta_store_versions=4)
        ident = delivery_identity(a)
        assert ident == {"store_versions": 4, "compression": "topk",
                         "compression_ratio": 0.05,
                         "payload_filter": "kernel"}


# ---------------------------------------------------------------------------
# the tentpole pins: S2C parity, async×compression, eviction, ledger
# ---------------------------------------------------------------------------


class TestS2CDeltaParity:
    def test_delta_sync_bitwise_equals_full_broadcast(self):
        """S2C delta shipping (the default) must reproduce the
        full-broadcast federation BITWISE — server global AND every
        client's installed params — with delta frames provably used."""
        import jax

        reg = telemetry.registry()
        frames0 = reg.counter("comm.delta.s2c_delta_frames")
        r_full, s_full, c_full = run_world("s2c-full", s2c_delta="off")
        assert reg.counter("comm.delta.s2c_delta_frames") == frames0
        r_delta, s_delta, c_delta = run_world("s2c-delta")
        assert reg.counter("comm.delta.s2c_delta_frames") > frames0
        for i, (a, b) in enumerate(zip(global_leaves(s_full),
                                       global_leaves(s_delta))):
            assert a.dtype == b.dtype and np.array_equal(a, b), f"leaf {i}"
        assert r_delta["test_acc"] == r_full["test_acc"]
        for cf, cd in zip(c_full, c_delta):
            for a, b in zip(
                    jax.tree.leaves(cf.manager.trainer.get_model_params()),
                    jax.tree.leaves(cd.manager.trainer.get_model_params())):
                assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_delta_frames_save_bytes_on_the_wire(self):
        reg = telemetry.registry()
        saved0 = reg.counter("comm.delta.s2c_bytes_saved")
        run_world("s2c-bytes", compression="topk", compression_ratio=0.05)
        assert reg.counter("comm.delta.s2c_bytes_saved") > saved0


class TestAsyncCompression:
    def _server(self, run_id, **kw):
        args_s = make_args(run_id, role="server", aggregation_mode="async",
                           async_buffer_size=3, **kw)
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        return FedMLCrossSiloServer(args_s, None, ds, bundle).manager, args_s

    def test_stale_delta_decodes_against_true_base_and_weight(self):
        """ISSUE 9 acceptance: a client that trained version 1 while the
        server moved to version 3 has its compressed delta decoded against
        the STORED version-1 global (not the head) and folded with weight
        n·(1+s)^-alpha for s = 2 — exactly."""
        import jax

        from fedml_tpu.core.compression import UpdateCodec
        from fedml_tpu.utils.tree import (
            tree_flatten_to_vector,
            tree_unflatten_from_vector,
        )

        mgr, args_s = self._server(
            "stale-decode", compression="topk", compression_ratio=0.25,
            async_staleness_alpha=1.0,
        )
        gvec, treedef, shapes = tree_flatten_to_vector(mgr.global_params)
        base1 = np.asarray(gvec) + 1.0  # a known version-1 global
        mgr.store.put(1, base1)
        mgr.store.put(2, np.asarray(gvec) + 2.0)
        mgr.round_idx = 3  # head version
        mgr.store.put(3, np.asarray(gvec) + 3.0)

        # the client trained FROM version 1 and ships a compressed delta
        codec = UpdateCodec(args_s)
        trained = base1 + np.linspace(0.0, 1.0, base1.size,
                                      dtype=np.float32)
        arrays, meta = codec.encode(base1, trained, 1)
        item = (time.monotonic(), 2, 1, 5.0, arrays, meta, None, None)
        mgr._async_fold(item)

        entries = mgr.buffer.drain()
        assert len(entries) == 1
        e = entries[0]
        assert e.sender == 2 and e.client_version == 1
        assert e.staleness == 3 - 1
        assert e.weight == pytest.approx(5.0 * (1.0 + 2) ** -1.0)
        # decoded against the TRUE base: bitwise equal to decoding by hand
        expect = tree_unflatten_from_vector(
            UpdateCodec.decode(base1, arrays, meta), treedef, shapes)
        for a, b in zip(jax.tree.leaves(expect), jax.tree.leaves(e.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_evicted_c2s_base_drops_and_resyncs(self):
        """A compressed delta whose base version was evicted cannot decode
        — the update is dropped (counted) and the sender is resynced at
        version head, never folded corrupt."""
        from fedml_tpu.core.compression import UpdateCodec
        from fedml_tpu.utils.tree import tree_flatten_to_vector

        reg = telemetry.registry()
        missing0 = reg.counter("comm.delta.c2s_base_missing")
        mgr, args_s = self._server(
            "evict-c2s", compression="topk", compression_ratio=0.25,
            delta_store_versions=2,
        )
        gvec, _, _ = tree_flatten_to_vector(mgr.global_params)
        base0 = np.asarray(gvec)
        for ver in (5, 6):  # capacity 2: version 0 (init) is evicted
            mgr.store.put(ver, base0 + ver)
        mgr.round_idx = 6
        codec = UpdateCodec(args_s)
        arrays, meta = codec.encode(base0, base0 + 0.5, 0)
        mgr._async_fold((time.monotonic(), 1, 0, 1.0, arrays, meta, None,
                         None))
        assert mgr.buffer.occupancy() == 0
        assert reg.counter("comm.delta.c2s_base_missing") == missing0 + 1

    def test_async_compressed_world_matches_sync_compressed(self):
        """async K=N alpha=0 ≡ sync BITWISE — now WITH compression on,
        proving the store-decoded path hits the same aggregation core."""
        r_sync, s_sync, _ = run_world(
            "comp-sync", compression="topk", compression_ratio=0.1)
        r_async, s_async, _ = run_world(
            "comp-async", aggregation_mode="async", async_buffer_size=3,
            async_staleness_alpha=0.0, compression="topk",
            compression_ratio=0.1,
        )
        assert s_async.manager.round_idx == s_sync.manager.round_idx == 3
        for i, (a, b) in enumerate(zip(global_leaves(s_sync),
                                       global_leaves(s_async))):
            assert a.dtype == b.dtype and np.array_equal(a, b), f"leaf {i}"


class TestS2CEvictionFallback:
    def test_evicted_ack_falls_back_to_full_frame(self):
        mgr, _ = TestAsyncCompression()._server(
            "evict-s2c", delta_store_versions=2)
        reg = telemetry.registry()
        full0 = reg.counter("comm.delta.s2c_full_frames")
        delta0 = reg.counter("comm.delta.s2c_delta_frames")
        leaves = global_leaves(types.SimpleNamespace(manager=mgr))
        vec = np.concatenate([np.ravel(l) for l in leaves])
        with mgr._lock:
            mgr._acked[1] = 0  # client ACKed version 0 ...
        for ver in (7, 8):     # ... which capacity-2 evicts
            mgr.store.put(ver, vec + ver)
        arrays, meta = mgr._encode_model_payload(1, leaves, vec, {})
        assert meta is None and len(arrays) == len(leaves)
        assert reg.counter("comm.delta.s2c_full_frames") == full0 + 1
        # a live ACK gets a delta frame with the right base version
        with mgr._lock:
            mgr._acked[1] = 8
        arrays, meta = mgr._encode_model_payload(1, leaves, vec, {})
        assert meta is not None and meta["base_version"] == 8
        assert reg.counter("comm.delta.s2c_delta_frames") == delta0 + 1


class TestLedgerIdentity:
    def test_resume_under_different_delivery_config_refused(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        result, server, _ = run_world(
            "deliv-ledger", compression="topk", compression_ratio=0.1,
            checkpoint_dir=ckpt, checkpoint_rounds=1,
        )
        assert server.manager.round_idx == 3
        from fedml_tpu.core.runstate import RunLedger

        meta = RunLedger.for_checkpoint_dir(ckpt).meta()
        assert meta["world"]["delivery"]["compression"] == "topk"
        # dropping --compression is a DIFFERENT delivery config: refused
        args_s = make_args("deliv-ledger-2", role="server",
                           checkpoint_dir=ckpt)
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        with pytest.raises(RuntimeError, match="different federation"):
            FedMLCrossSiloServer(args_s, None, ds, bundle)
        # and so is a different store depth under the same codec
        args_s2 = make_args("deliv-ledger-3", role="server",
                            checkpoint_dir=ckpt, compression="topk",
                            compression_ratio=0.1, delta_store_versions=3)
        with pytest.raises(RuntimeError, match="different federation"):
            FedMLCrossSiloServer(args_s2, None, ds, bundle)


# ---------------------------------------------------------------------------
# dispatch policies
# ---------------------------------------------------------------------------


class TestDispatchPolicies:
    def test_server_push_completes(self):
        result, server, clients = run_world(
            "push", aggregation_mode="async", async_buffer_size=3,
            async_dispatch="server_push", comm_round=3,
        )
        assert server.manager.round_idx == 3
        assert result is not None
        for c in clients:
            assert c.manager.done.wait(timeout=30)

    def test_client_pull_completes_via_pull_requests(self):
        reg = telemetry.registry()
        pulls0 = reg.counter("traffic.pull_requests")
        result, server, clients = run_world(
            "pull", aggregation_mode="async", async_buffer_size=3,
            async_dispatch="client_pull", comm_round=3,
        )
        assert server.manager.round_idx == 3
        assert result is not None
        assert reg.counter("traffic.pull_requests") > pulls0
        for c in clients:
            assert c.manager.done.wait(timeout=30)

    def test_policy_requires_async_mode(self):
        with pytest.raises(ValueError, match="aggregation_mode=async"):
            Arguments(overrides=dict(async_dispatch="client_pull"))
        with pytest.raises(ValueError, match="async_dispatch"):
            Arguments(overrides=dict(aggregation_mode="async",
                                     async_dispatch="bonkers"))


# ---------------------------------------------------------------------------
# adapter filter
# ---------------------------------------------------------------------------


class TestAdapterFilter:
    def test_unselected_leaves_frozen_bitwise(self):
        """--payload_filter kernel: bias leaves never change from init —
        bitwise — while kernel leaves train; bytes saved is counted."""
        import jax

        from fedml_tpu.scale.partition_rules import named_tree_paths

        reg = telemetry.registry()
        saved0 = reg.counter("comm.delta.c2s_bytes_saved")
        result, server, _ = run_world("filter", payload_filter="kernel")
        assert server.manager.round_idx == 3
        args_s = make_args("filter-skel", role="server")
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        init = bundle.init(jax.random.PRNGKey(0))
        final = server.manager.global_params
        for (name, a), b in zip(named_tree_paths(init),
                                jax.tree.leaves(final)):
            a, b = np.asarray(a), np.asarray(b)
            if "kernel" in name:
                assert not np.array_equal(a, b), f"{name} never trained"
            else:
                assert np.array_equal(a, b), f"frozen leaf {name} drifted"
        assert reg.counter("comm.delta.c2s_bytes_saved") > saved0

    def test_filter_composes_with_compression(self):
        result, server, _ = run_world(
            "filter-codec", payload_filter="kernel", compression="topk",
            compression_ratio=0.25,
        )
        assert server.manager.round_idx == 3
        assert result is not None

    def test_filter_mismatch_dropped_loudly(self):
        """A filtered payload against an unfiltered server is refused,
        counted, and never merged."""
        mgr, _ = TestAsyncCompression()._server("filter-mismatch")
        reg = telemetry.registry()
        drops0 = reg.counter("comm.delta.filter_mismatch_drops")
        out = mgr._reconstruct_update(
            1, 0, [np.zeros(3, np.float32)], None,
            {"pattern": "kernel", "n_selected": 1})
        assert out is None
        assert reg.counter("comm.delta.filter_mismatch_drops") == drops0 + 1


# ---------------------------------------------------------------------------
# satellites: gRPC multiplexing + raw default
# ---------------------------------------------------------------------------


class TestGrpcRankMultiplexing:
    def test_port_mapping(self):
        from fedml_tpu.core.distributed.grpc_backend import port_for_rank

        assert [port_for_rank(9000, r, 1) for r in range(4)] \
            == [9000, 9001, 9002, 9003]
        assert port_for_rank(9000, 0, 8) == 9000
        assert [port_for_rank(9000, r, 4) for r in range(1, 9)] \
            == [9001] * 4 + [9002] * 4

    def test_ranks_share_one_server_and_route_correctly(self):
        from fedml_tpu.core.distributed.grpc_backend import (
            GRPCCommManager,
            _SharedGrpcServer,
            port_for_rank,
        )
        from fedml_tpu.core.distributed.message import Message
        from fedml_tpu.parallel.multihost import free_port

        base = free_port()
        servers0 = _SharedGrpcServer.server_count()
        mgrs = {}
        for rank in (0, 1, 2):
            mgrs[rank] = GRPCCommManager(
                host="127.0.0.1", port=port_for_rank(base, rank, 2),
                rank=rank, world_size=3, base_port=base, ranks_per_port=2,
            )
        try:
            # 3 ranks, 2 listening sockets: rank 0 alone, ranks 1+2 shared
            assert _SharedGrpcServer.server_count() == servers0 + 2
            got = {r: [] for r in (0, 1, 2)}

            class Obs:
                def __init__(self, r):
                    self.r = r

                def receive_message(self, t, m):
                    got[self.r].append((t, m.get_sender_id()))

            threads = []
            for r, m in mgrs.items():
                m.add_observer(Obs(r))
                th = threading.Thread(target=m.handle_receive_message,
                                      daemon=True)
                th.start()
                threads.append(th)

            def send(frm, to, tag):
                msg = Message(tag, frm, to)
                msg.set_arrays([np.arange(5, dtype=np.float32)])
                mgrs[frm].send_message(msg)

            send(0, 1, "to1")
            send(0, 2, "to2")
            send(1, 0, "to0a")
            send(2, 0, "to0b")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not (
                    ("to1", 0) in got[1] and ("to2", 0) in got[2]
                    and len([x for x in got[0]
                             if x[0].startswith("to0")]) == 2):
                time.sleep(0.02)
            assert ("to1", 0) in got[1]
            assert ("to2", 0) in got[2]
            assert "to1" not in [t for t, _ in got[2]]
            assert "to2" not in [t for t, _ in got[1]]
            assert sorted(t for t, _ in got[0] if t.startswith("to0")) \
                == ["to0a", "to0b"]
        finally:
            for m in mgrs.values():
                m.stop_receive_message()
        # the last rank out stopped its shared server
        assert _SharedGrpcServer.server_count() == servers0

    def test_duplicate_rank_registration_refused(self):
        from fedml_tpu.core.distributed.grpc_backend import GRPCCommManager
        from fedml_tpu.parallel.multihost import free_port

        port = free_port()
        m = GRPCCommManager(host="127.0.0.1", port=port, rank=1,
                            world_size=2, base_port=port - 1,
                            ranks_per_port=1)
        try:
            with pytest.raises(ValueError, match="already registered"):
                GRPCCommManager(host="127.0.0.1", port=port, rank=1,
                                world_size=2, base_port=port - 1,
                                ranks_per_port=1)
        finally:
            m.stop_receive_message()


class TestRawWireDefault:
    def test_schema_default_is_raw(self):
        assert Arguments(overrides={}).grpc_wire_format == "raw"
        assert Arguments(
            overrides=dict(grpc_wire_format="npz")).grpc_wire_format == "npz"
        with pytest.raises(ValueError, match="grpc_wire_format"):
            Arguments(overrides=dict(grpc_wire_format="pickle"))

    def test_corrupt_raw_frame_dropped_not_crashed(self):
        """Chaos corrupt-frame coverage for the now-default raw format:
        a bit-flipped raw frame is rejected by the payload digest and
        counted, exactly like the npz path."""
        from fedml_tpu.core.distributed.delivery import safe_deserialize
        from fedml_tpu.core.distributed.message import Message

        reg = telemetry.registry()
        for fmt in ("raw", "npz"):
            msg = Message("t", 1, 0)
            msg.set_arrays([np.arange(64, dtype=np.float32)])
            msg.wire_format = fmt
            msg.corrupt_on_wire = True
            corrupt0 = reg.counter("comm.corrupt_payloads")
            assert safe_deserialize(msg.serialize(), f"test-{fmt}") is None
            assert reg.counter("comm.corrupt_payloads") == corrupt0 + 1

    def test_comm_bytes_counter_counts_frames(self):
        from fedml_tpu.core.distributed.message import Message

        reg = telemetry.registry()
        b0 = reg.counter("comm.bytes_sent")
        msg = Message("t", 0, 1)
        msg.set_arrays([np.zeros(16, np.float32)])
        frame = msg.serialize()
        assert reg.counter("comm.bytes_sent") == b0 + len(frame)


class TestTopDeltaSummary:
    """`fedml_tpu top` surfaces the comm.delta.* family: hit rate, bytes
    saved per direction, store health — silent when the plane never
    engaged."""

    @staticmethod
    def _run_file(tmp_path, metrics):
        import json as _json

        p = tmp_path / "run_delta_edge_0.jsonl"
        events = [
            {"kind": "round_record", "round": 0, "wall_s": 1.0,
             "phases": {"dispatch": 0.5}},
            {"kind": "telemetry_summary", "metrics": metrics},
        ]
        p.write_text("".join(_json.dumps(e) + "\n" for e in events))
        return str(p)

    def test_delta_block_rendered(self, tmp_path, capsys):
        from fedml_tpu.cli import main

        path = self._run_file(tmp_path, {
            "counters": {
                "comm.delta.s2c_delta_frames": 18,
                "comm.delta.s2c_full_frames": 2,
                "comm.delta.s2c_bytes_saved": 3_000_000,
                "comm.delta.c2s_delta_decodes": 24,
                "comm.delta.c2s_bytes_saved": 5_500_000,
                "comm.delta.server_store.evictions": 3,
            },
            "gauges": {"comm.delta.server_store.occupancy": 8},
        })
        assert main(["top", path]) == 0
        out = capsys.readouterr().out
        assert "delivery plane" in out
        assert "18 delta / 2 full frames" in out
        assert "delta hit rate 0.90" in out
        assert "saved 3.00 MB" in out
        assert "24 delta decodes" in out
        assert "saved 5.50 MB" in out
        assert "occupancy 8" in out and "evictions 3" in out

    def test_plain_runs_stay_silent(self, tmp_path, capsys):
        from fedml_tpu.cli import main

        path = self._run_file(tmp_path, {"counters": {"rounds": 4}})
        assert main(["top", path]) == 0
        assert "delivery plane" not in capsys.readouterr().out


class TestArgumentsSurface:
    def test_delivery_knob_validation(self):
        with pytest.raises(ValueError, match="compression"):
            Arguments(overrides=dict(compression="gzip"))
        with pytest.raises(ValueError, match="s2c_delta"):
            Arguments(overrides=dict(s2c_delta="maybe"))
        with pytest.raises(ValueError, match="delta_store_versions"):
            Arguments(overrides=dict(delta_store_versions=0))
        with pytest.raises(ValueError, match="payload_filter"):
            Arguments(overrides=dict(payload_filter="("))
        a = Arguments(overrides=dict(
            compression="eftopk", compression_ratio="0.05",
            delta_store_versions="16", aggregation_mode="async",
            async_dispatch="server_push",
        ))
        assert a.compression_ratio == 0.05
        assert a.delta_store_versions == 16
        assert a.async_dispatch == "server_push"
