"""FedGraphNN: federated GNN training on packed dense graph blocks.

Mirrors the reference's app-layer coverage (``python/app/fedgraphnn/``):
graph classification/regression (MoleculeNet analog), node classification
(ego networks), link prediction (ego/recsys subgraphs) — each trained
through the standard sp engine, proving graphs are just another packed
tensor to every federated code path.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.models.gnn import normalize_adj, pack_graph, unpack_graph
from fedml_tpu.runner import FedMLRunner


def run_graph_sim(dataset, model="gcn", **kw):
    base = dict(
        dataset=dataset, model=model, client_num_in_total=8,
        client_num_per_round=8, comm_round=8, epochs=2, batch_size=16,
        learning_rate=0.05, frequency_of_the_test=20, backend="sp",
    )
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    model_bundle = model_mod.create(args, output_dim)
    return FedMLRunner(args, fedml.get_device(args), ds, model_bundle).run()


class TestGraphKernels:
    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(0)
        feats = jnp.asarray(rng.standard_normal((3, 8, 4)), jnp.float32)
        adj = jnp.asarray((rng.random((3, 8, 8)) < 0.3), jnp.float32)
        mask = jnp.ones((3, 8), jnp.float32)
        x = pack_graph(feats, adj, mask)
        f2, a2, m2 = unpack_graph(x, 4)
        np.testing.assert_array_equal(np.asarray(f2), np.asarray(feats))
        np.testing.assert_array_equal(np.asarray(a2), np.asarray(adj))
        np.testing.assert_array_equal(np.asarray(m2), np.asarray(mask))

    def test_normalize_adj_masks_padding(self):
        adj = jnp.ones((4, 4), jnp.float32)
        mask = jnp.asarray([1, 1, 0, 0], jnp.float32)
        a_hat = np.asarray(normalize_adj(adj, mask))
        assert a_hat[2:].sum() == 0 and a_hat[:, 2:].sum() == 0
        # real block is symmetric with unit row sums (complete 2-graph + I)
        np.testing.assert_allclose(a_hat[:2, :2].sum(-1), 1.0, atol=1e-5)
        np.testing.assert_allclose(a_hat, a_hat.T, atol=1e-6)

    def test_normalize_adj_isolated_node(self):
        adj = jnp.zeros((3, 3), jnp.float32)
        mask = jnp.ones((3,), jnp.float32)
        a_hat = np.asarray(normalize_adj(adj, mask))
        # isolated real nodes keep their (normalized) self-loop
        np.testing.assert_allclose(np.diag(a_hat), 1.0, atol=1e-5)


class TestFedGraphNN:
    def test_graph_classification_learns(self):
        res = run_graph_sim("moleculenet_clf")
        assert res["test_acc"] > 0.7  # 2-class chance = 0.5

    def test_graph_classification_gat(self):
        res = run_graph_sim("social_graph_clf", model="gat", comm_round=6)
        assert res["test_acc"] > 0.5  # 3-class chance = 0.33

    def test_graph_regression_fits(self):
        res = run_graph_sim("moleculenet_reg", comm_round=20, epochs=3,
                            learning_rate=0.03)
        # predict-the-mean baseline sits at the target variance (≈2.7)
        assert res["test_loss"] < 1.0

    def test_node_classification_learns(self):
        res = run_graph_sim("ego_node_clf", model="sage")
        assert res["test_acc"] > 0.4  # 5-class chance = 0.2

    def test_link_prediction_beats_chance(self):
        res = run_graph_sim("ego_link_pred", comm_round=6)
        # "acc" = correctly-scored node pairs; all-zeros baseline would sit
        # near the negative rate, and the weighted loss forbids it
        assert res["test_acc"] > 0.7

    def test_graph_dataset_shapes(self):
        args = fedml.init(
            Arguments(overrides=dict(
                dataset="ego_link_pred", model="gcn", client_num_in_total=4,
                client_num_per_round=4, comm_round=1, batch_size=8,
            )),
            should_init_logs=False,
        )
        ds, _ = data_mod.load(args)
        n = 32
        assert ds.train_x.shape[-2:] == (n, 16 + n + 1)
        assert ds.train_y.shape[-2:] == (n, n + 1)
        assert ds.task == "link_pred"


@pytest.mark.parametrize("conv", ["gcn", "gat", "sage"])
def test_all_convs_forward(conv):
    from fedml_tpu.models.gnn import GraphClassifier

    rng = np.random.default_rng(1)
    feats = jnp.asarray(rng.standard_normal((2, 12, 6)), jnp.float32)
    adj = jnp.asarray((rng.random((2, 12, 12)) < 0.4), jnp.float32)
    adj = jnp.triu(adj, 1) + jnp.swapaxes(jnp.triu(adj, 1), -1, -2)
    mask = jnp.ones((2, 12), jnp.float32)
    x = pack_graph(feats, adj, mask)
    model = GraphClassifier(6, 3, conv=conv)
    import jax

    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 3)
    assert np.isfinite(np.asarray(out)).all()
