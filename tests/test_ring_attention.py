"""Ring attention (sequence/context parallelism) tests: exactness vs dense
causal attention, and the full Cheetah train step with the sequence axis
active.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from fedml_tpu.parallel.ring_attention import make_ring_attention
from fedml_tpu.parallel.sharding import make_mesh
from fedml_tpu.parallel.train_step import CheetahTrainer, make_optimizer
from fedml_tpu.parallel.transformer import TransformerConfig, attention_scores


class TestRingAttentionExactness:
    @pytest.mark.parametrize("ring", [2, 4, 8])
    def test_matches_dense_causal(self, ring):
        mesh = make_mesh({"sequence": ring},
                         devices=jax.devices()[:ring])
        B, L, H, D = 2, 32, 4, 16
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)

        dense = attention_scores(q, k, v, None)

        spec = P(None, "sequence", None, None)
        ring_fn = shard_map(
            make_ring_attention(ring, "sequence"), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec, check_rep=False,
        )
        out = jax.jit(ring_fn)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=2e-5, rtol=2e-5)

    def test_non_causal_matches_softmax(self):
        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        B, L, H, D = 1, 16, 2, 8
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        logits = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(D)
        probs = jax.nn.softmax(logits, -1)
        dense = jnp.einsum("bhlm,bmhd->blhd", probs, v)
        spec = P(None, "sequence", None, None)
        ring_fn = shard_map(
            make_ring_attention(4, "sequence", causal=False), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec, check_rep=False,
        )
        out = jax.jit(ring_fn)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=2e-5, rtol=2e-5)


class TestSequenceParallelTraining:
    def test_train_step_with_sequence_axis(self):
        """Full Cheetah step with dp+sp mesh; loss must match the non-sp run."""
        cfg = TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=64, remat=False,
        )
        rng = np.random.RandomState(0)
        toks = jnp.asarray(rng.randint(0, 127, (4, 64)), jnp.int32)
        mask = jnp.ones((4, 64), jnp.int32)

        mesh_sp = make_mesh({"data": 2, "sequence": 4})
        tr_sp = CheetahTrainer(
            cfg, mesh_sp, optimizer=make_optimizer(learning_rate=1e-2,
                                                   warmup_steps=1),
            seq_sharded=True,
        )
        s_sp = tr_sp.init_state(jax.random.PRNGKey(0))
        s_sp, m_sp = tr_sp.train_step(s_sp, toks, mask)

        mesh_dp = make_mesh({"data": 2, "fsdp": 2, "tensor": 2})
        tr_dp = CheetahTrainer(
            cfg, mesh_dp, optimizer=make_optimizer(learning_rate=1e-2,
                                                   warmup_steps=1),
        )
        s_dp = tr_dp.init_state(jax.random.PRNGKey(0))
        s_dp, m_dp = tr_dp.train_step(s_dp, toks, mask)

        assert float(m_sp["loss"]) == pytest.approx(float(m_dp["loss"]),
                                                    rel=1e-4)
        # two more sp steps: loss decreases (learning through ring attention)
        losses = [float(m_sp["loss"])]
        for _ in range(2):
            s_sp, m_sp = tr_sp.train_step(s_sp, toks, mask)
            losses.append(float(m_sp["loss"]))
        assert losses[-1] < losses[0]


class TestRingBackwardExactness:
    """The hand-written custom-VJP blockwise backward (ring_bwd) must match
    autodiff through dense attention — a dropped scale or mis-rotated dk/dv
    would pass every forward test while corrupting all CP training."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_dense(self, causal):
        ring = 4
        mesh = make_mesh({"sequence": ring}, devices=jax.devices()[:ring])
        B, L, H, D = 2, 32, 4, 16
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        # arbitrary non-uniform cotangent via a weighted-sum loss
        w = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)

        def dense_loss(q, k, v):
            if causal:
                out = attention_scores(q, k, v, None)
            else:
                logits = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(D)
                probs = jax.nn.softmax(logits, -1)
                out = jnp.einsum("bhlm,bmhd->blhd", probs, v)
            return jnp.sum(out * w)

        spec = P(None, "sequence", None, None)
        ring_fn = shard_map(
            make_ring_attention(ring, "sequence", causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False,
        )

        def ring_loss(q, k, v):
            return jnp.sum(ring_fn(q, k, v) * w)

        want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        got = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
        for g, r in zip(want, got):
            np.testing.assert_allclose(np.asarray(r), np.asarray(g),
                                       atol=3e-5, rtol=3e-5)


class TestRingKernelPathInterpret:
    """The splash-kernel ring path (fwd multi-hop LSE merge AND the r5
    kernel backward) executed via Pallas interpret mode on the CPU mesh —
    before this, the S>=2 kernel branch had never run anywhere (r4 ADVICE:
    an index error here would corrupt all causal CP training silently).
    """

    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_path_matches_einsum_path_fwd_bwd(self, causal):
        ring = 2
        mesh = make_mesh({"sequence": ring}, devices=jax.devices()[:ring])
        B, L, H, D = 1, 256, 2, 128  # Lb=128: the kernels' minimum tile
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        w = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        spec = P(None, "sequence", None, None)

        def make(use_kernel):
            fn = shard_map(
                make_ring_attention(
                    ring, "sequence", causal=causal, use_kernel=use_kernel,
                    block_q=128, block_kv=128, interpret=use_kernel,
                ),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_rep=False,
            )

            def loss(q, k, v):
                return jnp.sum(fn(q, k, v) * w)

            return fn, loss

        ein_fn, ein_loss = make(False)
        ker_fn, ker_loss = make(True)

        out_e = jax.jit(ein_fn)(q, k, v)
        out_k = jax.jit(ker_fn)(q, k, v)
        np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_e),
                                   atol=2e-4, rtol=2e-4)

        ge = jax.jit(jax.grad(ein_loss, argnums=(0, 1, 2)))(q, k, v)
        gk = jax.jit(jax.grad(ker_loss, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(gk, ge):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-4, rtol=3e-4)
