"""Tests for the non-FedAvg algorithm families (SURVEY.md §2.6):
hierarchical FL, decentralized DSGD/PushSum, vertical FL, SplitNN, FedGKT,
TurboAggregate.
"""

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner


def run_sim(**kw):
    base = dict(
        dataset="synthetic", model="lr", client_num_in_total=8,
        client_num_per_round=8, comm_round=4, epochs=1, batch_size=16,
        learning_rate=0.1, frequency_of_the_test=1, backend="sp",
    )
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    runner = FedMLRunner(args, fedml.get_device(args), dataset, model)
    return runner.run()


class TestHierarchicalFL:
    def test_two_level_aggregation_learns(self):
        res = run_sim(federated_optimizer="hierarchical_fl", group_num=2,
                      group_comm_round=2, comm_round=4)
        assert res["test_acc"] > 0.5

    def test_more_groups(self):
        res = run_sim(federated_optimizer="hierarchical_fl", group_num=4,
                      group_comm_round=1, client_num_in_total=12, comm_round=4)
        assert res["test_acc"] > 0.5


class TestDecentralized:
    def test_dsgd_converges_and_reaches_consensus(self):
        res = run_sim(federated_optimizer="decentralized_fl",
                      decentralized_algorithm="dsgd",
                      topology_neighbor_num=2, comm_round=8)
        assert res["test_acc"] > 0.5
        assert res["consensus_dist"] < 2.0

    def test_pushsum_directed(self):
        res = run_sim(federated_optimizer="decentralized_fl",
                      decentralized_algorithm="pushsum",
                      out_neighbor_num=2, comm_round=8)
        assert res["test_acc"] > 0.5

    def test_gossip_mixing_contracts(self):
        """One W-mixing must shrink disagreement (doubly-stochastic ring)."""
        from fedml_tpu.core.topology import SymmetricTopologyManager

        topo = SymmetricTopologyManager(8, 2)
        topo.generate_topology()
        W = topo.mixing_matrix()
        rng = np.random.RandomState(0)
        x = rng.randn(8, 40)
        before = np.linalg.norm(x - x.mean(0), axis=1).mean()
        mixed = W @ x
        after = np.linalg.norm(mixed - mixed.mean(0), axis=1).mean()
        assert after < before
        # mass conservation: mean preserved by row-stochastic symmetric W
        np.testing.assert_allclose(mixed.mean(0), x.mean(0), atol=1e-6)


class TestVerticalFL:
    def test_two_party_learns(self):
        res = run_sim(federated_optimizer="vertical_fl", comm_round=6,
                      learning_rate=0.1)
        assert res["test_acc"] > 0.6


class TestSplitNN:
    def test_split_training_learns(self):
        res = run_sim(federated_optimizer="SplitNN", client_num_in_total=4,
                      client_num_per_round=4, comm_round=3, learning_rate=0.1)
        assert res["test_acc"] > 0.6


@pytest.mark.slow
class TestFedGKT:
    def test_knowledge_transfer_learns(self):
        res = run_sim(federated_optimizer="FedGKT", client_num_in_total=4,
                      client_num_per_round=4, comm_round=6, epochs=5,
                      learning_rate=0.2)
        assert res["test_acc"] > 0.5
        assert res["server_loss"] < 5.0


class TestTurboAggregate:
    def test_secure_ring_matches_fedavg(self):
        plain = run_sim(federated_optimizer="FedAvg", comm_round=4)
        secure = run_sim(federated_optimizer="turboaggregate", comm_round=4,
                         ta_group_size=3)
        assert secure["test_acc"] > 0.5
        # quantized share aggregation ≈ trusted-server average
        assert abs(secure["test_acc"] - plain["test_acc"]) < 0.15


@pytest.mark.slow
class TestFedSeg:
    """VERDICT missing #6: segmentation runtime (reference simulation/mpi/fedseg)."""

    def test_fedseg_learns_and_reports_miou(self):
        # width 16 + 1 epoch: full-width FCN convs at 3 epochs x 6 rounds
        # cost ~40 min of single-core CPU in CI — same code path, 20x less
        res = run_sim(federated_optimizer="FedSeg", dataset="pascal_voc",
                      model="fcn", client_num_in_total=4,
                      client_num_per_round=4, comm_round=8, epochs=1,
                      batch_size=8, learning_rate=0.15, seg_model_width=16)
        assert "test_miou" in res and "pixel_acc" in res
        assert res["pixel_acc"] > 0.5  # synthetic blobs are separable
        assert res["test_miou"] > 0.05


@pytest.mark.slow
class TestFedGAN:
    """VERDICT missing #6: adversarial runtime (reference simulation/mpi/fedgan)."""

    def test_fedgan_trains_both_nets(self):
        from fedml_tpu.simulation.fedgan_api import FedGanAPI

        args = fedml.init(Arguments(overrides=dict(
            dataset="synthetic", model="lr", federated_optimizer="FedGAN",
            client_num_in_total=4, client_num_per_round=4, comm_round=6,
            epochs=3, batch_size=16, learning_rate=2e-3,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        api = FedGanAPI(args, None, ds)
        res = api.train()
        assert np.isfinite(res["d_loss"]) and np.isfinite(res["g_loss"])
        # the discriminator must not have trivially won: its confidence that
        # generated samples are fake stays off the floor
        assert res["d_score_on_fake"] > 0.02
        samples = api.sample(16)
        assert samples.shape == (16,) + tuple(ds.train_x.shape[2:])
        assert np.all(np.isfinite(samples))


@pytest.mark.slow
class TestFedNAS:
    """VERDICT missing #6: DARTS search runtime (reference simulation/mpi/fednas)."""

    def test_fednas_searches_and_learns(self):
        res = run_sim(federated_optimizer="FedNAS", model="darts",
                      client_num_in_total=4, client_num_per_round=4,
                      comm_round=6, epochs=2, learning_rate=0.05)
        assert res["test_acc"] > 0.5  # synthetic is linearly separable
        assert "genotype" in res and len(res["genotype"]) == 3
        # alphas moved: at least one layer prefers a non-zero op
        assert any(v != 0 for v in res["genotype"].values())
