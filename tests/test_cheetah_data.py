"""Cheetah data path + LEAF readers (VERDICT next #9 / weak #8):
the trainer must consume the data layer's packed token streams, and
femnist/shakespeare must load real LEAF JSON when staged."""

import json
import os

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner


class TestCheetahRealTokens:
    def test_loss_decreases_on_corpus_tokens(self):
        """Markov-chain shakespeare tokens are learnable: the loss after
        training must beat the first-step loss by a clear margin (random
        tokens would stay at ~ln(V))."""
        args = fedml.init(Arguments(overrides=dict(
            training_type="distributed", dataset="shakespeare", model="transformer",
            model_size="tiny", vocab_size=90, total_steps=30, batch_size=8,
            seq_len=64, client_num_in_total=8, client_num_per_round=8,
            learning_rate=3e-3,
            warmup_steps=5,
        )), should_init_logs=False)
        ds, _ = data_mod.load(args)
        runner = FedMLRunner(args, fedml.get_device(args), ds, None)
        # the batch generator must draw from the corpus, not rng.randint
        stream = runner.runner._token_stream()
        assert stream is not None and stream.size > 1000
        gen = runner.runner._batches(np.random.RandomState(0))
        batch = next(gen)
        assert batch.shape == (8, 64)
        assert int(batch.max()) < 90
        res = runner.run()
        import math

        assert res["final_loss"] < math.log(90) - 0.4, res

    def test_synthetic_fallback_without_dataset(self):
        args = fedml.init(Arguments(overrides=dict(
            training_type="distributed", dataset="synthetic", model="transformer",
            model_size="tiny", total_steps=2, batch_size=8, seq_len=32,
        )), should_init_logs=False)
        runner = FedMLRunner(args, fedml.get_device(args), None, None)
        assert runner.runner._token_stream() is None
        res = runner.run()
        assert res["steps"] == 2

    def test_custom_size_yaml_knobs_reach_config(self):
        """attn blocks / MoE routing / remat are YAML-reachable through
        model_size=custom (cheetah/runner.config_from_args)."""
        args = fedml.init(Arguments(overrides=dict(
            training_type="distributed", dataset="synthetic",
            model="transformer", model_size="custom", vocab_size=128,
            d_model=64, n_layers=2, n_heads=4, n_kv_heads=4, d_ff=128,
            seq_len=64, batch_size=4, total_steps=2,
            moe_experts=4, moe_top_k=2, moe_capacity_factor=2.0,
            attn_block_q=256, attn_block_kv=256, remat=False,
            mesh_shape="data:2,expert:2,fsdp:2",
        )), should_init_logs=False)
        runner = FedMLRunner(args, fedml.get_device(args), None, None)
        cfg = runner.runner.cfg
        assert cfg.moe_experts == 4 and cfg.moe_top_k == 2
        assert cfg.attn_block_q == 256 and cfg.remat is False
        res = runner.run()
        assert res["steps"] == 2 and np.isfinite(res["final_loss"])
        # YAML string booleans must not silently truthy ("false" -> True)
        from fedml_tpu.cheetah.runner import config_from_args

        args.remat = "false"
        assert config_from_args(args).remat is False
        # unset knobs inherit the dataclass defaults (single source of truth)
        bare = fedml.init(Arguments(overrides=dict(
            training_type="distributed", dataset="synthetic",
            model="transformer", model_size="custom", vocab_size=64,
            d_model=32, n_layers=2, n_heads=2, n_kv_heads=2, d_ff=64,
            seq_len=32,
        )), should_init_logs=False)
        cfg2 = config_from_args(bare)
        assert cfg2.moe_experts == 0 and cfg2.remat is True


def _write_leaf_shakespeare(root):
    os.makedirs(os.path.join(root, "shakespeare", "train"))
    os.makedirs(os.path.join(root, "shakespeare", "test"))
    users = {}
    for u in range(3):
        text = ("the quick brown fox jumps over the lazy dog " * 20)
        xs = [text[i:i + 80] for i in range(0, 400, 80)]
        ys = [text[i + 80] for i in range(0, 400, 80)]
        users[f"user{u}"] = {"x": xs, "y": ys}
    blob = {
        "users": list(users), "user_data": users,
        "num_samples": [len(users[u]["x"]) for u in users],
    }
    with open(os.path.join(root, "shakespeare", "train", "all.json"), "w") as f:
        json.dump(blob, f)
    with open(os.path.join(root, "shakespeare", "test", "all.json"), "w") as f:
        json.dump(blob, f)


def _write_leaf_femnist(root):
    os.makedirs(os.path.join(root, "femnist", "train"))
    os.makedirs(os.path.join(root, "femnist", "test"))
    rng = np.random.RandomState(0)
    users = {}
    for u in range(4):
        n = 6 + u
        users[f"w{u}"] = {
            "x": rng.rand(n, 784).round(3).tolist(),
            "y": rng.randint(0, 62, n).tolist(),
        }
    blob = {
        "users": list(users), "user_data": users,
        "num_samples": [len(users[u]["y"]) for u in users],
    }
    with open(os.path.join(root, "femnist", "train", "all.json"), "w") as f:
        json.dump(blob, f)
    with open(os.path.join(root, "femnist", "test", "all.json"), "w") as f:
        json.dump(blob, f)


class TestLeafReaders:
    def test_shakespeare_leaf_roundtrip(self, tmp_path):
        _write_leaf_shakespeare(str(tmp_path))
        args = fedml.init(Arguments(overrides=dict(
            dataset="shakespeare", data_cache_dir=str(tmp_path),
            client_num_in_total=3, client_num_per_round=2, batch_size=4,
        )), should_init_logs=False)
        ds, class_num = data_mod.load(args)
        assert class_num == 90
        assert ds.client_num == 3  # LEAF users define the federation
        assert ds.meta.get("natural_partition") is True
        x, y, n = ds.client_shard(0)
        assert n > 0 and x.shape[1] == 80
        # per-position NWP targets: y is x shifted with the next char last
        real = np.asarray(x[0], np.int32)
        np.testing.assert_array_equal(np.asarray(y[0])[:-1], real[1:])

    def test_femnist_leaf_natural_partition(self, tmp_path):
        _write_leaf_femnist(str(tmp_path))
        args = fedml.init(Arguments(overrides=dict(
            dataset="femnist", data_cache_dir=str(tmp_path),
            client_num_in_total=999, client_num_per_round=2, batch_size=4,
        )), should_init_logs=False)
        ds, class_num = data_mod.load(args)
        assert class_num == 62
        assert ds.client_num == 4
        assert args.client_num_in_total == 4  # overridden by the files
        counts = [ds.client_shard(c)[2] for c in range(4)]
        assert counts == [6, 7, 8, 9]

    def test_femnist_falls_back_synthetic(self, tmp_path):
        args = fedml.init(Arguments(overrides=dict(
            dataset="femnist", data_cache_dir=str(tmp_path),
            client_num_in_total=5, client_num_per_round=2, batch_size=4,
        )), should_init_logs=False)
        ds, _ = data_mod.load(args)
        assert ds.client_num == 5  # synthetic respects the args

    def test_char_encoding_stable(self):
        from fedml_tpu.data.leaf import ALL_LETTERS, encode_chars

        assert len(ALL_LETTERS) == 80
        enc = encode_chars("the", 5)
        assert enc.shape == (5,)
        assert enc[3] == enc[4] == 0  # padding
        assert (enc[:3] > 0).all()


def _write_tff_cifar(root):
    import h5py

    rng = np.random.RandomState(0)
    for split, n_clients in (("train", 3), ("test", 2)):
        path = os.path.join(root, f"fed_cifar100_{split}.h5")
        with h5py.File(path, "w") as h5:
            g = h5.create_group("examples")
            for c in range(n_clients):
                cg = g.create_group(f"client_{c}")
                n = 5 + c
                cg.create_dataset(
                    "image", data=rng.randint(0, 255, (n, 32, 32, 3), np.uint8)
                )
                cg.create_dataset(
                    "label", data=rng.randint(0, 100, (n,), np.int64)
                )


def _write_tff_shakespeare(root):
    import h5py

    for split in ("train", "test"):
        path = os.path.join(root, f"shakespeare_{split}.h5")
        with h5py.File(path, "w") as h5:
            g = h5.create_group("examples")
            for c in range(2):
                cg = g.create_group(f"u{c}")
                snippets = np.asarray(
                    [b"to be or not to be that is the question " * 6], object
                )
                cg.create_dataset(
                    "snippets",
                    data=snippets.astype(h5py.string_dtype()),
                )


class TestTFFH5Readers:
    def test_fed_cifar100_h5(self, tmp_path):
        _write_tff_cifar(str(tmp_path))
        args = fedml.init(Arguments(overrides=dict(
            dataset="fed_cifar100", data_cache_dir=str(tmp_path),
            client_num_in_total=3, client_num_per_round=2, batch_size=4,
        )), should_init_logs=False)
        ds, class_num = data_mod.load(args)
        assert class_num == 100
        assert ds.client_num == 3
        assert ds.meta.get("natural_partition") is True
        counts = [ds.client_shard(c)[2] for c in range(3)]
        assert counts == [5, 6, 7]
        assert ds.test_x.shape[1:] == (32, 32, 3)
        assert float(ds.train_x.max()) <= 1.0

    def test_fed_shakespeare_h5(self, tmp_path):
        _write_tff_shakespeare(str(tmp_path))
        args = fedml.init(Arguments(overrides=dict(
            dataset="fed_shakespeare", data_cache_dir=str(tmp_path),
            client_num_in_total=2, client_num_per_round=2, batch_size=2,
        )), should_init_logs=False)
        ds, class_num = data_mod.load(args)
        assert class_num == 90
        assert ds.client_num == 2
        x, y, n = ds.client_shard(0)
        assert n >= 3 and x.shape[1] == 80
        # per-position next-char targets: y = x shifted by one
        real = np.asarray(x[0], np.int32)
        np.testing.assert_array_equal(np.asarray(y[0])[:-1], real[1:])
        assert int(x.max()) < 90

    def test_tff_vocab_ids_in_range(self):
        from fedml_tpu.data.tff_h5 import BOS_ID, EOS_ID, encode_snippet

        ids = encode_snippet("hello world")
        assert ids[0] == BOS_ID and ids[-1] == EOS_ID
        assert int(ids.max()) <= EOS_ID < 90
