"""Data layer + model zoo tests (reference test strategy: SURVEY.md §4 —
unit pyramid over pure functions, tiny-config shapes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments


def make_args(**kw):
    base = dict(
        dataset="synthetic", model="lr", client_num_in_total=8,
        client_num_per_round=4, comm_round=2, epochs=1, batch_size=8,
    )
    base.update(kw)
    return Arguments(overrides=base)


class TestData:
    def test_packed_layout(self):
        args = make_args(dataset="mnist", client_num_in_total=12)
        ds, class_num = data_mod.load(args)
        assert class_num == 10
        assert ds.train_x.shape[0] == 12
        assert ds.train_x.shape[2:] == (28, 28, 1)
        assert ds.cap % args.batch_size == 0
        assert ds.train_counts.sum() > 0
        assert (ds.train_counts <= ds.cap).all()

    def test_hetero_partition_skew(self):
        args = make_args(dataset="cifar10", partition_method="hetero",
                         partition_alpha=0.1, client_num_in_total=10)
        ds, _ = data_mod.load(args)
        # low alpha → clients' class histograms differ
        hists = []
        for i in range(ds.client_num):
            n = ds.train_counts[i]
            hists.append(np.bincount(ds.train_y[i][:n], minlength=10))
        hists = np.stack(hists).astype(float)
        hists /= np.maximum(hists.sum(1, keepdims=True), 1)
        assert np.std(hists, axis=0).mean() > 0.05

    def test_homo_partition_even(self):
        args = make_args(partition_method="homo")
        ds, _ = data_mod.load(args)
        assert ds.train_counts.max() - ds.train_counts.min() <= 1

    def test_nwp_dataset(self):
        args = make_args(dataset="shakespeare", client_num_in_total=4)
        ds, class_num = data_mod.load(args)
        assert class_num == 90
        assert ds.task == "nwp"
        assert ds.train_x.dtype == np.int32
        # targets are inputs shifted left
        n = ds.train_counts[0]
        assert (ds.train_y[0, :n, :-1] == ds.train_x[0, :n, 1:]).all()

    def test_tagpred_dataset(self):
        args = make_args(dataset="stackoverflow_lr", client_num_in_total=4)
        ds, class_num = data_mod.load(args)
        assert class_num == 500
        assert ds.train_y.shape[-1] == 500

    def test_unknown_dataset_raises(self):
        with pytest.raises(ValueError, match="unknown dataset"):
            data_mod.load(make_args(dataset="nope"))

    def test_reference_tuple_shape(self):
        ds, _ = data_mod.load(make_args())
        tup = ds.as_reference_tuple()
        assert len(tup) == 8
        assert tup[0] == ds.train_data_num


class TestModels:
    @pytest.mark.parametrize(
        "model,dataset",
        [
            ("lr", "mnist"),
            ("cnn", "femnist"),
            ("resnet20", "cifar10"),
            ("mlp", "synthetic"),
        ],
    )
    def test_forward_shapes(self, model, dataset):
        args = make_args(model=model, dataset=dataset)
        ds_spec = data_mod.REGISTRY[dataset]
        bundle = model_mod.create(args, ds_spec.class_num)
        params = bundle.init(jax.random.PRNGKey(0))
        x = bundle.dummy_input(3)
        out = bundle.apply(params, x)
        assert out.shape == (3, ds_spec.class_num)

    def test_rnn_shapes(self):
        args = make_args(model="rnn", dataset="shakespeare")
        bundle = model_mod.create(args, 90)
        params = bundle.init(jax.random.PRNGKey(0))
        x = jnp.zeros((2, 80), jnp.int32)
        out = bundle.apply(params, x)
        assert out.shape == (2, 80, 90)

    def test_resnet18_gn_deep(self):
        args = make_args(model="resnet18_gn", dataset="cifar10")
        bundle = model_mod.create(args, 10)
        params = bundle.init(jax.random.PRNGKey(0))
        assert bundle.param_count(params) > 10_000_000  # ~11M like torch resnet18

    def test_dropout_determinism(self):
        args = make_args(model="cnn", dataset="femnist")
        bundle = model_mod.create(args, 62)
        params = bundle.init(jax.random.PRNGKey(0))
        x = jnp.ones((2, 28, 28, 1))
        a = bundle.apply(params, x, train=False)
        b = bundle.apply(params, x, train=False)
        assert jnp.allclose(a, b)

    def test_unknown_model_raises(self):
        with pytest.raises(ValueError, match="unknown model"):
            model_mod.create(make_args(model="nope"), 10)
