import jax
import pytest

from fedml_tpu import device
from fedml_tpu.arguments import Arguments


def test_virtual_8_devices():
    assert jax.device_count() == 8


def test_build_default_clients_mesh():
    mesh = device.build_mesh()
    assert mesh.axis_names == ("clients",)
    assert mesh.devices.size == 8


def test_build_2d_mesh_with_inference():
    mesh = device.build_mesh({"data": 2, "tensor": -1})
    assert mesh.devices.shape == (2, 4)


def test_mesh_size_mismatch():
    with pytest.raises(ValueError):
        device.build_mesh({"data": 3})


def test_get_mesh_from_args():
    args = Arguments(overrides={"mesh_shape": "clients:8"})
    mesh = device.get_mesh(args)
    assert mesh.axis_names == ("clients",)
