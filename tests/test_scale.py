"""Million-client cohort substrate tests (fedml_tpu/scale/ — ISSUE 6).

Pins the subsystem's contracts:

1. **Registry**: packed-column round-trip (save/load), sampling
   determinism under a fixed seed (across instances and processes-worth of
   rebuilds), weighted-sampling bias, participation/staleness accounting,
   ledger identity digests.
2. **Prefetcher**: the stream never blocks the round beyond its own data
   (cold takes work), never serves a stale shard (wrong-cohort takes are
   misses, and a prefetching run is BITWISE equal to a synchronous one),
   and overlap is measured.
3. **Partition rules**: regex→PartitionSpec resolution fixtures including
   rule precedence, scalar exemption, the no-match fallback, and the
   parse syntax; rule-driven mesh sharding reproduces the legacy
   hard-coded first-axis sharding bitwise over the model zoo.
4. **Recompile-safety**: steady-state registry rounds trigger ZERO XLA
   compiles (cohort resampling can never be a recompile source).
5. **Crash-safety**: a registry-backed run preempted mid-run resumes
   bitwise-identical to an uninterrupted run, and the ledger's registry
   identity makes resuming against a different registry a loud error.
"""

from __future__ import annotations

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.scale import (
    ClientRegistry,
    ShardPrefetcher,
    cohort_key,
    make_shardings,
    match_partition_rules,
    named_tree_paths,
    parse_partition_rules,
)
from fedml_tpu.simulation.mesh_api import MeshFedAvgAPI
from fedml_tpu.simulation.sp_api import FedAvgAPI


def _make_api(backend="sp", cls=None, **kw):
    base = dict(
        dataset="synthetic", model="lr", client_num_in_total=16,
        client_num_per_round=8, comm_round=4, epochs=1, batch_size=16,
        learning_rate=0.1, frequency_of_the_test=100, preempt_signals=False,
    )
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, od = data_mod.load(args)
    cls = cls or (MeshFedAvgAPI if backend == "mesh" else FedAvgAPI)
    return cls(args, fedml.get_device(args), ds, model_mod.create(args, od))


def _leaves(api):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(api.global_params)]


def _close(api):
    if api.cohort_engine is not None:
        api.cohort_engine.close()


# ---------------------------------------------------------------------------
# 1. registry
# ---------------------------------------------------------------------------


class TestClientRegistry:
    def test_roundtrip_and_identity(self, tmp_path):
        reg = ClientRegistry.synthetic(1000, backing_shards=16, seed=7,
                                       weight_concentration=2.0)
        reg.note_participation(reg.sample(0, 32))
        path = str(tmp_path / "reg.npz")
        reg.save(path)
        back = ClientRegistry.load(path)
        assert back.num_clients == 1000
        np.testing.assert_array_equal(back.weights, reg.weights)
        np.testing.assert_array_equal(back.shard_ptrs, reg.shard_ptrs)
        np.testing.assert_array_equal(
            back.participation, reg.counters()["participation"]
        )
        assert back.identity() == reg.identity()
        # identity digests the sampling-relevant columns
        other = ClientRegistry.synthetic(1000, backing_shards=16, seed=8)
        assert other.identity() != reg.identity()

    def test_sampling_determinism_across_instances(self):
        a = ClientRegistry.synthetic(5000, backing_shards=10, seed=3)
        b = ClientRegistry.synthetic(5000, backing_shards=10, seed=3)
        for r in (0, 1, 17):
            np.testing.assert_array_equal(a.sample(r, 64), b.sample(r, 64))
        # different rounds → different cohorts; no replacement within one
        c0, c1 = a.sample(0, 64), a.sample(1, 64)
        assert not np.array_equal(c0, c1)
        assert len(np.unique(c0)) == 64
        assert c0.min() >= 0 and c0.max() < 5000

    def test_weighted_sampling_bias(self):
        w = np.ones(1000, np.float32)
        w[:10] = 200.0  # ten heavyweight clients
        reg = ClientRegistry(w, np.zeros(1000, np.int32), seed=0)
        hits = 0
        for r in range(20):
            hits += int((reg.sample(r, 50) < 10).sum())
        # heavyweights are ~2/3 of the total mass; uniform would give ~1%
        assert hits > 100

    def test_participation_and_staleness(self):
        reg = ClientRegistry.synthetic(100, backing_shards=4, seed=0)
        c0 = reg.sample(0, 10)
        reg.note_participation(c0)
        reg.note_participation(reg.sample(1, 10))
        counts = reg.counters()
        assert counts["participation"].sum() == 20
        assert (counts["staleness"][c0] <= 1).all()

    def test_shard_rows_map_and_bounds(self):
        reg = ClientRegistry.synthetic(128, backing_shards=8, seed=0)
        rows = reg.shard_rows(reg.sample(0, 16))
        assert rows.min() >= 0 and rows.max() < 8
        with pytest.raises(ValueError, match="cohort size"):
            reg.device_sampler(0)
        with pytest.raises(ValueError, match="cohort size"):
            reg.device_sampler(129)
        with pytest.raises(ValueError, match="strictly positive"):
            ClientRegistry(np.zeros(4), np.zeros(4, np.int32))
        with pytest.raises(ValueError, match="non-negative"):
            ClientRegistry(np.ones(4), np.array([0, 1, -3, 2], np.int32))
        with pytest.raises(ValueError, match="entries"):
            ClientRegistry(np.ones(4), np.zeros(4, np.int32),
                           participation=np.zeros(7, np.int32))

    def test_scaffold_refuses_aliased_registry(self):
        # 4000 virtual clients over 16 shards: every cohort holds duplicate
        # rows, so the per-client variate scatter would be order-dependent
        with pytest.raises(ValueError, match="SCAFFOLD"):
            _make_api(client_registry="4000", cohort_size=32,
                      federated_optimizer="SCAFFOLD")


# ---------------------------------------------------------------------------
# 2. prefetcher
# ---------------------------------------------------------------------------


class TestShardPrefetcher:
    def test_hit_serves_scheduled_buffer(self):
        pf = ShardPrefetcher(depth=2)
        try:
            pf.schedule("a", lambda: ("payload-a",))
            out = pf.take("a", lambda: ("fresh-a",))
            assert out == ("payload-a",)
        finally:
            pf.stop()

    def test_cold_take_never_blocks(self):
        pf = ShardPrefetcher(depth=1)
        try:
            assert pf.take("never-scheduled", lambda: 42) == 42
        finally:
            pf.stop()

    def test_never_serves_stale_shard(self):
        pf = ShardPrefetcher(depth=1)
        try:
            pf.schedule("round-1", lambda: "old-cohort")
            # the round asks for a DIFFERENT cohort: the buffered entry
            # must not be served under the wrong key
            assert pf.take("round-2", lambda: "right-cohort") == \
                "right-cohort"
        finally:
            pf.stop()

    def test_depth_zero_is_synchronous(self):
        pf = ShardPrefetcher(depth=0)
        assert not pf.schedule("a", lambda: 1)
        assert pf.take("a", lambda: 2) == 2
        stats = pf.stats()
        assert stats["overlap_fraction"] == 0.0  # fully exposed I/O
        pf.stop()

    def test_gather_error_degrades_to_sync(self):
        pf = ShardPrefetcher(depth=1)
        try:
            def boom():
                raise RuntimeError("disk on fire")

            pf.schedule("k", boom)
            assert pf.take("k", lambda: "recovered") == "recovered"
        finally:
            pf.stop()

    def test_eviction_bounds_memory(self):
        pf = ShardPrefetcher(depth=1)
        try:
            pf.schedule("k1", lambda: 1)
            pf.take("k1", lambda: 1)  # ensure k1 finished
            pf.schedule("k2", lambda: 2)
            pf.take("k2", lambda: 2)
            pf.schedule("k3", lambda: 3)  # evicts any parked k2 leftovers
            assert pf.take("k3", lambda: 3) == 3
        finally:
            pf.stop()

    def test_cohort_key_is_content_addressed(self):
        a = np.array([3, 1, 2])
        assert cohort_key(a) == cohort_key(np.array([3, 1, 2]))
        assert cohort_key(a) != cohort_key(np.array([1, 2, 3]))


# ---------------------------------------------------------------------------
# 3. partition rules
# ---------------------------------------------------------------------------


class TestPartitionRules:
    def _tree(self):
        return {
            "cohort": {"x": np.zeros((8, 4)), "y": np.zeros((8,))},
            "params": {"dense": {"w": np.zeros((4, 2)),
                                 "b": np.zeros((2,))}},
            "step": np.zeros(()),  # scalar: never partitioned
        }

    def test_named_paths(self):
        names = dict(named_tree_paths(self._tree()))
        assert "cohort/x" in names and "params/dense/w" in names

    def test_first_match_wins_and_scalar_exemption(self):
        from jax.sharding import PartitionSpec as P

        rules = [
            (r"^cohort/x$", P("clients", None)),
            (r"^cohort/", P("clients")),
            (r".*", P()),
        ]
        specs = match_partition_rules(rules, self._tree())
        assert specs["cohort"]["x"] == P("clients", None)
        assert specs["cohort"]["y"] == P("clients")
        assert specs["params"]["dense"]["w"] == P()
        assert specs["step"] == P()

    def test_no_match_fallback_and_strict_mode(self):
        from jax.sharding import PartitionSpec as P

        rules = [(r"^cohort/", P("clients"))]
        specs = match_partition_rules(rules, self._tree(),
                                      fallback=P())
        assert specs["params"]["dense"]["w"] == P()
        with pytest.raises(ValueError, match="no partition rule matches"):
            match_partition_rules(rules, self._tree(), fallback=None)

    def test_parse_syntax(self):
        from jax.sharding import PartitionSpec as P

        rules = parse_partition_rules(
            "cohort/.*=clients; embed=clients,tensor; big=data+fsdp; .*="
        )
        assert rules[0] == ("cohort/.*", P("clients"))
        assert rules[1] == ("embed", P("clients", "tensor"))
        assert rules[2] == ("big", P(("data", "fsdp")))
        assert rules[3] == (".*", P())
        assert parse_partition_rules("") == []
        with pytest.raises(ValueError, match="bad partition rule"):
            parse_partition_rules("no-equals-sign")
        with pytest.raises(ValueError, match="pattern"):
            parse_partition_rules("[unclosed=clients")

    def test_make_shardings_validates_axes(self):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("clients",))
        sh = make_shardings(mesh, {"a": P("clients"), "b": P()})
        assert sh["a"].spec == P("clients")
        with pytest.raises(ValueError, match="names axis"):
            make_shardings(mesh, {"a": P("tensor")})


# ---------------------------------------------------------------------------
# 4. engine integration: determinism, streaming parity, recompiles
# ---------------------------------------------------------------------------


class TestRegistryRounds:
    def test_cohorts_deterministic_and_in_range(self):
        api = _make_api(client_registry="4000", cohort_size=32)
        try:
            c0 = api._client_sampling(0)
            assert np.array_equal(c0, api._client_sampling(0))
            assert len(c0) == 32 and c0.max() < api.ds.client_num
            api2 = _make_api(client_registry="4000", cohort_size=32)
            try:
                assert np.array_equal(c0, api2._client_sampling(0))
            finally:
                _close(api2)
        finally:
            _close(api)

    def test_prefetch_run_bitwise_equals_synchronous_run(self):
        """The streamed path must never serve a stale/wrong shard: a run
        with the prefetcher on is BITWISE identical to one with it off."""
        sync = _make_api(client_registry="2000", cohort_size=24,
                         cohort_prefetch=0)
        pre = _make_api(client_registry="2000", cohort_size=24,
                        cohort_prefetch=1)
        try:
            for r in range(4):
                sync.run_round(r)
                pre.run_round(r)
            for a, b in zip(_leaves(sync), _leaves(pre)):
                assert np.array_equal(a, b)
            stats = pre.cohort_engine.stats()
            # rounds 1..3 were prefetched while 0..2 ran
            assert stats["gather_s"] > 0
        finally:
            _close(sync)
            _close(pre)

    def test_prefetch_overlap_is_measured(self):
        api = _make_api(client_registry="2000", cohort_size=16)
        try:
            for r in range(5):
                api.run_round(r)
            stats = api.cohort_engine.stats()
            assert stats["overlap_fraction"] > 0.0
        finally:
            _close(api)

    def test_zero_steady_state_recompiles(self):
        """Cohort resampling at registry scale must never recompile: the
        sampler takes the round as a traced scalar and the cohort shapes
        are static (pad-to-bucket)."""
        from fedml_tpu.core.mlops import telemetry

        telemetry.install_jax_listeners()
        api = _make_api(client_registry="3000", cohort_size=32)
        try:
            for r in range(2):  # warmup: compile wall lives here
                api.run_round(r)
            before = telemetry.registry().counter("jax.compiles")
            for r in range(2, 6):
                api.run_round(r)
            assert telemetry.registry().counter("jax.compiles") == before
        finally:
            _close(api)

    def test_superround_matches_per_round_registry_path(self):
        """The scan body samples with the registry's own jit'd sampler —
        the cohort trajectory (and so the params) must match per-round
        launches bitwise."""
        per = _make_api(client_registry="2000", cohort_size=8,
                        cohort_prefetch=0)
        scan = _make_api(client_registry="2000", cohort_size=8,
                         superround_k=4)
        try:
            for r in range(4):
                per.run_round(r)
            scan.run_rounds(0, 4)
            assert scan._superround_step is not None
            for a, b in zip(_leaves(per), _leaves(scan)):
                assert np.array_equal(a, b)
            # accounting was replayed host-side for the scanned rounds, and
            # the per-round path counts the SAME rounds — lookahead
            # sampling (the prefetcher peeks at round k) must not count
            part = scan.cohort_engine.registry.counters()["participation"]
            assert part.sum() == 4 * 8
            part_per = per.cohort_engine.registry.counters()["participation"]
            assert part_per.sum() == 4 * 8
        finally:
            _close(per)
            _close(scan)

    def test_cohort_size_requires_registry(self):
        with pytest.raises(ValueError, match="cohort_size requires"):
            Arguments(overrides=dict(cohort_size=8))


# ---------------------------------------------------------------------------
# 5. mesh: rule-driven sharding parity + registry on the mesh path
# ---------------------------------------------------------------------------


class LegacyFirstAxisMesh(MeshFedAvgAPI):
    """The pre-rules hard-coded placement, kept verbatim as the parity
    oracle: cohort arrays split on the first axis over ``clients``,
    everything else replicated."""

    def __init__(self, *a, **kw):
        from jax.sharding import NamedSharding, PartitionSpec as P

        super().__init__(*a, **kw)
        self._shard = NamedSharding(self.mesh, P("clients"))
        self._repl = NamedSharding(self.mesh, P())

    def _place_cohort(self, arrays):
        import jax

        cx, cy, cn = arrays
        return (
            jax.device_put(np.asarray(cx), self._shard),
            jax.device_put(np.asarray(cy), self._shard),
            jax.device_put(np.asarray(cn, np.int32), self._shard),
        )

    def _place(self, arr):
        import jax

        return jax.device_put(jax.device_get(arr), self._shard)

    def _prepare_round(self):
        import jax

        self.global_params = jax.device_put(self.global_params, self._repl)

    def _place_state(self, state):
        import jax

        return jax.tree.map(
            lambda x: jax.device_put(x, self._repl), state
        )


class TestMeshRuleParity:
    @pytest.mark.parametrize("kw", [
        dict(model="lr"),
        dict(model="mlp"),
        dict(model="lr", client_num_per_round=6),  # cohort padding
        dict(model="lr", federated_optimizer="SCAFFOLD"),
    ])
    def test_rule_driven_sharding_is_bitwise_equal_to_first_axis(self, kw):
        legacy = _make_api(backend="mesh", cls=LegacyFirstAxisMesh, **kw)
        ruled = _make_api(backend="mesh", **kw)
        for r in range(3):
            legacy.run_round(r)
            ruled.run_round(r)
        for a, b in zip(_leaves(legacy), _leaves(ruled)):
            assert a.dtype == b.dtype and np.array_equal(a, b), \
                "rule-driven mesh sharding diverged from first-axis"

    def test_registry_on_mesh_path(self):
        api = _make_api(backend="mesh", client_registry="2000",
                        cohort_size=24)
        try:
            for r in range(3):
                out = api.run_round(r)
            assert np.isfinite(float(np.asarray(out["train_loss"])))
        finally:
            _close(api)

    def test_custom_rules_still_converge(self):
        # an explicit rule string equivalent to the default: same results
        api = _make_api(
            backend="mesh",
            mesh_partition_rules="cohort/.*=clients",
            mesh_state_rules=".*=",
        )
        ref = _make_api(backend="mesh")
        for r in range(2):
            api.run_round(r)
            ref.run_round(r)
        for a, b in zip(_leaves(api), _leaves(ref)):
            assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# 6. crash-safety: resume with a registry-backed run
# ---------------------------------------------------------------------------


class TestRegistryResume:
    def test_preempt_resume_bitwise_parity(self, tmp_path):
        from fedml_tpu.core.runstate import (
            PreemptionError, RunLedger, preemption_guard,
        )

        reg_kw = dict(client_registry="2000", cohort_size=16,
                      comm_round=6, checkpoint_rounds=2)
        ref = _make_api(**dict(reg_kw, checkpoint_rounds=0))
        ref.train()
        ref_params = _leaves(ref)

        api1 = _make_api(**reg_kw,
                         checkpoint_dir=str(tmp_path / "ckpt"))
        orig = api1.run_round

        def hooked(r):
            out = orig(r)
            if r == 2:
                preemption_guard().request()
            return out

        api1.run_round = hooked
        preemption_guard().reset()
        with pytest.raises(PreemptionError):
            api1.train()
        preemption_guard().reset()

        led = RunLedger.for_checkpoint_dir(str(tmp_path / "ckpt"))
        assert led.last_round() == 2
        # the ledger's run_meta pins the registry identity
        meta = led.meta()
        assert meta["world"]["registry"]["num_clients"] == 2000
        assert meta["world"]["registry"]["cohort_size"] == 16

        api2 = _make_api(**reg_kw, checkpoint_dir=str(tmp_path / "ckpt"))
        api2.train()
        for a, b in zip(ref_params, _leaves(api2)):
            assert a.dtype == b.dtype and np.array_equal(a, b), \
                "registry-backed resume diverged from uninterrupted run"
        # committed cohorts are the deterministic registry cohorts
        rounds = {r["round"]: r["cohort"] for r in led.rounds()}
        assert sorted(rounds) == list(range(6))

    def test_resume_with_different_registry_is_loud(self, tmp_path):
        api1 = _make_api(client_registry="2000", cohort_size=16,
                         comm_round=2, checkpoint_rounds=1,
                         checkpoint_dir=str(tmp_path / "ckpt"))
        api1.train()
        api2 = _make_api(client_registry="4000", cohort_size=16,
                         comm_round=4, checkpoint_rounds=1,
                         checkpoint_dir=str(tmp_path / "ckpt"))
        with pytest.raises(RuntimeError, match="run_meta mismatch"):
            api2.train()
        _close(api2)


# ---------------------------------------------------------------------------
# 7. wire-format satellites (ADVICE.md): frame validation + array contract
# ---------------------------------------------------------------------------


class TestWireContracts:
    def test_truncated_tensor_frame_is_a_clean_error(self):
        from fedml_tpu.core.distributed.tensor_transport import (
            decode_frames, encode_frames,
        )

        body = encode_frames([np.arange(32, dtype=np.float32)])
        with pytest.raises(ValueError, match="truncated tensor frame"):
            decode_frames(body[:-8])

    def test_corrupt_frame_header_is_a_clean_error(self):
        import json

        from fedml_tpu.core.distributed.tensor_transport import (
            RAW_MAGIC, decode_frames,
        )

        header = json.dumps(
            [{"dtype": "not-a-dtype", "shape": [4], "off": 0}]
        ).encode()
        body = (RAW_MAGIC + len(header).to_bytes(4, "big") + header
                + b"\x00" * 16)
        with pytest.raises(ValueError, match="corrupt tensor frame header"):
            decode_frames(body)
        header2 = json.dumps(
            [{"dtype": "<f4", "shape": [4], "off": -3}]
        ).encode()
        body2 = (RAW_MAGIC + len(header2).to_bytes(4, "big") + header2
                 + b"\x00" * 16)
        with pytest.raises(ValueError, match="corrupt tensor frame header"):
            decode_frames(body2)
        # adversarial shape that would wrap int64 under np.prod: must hit
        # the clean bounds error, not a raw numpy failure mid-decode
        header3 = json.dumps(
            [{"dtype": "<f4", "shape": [2 ** 40, 2 ** 40], "off": 0}]
        ).encode()
        body3 = (RAW_MAGIC + len(header3).to_bytes(4, "big") + header3
                 + b"\x00" * 16)
        with pytest.raises(ValueError, match="truncated tensor frame"):
            decode_frames(body3)
        # bit-flipped header bytes: a clean error, not a raw JSON failure
        good = json.dumps([{"dtype": "<f4", "shape": [2], "off": 0}]).encode()
        flipped = bytes([good[0] ^ 0xFF]) + good[1:]
        body4 = (RAW_MAGIC + len(flipped).to_bytes(4, "big") + flipped
                 + b"\x00" * 8)
        with pytest.raises(ValueError, match="corrupt tensor frame header"):
            decode_frames(body4)

    def test_registry_mode_skips_resident_dataset_copy(self):
        # streaming rounds must not park a dead HBM copy of the dataset;
        # superround is the exception (its scan gathers on device)
        api = _make_api(client_registry="2000", cohort_size=16)
        try:
            assert not api.hbm_resident
        finally:
            _close(api)
        scan = _make_api(client_registry="2000", cohort_size=8,
                         superround_k=2)
        try:
            assert scan.hbm_resident  # the scan body needs _dev_x et al.
        finally:
            _close(scan)

    def test_get_arrays_copy_contract(self):
        from fedml_tpu.core.distributed.message import Message

        msg = Message("t", 1, 2)
        msg.set_arrays([np.arange(8, dtype=np.float32)])
        msg.wire_format = "raw"
        back = Message.deserialize(msg.serialize())
        view = back.get_arrays()[0]
        # zero-copy views over the wire buffer are READ-ONLY
        assert not view.flags.writeable
        with pytest.raises(ValueError):
            view[0] = 99.0
        # the documented opt-in: fresh writable arrays, independent buffer
        writable = back.get_arrays(copy=True)[0]
        assert writable.flags.writeable
        writable[0] = 99.0
        np.testing.assert_array_equal(back.get_arrays()[0],
                                      np.arange(8, dtype=np.float32))
