import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu import constants


def test_defaults():
    args = Arguments()
    assert args.training_type == constants.FEDML_TRAINING_PLATFORM_SIMULATION
    assert args.backend == constants.FEDML_SIMULATION_TYPE_SP
    assert args.client_num_in_total == 10


def test_yaml_family_flatten(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        """
common_args:
  training_type: "simulation"
  random_seed: 7
data_args:
  dataset: "mnist"
  batch_size: 16
train_args:
  client_num_in_total: 100
  client_num_per_round: 10
  comm_round: 3
  learning_rate: "0.5"
"""
    )
    args = Arguments()
    args.load_yaml_config(str(cfg))
    args.validate()
    assert args.dataset == "mnist"
    assert args.random_seed == 7
    assert args.client_num_in_total == 100
    # typed coercion: "0.5" string -> float
    assert args.learning_rate == 0.5


def test_validation_errors():
    with pytest.raises(ValueError):
        Arguments(overrides={"training_type": "nope"})
    with pytest.raises(ValueError):
        Arguments(overrides={"client_num_per_round": 20, "client_num_in_total": 5})
    with pytest.raises(ValueError):
        Arguments(overrides={"batch_size": 0})
    with pytest.raises(ValueError):
        Arguments(overrides={"learning_rate": "abc"})


def test_mesh_shape_parse():
    args = Arguments(overrides={"mesh_shape": "data:2, tensor:4"})
    assert args.parse_mesh_shape() == {"data": 2, "tensor": 4}
