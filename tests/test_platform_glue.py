"""Platform glue: log daemon, remote config, deployment agents, CLI surface.

Mirrors the reference's MLOps/deployment plane behavior
(core/mlops/mlops_runtime_log_daemon.py, mlops_configs.py,
cli/edge_deployment/client_runner.py) on the TPU-pod-shaped local
implementations.
"""

import json
import os
import zipfile

import pytest

from fedml_tpu.agent import (
    STATUS_FAILED,
    STATUS_FINISHED,
    STATUS_RUNNING,
    Agent,
    agent_state,
    login,
    logout,
    submit_job,
)
from fedml_tpu.cli import main as cli_main
from fedml_tpu.core.mlops.log_daemon import LogProcessor, MLOpsRuntimeLogDaemon
from fedml_tpu.core.mlops.remote_config import RemoteConfig


# ---------------------------------------------------------------------------
# log daemon
# ---------------------------------------------------------------------------


def _write_lines(path, lines):
    with open(path, "a") as f:
        f.writelines(line + "\n" for line in lines)


def test_log_processor_ships_and_resumes(tmp_path):
    log = tmp_path / "run.log"
    dest = tmp_path / "shipped"
    _write_lines(log, [f"line-{i}" for i in range(5)])

    proc = LogProcessor(str(log), "r1", 0, f"dir:{dest}")
    assert proc.poll_once() == 5
    # nothing new → nothing shipped; index persisted
    assert proc.poll_once() == 0

    _write_lines(log, ["line-5", "line-6"])
    assert proc.poll_once() == 2

    out = (dest / "run_r1_edge_0.log").read_text().splitlines()
    assert out == [f"line-{i}" for i in range(7)]

    # a NEW processor (process restart) resumes from the saved line index
    proc2 = LogProcessor(str(log), "r1", 0, f"dir:{dest}")
    assert proc2.poll_once() == 0


def test_log_processor_holds_back_partial_line(tmp_path):
    log = tmp_path / "run.log"
    dest = tmp_path / "shipped"
    with open(log, "w") as f:
        f.write("complete\npart")  # writer caught mid-line
    proc = LogProcessor(str(log), "r3", 0, f"dir:{dest}")
    assert proc.poll_once() == 1  # only the terminated line ships
    with open(log, "a") as f:
        f.write("ial\n")
    assert proc.poll_once() == 1
    out = (dest / "run_r3_edge_0.log").read_text().splitlines()
    assert out == ["complete", "partial"]  # never truncated


def test_log_processor_failing_sink_keeps_index(tmp_path):
    log = tmp_path / "run.log"
    _write_lines(log, ["a", "b", "c"])
    calls = []

    def flaky_sink(run_id, edge_id, lines):
        calls.append(list(lines))
        return len(calls) > 1  # first ship fails

    proc = LogProcessor(str(log), "r2", 1, flaky_sink)
    assert proc.poll_once() == 0  # sink down: index unchanged
    assert proc.poll_once() == 3  # retry ships the same batch
    assert calls[0] == calls[1]


def test_log_processor_resets_on_truncation(tmp_path):
    log = tmp_path / "run.log"
    dest = tmp_path / "shipped"
    _write_lines(log, ["old-1", "old-2", "old-3"])
    proc = LogProcessor(str(log), "r4", 0, f"dir:{dest}")
    assert proc.poll_once() == 3
    log.write_text("new-1\n")  # rotation: file restarts smaller
    assert proc.poll_once() == 1  # offset reset, new content ships
    out = (dest / "run_r4_edge_0.log").read_text().splitlines()
    assert out[-1] == "new-1"


def test_log_daemon_registry(tmp_path):
    MLOpsRuntimeLogDaemon.reset_instance()
    log = tmp_path / "run.log"
    _write_lines(log, ["x"])
    daemon = MLOpsRuntimeLogDaemon.get_instance(f"dir:{tmp_path / 'out'}")
    daemon.start_log_processor("r", 0, str(log), upload_interval_s=0.05)
    try:
        deadline = 50
        import time

        for _ in range(deadline):
            out = tmp_path / "out" / "run_r_edge_0.log"
            if out.exists() and out.read_text().strip() == "x":
                break
            time.sleep(0.1)
        else:
            raise AssertionError("daemon thread never shipped the line")
    finally:
        MLOpsRuntimeLogDaemon.reset_instance()


# ---------------------------------------------------------------------------
# remote config
# ---------------------------------------------------------------------------


def test_remote_config_file_fetch_and_cache_fallback(tmp_path):
    RemoteConfig.reset_instance()
    src = tmp_path / "cfg.json"
    src.write_text(json.dumps({
        "mqtt_config": {"BROKER_HOST": "h", "BROKER_PORT": 1883},
        "s3_config": {"BUCKET_NAME": "b"},
    }))
    rc = RemoteConfig(str(src), cache_dir=str(tmp_path / "cache"))
    cfg = rc.fetch_configs(["mqtt_config", "s3_config"])
    assert cfg["mqtt_config"]["BROKER_HOST"] == "h"

    # source disappears → served from cache with a warning, not an error
    src.unlink()
    cfg2 = rc.fetch_configs(["mqtt_config"])
    assert cfg2["mqtt_config"]["BROKER_PORT"] == 1883


def test_remote_config_no_source_no_cache_raises(tmp_path):
    import pytest

    rc = RemoteConfig(str(tmp_path / "missing.json"),
                      cache_dir=str(tmp_path / "cache"))
    with pytest.raises(RuntimeError):
        rc.fetch_configs()


def test_remote_config_unwraps_data_envelope(tmp_path):
    # the reference endpoint nests payload under {"data": ...}
    src = tmp_path / "cfg.json"
    src.write_text(json.dumps({"data": {"ml_ops_config": {"LOG_SERVER": "u"}}}))
    rc = RemoteConfig(str(src), cache_dir=str(tmp_path / "cache"))
    assert rc.fetch_configs(["ml_ops_config"])["ml_ops_config"][
        "LOG_SERVER"] == "u"


# ---------------------------------------------------------------------------
# agents
# ---------------------------------------------------------------------------


def _make_package(tmp_path, name, entry_body, entry="main.py"):
    pkg_dir = tmp_path / name
    pkg_dir.mkdir()
    (pkg_dir / entry).write_text(entry_body)
    pkg = tmp_path / f"{name}.zip"
    with zipfile.ZipFile(pkg, "w") as z:
        z.write(pkg_dir / entry, entry)
        z.writestr("fedml_package.json",
                   json.dumps({"type": "client", "entry_point": entry}))
    return str(pkg)


def test_agent_runs_job_to_finished(tmp_path):
    pkg = _make_package(
        tmp_path, "ok",
        "import sys, json\n"
        "json.dump({'args': sys.argv[1:]}, open('out.json', 'w'))\n",
    )
    jobs = str(tmp_path / "jobs")
    job_id = submit_job(pkg, jobs, run_args=["--lr", "0.1"])
    agent = Agent(jobs, str(tmp_path / "work"))
    result = agent.run_once()
    assert result is not None and result.job_id == job_id
    assert result.status == STATUS_FINISHED
    out = json.load(open(os.path.join(result.run_dir, "out.json")))
    assert out["args"] == ["--lr", "0.1"]
    # full observable FSM, reference status names
    statuses = agent.job_statuses(job_id)
    assert statuses[0] == "UPGRADING" and STATUS_RUNNING in statuses
    assert statuses[-1] == STATUS_FINISHED
    # queue drained
    assert agent.run_once() is None


def test_agent_reports_failed_on_nonzero_exit(tmp_path):
    pkg = _make_package(tmp_path, "bad", "raise SystemExit(3)\n")
    jobs = str(tmp_path / "jobs")
    submit_job(pkg, jobs)
    result = Agent(jobs, str(tmp_path / "work")).run_once()
    assert result.status == STATUS_FAILED and result.returncode == 3


def test_agent_rejects_zip_slip(tmp_path):
    evil = tmp_path / "evil.zip"
    with zipfile.ZipFile(evil, "w") as z:
        z.writestr("../../escape.py", "print('pwn')\n")
        z.writestr("fedml_package.json",
                   json.dumps({"entry_point": "main.py"}))
    jobs = str(tmp_path / "jobs")
    submit_job(str(evil), jobs)
    result = Agent(jobs, str(tmp_path / "work")).run_once()
    assert result.status == STATUS_FAILED
    # '../../escape.py' relative to work/<job>/ would land in tmp_path itself
    assert not (tmp_path / "escape.py").exists()


def test_agent_requeues_stale_claim(tmp_path):
    pkg = _make_package(tmp_path, "ok2", "print('ran')\n")
    jobs = str(tmp_path / "jobs")
    job_id = submit_job(pkg, jobs)
    # a dead agent's claim: rename pending → claimed and backdate it
    src = os.path.join(jobs, f"{job_id}.job.json")
    claimed = os.path.join(jobs, f"{job_id}.job.claimed")
    os.rename(src, claimed)
    old = 10_000.0
    os.utime(claimed, (os.path.getmtime(claimed) - old,) * 2)

    agent = Agent(jobs, str(tmp_path / "work"), stale_claim_s=3600.0)
    result = agent.run_once()  # revives the orphan and runs it
    assert result is not None and result.status == STATUS_FINISHED
    assert not os.path.exists(claimed)  # finished claims are reaped


def test_claim_refreshes_mtime_so_queued_age_does_not_count(tmp_path):
    # a job that sat in the queue longer than stale_claim_s must NOT look
    # stale the instant it is claimed (ADVICE r2: rename preserves submit
    # mtime, letting a peer steal and double-run the job)
    pkg = _make_package(tmp_path, "aged", "print('ran')\n")
    jobs = str(tmp_path / "jobs")
    job_id = submit_job(pkg, jobs)
    pending = os.path.join(jobs, f"{job_id}.job.json")
    os.utime(pending, (os.path.getmtime(pending) - 10_000.0,) * 2)

    agent = Agent(jobs, str(tmp_path / "work"), stale_claim_s=3600.0)
    desc = agent._claim_next()
    assert desc["job_id"] == job_id
    # the claim filename is agent-unique so utime/open success proves
    # ownership even if a reviver re-pends and a peer re-claims the job
    claimed = os.path.join(jobs, f"{job_id}.job.claimed.{agent.agent_id}")
    import time as _time
    assert _time.time() - os.path.getmtime(claimed) < 60.0
    # a peer's reviver pass leaves the fresh claim alone
    peer = Agent(jobs, str(tmp_path / "work2"), stale_claim_s=3600.0)
    peer._requeue_stale_claims()
    assert os.path.exists(claimed)
    assert not os.path.exists(pending)


def test_stop_file_cleared_so_resubmitted_job_id_runs(tmp_path):
    from fedml_tpu.agent import request_stop

    pkg = _make_package(tmp_path, "stopme",
                        "import time\n"
                        "open('started', 'w').close()\n"
                        "time.sleep(60)\n")
    jobs = str(tmp_path / "jobs")
    agent = Agent(jobs, str(tmp_path / "work"))
    job_id = submit_job(pkg, jobs, job_id="job-fixed")
    request_stop(job_id, jobs)  # stop lands before the job even starts
    result = agent.run_once()
    assert result.status in (STATUS_FINISHED, STATUS_FAILED)
    # the kill switch must not survive to murder a resubmission of the id
    assert not os.path.exists(os.path.join(jobs, f"{job_id}.stop"))
    ok_pkg = _make_package(tmp_path, "ok3", "print('second life')\n")
    submit_job(ok_pkg, jobs, job_id="job-fixed")
    result2 = agent.run_once()
    assert result2.status == STATUS_FINISHED


def test_remote_config_explicit_params_do_not_hijack_singleton(tmp_path):
    RemoteConfig.reset_instance()
    default = RemoteConfig.get_instance()
    src = tmp_path / "cfg.json"
    src.write_text(json.dumps({"mqtt_config": {"host": "x"}}))
    explicit = RemoteConfig.get_instance(str(src),
                                         cache_dir=str(tmp_path / "c"))
    # explicit params → standalone instance honoring BOTH params...
    assert explicit.uri == str(src)
    assert explicit.cache_dir == str(tmp_path / "c")
    # ...and the process-wide default is untouched
    assert RemoteConfig.get_instance() is default
    RemoteConfig.reset_instance()


def test_login_logout_roundtrip(tmp_path):
    sd = str(tmp_path / "state")
    state = login("acct-7", role="server", state_dir=sd)
    assert state["role"] == "server"
    assert agent_state(state_dir=sd)["account_id"] == "acct-7"
    assert logout(state_dir=sd)
    assert agent_state(state_dir=sd) is None
    assert not logout(state_dir=sd)


# ---------------------------------------------------------------------------
# CLI deployment surface
# ---------------------------------------------------------------------------


def test_cli_build_launch_agent_pipeline(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    src = tmp_path / "train_dir"
    src.mkdir()
    (src / "main.py").write_text("print('trained')\n")

    assert cli_main(["build", "-sf", str(src), "-ep", "main.py",
                     "-o", str(tmp_path / "pkg.zip")]) == 0
    assert cli_main(["login", "acct", "--role", "client",
                     "--state_dir", str(tmp_path / "st")]) == 0
    # options precede the package; everything after it (flag-style included)
    # is handed to the job's entry point verbatim
    assert cli_main(["launch", "--jobs_dir", str(tmp_path / "jobs"),
                     str(tmp_path / "pkg.zip"), "--epochs", "2"]) == 0
    assert cli_main(["agent", "--once",
                     "--jobs_dir", str(tmp_path / "jobs"),
                     "--work_dir", str(tmp_path / "work"),
                     "--state_dir", str(tmp_path / "st")]) == 0
    out = capsys.readouterr().out
    assert "FINISHED" in out


@pytest.mark.slow
def test_reproduce_baselines_harness_fixture_run(tmp_path):
    """The published-baseline harness (tools/reproduce_baselines.py) runs a
    benchmark row end-to-end against the checked-in REAL-format fixture and
    reports data provenance honestly: real data for the fixture-staged row,
    synthetic (reproduces=null) without staging."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fixture = os.path.join(repo, "tests", "fixtures", "stackoverflow")

    def run(*argv):
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "reproduce_baselines.py"),
             "--platform", "cpu", *argv],
            capture_output=True, text=True, timeout=540,
        )
        assert p.returncode == 0, p.stderr[-800:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    real = run("--row", "stackoverflow_lr", "--cache-dir", fixture,
               "--rounds", "2")
    assert real["data"] == "real" and real["reproduces"] is None
    # the repo STAGES real MNIST (the t10k files at data_real/ — see
    # BASELINE.md): the default-cache run is real data under the disclosed
    # t10k-split protocol, never an unqualified reproduces claim
    staged = run("--row", "mnist_lr", "--rounds", "2",
                 "--cache-dir", os.path.join(repo, "data_real"))
    assert staged["data"] == "real"
    assert staged["protocol"] == "mnist_t10k_split"
    assert staged["reproduces"] is None
    assert staged["published_acc"] == 81.9
    # an explicitly-empty cache dir still degrades to synthetic, honestly
    synth = run("--row", "mnist_lr", "--rounds", "2",
                "--cache-dir", str(tmp_path))
    assert synth["data"] == "synthetic" and synth["reproduces"] is None
