"""Fault injection: transport-level failures drive every recovery path.

The reference has NO fault-injection harness (SURVEY.md §5) — its failure
story is last-will + fail-stop abort. Here system faults are injected at the
transport (core/distributed/faults.py) and the production FSMs recover:
round deadlines aggregate the survivors, and straggler revival readmits a
client whose loss was transient.
"""

import threading
import time

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.faults import FaultPlan, FaultyComm
from fedml_tpu.core.distributed.message import Message
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer


def make_args(run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=3, client_num_per_round=3, comm_round=2,
        epochs=2, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1,
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_faulty_world(run_id, client_plans, n_clients=3, **kw):
    args_s = make_args(run_id, role="server", client_num_in_total=n_clients,
                       **kw)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)

    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args(run_id, role="client", rank=rank,
                           client_num_in_total=n_clients, **kw)
        if rank in client_plans:
            args_c.fault_plan = client_plans[rank]
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    result = server.run()
    return result, server, clients


class TestFaultPlanUnit:
    def test_drop_rule_matches_header_only(self):
        plan = FaultPlan().drop(sender=3, round_idx=0)
        sent = []

        class Sink:
            def send_message(self, m):
                sent.append(m.get_type())

            def add_observer(self, o): ...
            def remove_observer(self, o): ...
            def handle_receive_message(self): ...
            def stop_receive_message(self): ...

        comm = FaultyComm(Sink(), plan, rank=3)
        status = Message("status", sender_id=3, receiver_id=0)  # no round
        comm.send_message(status)
        model = Message("model", sender_id=3, receiver_id=0)
        model.add(Message.MSG_ARG_KEY_ROUND_IDX, 0)
        comm.send_message(model)
        later = Message("model", sender_id=3, receiver_id=0)
        later.add(Message.MSG_ARG_KEY_ROUND_IDX, 1)
        comm.send_message(later)
        assert sent == ["status", "model"] and len(sent) == 2

    def test_seeded_loss_is_reproducible(self):
        def count_through(seed):
            plan = FaultPlan().loss(0.5, seed=seed)
            sent = []

            class Sink:
                def send_message(self, m):
                    sent.append(1)

                def add_observer(self, o): ...
                def remove_observer(self, o): ...
                def handle_receive_message(self): ...
                def stop_receive_message(self): ...

            comm = FaultyComm(Sink(), plan, rank=1)
            for _ in range(50):
                comm.send_message(Message("m", 1, 0))
            return len(sent)

        assert count_through(7) == count_through(7)
        assert 5 < count_through(7) < 45  # actually lossy, not all-or-nothing


class TestFaultShapes:
    """ISSUE 12: the chaos matrix's new fault shapes — partitions,
    stragglers, and the server kill switch."""

    class Sink:
        def __init__(self):
            self.sent = []

        def send_message(self, m):
            self.sent.append(m.get_type())

        def add_observer(self, o): ...
        def remove_observer(self, o): ...
        def handle_receive_message(self): ...
        def stop_receive_message(self): ...

    def test_partition_is_visible_bidirectional_and_heals(self):
        """Messages CROSSING the partitioned rank set fail VISIBLY (the
        at-least-once layer's signal) during the window; same-side traffic
        flows; after the window everything flows again."""
        import pytest

        from fedml_tpu.core.distributed.delivery import TransientSendError

        sink = self.Sink()
        plan = FaultPlan().partition({0}, start_s=0.0, duration_s=0.5)
        comm = FaultyComm(sink, plan, rank=0)
        with pytest.raises(TransientSendError, match="partition"):
            comm.send_message(Message("s2c", 0, 1))  # crossing: cut
        with pytest.raises(TransientSendError, match="partition"):
            comm.send_message(Message("c2s", 1, 0))  # crossing, other way
        comm.send_message(Message("gossip", 1, 2))   # same side: flows
        assert sink.sent == ["gossip"]
        time.sleep(0.6)  # the partition heals
        comm.send_message(Message("s2c", 0, 1))
        assert sink.sent == ["gossip", "s2c"]

    def test_straggle_is_a_sender_delay_rule(self):
        plan = FaultPlan().straggle(2, 1.5, round_idx=3)
        assert plan.delays == [{"sender": 2, "receiver": None, "round": 3,
                                "seconds": 1.5}]

    def test_kill_server_validates_phase(self):
        import pytest

        with pytest.raises(ValueError, match="kill_server phase"):
            FaultPlan().kill_server("between_rounds", 1)
        plan = FaultPlan().kill_server("mid_fold", 2)
        assert plan.kill_phase == "mid_fold" and plan.kill_round == 2
        # a non-matching phase/round is a no-op (we are still alive to
        # assert this — a match would have SIGKILLed the test runner)
        plan.maybe_kill_server("pre_fold", 2)
        plan.maybe_kill_server("mid_fold", 1)

    def test_external_kill_goes_dark(self):
        """FaultyComm.kill(): the deterministic fail-stop used by the
        failover tests — sends vanish, the receive loop stops."""
        sink = self.Sink()
        stopped = []
        sink.stop_receive_message = lambda: stopped.append(1)
        comm = FaultyComm(sink, FaultPlan(), rank=0)
        comm.send_message(Message("alive", 0, 1))
        comm.kill()
        comm.send_message(Message("after-death", 0, 1))
        assert sink.sent == ["alive"]
        assert stopped == [1]


class TestFaultRecovery:
    def test_transient_message_loss_revives_client(self):
        """Client 3's round-0 model vanishes on the wire: the deadline
        aggregates 2/3, and its round-1 model revives it — one lost message
        must not exclude a live client forever. Clients 1/2 are slowed so
        3's on-time round-1 model provably lands while the round is open."""
        plans = {
            3: FaultPlan().drop(sender=3, round_idx=0),
            1: FaultPlan().delay(1.0),
            2: FaultPlan().delay(1.0),
        }
        result, server, clients = run_faulty_world(
            "flt1", plans, round_timeout=6.0,
        )
        assert server.manager.round_idx == 2
        assert 3 not in server.manager._dead  # revived by its round-1 model
        assert result is not None and result["test_acc"] > 0.4
        for c in clients:
            assert c.manager.done.wait(timeout=30)

    def test_crashed_client_is_dropped_and_training_completes(self):
        """Client 2 dies after its round-0 upload (ONLINE + model = 2 sends):
        the round-1 deadline drops it and the other clients finish."""
        plan = FaultPlan().crash(rank=2, after_sends=2)
        result, server, clients = run_faulty_world(
            "flt2", {2: plan}, round_timeout=6.0,
        )
        assert server.manager.round_idx == 2
        assert 2 in server.manager._dead
        assert result is not None and result["test_acc"] > 0.4
        for c in clients:
            if c.manager.rank != 2:
                assert c.manager.done.wait(timeout=30)


class TestDeadlineRaces:
    """Satellite (ISSUE 4): the two cross-silo deadline races, driven by
    deterministic FaultPlan delays — no sleeps in the asserts; the only
    timing is the injected link latency itself.

    The races live in server_manager._on_round_timeout vs
    _on_model_received: a model landing exactly at the deadline must end up
    EITHER inside the closing round or cleanly dropped-then-revived — never
    double-counted, never wedging the round. The per-round contribution
    counters (aggregation-side) are the oracle."""

    def test_straggler_at_exact_timeout_boundary(self):
        """Client 3's round-0 model is delayed by EXACTLY round_timeout —
        the model-arrival and deadline callbacks race. Whichever side wins
        (counted into the closing round; dropped-then-revived into round 1;
        or dropped with the revival landing after the short run ended), the
        invariants hold: every round aggregates exactly once per client,
        the always-on-time clients are in every round, and the federation
        neither wedges nor double-counts."""
        timeout = 3.0
        plans = {3: FaultPlan().delay(timeout, sender=3, round_idx=0)}
        result, server, clients = run_faulty_world(
            "race-exact", plans, round_timeout=timeout,
        )
        m = server.manager
        assert m.round_idx == 2
        assert sorted(m.contrib_counts) == [0, 1]  # each round ONCE
        for rnd, per in m.contrib_counts.items():
            assert all(v == 1 for v in per.values()), (rnd, per)
            assert {1, 2} <= set(per) <= {1, 2, 3}, (rnd, per)
        assert result is not None and result["test_acc"] > 0.4

    def test_stale_deferred_timeout_aggregation_is_a_noop(self):
        """ISSUE 7 satellite: _on_round_timeout verifies the round under
        the lock, RELEASES it, then calls the aggregation. If the round
        closes in that window (its last model arrived concurrently), the
        deferred aggregation call arrives one round late — it must be a
        clean no-op on the next round's early arrivals, never a premature
        partial aggregation or a double count. Driven by direct method
        calls, so the interleaving is exact, not probabilistic."""
        import jax
        import numpy as np

        from fedml_tpu.cross_silo.message_define import MyMessage

        def model_msg(manager, rank, round_idx):
            msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                          rank, 0)
            msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, round_idx)
            msg.add(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 5.0)
            msg.set_arrays([np.asarray(l) for l in
                            jax.tree.leaves(manager.global_params)])
            return msg

        args = make_args("race-guard", role="server",
                         client_num_in_total=2, client_num_per_round=2,
                         round_timeout=30.0)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        m = FedMLCrossSiloServer(args, None, ds, bundle).manager
        try:
            # round 0 completes normally
            m._on_model_received(model_msg(m, 1, 0))
            m._on_model_received(model_msg(m, 2, 0))
            assert m.round_idx == 1
            # ONE early round-1 model is pending when the stale deferred
            # aggregation call from round 0's timeout thread finally runs
            m._on_model_received(model_msg(m, 1, 1))
            assert m.round_idx == 1 and 1 in m._models
            m._finish_round(0)  # the raced, deferred call
            # no premature partial aggregation of round 1:
            assert m.round_idx == 1
            assert 1 in m._models
            assert 1 not in m.contrib_counts
            # and round 1 still completes normally afterwards
            m._on_model_received(model_msg(m, 2, 1))
            assert m.round_idx == 2
            assert sorted(m.contrib_counts[1]) == [1, 2]
            assert all(v == 1 for per in m.contrib_counts.values()
                       for v in per.values())
        finally:
            if m._round_timer is not None:
                m._round_timer.cancel()

    def test_dropped_client_revival_is_exactly_once(self):
        """Client 3's round-0 model arrives long after the deadline: the
        round closes without it (dropped), the late round-0 model is
        rejected as stale, and its on-time round-1 model revives it.
        Clients 1/2 are slowed in round 1 so 3's revival model provably
        lands while the round is open."""
        # deadline sized like the other load-safe tests here (6 s): the
        # on-time clients' round-0 models must land inside it even when a
        # parallel suite run starves the host core
        timeout = 6.0
        plans = {
            3: FaultPlan().delay(2 * timeout + 2.0, sender=3, round_idx=0),
            1: FaultPlan().delay(1.0, sender=1, round_idx=1),
            2: FaultPlan().delay(1.0, sender=2, round_idx=1),
        }
        result, server, clients = run_faulty_world(
            "race-revive", plans, round_timeout=timeout,
        )
        m = server.manager
        assert m.round_idx == 2
        # round 0 closed WITHOUT client 3 — its model was still in flight
        assert sorted(m.contrib_counts.get(0, {})) == [1, 2]
        # round 1 revived it, exactly once; the stale round-0 model that
        # eventually arrived must not appear anywhere
        assert sorted(m.contrib_counts.get(1, {})) == [1, 2, 3]
        for rnd, per in m.contrib_counts.items():
            assert all(v == 1 for v in per.values()), (rnd, per)
        assert 3 not in m._dead  # revived, not permanently excluded
        assert result is not None and result["test_acc"] > 0.4
