"""Hierarchical edge-aggregation tier (docs/traffic.md "Hierarchical edge
tier", docs/robustness.md "Edge tier failure domains").

The load-bearing claim: a 2-tier world is a TRANSPORT optimization, never a
math change — edges pre-fold only the control plane and ship
entry-preserving summaries, so the root runs the exact flat decode + fold +
aggregate code per client entry. That makes "2-tier ≡ flat, bitwise" an
executable invariant, which these tests pin fault-free and under the tier's
own failure matrix (edge fail-stop at each protocol phase, root–edge
partition) with exactly-once contribution accounting throughout.
"""

import os
import types

import numpy as np
import pytest

from fedml_tpu import chaos
from fedml_tpu.core.mlops import telemetry
from fedml_tpu.hierarchy import Topology, pack_summary, unpack_summary
from fedml_tpu.traffic.async_aggregator import (
    AsyncConfig,
    AsyncUpdateBuffer,
    staleness_weight,
)


class TestTopology:
    def test_flat_rank_space_is_preserved(self):
        """Clients keep the EXACT ranks (and therefore data shards and
        sender ids) they have in a flat world — the bitwise-parity
        precondition."""
        topo = Topology(clients=10, edges=3)
        assert [r for r in range(20) if topo.is_client(r)] == list(
            range(1, 11))
        assert topo.edge_ranks == [11, 12, 13]
        assert topo.world_size == 14
        assert not topo.is_edge(10) and not topo.is_client(11)

    def test_home_edge_partitions_clients_in_contiguous_blocks(self):
        topo = Topology(clients=10, edges=3)
        homes = [topo.home_edge(c) for c in range(1, 11)]
        assert homes == sorted(homes)  # contiguous blocks
        for e in topo.edge_ranks:
            assert topo.edge_clients(e) == [
                c for c in range(1, 11) if topo.home_edge(c) == e]
        # every client has exactly one home
        assert sum(len(topo.edge_clients(e)) for e in topo.edge_ranks) == 10

    def test_rehome_ring_ends_at_root_and_skips_home(self):
        topo = Topology(clients=10, edges=3)
        for c in range(1, 11):
            targets = topo.rehome_targets(c)
            assert targets[-1] == 0
            assert topo.home_edge(c) not in targets
            assert sorted(targets[:-1] + [topo.home_edge(c)]) == \
                topo.edge_ranks

    def test_aligned_rank_base_pads_not_overlaps(self):
        topo = Topology(clients=10, edges=2, edge_rank_base=17)
        assert topo.edge_ranks == [17, 18]
        assert topo.world_size == 19
        with pytest.raises(ValueError):
            Topology(clients=10, edges=2, edge_rank_base=5)

    def test_from_args_is_the_single_knob(self):
        flat = types.SimpleNamespace(client_num_in_total=8)
        assert Topology.from_args(flat) is None
        tiered = types.SimpleNamespace(client_num_in_total=8,
                                       hierarchy_edges=2)
        topo = Topology.from_args(tiered)
        assert topo is not None and topo.edge_rank_base == 9


class TestSummaryCodec:
    def test_roundtrip_is_entry_preserving(self):
        """Frames come back VERBATIM (same objects, no float touched) with
        the per-client control-plane identity intact — the transport
        batches, the math never changes."""
        frames_a = [np.arange(6, dtype=np.float32),
                    np.ones(3, dtype=np.float32)]
        frames_b = [np.full(6, 2.5, dtype=np.float32)]
        meta, arrays = pack_summary([
            {"sender": 3, "client_version": 7, "num_samples": 11.0,
             "arrays": frames_a, "staleness": 1},
            {"sender": 1, "client_version": 8, "num_samples": 4.0,
             "arrays": frames_b, "dmeta": {"base_version": 7}},
        ], stats={"folds": 2}, seq=5)
        assert meta["seq"] == 5 and meta["stats"] == {"folds": 2}
        entries = unpack_summary(meta, arrays)
        assert [e["sender"] for e in entries] == [3, 1]
        assert entries[0]["arrays"][0] is frames_a[0]
        assert entries[0]["arrays"][1] is frames_a[1]
        assert entries[1]["arrays"] == frames_b
        assert entries[1]["dmeta"] == {"base_version": 7}
        assert entries[0]["num_samples"] == 11.0

    def test_frame_count_mismatch_rejected(self):
        meta, arrays = pack_summary([
            {"sender": 1, "client_version": 0, "num_samples": 1.0,
             "arrays": [np.zeros(2, dtype=np.float32)]}])
        with pytest.raises(ValueError):
            unpack_summary(meta, arrays + [np.zeros(1, dtype=np.float32)])


class TestStalenessComposition:
    """Tier composition of the FedBuff staleness math: an entry's weight at
    the root depends ONLY on (root head − client_version) — both of which a
    summary entry carries verbatim — so the edge hop cannot perturb it."""

    def test_alpha_zero_weights_are_exactly_one(self):
        for s in (0, 1, 5, 1000):
            assert staleness_weight(s, 0.0) == 1.0

    def test_root_weight_identical_through_summary_roundtrip(self):
        alpha = 0.5
        cfg = AsyncConfig(buffer_size=3, staleness_alpha=alpha)
        flat = AsyncUpdateBuffer(cfg)
        root = AsyncUpdateBuffer(cfg)
        head = 9
        updates = [(4, 3.0, 7), (2, 5.0, 9), (6, 1.0, 5)]
        for sender, n, v in updates:
            params = {"w": np.full(4, sender, dtype=np.float32)}
            assert flat.fold(sender, n, params, v, head) == "buffered"
            # tiered path: the entry rides a summary, then folds at root
            meta, arrays = pack_summary([
                {"sender": sender, "client_version": v, "num_samples": n,
                 "arrays": [params["w"]]}])
            (e,) = unpack_summary(meta, arrays)
            assert root.fold(e["sender"], e["num_samples"],
                             {"w": e["arrays"][0]}, e["client_version"],
                             head) == "buffered"
        for f, r in zip(flat.drain(), root.drain()):
            assert f.sender == r.sender
            assert f.staleness == r.staleness == max(head - f.client_version,
                                                     0)
            # exact float equality, not approx: same inputs, same formula
            assert f.weight == r.weight == f.num_samples * staleness_weight(
                f.staleness, alpha)
            assert np.array_equal(f.params["w"], r.params["w"])


def _cfg(tmp_path, **kw):
    a = types.SimpleNamespace(
        clients=4, rounds=2, epochs=1, seed=7, loss=0.0, duplicate=0.0,
        corrupt=0.0, kill_round=-1, checkpoint_rounds=1,
        workdir=str(tmp_path), timeout=240.0, worker=False, out="",
        checkpoint_dir="", edges=2,
    )
    for k, v in kw.items():
        setattr(a, k, v)
    return a


def _assert_bitwise(ref, tiered):
    assert len(ref["params"]) == len(tiered["params"])
    for i, (x, y) in enumerate(zip(ref["params"], tiered["params"])):
        assert x.dtype == y.dtype and np.array_equal(x, y), \
            f"leaf {i} diverged through the edge tier"


def _assert_exactly_once(result, clients):
    for rnd, per in result["server"].contrib_counts.items():
        assert sorted(per) == list(range(1, clients + 1)), (rnd, per)
        assert all(v == 1 for v in per.values()), (rnd, per)


class TestTieredWorld:
    def test_fault_free_two_tier_equals_flat_bitwise(self, tmp_path):
        """The tentpole invariant: same seeds, same shards — a 2-tier world
        (clients → 2 edges → root) finishes with EXACTLY the flat world's
        final params, every contribution counted once."""
        a = _cfg(tmp_path)
        ref = chaos.run_world(
            a, run_id=f"hier-ref-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "ref"), faulty=False)
        tiered = chaos.run_world(
            a, run_id=f"hier-2t-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "tier"), faulty=True)
        assert len(tiered["edges"]) == 2
        assert not any(e.killed for e in tiered["edges"])
        _assert_bitwise(ref, tiered)
        _assert_exactly_once(tiered, 4)

    def test_edge_kill_pre_fold_rehomes_and_matches_flat(self, tmp_path):
        """Kill the first edge the moment a client update reaches it: its
        orphans must detect the corpse, re-home (sibling edge or root
        degraded mode), replay their cached still-stamped updates, and the
        run must STILL land bitwise on the flat params — with the dedup
        window + committed-round guard keeping every (client, round)
        contribution exactly-once."""
        telemetry.registry().reset()
        a = _cfg(tmp_path, kill_edge="pre_fold",
                 loss=0.05, duplicate=0.1, corrupt=0.1)
        ref = chaos.run_world(
            a, run_id=f"hier-kref-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "ref"), faulty=False)
        tiered = chaos.run_world(
            a, run_id=f"hier-kill-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "kill"), faulty=True)
        assert any(e.killed for e in tiered["edges"]), \
            "armed pre_fold kill never fired"
        counters = telemetry.registry().snapshot()["counters"]
        assert counters.get("comm.rehomes", 0) > 0, \
            "no orphan ever re-homed"
        _assert_bitwise(ref, tiered)
        _assert_exactly_once(tiered, 4)


@pytest.mark.slow
class TestTieredChaosMatrixSlow:
    @pytest.mark.parametrize("phase", ["mid_fold", "post_commit"])
    def test_edge_kill_phase_matches_flat(self, tmp_path, phase):
        """The remaining kill phases: summary built-but-unsent (mid_fold —
        the buffer dies with the edge, clients re-offer) and already-sent
        (post_commit — the replay must dedup, not double-count)."""
        telemetry.registry().reset()
        a = _cfg(tmp_path, kill_edge=phase,
                 loss=0.05, duplicate=0.1, corrupt=0.1)
        ref = chaos.run_world(
            a, run_id=f"hier-{phase}-ref-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "ref"), faulty=False)
        tiered = chaos.run_world(
            a, run_id=f"hier-{phase}-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "kill"), faulty=True)
        assert any(e.killed for e in tiered["edges"])
        _assert_bitwise(ref, tiered)
        _assert_exactly_once(tiered, 4)

    def test_root_edge_partition_heals_bitwise(self, tmp_path):
        """Cut the first edge off from the root mid-run: the edge rides the
        cut on its resync FSM and re-ships its cached summary on heal; the
        committed-round guard absorbs whatever had already arrived."""
        telemetry.registry().reset()
        a = _cfg(tmp_path, edge_partition="1.0:2.0",
                 loss=0.05, duplicate=0.1, corrupt=0.1, rounds=3)
        ref = chaos.run_world(
            a, run_id=f"hier-part-ref-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "ref"), faulty=False)
        tiered = chaos.run_world(
            a, run_id=f"hier-part-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "part"), faulty=True)
        assert not any(e.killed for e in tiered["edges"])
        counters = telemetry.registry().snapshot()["counters"]
        assert (counters.get("comm.heartbeat_misses", 0)
                + counters.get("comm.resync_replays", 0)) > 0, \
            "partition window never bit"
        _assert_bitwise(ref, tiered)
        _assert_exactly_once(tiered, 4)
