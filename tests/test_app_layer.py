"""Application layer: FedNLP / FedCV / healthcare tasks end-to-end.

Mirrors the reference's ``python/app/`` coverage (456 files of per-domain
trainers) through the one engine: every app task is a (dataset spec, model,
loss) triple on the standard sp runtime — seq tagging, span extraction,
prefix-LM seq2seq, dense detection, tabular healthcare.
(FedGraphNN lives in tests/test_graphnn.py.)
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner


def run_app(dataset, model, **kw):
    base = dict(
        dataset=dataset, model=model, client_num_in_total=8,
        client_num_per_round=8, comm_round=8, epochs=2, batch_size=16,
        learning_rate=0.1, frequency_of_the_test=20, backend="sp",
    )
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    return FedMLRunner(args, fedml.get_device(args), ds, bundle).run()


class TestFedNLP:
    def test_seq_tagging_learns_context(self):
        # 9-tag chance ≈ 0.11; the trigger rule needs the BiLSTM's context.
        # plain SGD on an LSTM needs a hot lr (no adaptivity, tiny scale)
        res = run_app("fednlp_seq_tagging", "bilstm_tagger",
                      learning_rate=1.0, comm_round=12, epochs=3)
        assert res["test_acc"] > 0.5

    def test_span_extraction_finds_spans(self):
        res = run_app("fednlp_span_extraction", "span_extractor",
                      learning_rate=1.0, comm_round=12, epochs=3)
        # exact-match over 32 start × 32 end positions; chance ≈ 0.1%
        assert res["test_acc"] > 0.5

    def test_seq2seq_prefix_lm_learns(self):
        # sequence reversal is a copy task: attention solves it, a small
        # LSTM's fixed-width state cannot — so the transformer is the model
        res = run_app("fednlp_seq2seq", "transformer", learning_rate=0.3,
                      comm_round=12, epochs=3)
        # per-token accuracy on the target region; 31-vocab chance ≈ 3%
        assert res["test_acc"] > 0.8


class TestFedCVDetection:
    def test_detection_centers_classified(self):
        res = run_app("coco128_det", "centernet", learning_rate=0.05,
                      comm_round=6, epochs=2, batch_size=8,
                      client_num_in_total=4, client_num_per_round=4)
        # "acc" = argmax class correct at real centers; 6-class chance ≈ 0.17
        assert res["test_acc"] > 0.4
        assert np.isfinite(res["test_loss"])

    def test_detection_shapes(self):
        args = fedml.init(Arguments(overrides=dict(
            dataset="coco128_det", model="centernet",
            client_num_in_total=4, client_num_per_round=4, batch_size=8,
        )), should_init_logs=False)
        ds, output_dim = data_mod.load(args)
        assert ds.train_y.shape[-3:] == (8, 8, 6 + 3)
        bundle = model_mod.create(args, output_dim)
        import jax

        params = bundle.init(jax.random.PRNGKey(0))
        out = bundle.apply(params, bundle.dummy_input(2))
        assert out.shape == (2, 8, 8, 6 + 2)


class TestHealthcare:
    def test_heart_disease_tabular(self):
        res = run_app("fed_heart_disease", "lr", client_num_in_total=4,
                      client_num_per_round=4, comm_round=10)
        assert res["test_acc"] > 0.7  # binary, linearly separable

    def test_tcga_brca_regression(self):
        res = run_app("fed_tcga_brca", "lr", client_num_in_total=4,
                      client_num_per_round=4, comm_round=12,
                      learning_rate=0.05)
        assert res["test_loss"] < 0.5  # targets ~unit variance; MSE → noise

    def test_isic_imaging(self):
        res = run_app("fed_isic2019", "cnn", client_num_in_total=4,
                      client_num_per_round=4, comm_round=6,
                      batch_size=8, learning_rate=0.05)
        assert res["test_acc"] > 0.4  # 8-class chance = 0.125


class TestCheetahBackbone:
    """Row 75's scale path: the SAME transformer the flagship pretrains,
    carrying the FedNLP task heads and scaling via the flagship's YAML
    knobs (model_size/d_model/... up to 7B)."""

    def test_seq_tagging_on_cheetah(self):
        res = run_app("fednlp_seq_tagging", "cheetah_tagger",
                      learning_rate=0.5, comm_round=10, epochs=3)
        assert res["test_acc"] > 0.5  # 9-tag chance ~0.11

    def test_span_extraction_on_cheetah(self):
        # encoder attention (END pointers need lookahead) + learned
        # positions (rotary solutions average destructively under FedAvg)
        res = run_app("fednlp_span_extraction", "cheetah_span",
                      pos_emb="learned", learning_rate=0.15,
                      comm_round=24, epochs=5)
        assert res["test_acc"] > 0.5  # exact match; chance ~0.1%

    def test_seq2seq_on_cheetah(self):
        # prefix-LM seq2seq IS the Cheetah LM — no head needed. Learned
        # absolute positions (cfg.pos_emb) are load-bearing: rotary clients
        # converge to per-client-rotated solutions whose FedAvg average
        # destroys the task (measured: stuck at 8% / diverging loss)
        res = run_app("fednlp_seq2seq", "cheetah", pos_emb="learned",
                      learning_rate=0.3, comm_round=12, epochs=3)
        assert res["test_acc"] > 0.8

    def test_backbone_scales_with_flagship_knobs(self):
        """The head bundles take the flagship config surface: a d256 x 4L
        GQA backbone builds and runs from the same args that size the LM."""
        import jax

        args = fedml.init(Arguments(overrides=dict(
            dataset="fednlp_seq_tagging", model="cheetah_tagger",
            model_size="custom", d_model=256, n_layers=4, n_heads=8,
            n_kv_heads=2, d_ff=704, client_num_in_total=4,
            client_num_per_round=4,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        assert bundle.cfg.d_model == 256 and bundle.cfg.n_kv_heads == 2
        params = bundle.init(jax.random.PRNGKey(0))
        out = bundle.apply(params, np.zeros((2, bundle.cfg.max_seq_len),
                                            np.int32))
        assert out.shape == (2, bundle.cfg.max_seq_len, od)


class TestDetection224:
    def test_detection_224px_via_native_pipeline(self):
        """Real-resolution detection (224px, deeper CenterNet) trained with
        batches produced by the native host pipeline (C++ BatchPrefetcher
        carrying float32 dense targets bit-exact)."""
        import jax
        import jax.numpy as jnp
        import optax

        from fedml_tpu import native
        from fedml_tpu.ml.losses import get_loss_fn

        args = fedml.init(Arguments(overrides=dict(
            dataset="fedcv_det224", model="centernet",
            client_num_in_total=4, client_num_per_round=4, batch_size=4,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        assert tuple(ds.train_x.shape[2:]) == (224, 224, 3)
        assert ds.train_y.shape[-3:] == (56, 56, 6 + 3)
        bundle = model_mod.create(args, od)
        params = bundle.init(jax.random.PRNGKey(0))
        loss_fn = get_loss_fn("detection")

        opt = optax.adam(3e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, bx, by):
            def loss(p):
                logits = bundle.apply(p, bx, train=True)
                l, _ = loss_fn(logits, by, jnp.ones((bx.shape[0],)))
                return l

            l, g = jax.value_and_grad(loss)(params)
            updates, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(params, updates), opt_state, l

        # one client's real rows through the native prefetcher
        n0 = int(ds.train_counts[0])
        pf = native.BatchPrefetcher(
            ds.train_x[0][:n0], ds.train_y[0][:n0], batch_size=4, seed=0
        )
        try:
            losses = []
            for _ in range(10):
                bx, by, _ = pf.next()
                assert by.dtype == np.float32  # targets rode bit-exact
                params, opt_state, l = step(
                    params, opt_state, jnp.asarray(bx), jnp.asarray(by)
                )
                losses.append(float(l))
        finally:
            pf.close()
        assert losses[-1] < losses[0], losses
