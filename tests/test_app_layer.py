"""Application layer: FedNLP / FedCV / healthcare tasks end-to-end.

Mirrors the reference's ``python/app/`` coverage (456 files of per-domain
trainers) through the one engine: every app task is a (dataset spec, model,
loss) triple on the standard sp runtime — seq tagging, span extraction,
prefix-LM seq2seq, dense detection, tabular healthcare.
(FedGraphNN lives in tests/test_graphnn.py.)
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner


def run_app(dataset, model, **kw):
    base = dict(
        dataset=dataset, model=model, client_num_in_total=8,
        client_num_per_round=8, comm_round=8, epochs=2, batch_size=16,
        learning_rate=0.1, frequency_of_the_test=20, backend="sp",
    )
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    return FedMLRunner(args, fedml.get_device(args), ds, bundle).run()


class TestFedNLP:
    def test_seq_tagging_learns_context(self):
        # 9-tag chance ≈ 0.11; the trigger rule needs the BiLSTM's context.
        # plain SGD on an LSTM needs a hot lr (no adaptivity, tiny scale)
        res = run_app("fednlp_seq_tagging", "bilstm_tagger",
                      learning_rate=1.0, comm_round=12, epochs=3)
        assert res["test_acc"] > 0.5

    def test_span_extraction_finds_spans(self):
        res = run_app("fednlp_span_extraction", "span_extractor",
                      learning_rate=1.0, comm_round=12, epochs=3)
        # exact-match over 32 start × 32 end positions; chance ≈ 0.1%
        assert res["test_acc"] > 0.5

    def test_seq2seq_prefix_lm_learns(self):
        # sequence reversal is a copy task: attention solves it, a small
        # LSTM's fixed-width state cannot — so the transformer is the model
        res = run_app("fednlp_seq2seq", "transformer", learning_rate=0.3,
                      comm_round=12, epochs=3)
        # per-token accuracy on the target region; 31-vocab chance ≈ 3%
        assert res["test_acc"] > 0.8

    @pytest.mark.slow
    def test_seq2seq_generation_metrics(self):
        """ROUGE-L / BLEU / exact-match via true autoregressive greedy
        decoding (VERDICT r4 missing #1: 'seq2seq has per-token acc, no
        ROUGE/BLEU' — reference app/fednlp/seq2seq evaluates generation).
        Teacher-forced token accuracy can flatter a model that derails once
        it consumes its own outputs; decoding closes that gap."""
        from fedml_tpu.data.datasets import REGISTRY
        from fedml_tpu.ml.generation_metrics import evaluate_generation
        from fedml_tpu.simulation.sp_api import FedAvgAPI

        args = fedml.init(Arguments(overrides=dict(
            dataset="fednlp_seq2seq", model="transformer",
            client_num_in_total=8, client_num_per_round=8, comm_round=12,
            epochs=3, batch_size=16, learning_rate=0.3,
            frequency_of_the_test=100, backend="sp",
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)
        for r in range(int(args.comm_round)):
            args.round_idx = r
            api._train_round(r)
        spec = REGISTRY["fednlp_seq2seq"]
        src_len = (spec.seq_len - 1) // 2
        m = evaluate_generation(
            bundle, api.global_params, ds.test_x, ds.test_y,
            prompt_len=src_len + 1, tgt_len=src_len,
        )
        print(f"seq2seq generation: rouge_l={m['rouge_l']:.3f} "
              f"bleu={m['bleu']:.3f} em={m['exact_match']:.3f} "
              f"(n={m['n_eval']:.0f})")
        # a converged reversal model must generate well, not just score
        # teacher-forced tokens (31-vocab chance ROUGE-L ~= 0.1)
        assert m["n_eval"] >= 64
        assert m["rouge_l"] > 0.6
        assert m["bleu"] > 0.4


class TestFedCVDetection:
    def test_detection_centers_classified(self):
        res = run_app("coco128_det", "centernet", learning_rate=0.05,
                      comm_round=6, epochs=2, batch_size=8,
                      client_num_in_total=4, client_num_per_round=4)
        # "acc" = argmax class correct at real centers; 6-class chance ≈ 0.17
        assert res["test_acc"] > 0.4
        assert np.isfinite(res["test_loss"])

    def test_detection_shapes(self):
        args = fedml.init(Arguments(overrides=dict(
            dataset="coco128_det", model="centernet",
            client_num_in_total=4, client_num_per_round=4, batch_size=8,
        )), should_init_logs=False)
        ds, output_dim = data_mod.load(args)
        assert ds.train_y.shape[-3:] == (8, 8, 6 + 3)
        bundle = model_mod.create(args, output_dim)
        import jax

        params = bundle.init(jax.random.PRNGKey(0))
        out = bundle.apply(params, bundle.dummy_input(2))
        assert out.shape == (2, 8, 8, 6 + 2)


class TestFederatedDetection224:
    @pytest.mark.slow
    def test_federated_224px_with_map50(self):
        """Real-resolution detection FEDERATED through the sp engine
        (VERDICT r4 #7 — the old 224px test was a single-client loop), with
        mAP@0.5 reported by the shared decode/matching machinery. The
        engine's lax.map cohort path keeps XLA:CPU off the pathological
        vmapped-grouped-conv lowering."""
        import jax

        from fedml_tpu.ml.detection_metrics import evaluate_map50

        args = fedml.init(Arguments(overrides=dict(
            dataset="fedcv_det224_mini", model="centernet",
            client_num_in_total=4, client_num_per_round=2, comm_round=3,
            epochs=2, batch_size=4, learning_rate=3e-3,
            client_optimizer="adam", frequency_of_the_test=1000,
            random_seed=3,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        assert tuple(ds.train_x.shape[2:]) == (224, 224, 3)
        bundle = model_mod.create(args, od)

        from fedml_tpu.simulation.sp_api import FedAvgAPI

        api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)
        init_25 = evaluate_map50(bundle, api.global_params,
                                 ds.test_x, ds.test_y, batch_size=4,
                                 iou_thresh=0.25)
        for r in range(int(args.comm_round)):
            args.round_idx = r
            api._train_round(r)
        from fedml_tpu.ml.detection_metrics import (
            collect_detection_logits, map_at_50,
        )

        logits = collect_detection_logits(bundle, api.global_params,
                                          ds.test_x, batch_size=4)
        targets = [np.asarray(t, np.float32) for t in ds.test_y]
        trained_50 = map_at_50(logits, targets)
        trained_25 = map_at_50(logits, targets, iou_thresh=0.25)
        print(f"federated det224 mAP@0.5={trained_50['map50']:.3f} "
              f"mAP@0.25: init={init_25['map50']:.3f} -> "
              f"trained={trained_25['map50']:.3f} "
              f"(gt={trained_50['total_gt']:.0f})")
        assert trained_50["total_gt"] > 0
        assert np.isfinite(trained_50["map50"])
        # federated training must produce real localization signal over the
        # random init; IoU 0.25 isolates heatmap localization from the
        # slower (0.1-weighted L1) size-regression convergence — mAP@0.5 is
        # REPORTED above but too noisy to gate a 24-step run on
        assert trained_25["map50"] > init_25["map50"] + 0.02


class TestDetectionMetrics:
    """Host-side decode + mAP@0.5 (ml/detection_metrics.py)."""

    @staticmethod
    def _logits_from_target(tg, conf=6.0):
        """Perfect predictions: heatmap logit +conf at GT centers, -conf
        elsewhere; exact size regression."""
        C = tg.shape[-1] - 3
        logits = np.full(tg.shape[:2] + (C + 2,), -conf, np.float32)
        cy, cx = np.nonzero(tg[..., -1] > 0.5)
        for y, x in zip(cy, cx):
            logits[y, x, np.argmax(tg[y, x, :C])] = conf
            logits[y, x, C:C + 2] = tg[y, x, C:C + 2]
        return logits

    def test_perfect_predictions_score_one(self):
        from fedml_tpu.data.datasets import REGISTRY, synth_detection
        from fedml_tpu.ml.detection_metrics import map_at_50

        spec = REGISTRY["coco128_det"]
        _, _, ex, ey = synth_detection(spec, 2, 8, seed=0)
        logits = [self._logits_from_target(t) for t in ey]
        res = map_at_50(logits, ey)
        assert res["map50"] == pytest.approx(1.0)
        assert res["total_gt"] >= 8

    def test_empty_and_wrong_predictions(self):
        from fedml_tpu.data.datasets import REGISTRY, synth_detection
        from fedml_tpu.ml.detection_metrics import map_at_50

        spec = REGISTRY["coco128_det"]
        _, _, _, ey = synth_detection(spec, 2, 4, seed=1)
        # no predictions at all
        empty = [np.full(t.shape[:2] + (t.shape[-1] - 1,), -9.0, np.float32)
                 for t in ey]
        assert map_at_50(empty, ey)["map50"] == 0.0
        # confident boxes in the wrong places score ~0
        rng = np.random.RandomState(0)
        noise = [np.asarray(rng.randn(*e.shape), np.float32) * 3 for e in empty]
        assert map_at_50(noise, ey)["map50"] < 0.3

    def test_decode_roundtrip(self):
        from fedml_tpu.data.datasets import REGISTRY, synth_detection
        from fedml_tpu.ml.detection_metrics import (
            decode_ground_truth, decode_predictions,
        )

        spec = REGISTRY["coco128_det"]
        _, _, _, ey = synth_detection(spec, 2, 2, seed=2)
        gt = decode_ground_truth(ey[0])
        preds = decode_predictions(self._logits_from_target(ey[0]))
        assert len(preds) == len(gt)
        got = {(c, tuple(round(v, 3) for v in box)) for _s, c, box in preds}
        want = {(c, tuple(round(v, 3) for v in box)) for c, box in gt}
        assert got == want


class TestHealthcare:
    def test_heart_disease_tabular(self):
        res = run_app("fed_heart_disease", "lr", client_num_in_total=4,
                      client_num_per_round=4, comm_round=10)
        assert res["test_acc"] > 0.7  # binary, linearly separable

    def test_tcga_brca_regression(self):
        res = run_app("fed_tcga_brca", "lr", client_num_in_total=4,
                      client_num_per_round=4, comm_round=12,
                      learning_rate=0.05)
        assert res["test_loss"] < 0.5  # targets ~unit variance; MSE → noise

    def test_isic_imaging(self):
        res = run_app("fed_isic2019", "cnn", client_num_in_total=4,
                      client_num_per_round=4, comm_round=6,
                      batch_size=8, learning_rate=0.05)
        assert res["test_acc"] > 0.4  # 8-class chance = 0.125


class TestCheetahBackbone:
    """Row 75's scale path: the SAME transformer the flagship pretrains,
    carrying the FedNLP task heads and scaling via the flagship's YAML
    knobs (model_size/d_model/... up to 7B)."""

    def test_seq_tagging_on_cheetah(self):
        res = run_app("fednlp_seq_tagging", "cheetah_tagger",
                      learning_rate=0.5, comm_round=10, epochs=3)
        assert res["test_acc"] > 0.5  # 9-tag chance ~0.11

    def test_span_extraction_on_cheetah(self):
        # encoder attention (END pointers need lookahead) + learned
        # positions (rotary solutions average destructively under FedAvg)
        res = run_app("fednlp_span_extraction", "cheetah_span",
                      pos_emb="learned", learning_rate=0.15,
                      comm_round=24, epochs=5)
        assert res["test_acc"] > 0.5  # exact match; chance ~0.1%

    def test_seq2seq_on_cheetah(self):
        # prefix-LM seq2seq IS the Cheetah LM — no head needed. Learned
        # absolute positions (cfg.pos_emb) are load-bearing: rotary clients
        # converge to per-client-rotated solutions whose FedAvg average
        # destroys the task (measured: stuck at 8% / diverging loss)
        res = run_app("fednlp_seq2seq", "cheetah", pos_emb="learned",
                      learning_rate=0.3, comm_round=12, epochs=3)
        assert res["test_acc"] > 0.8

    def test_backbone_scales_with_flagship_knobs(self):
        """The head bundles take the flagship config surface: a d256 x 4L
        GQA backbone builds and runs from the same args that size the LM."""
        import jax

        args = fedml.init(Arguments(overrides=dict(
            dataset="fednlp_seq_tagging", model="cheetah_tagger",
            model_size="custom", d_model=256, n_layers=4, n_heads=8,
            n_kv_heads=2, d_ff=704, client_num_in_total=4,
            client_num_per_round=4,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        assert bundle.cfg.d_model == 256 and bundle.cfg.n_kv_heads == 2
        params = bundle.init(jax.random.PRNGKey(0))
        out = bundle.apply(params, np.zeros((2, bundle.cfg.max_seq_len),
                                            np.int32))
        assert out.shape == (2, bundle.cfg.max_seq_len, od)


class TestDetection224:
    def test_detection_224px_via_native_pipeline(self):
        """Real-resolution detection (224px, deeper CenterNet) trained with
        batches produced by the native host pipeline (C++ BatchPrefetcher
        carrying float32 dense targets bit-exact)."""
        import jax
        import jax.numpy as jnp
        import optax

        from fedml_tpu import native
        from fedml_tpu.ml.losses import get_loss_fn

        args = fedml.init(Arguments(overrides=dict(
            dataset="fedcv_det224", model="centernet",
            client_num_in_total=4, client_num_per_round=4, batch_size=4,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        assert tuple(ds.train_x.shape[2:]) == (224, 224, 3)
        assert ds.train_y.shape[-3:] == (56, 56, 6 + 3)
        bundle = model_mod.create(args, od)
        params = bundle.init(jax.random.PRNGKey(0))
        loss_fn = get_loss_fn("detection")

        opt = optax.adam(3e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, bx, by):
            def loss(p):
                logits = bundle.apply(p, bx, train=True)
                l, _ = loss_fn(logits, by, jnp.ones((bx.shape[0],)))
                return l

            l, g = jax.value_and_grad(loss)(params)
            updates, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(params, updates), opt_state, l

        # one client's real rows through the native prefetcher
        n0 = int(ds.train_counts[0])
        pf = native.BatchPrefetcher(
            ds.train_x[0][:n0], ds.train_y[0][:n0], batch_size=4, seed=0
        )
        try:
            losses = []
            for _ in range(10):
                bx, by, _ = pf.next()
                assert by.dtype == np.float32  # targets rode bit-exact
                params, opt_state, l = step(
                    params, opt_state, jnp.asarray(bx), jnp.asarray(by)
                )
                losses.append(float(l))
        finally:
            pf.close()
        assert losses[-1] < losses[0], losses
