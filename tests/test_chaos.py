"""Chaos soak: faults + mid-run SIGTERM/restart must reproduce the
fault-free run bitwise (ISSUE 4 acceptance criteria).

Two layers:

- in-process: the full fault matrix (visible loss + duplication +
  corruption) without a kill — fast, exercises retry/dedup/checksum
  end-to-end;
- subprocess: the REAL preemption path — ``fedml_tpu chaos --worker``
  SIGTERMs itself after the ledger commits round R, exits with
  EXIT_PREEMPTED (75), restarts with ``--resume auto``, and the combined
  run must match the fault-free reference bitwise with the ledger streams
  diffing clean.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

from fedml_tpu import chaos
from fedml_tpu.core.runstate import EXIT_PREEMPTED, RunLedger


def _cfg(tmp_path, **kw):
    a = types.SimpleNamespace(
        clients=2, rounds=4, epochs=1, seed=7, loss=0.1, duplicate=0.2,
        corrupt=0.2, kill_round=1, checkpoint_rounds=1,
        workdir=str(tmp_path), timeout=240.0, worker=False, out="",
        checkpoint_dir="",
    )
    for k, v in kw.items():
        setattr(a, k, v)
    return a


def _run_leg(tmp_path, a, out, ckpt, kill_round):
    cmd = chaos._worker_cmd(a, out, ckpt, kill_round)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        cmd, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(chaos.__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    return proc


class TestChaosInProcess:
    def test_fault_matrix_parity_bitwise(self, tmp_path):
        """Seeded loss + duplication + corruption on every client link:
        final global params bitwise-equal to the fault-free run, and no
        contribution counted twice."""
        a = _cfg(tmp_path)
        ref = chaos.run_world(
            a, run_id=f"chaosref-{os.getpid()}-a",
            checkpoint_dir=str(tmp_path / "ref_ckpt"), faulty=False)
        noisy = chaos.run_world(
            a, run_id=f"chaosnoisy-{os.getpid()}-b",
            checkpoint_dir=str(tmp_path / "noisy_ckpt"), faulty=True)
        assert len(ref["params"]) == len(noisy["params"])
        for i, (x, y) in enumerate(zip(ref["params"], noisy["params"])):
            assert x.dtype == y.dtype and np.array_equal(x, y), \
                f"leaf {i} diverged under faults"
        for rnd, per in noisy["server"].contrib_counts.items():
            assert sorted(per) == [1, 2], (rnd, per)
            assert all(v == 1 for v in per.values()), (rnd, per)


class TestChaosCompressed:
    def test_fault_matrix_parity_with_delta_frames(self, tmp_path):
        """ISSUE 9 satellite: the chaos matrix with --compression on — the
        wire now carries compressed C2S deltas and lossless S2C delta
        frames, and dedup + payload digests must still hold the run
        BITWISE equal to the fault-free reference (quantize: stateless, so
        replay/retry is idempotent)."""
        from fedml_tpu.core.mlops import telemetry

        reg = telemetry.registry()
        corrupt0 = reg.counter("comm.corrupt_payloads")
        decodes0 = reg.counter("comm.delta.c2s_delta_decodes")
        a = _cfg(tmp_path, compression="quantize", compression_ratio=0.1)
        ref = chaos.run_world(
            a, run_id=f"chaoscomp-{os.getpid()}-a",
            checkpoint_dir=str(tmp_path / "ref_ckpt"), faulty=False)
        noisy = chaos.run_world(
            a, run_id=f"chaoscomp-{os.getpid()}-b",
            checkpoint_dir=str(tmp_path / "noisy_ckpt"), faulty=True)
        for i, (x, y) in enumerate(zip(ref["params"], noisy["params"])):
            assert x.dtype == y.dtype and np.array_equal(x, y), \
                f"leaf {i} diverged under faults with compression on"
        for rnd, per in noisy["server"].contrib_counts.items():
            assert all(v == 1 for v in per.values()), (rnd, per)
        # the fault matrix actually bit delta frames (digest drops) and
        # the delta path actually ran (compressed decodes)
        assert reg.counter("comm.corrupt_payloads") > corrupt0
        assert reg.counter("comm.delta.c2s_delta_decodes") > decodes0

    def test_eftopk_refused_for_chaos(self, tmp_path):
        """Error-feedback compression cannot hold bitwise parity across a
        kill/restart (the client residual dies with the process) — the
        harness refuses it instead of flaking."""
        a = _cfg(tmp_path, compression="eftopk")
        with pytest.raises(ValueError, match="eftopk"):
            chaos.run_world(a, run_id="x", checkpoint_dir=str(tmp_path),
                            faulty=False)


class TestChaosKillRestart:
    def test_sigterm_resume_bitwise_parity_and_ledger_diff(self, tmp_path):
        """kill -TERM during round R (timed off the durable ledger commit),
        restart with --resume auto: the resumed run starts at exactly the
        committed round + 1, re-uses the recorded history, and finishes
        bitwise-identical to the fault-free run."""
        a = _cfg(tmp_path)
        ref = chaos.run_world(
            a, run_id=f"chaoskref-{os.getpid()}",
            checkpoint_dir=str(tmp_path / "ref_ckpt"), faulty=False)

        out = str(tmp_path / "out")
        ckpt = str(tmp_path / "chaos_ckpt")
        p1 = _run_leg(tmp_path, a, out, ckpt, kill_round=1)
        assert p1.returncode == EXIT_PREEMPTED, (
            f"expected preempted exit {EXIT_PREEMPTED}, got "
            f"{p1.returncode}:\n{p1.stdout.decode(errors='replace')[-3000:]}"
        )
        with open(os.path.join(out, chaos.REPORT_FILE)) as f:
            report1 = json.load(f)
        assert report1["preempted"] is True

        ledger = RunLedger.for_checkpoint_dir(ckpt)
        committed = ledger.last_round()
        assert committed is not None and committed >= 1

        p2 = _run_leg(tmp_path, a, out, ckpt, kill_round=-1)
        assert p2.returncode == 0, \
            p2.stdout.decode(errors="replace")[-3000:]
        with open(os.path.join(out, chaos.REPORT_FILE)) as f:
            report2 = json.load(f)
        assert report2["preempted"] is False
        assert report2["round_idx"] == a.rounds

        # resumed at exactly committed+1: the resumed process only
        # aggregated rounds it actually ran
        resumed_rounds = sorted(int(r) for r in report2["contrib_counts"])
        assert resumed_rounds[0] == committed + 1
        assert resumed_rounds[-1] == a.rounds - 1
        for rnd, per in report2["contrib_counts"].items():
            assert all(v == 1 for v in per.values()), (rnd, per)

        # bitwise parity with the fault-free reference
        with np.load(os.path.join(out, chaos.FINAL_PARAMS_FILE)) as z:
            chaos_params = [z[k] for k in z.files]
        assert len(chaos_params) == len(ref["params"])
        for i, (x, y) in enumerate(zip(ref["params"], chaos_params)):
            assert x.dtype == y.dtype and np.array_equal(x, y), \
                f"leaf {i} not bitwise equal after kill+resume"

        # RoundRecord JSONL stream diff: newest record per round in the
        # killed+resumed ledger must equal the fault-free run's stream on
        # (round, cohort), covering every round exactly once
        ref_ledger = RunLedger.for_checkpoint_dir(str(tmp_path / "ref_ckpt"))
        ref_stream = {r["round"]: r["cohort"] for r in ref_ledger.rounds()}
        stream = {}
        for r in ledger.rounds():
            stream[r["round"]] = r["cohort"]  # newest wins
        assert stream == ref_stream
        assert sorted(stream) == list(range(a.rounds))
        # and the chaos run's combined ledger counted nobody twice
        for r in ledger.rounds():
            for client, count in (r.get("contrib") or {}).items():
                assert count == 1, (r["round"], client, count)


@pytest.mark.slow
class TestChaosCLI:
    def test_chaos_cli_end_to_end(self, tmp_path):
        """The full `fedml_tpu chaos` orchestrator (what
        tools/chaos_smoke.sh runs in CI)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "chaos",
             "--clients", "2", "--rounds", "3", "--seed", "7",
             "--loss", "0.1", "--duplicate", "0.2", "--corrupt", "0.2",
             "--kill-round", "0", "--workdir", str(tmp_path)],
            timeout=300, env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(chaos.__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        assert proc.returncode == 0, proc.stderr.decode(
            errors="replace")[-3000:]
        verdict = json.loads(proc.stdout.decode())
        assert verdict["ok"] and verdict["parity"], verdict
