"""Crash-safe rounds: durable run ledger, resume modes, preemption drain.

The invariant under test (ISSUE 4 tentpole): kill the process anywhere,
restart with --resume auto, and the federation converges to the SAME params
as an uninterrupted run — with the ledger as the auditable round history.
"""

import os

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core import runstate
from fedml_tpu.core.runstate import (
    EXIT_PREEMPTED,
    PreemptionError,
    RunLedger,
    checkpoint_cadence,
    preemption_guard,
    resume_mode,
)


class TestLedger:
    def test_commit_and_read_back(self, tmp_path):
        led = RunLedger.for_checkpoint_dir(str(tmp_path))
        led.ensure_meta(seed=3, world={"engine": "X"})
        led.commit_round(0, ckpt_step=0, cohort=[2, 1], contrib={"1": 1})
        led.commit_round(1, ckpt_step=1, cohort=None)
        assert led.last_round() == 1
        assert led.cohort_for(0) == [2, 1]
        assert led.cohort_for(1) is None
        rounds = led.rounds()
        assert [r["round"] for r in rounds] == [0, 1]
        assert rounds[0]["contrib"] == {"1": 1}
        assert led.meta()["seed"] == 3

    def test_torn_tail_is_dropped(self, tmp_path):
        led = RunLedger.for_checkpoint_dir(str(tmp_path))
        led.commit_round(0, ckpt_step=0, cohort=[0])
        led.commit_round(1, ckpt_step=1, cohort=[1])
        with open(led.path, "a") as f:
            f.write('{"kind":"round","round":2,"ckpt_')  # kill -9 mid-write
        fresh = RunLedger(led.path)
        assert fresh.last_round() == 1
        # and a checksum-corrupted line (bit rot) also ends the prefix
        lines = open(led.path).read().splitlines()[:2]
        lines[1] = lines[1].replace('"round":1', '"round":9')
        with open(led.path, "w") as f:
            f.write("\n".join(lines) + "\n")
        assert RunLedger(led.path).last_round() == 0

    def test_meta_mismatch_raises(self, tmp_path):
        led = RunLedger.for_checkpoint_dir(str(tmp_path))
        led.ensure_meta(seed=1, world={"clients": 4})
        led.ensure_meta(seed=1, world={"clients": 4})  # same run: fine
        with pytest.raises(RuntimeError, match="different federation"):
            RunLedger.for_checkpoint_dir(str(tmp_path)).ensure_meta(
                seed=2, world={"clients": 4}
            )

    def test_appends_survive_across_instances(self, tmp_path):
        """A restarted process appends to the same ledger — the combined
        stream is one run history."""
        RunLedger.for_checkpoint_dir(str(tmp_path)).commit_round(
            0, ckpt_step=0, cohort=[1])
        RunLedger.for_checkpoint_dir(str(tmp_path)).commit_round(
            1, ckpt_step=1, cohort=[2])
        assert [r["round"] for r in
                RunLedger.for_checkpoint_dir(str(tmp_path)).rounds()] == [0, 1]


class TestKnobs:
    def test_resume_mode_normalization(self):
        class A:
            pass

        a = A()
        for raw, want in [("auto", "auto"), ("", "auto"), (True, "auto"),
                          (False, "never"), ("never", "never"),
                          ("require", "require"), ("REQUIRE", "require")]:
            a.resume = raw
            assert resume_mode(a) == want, raw
        a.resume = "sometimes"
        with pytest.raises(ValueError):
            resume_mode(a)

    def test_checkpoint_cadence_alias(self):
        class A:
            pass

        a = A()
        assert checkpoint_cadence(a) == 1
        a.checkpoint_every_rounds = 4
        assert checkpoint_cadence(a) == 4
        a.checkpoint_rounds = 2  # the preferred knob wins
        assert checkpoint_cadence(a) == 2

    def test_exit_code_is_tempfail(self):
        assert EXIT_PREEMPTED == 75  # EX_TEMPFAIL: "transient, retry me"


def _sp_api(tmp_path, rounds, **kw):
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    overrides = dict(
        dataset="synthetic", model="lr", client_num_in_total=16,
        client_num_per_round=8, comm_round=rounds, epochs=1,
        batch_size=16, learning_rate=0.1, frequency_of_the_test=100,
        preempt_signals=False,
    )
    overrides.update(kw)
    if tmp_path is not None:
        overrides.setdefault("checkpoint_dir", str(tmp_path / "ckpt"))
    args = fedml.init(Arguments(overrides=overrides), should_init_logs=False)
    ds, od = data_mod.load(args)
    return FedAvgAPI(args, fedml.get_device(args), ds,
                     model_mod.create(args, od))


def _leaves(api):
    import jax

    return [np.asarray(l) for l in jax.tree.leaves(api.global_params)]


class TestPreemptionDrain:
    """SIGTERM mid-run (here: the programmatic latch) must drain the
    in-flight chunk, commit checkpoint + ledger, raise PreemptionError —
    and the resumed run must finish BITWISE identical to an uninterrupted
    one."""

    def test_sp_preempt_resume_bitwise_parity(self, tmp_path):
        ref = _sp_api(None, rounds=6)
        ref.train()
        ref_params = _leaves(ref)

        api1 = _sp_api(tmp_path, rounds=6, checkpoint_rounds=2)
        orig = api1.run_round

        def hooked(r):
            out = orig(r)
            if r == 2:
                preemption_guard().request()
            return out

        api1.run_round = hooked
        preemption_guard().reset()
        with pytest.raises(PreemptionError) as ei:
            api1.train()
        assert ei.value.last_round == 2
        preemption_guard().reset()

        # the drain committed OFF the cadence: rounds 0..2 are durable
        led = RunLedger.for_checkpoint_dir(str(tmp_path / "ckpt"))
        assert led.last_round() == 2

        api2 = _sp_api(tmp_path, rounds=6, checkpoint_rounds=2)
        api2.train()
        assert [e["round"] for e in api2.history] == [3, 4, 5]
        for a, b in zip(ref_params, _leaves(api2)):
            assert a.dtype == b.dtype and np.array_equal(a, b), \
                "resumed params differ from the uninterrupted run"

        # ledger-stream diff vs an uninterrupted CHECKPOINTED run: the
        # per-round cohorts must be identical (the recorded cohort is what
        # the resumed run re-used — sampling is round-keyed)
        ref2_dir = tmp_path / "ref2"
        ref2 = _sp_api(ref2_dir, rounds=6, checkpoint_rounds=2,
                       checkpoint_dir=str(ref2_dir / "ckpt"))
        ref2.train()
        led_ref = RunLedger.for_checkpoint_dir(str(ref2_dir / "ckpt"))
        stream = {r["round"]: r["cohort"] for r in led.rounds()}
        stream_ref = {r["round"]: r["cohort"] for r in led_ref.rounds()}
        assert stream == stream_ref
        assert sorted(stream) == list(range(6))

    def test_superround_chunker_aligns_to_checkpoint_cadence(self, tmp_path):
        """Superround scan boundaries must align to the checkpoint cadence
        so a preemption commit lands on a scanned-chunk boundary — resume
        parity vs an uninterrupted superround run, bitwise."""
        ref = _sp_api(None, rounds=6, superround_k=2,
                      client_num_per_round=16)
        ref.train()
        ref_params = _leaves(ref)

        api1 = _sp_api(tmp_path, rounds=6, superround_k=2,
                       client_num_per_round=16, checkpoint_rounds=2)
        orig = api1.run_rounds

        def hooked(start, k):
            out = orig(start, k)
            if start == 2:
                preemption_guard().request()
            return out

        api1.run_rounds = hooked
        preemption_guard().reset()
        with pytest.raises(PreemptionError) as ei:
            api1.train()
        assert ei.value.last_round == 3  # chunks [0,1][2,3] committed
        preemption_guard().reset()

        api2 = _sp_api(tmp_path, rounds=6, superround_k=2,
                       client_num_per_round=16, checkpoint_rounds=2)
        api2.train()
        for a, b in zip(ref_params, _leaves(api2)):
            assert a.dtype == b.dtype and np.array_equal(a, b)

    def test_preempt_exit_code_contract(self, tmp_path):
        """PreemptionError carries the committed round; callers map it to
        EXIT_PREEMPTED (75) — asserted end-to-end by test_chaos.py."""
        api = _sp_api(tmp_path, rounds=3)
        orig = api.run_round

        def hooked(r):
            out = orig(r)
            preemption_guard().request()
            return out

        api.run_round = hooked
        preemption_guard().reset()
        with pytest.raises(PreemptionError) as ei:
            api.train()
        preemption_guard().reset()
        assert ei.value.last_round == 0
        assert str(EXIT_PREEMPTED) in str(ei.value)


class TestResumeModes:
    def test_resume_never_demands_fresh_dir(self, tmp_path):
        api1 = _sp_api(tmp_path, rounds=2)
        api1.train()
        with pytest.raises(RuntimeError, match="resume never"):
            _sp_api(tmp_path, rounds=4, resume="never").train()

    def test_resume_require_demands_checkpoint(self, tmp_path):
        with pytest.raises(RuntimeError, match="resume require"):
            _sp_api(tmp_path, rounds=2, resume="require").train()
        # and with a checkpoint present it resumes normally
        _sp_api(tmp_path, rounds=2).train()
        api = _sp_api(tmp_path, rounds=4, resume="require")
        api.train()
        assert [e["round"] for e in api.history] == [2, 3]

    def test_mesh_world_mismatch_is_loud(self, tmp_path):
        """A ledger written by one world must refuse a different one (the
        mesh engine pins its topology through the same run_meta path)."""
        _sp_api(tmp_path, rounds=2).train()
        with pytest.raises(RuntimeError, match="different federation"):
            _sp_api(tmp_path, rounds=2, random_seed=99).train()
