"""Distributed-tracing plane tests (core/mlops/tracing.py — docs/tracing.md).

Pins the tracing plane's contracts:

1. **Zero cost when disabled**: every entry point is one bool check that
   returns the shared no-op span; an untraced federation's wire and sink
   are bitwise-free of trace artifacts.
2. **Causal propagation**: the wire context survives transport faults —
   retries and dedup drops become span events/annotations, NEVER duplicate
   spans — and a traced loopback federation merges into one orphan-free
   trace whose fold chains walk back to their dispatch.
3. **Clock alignment**: the NTP-style estimator recovers a synthetic skew
   from probe pairs, preferring the minimum-delay pair.
4. **Merge determinism**: identical span files produce byte-identical
   merged output, regardless of file discovery order.
5. **Flight recorder**: the post-mortem names the last protocol phase and
   recovers still-open spans for the merge.
"""

from __future__ import annotations

import json
import threading
import time
import types

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core import mlops
from fedml_tpu.core.distributed.faults import FaultPlan
from fedml_tpu.core.mlops import telemetry, tracing
from fedml_tpu.core.mlops.tracing import (
    ClockOffsetEstimator,
    NULL_SPAN,
    TraceContext,
    Tracer,
)
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer


@pytest.fixture(autouse=True)
def clean_state():
    telemetry.registry().reset()
    yield
    mlops.close()
    telemetry.registry().reset()
    mlops.MLOpsStore.enabled = False
    mlops.MLOpsStore.jsonl_path = None


def tracer_args(tmp_path, enabled=True, sample=1.0):
    return types.SimpleNamespace(enable_tracing=enabled,
                                 trace_sample=sample,
                                 trace_dir=str(tmp_path))


def make_tracer(tmp_path, run_id, rank=0, **kw):
    t = tracing.tracer_for(run_id, rank)
    t.configure(tracer_args(tmp_path, **kw))
    return t


# ---------------------------------------------------------------------------
# context + zero-cost
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_wire_roundtrip(self):
        ctx = TraceContext("run9", 3, "0.123.7", parent="1.99.2")
        back = TraceContext.from_wire(ctx.to_wire())
        assert (back.run_id, back.round_idx, back.span_id, back.parent) == \
            ("run9", 3, "0.123.7", "1.99.2")

    def test_none_parent_survives(self):
        back = TraceContext.from_wire(TraceContext("r", 0, "s").to_wire())
        assert back.parent is None

    def test_malformed_wire_drops_not_raises(self):
        assert TraceContext.from_wire(None) is None
        assert TraceContext.from_wire("garbage") is None
        assert TraceContext.from_wire([1, 2]) is None
        assert TraceContext.from_wire(["r", "not-an-int", "s", None]) is None

    def test_child_links_parent(self):
        ctx = TraceContext("r", 2, "a")
        child = ctx.child("b")
        assert child.parent == "a" and child.round_idx == 2


class TestZeroCostDisabled:
    def test_disabled_tracer_returns_shared_null_span(self, tmp_path):
        t = make_tracer(tmp_path, "trc-off", enabled=False)
        assert t.span("anything") is NULL_SPAN
        assert t.span("nested", round_idx=3, client=1) is NULL_SPAN
        assert t.record_span("x", time.monotonic(), 0.1) is None
        assert t.current_context() is None
        assert not t.sampled(0)
        t.event("noop")  # must not raise, must not allocate a span
        assert t.flush_flight("off") is None

    def test_null_span_is_inert(self):
        with NULL_SPAN as s:
            s.event("e", k=1)
            s.annotate("k", "v")
            assert s.context() is None
            assert s.span_id is None

    def test_sampling_is_deterministic_across_instances(self, tmp_path):
        a = make_tracer(tmp_path, "trc-samp", rank=0, sample=0.5)
        b = make_tracer(tmp_path, "trc-samp", rank=1, sample=0.5)
        decisions = [a.sampled(r) for r in range(64)]
        assert decisions == [b.sampled(r) for r in range(64)]
        assert any(decisions) and not all(decisions)
        full = make_tracer(tmp_path, "trc-samp-full", sample=1.0)
        assert all(full.sampled(r) for r in range(16))
        off = make_tracer(tmp_path, "trc-samp-zero", sample=0.0)
        assert not any(off.sampled(r) for r in range(16))


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------


class TestClockOffsetEstimator:
    def test_recovers_synthetic_skew(self):
        est = ClockOffsetEstimator()
        skew = 1.7  # peer clock runs 1.7s ahead of ours
        rng = np.random.RandomState(3)
        t = 100.0
        for _ in range(32):
            up, down = rng.uniform(0.001, 0.05, size=2)
            t_send = t
            t_peer_recv = t + up + skew
            t_peer_send = t_peer_recv + 0.002
            t_recv = t_peer_send - skew + down
            est.add_pair(t_send, t_peer_recv, t_peer_send, t_recv)
            t += 0.5
        offset, uncertainty = est.estimate()
        # the min-delay pair bounds asymmetry error by delay/2
        assert abs(offset - skew) <= uncertainty + 1e-9
        assert abs(offset - skew) < 0.05

    def test_min_delay_pair_wins(self):
        est = ClockOffsetEstimator()
        # a tight, symmetric pair: exact offset, tiny delay
        est.add_pair(0.0, 2.001, 2.002, 0.003)
        # a wildly asymmetric, slow pair that would mis-estimate
        est.add_pair(10.0, 12.9, 12.901, 10.902)
        offset, uncertainty = est.estimate()
        assert abs(offset - 2.0) < 0.01
        assert uncertainty < 0.01

    def test_window_slides(self):
        est = ClockOffsetEstimator(window=4)
        for i in range(10):
            est.add_pair(i, i + 1.0, i + 1.001, i + 0.01)
        assert est.n == 4

    def test_empty_estimate_is_none(self):
        assert ClockOffsetEstimator().estimate() is None


# ---------------------------------------------------------------------------
# span recording + flight recorder
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nested_spans_parent_on_same_thread(self, tmp_path):
        t = make_tracer(tmp_path, "trc-nest")
        with t.span("outer", round_idx=1) as outer:
            with t.span("inner") as inner:
                assert inner.parent == outer.span_id
                assert inner.round_idx == 1  # inherited from ambient parent
        recs = [r for r in t._ring if r.get("kind") == tracing.SPAN_KIND]
        assert [r["name"] for r in recs] == ["inner", "outer"]

    def test_explicit_end_is_idempotent(self, tmp_path):
        t = make_tracer(tmp_path, "trc-idem")
        with t.span("s") as s:
            s.end()
        spans = [r for r in t._ring if r.get("kind") == tracing.SPAN_KIND]
        assert len(spans) == 1

    def test_adopted_context_parents_new_spans(self, tmp_path):
        t = make_tracer(tmp_path, "trc-adopt")
        t.adopt(TraceContext("trc-adopt", 5, "9.9.9"))
        try:
            with t.span("handler_work") as s:
                assert s.parent == "9.9.9" and s.round_idx == 5
            assert t.current_context().span_id == "9.9.9"
        finally:
            t.adopt(None)
        assert t.current_context() is None

    def test_event_attaches_to_open_span_never_a_span(self, tmp_path):
        t = make_tracer(tmp_path, "trc-ev")
        with t.span("upload") as s:
            t.event("send_retry", attempt=1)
        rec = next(r for r in t._ring if r.get("name") == "upload")
        assert rec["events"][0]["name"] == "send_retry"
        assert not any(r.get("kind") == tracing.SPAN_KIND
                       and r.get("name") == "send_retry" for r in t._ring)

    def test_flight_postmortem_names_phase_and_open_spans(self, tmp_path):
        t = make_tracer(tmp_path, "trc-flight")
        t.note_phase("mid_fold", 7)
        open_span = t.span("fold", round_idx=7)
        path = t.flush_flight("kill_server:mid_fold")
        open_span.end()
        post = tracing.read_postmortem(str(tmp_path), "trc-flight", 0)
        assert post is not None and path is not None
        assert post["reason"] == "kill_server:mid_fold"
        assert post["last_phase"]["phase"] == "mid_fold"
        assert post["last_phase"]["round"] == 7
        assert [s["name"] for s in post["open_spans"]] == ["fold"]
        # the merge recovers the open span from the flight ring
        spans, _clocks = tracing.read_trace([path])
        assert any(s["name"] == "fold" for s in spans)


# ---------------------------------------------------------------------------
# analysis plane
# ---------------------------------------------------------------------------


def synth_span(span, name, t0, dur, rank=0, pid=100, parent=None,
               round_idx=0, client=None, annot=None):
    rec = {"kind": tracing.SPAN_KIND, "v": 1, "run": "synth", "rank": rank,
           "pid": pid, "span": span, "parent": parent, "name": name,
           "round": round_idx, "ts": 1000.0 + t0, "mono": t0,
           "dur": dur}
    if client is not None:
        rec["client"] = client
    if annot:
        rec["annot"] = annot
    return rec


def synth_chain(round_idx=0, client=1, base=0.0, slow=0.0):
    """dispatch → upload → admission → queue_wait → fold, one client."""
    cpid = 200 + client
    tag = f"r{round_idx}c{client}"
    return [
        synth_span(f"0.100.d{tag}", "dispatch", base + 0.0, 0.01,
                   round_idx=round_idx, client=client),
        synth_span(f"{client}.{cpid}.u{tag}", "upload", base + 0.05 + slow,
                   0.01, rank=client, pid=cpid,
                   parent=f"0.100.d{tag}", round_idx=round_idx,
                   client=client),
        synth_span(f"0.100.a{tag}", "admission", base + 0.07 + slow, 0.002,
                   parent=f"{client}.{cpid}.u{tag}", round_idx=round_idx,
                   client=client),
        synth_span(f"0.100.q{tag}", "queue_wait", base + 0.073 + slow,
                   0.004, parent=f"0.100.a{tag}", round_idx=round_idx,
                   client=client),
        synth_span(f"0.100.f{tag}", "fold", base + 0.077 + slow, 0.006,
                   parent=f"0.100.q{tag}", round_idx=round_idx,
                   client=client),
    ]


class TestAnalysis:
    def test_critical_path_walks_chain_with_transit_gaps(self):
        merged = tracing.merge_trace(synth_chain())
        path = tracing.critical_path(merged, 0)
        names = [s["name"] for s in path]
        assert names == ["dispatch", "transit", "upload", "transit",
                         "admission", "transit", "queue_wait", "fold"]
        # the think-time gap dominates, and segment durations are exact
        transit = sum(s["dur_s"] for s in path if s["name"] == "transit")
        assert transit == pytest.approx(0.051, abs=1e-9)
        assert tracing.critical_path(merged, 99) == []

    def test_straggler_attribution_blames_the_slow_client(self):
        spans = (synth_chain(client=1) + synth_chain(client=2, slow=0.4)
                 + synth_chain(round_idx=1, client=1, base=1.0)
                 + synth_chain(round_idx=1, client=2, base=1.0, slow=0.4))
        merged = tracing.merge_trace(spans)
        top = tracing.straggler_attribution(merged, k=2)
        assert top[0]["client"] == 2
        assert top[0]["rounds_gated"] == 2
        assert top[0]["wait_s"] == pytest.approx(0.8, abs=1e-6)

    def test_dispatch_ready_sums_fold_plus_queue_wait(self):
        spans = synth_chain(client=1) + synth_chain(client=2, slow=0.2)
        merged = tracing.merge_trace(spans)
        total, folds = tracing.dispatch_ready_from_trace(merged)
        assert folds == 2
        assert total == pytest.approx(2 * (0.006 + 0.004), abs=1e-9)

    def test_dispatch_ready_excludes_unobserved_folds(self):
        spans = synth_chain(client=1)
        stale = synth_chain(client=2)
        stale[-1]["annot"] = {"outcome": "stale"}
        merged = tracing.merge_trace(spans + stale)
        total, folds = tracing.dispatch_ready_from_trace(merged)
        assert folds == 1
        assert total == pytest.approx(0.010, abs=1e-9)

    def test_wall_anchor_alignment_rebases_cross_process_spans(self):
        # the client process's monotonic clock starts 500s apart from the
        # server's, but both share a wall clock (same host): the anchor
        # fallback must land the upload INSIDE its causal window
        server = synth_span("0.100.d", "dispatch", 1000.0, 0.01)
        client = synth_span("1.201.u", "upload", 1500.05, 0.01, rank=1,
                            pid=201, parent="0.100.d")
        client["ts"] = 1000.05 + 1000.0  # wall: 50ms after dispatch t0
        merged = tracing.merge_trace([server, client])
        by_name = {m["name"]: m for m in merged["spans"]}
        assert by_name["upload"]["t0"] == pytest.approx(0.05, abs=1e-6)
        assert merged["orphans"] == []

    def test_chrome_export_shape(self):
        merged = tracing.merge_trace(synth_chain())
        chrome = tracing.to_chrome(merged)
        evs = chrome["traceEvents"]
        assert sum(1 for e in evs if e["ph"] == "X") == 5
        assert all(e["ts"] >= 0 for e in evs if e["ph"] == "X")
        assert any(e["ph"] == "M" for e in evs)


class TestMergeDeterminism:
    def _write_files(self, tmp_path):
        spans = (synth_chain(client=1) + synth_chain(client=2, slow=0.1))
        f1 = tmp_path / "run_synth_edge_0.jsonl"
        f2 = tmp_path / "run_synth_edge_1.jsonl"
        with open(f1, "w") as f:
            for rec in spans[:4]:
                f.write(json.dumps(rec) + "\n")
        with open(f2, "w") as f:
            for rec in spans[4:]:
                f.write(json.dumps(rec) + "\n")
        return [str(f1), str(f2)]

    def test_merge_is_byte_identical_regardless_of_file_order(
            self, tmp_path):
        paths = self._write_files(tmp_path)
        outs = []
        for order in (paths, list(reversed(paths)), paths):
            spans, clocks = tracing.read_trace(order)
            outs.append(json.dumps(tracing.merge_trace(spans, clocks),
                                   sort_keys=True))
        assert outs[0] == outs[1] == outs[2]

    def test_duplicate_records_dedupe_on_span_identity(self, tmp_path):
        paths = self._write_files(tmp_path)
        # a flight-recorder ring replays the same spans the sink already
        # holds: the merge must not double-count
        spans1, _ = tracing.read_trace(paths)
        spans2, _ = tracing.read_trace(paths + paths)
        assert len(spans1) == len(spans2)

    def test_torn_jsonl_tail_is_tolerated(self, tmp_path):
        paths = self._write_files(tmp_path)
        with open(paths[0], "a") as f:
            f.write('{"kind": "trace_span", "truncated')  # crashed writer
        spans, _ = tracing.read_trace(paths)
        assert len(spans) == 10


# ---------------------------------------------------------------------------
# traced federation end-to-end (loopback, under transport faults)
# ---------------------------------------------------------------------------


def make_args(tmp_path, run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        epochs=1, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1000,
        enable_tracing=True, trace_sample=1.0, trace_dir=str(tmp_path),
        enable_tracking=True, tracking_dir=str(tmp_path),
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_traced_world(tmp_path, run_id, faulty=False, **kw):
    args_s = make_args(tmp_path, run_id, role="server", **kw)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)
    clients = []
    for rank in (1, 2):
        args_c = make_args(tmp_path, run_id, role="client", rank=rank, **kw)
        if faulty:
            plan = FaultPlan()
            plan.loss(0.25, seed=100 + rank, visible=True)
            plan.duplicate(p=0.4, seed=200 + rank)
            args_c.fault_plan = plan
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    server.run()
    for t in threads:
        t.join(timeout=30)
    for c in clients:
        assert c.manager.done.is_set()
    mlops.flush()
    return server


class TestTracedFederation:
    def test_faulty_wire_never_duplicates_spans(self, tmp_path):
        """Retries and dedup drops must stay events/annotations: under
        visible loss + wire duplication, span ids stay globally unique and
        every fold chain walks back to its dispatch (no orphans)."""
        run_traced_world(tmp_path, "trc-fault", faulty=True)
        files = tracing.collect_trace_files(str(tmp_path), "trc-fault")
        spans, clocks = tracing.read_trace(files)
        assert spans, "traced run produced no spans"
        # raw (pre-dedup) records in the sink: globally unique span ids
        raw_ids = []
        for path in files:
            if not path.endswith(".jsonl"):
                continue
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("kind") == tracing.SPAN_KIND:
                        raw_ids.append(
                            (rec["rank"], rec["pid"], rec["span"]))
        assert len(raw_ids) == len(set(raw_ids))
        names = {s["name"] for s in spans}
        assert {"dispatch", "decode", "train", "upload", "fold"} <= names
        # faults surface as events/annotations, never span names
        assert not names & {"send_retry", "dedup_drop", "stale_epoch_drop"}
        merged = tracing.merge_trace(spans, clocks)
        assert merged["orphans"] == []
        assert merged["rounds"] == [0, 1, 2]
        for r in merged["rounds"]:
            path = tracing.critical_path(merged, r)
            assert path, f"round {r} has no critical path"

    def test_untraced_run_is_bitwise_invisible(self, tmp_path):
        server = run_traced_world(tmp_path, "trc-silent",
                                  enable_tracing=False)
        assert server.manager.world.trace.enabled is False
        assert server.manager.world.trace.span("x") is NULL_SPAN
        for path in tracing.collect_trace_files(str(tmp_path),
                                                "trc-silent"):
            if not path.endswith(".jsonl"):
                continue
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    assert rec.get("kind") not in (tracing.SPAN_KIND,
                                                   tracing.CLOCK_KIND)
                    assert "_trace" not in json.dumps(rec)

    def test_heartbeat_probes_feed_clock_gauges(self, tmp_path):
        run_traced_world(tmp_path, "trc-hb", heartbeat_s=0.1,
                         heartbeat_miss_limit=10)
        files = tracing.collect_trace_files(str(tmp_path), "trc-hb")
        _spans, clocks = tracing.read_trace(files)
        assert clocks, "heartbeat exchange emitted no trace_clock records"
        for rec in clocks:
            # same-host loopback: the offset estimate must be ~zero and
            # bounded by the probe's own uncertainty claim
            assert abs(rec["offset_s"]) < 0.5
            assert rec["uncertainty_s"] >= 0
        gauges = telemetry.registry().snapshot()["gauges"]
        assert "trace.clock_offset_s" in gauges
        assert "trace.clock_uncertainty_s" in gauges


class TestTraceCLI:
    def test_trace_cmd_merges_and_exports_chrome(self, tmp_path, capsys):
        spans = synth_chain(client=1) + synth_chain(client=2, slow=0.1)
        with open(tmp_path / "run_synth_edge_0.jsonl", "w") as f:
            for rec in spans:
                f.write(json.dumps(rec) + "\n")
        from fedml_tpu.cli import main as cli_main

        chrome = tmp_path / "out.chrome.json"
        rc = cli_main(["trace", str(tmp_path), "--json",
                       "--chrome", str(chrome)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["spans"] == 10
        assert out["orphans"] == []
        assert out["critical_path"]
        assert set(out["critical_path_segments"]) >= {"dispatch", "fold"}
        assert json.load(open(chrome))["traceEvents"]

    def test_trace_cmd_empty_dir_fails_cleanly(self, tmp_path, capsys):
        from fedml_tpu.cli import main as cli_main

        assert cli_main(["trace", str(tmp_path)]) == 1
        assert "no trace files" in capsys.readouterr().out
