"""P001 fixture: a sent-but-never-handled type + a wrong-role registration."""


class Defines:
    MSG_TYPE_C2S_UPLOAD = "c2s_upload"
    MSG_TYPE_C2S_STATUS = "c2s_status"
    MSG_TYPE_S2C_ORPHAN = "s2c_orphan"
    MSG_TYPE_S2C_FINISH = "s2c_finish"


class ServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_UPLOAD, self._on_upload
        )

    def _on_upload(self, msg):
        # line 19: S2C_ORPHAN has no handler anywhere -> P001
        self.send_message(Message(Defines.MSG_TYPE_S2C_ORPHAN, 0, 1))
        self.send_message(Message(Defines.MSG_TYPE_S2C_FINISH, 0, 1))
        self.finish()


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_FINISH, self._on_finish
        )
        # line 30: a C2S type registered ONLY on a client manager -> P001
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_STATUS, self._on_status
        )

    def _on_status(self, msg):
        pass

    def _on_finish(self, msg):
        self.done.set()
        self.finish()

    def _report(self):
        self.send_message(Message(Defines.MSG_TYPE_C2S_UPLOAD, 1, 0))
        self.send_message(Message(Defines.MSG_TYPE_C2S_STATUS, 1, 0))
