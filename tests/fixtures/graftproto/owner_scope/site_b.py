"""Owner-scoping fixture, package B: a same-named define class with a
same-named attribute bound to a DIFFERENT wire value. Each module must
resolve MyMessage against its own class, never a bare-name merge."""


class MyMessage:
    MSG_TYPE_S2C_GO = "b_go"


class ServerManagerB:
    def _drive(self):
        self.send_message(Message(MyMessage.MSG_TYPE_S2C_GO, 0, 1))


class ClientManagerB:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_GO, self._on_go
        )

    def _on_go(self, msg):
        self.finish()
