"""Owner-scoping fixture, package A: the define class is named MyMessage —
exactly like package B's — but carries A's own wire values."""


class MyMessage:
    MSG_TYPE_S2C_GO = "a_go"


class ServerManagerA:
    def _drive(self):
        self.send_message(Message(MyMessage.MSG_TYPE_S2C_GO, 0, 1))


class ClientManagerA:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_GO, self._on_go
        )

    def _on_go(self, msg):
        self.finish()
