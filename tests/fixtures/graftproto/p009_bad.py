"""P009 fixture: blocking calls while holding a lock — direct (fsync,
sleep, untimed get/join) and through a resolvable callee."""

import os
import threading
import time


class Committer:
    def __init__(self):
        self._lock = threading.Lock()

    def commit(self, line):
        with self._lock:
            f = open("ledger", "a")
            f.write(line)
            os.fsync(f.fileno())  # line 17 -> P009
            time.sleep(0.01)  # line 18 -> P009

    def drain(self):
        with self._lock:
            item = self._queue.get()  # line 22 -> P009 (untimed)
            self._thread.join()  # line 23 -> P009 (untimed)
        return item

    def _settle(self):
        time.sleep(1.0)

    def indirect(self):
        with self._lock:
            self._settle()  # line 31 -> P009 (callee blocks)
