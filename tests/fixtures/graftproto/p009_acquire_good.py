"""Good twin of p009_acquire_bad: release() ends the held window, so the
blocking calls after it are lock-free — no P008/P009."""

import os
import threading
import time


class Committer:
    def __init__(self):
        self._lock = threading.Lock()
        self._fd = 3
        self._count = 0

    def commit(self):
        self._lock.acquire()
        try:
            self._count += 1
        finally:
            self._lock.release()
        os.fsync(self._fd)  # after release: clean

    def settle(self):
        self._lock.acquire()
        self._count += 1
        self._lock.release()
        time.sleep(0.5)  # after release: clean
