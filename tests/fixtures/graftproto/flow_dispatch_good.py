"""Flow-DSL known-good: handlers registered ONLY through add_flow.

The PR 5 blind spot: sends of the flow dispatch type were visible
(Message(MSG_TYPE_FLOW, ...)) but add_flow callback registrations were
not, so a flow-driven manager looked like it dispatched 'flow_step' into
the void (false P001) and its callbacks escaped P004/P005 entirely. This
fixture must be CLEAN."""


class MyMessage:
    MSG_TYPE_FLOW = "flow_step"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"


class Message:
    def __init__(self, msg_type, sender=0, receiver=0):
        self.type = msg_type

    def get(self, key):
        return 0


class TrainingFlowManager:
    """Registers its steps through the DSL, never touches
    register_message_receive_handler directly."""

    def __init__(self, flow):
        self.round_idx = 0
        self.progress = {}
        self.done = None
        flow.add_flow("init", self._init_step, "server", "ONCE")
        flow.add_flow("train", self._train_step, "client")
        flow.add_flow("finish", self._finish_step, "server", "FINISH")

    def _init_step(self, executor):
        return executor.get_params()

    def _train_step(self, executor):
        msg_round = int(executor.get_params().get("round_idx"))
        if msg_round < self.round_idx:  # replay guard: stale pass dropped
            return None
        self.round_idx = msg_round + 1
        self.progress[msg_round] = "trained"
        return executor.get_params()

    def _finish_step(self, executor):
        self.finish()
        return None

    def finish(self):
        pass

    def _dispatch(self, step_idx):
        # the flow plane's own dispatch: the send side of 'flow_step'
        return Message(MyMessage.MSG_TYPE_FLOW, 0, step_idx)
