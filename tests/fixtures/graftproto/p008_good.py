"""P008 good twin: both threads acquire in the same global order."""

import threading


class Engine:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._comm_lock = threading.Lock()
        self.step = 0

    def trainer_side(self):
        with self._state_lock:
            with self._comm_lock:
                self.step += 1

    def comm_side(self):
        with self._state_lock:
            with self._comm_lock:
                self.step += 1
