"""P007 good twin: digest attached before the store write."""


class Uploader:
    def offload(self, message):
        message.add("_sha256", arrays_digest(message.arrays))
        key = self.payload_store.put_dedup(message.arrays)
        message.add("payload_ref", key)
        message.set_arrays([])
