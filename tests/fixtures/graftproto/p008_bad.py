"""P008 fixture: the classic A->B / B->A lock-order inversion between the
trainer thread and the comm thread."""

import threading


class Engine:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._comm_lock = threading.Lock()
        self.step = 0

    def trainer_side(self):
        with self._state_lock:
            # line 16: comm lock acquired under state lock -> P008
            with self._comm_lock:
                self.step += 1

    def comm_side(self):
        with self._comm_lock:
            # line 22: state lock acquired under comm lock -> P008
            with self._state_lock:
                self.step += 1
