"""P003 fixture: duplicate wire value, dead constant, stale attribute ref,
and a raw literal shadowing a define-class constant."""


class Defines:
    MSG_TYPE_S2C_SYNC = "s2c_sync"
    MSG_TYPE_S2C_PING = "s2c_sync"  # line 7: duplicate wire value -> P003
    MSG_TYPE_S2C_DEAD = "s2c_dead"  # line 8: never sent nor handled -> P003


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_SYNC, self._on_sync
        )
        # line 18: MSG_TYPE_S2C_RENAMED does not exist on Defines -> P003
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_RENAMED, self._on_other
        )

    def _on_sync(self, msg):
        self.finish()

    def _on_other(self, msg):
        pass


class ServerManager:
    def _sync(self):
        # line 31: raw literal duplicating Defines.MSG_TYPE_S2C_SYNC -> P003
        self.send_message(Message("s2c_sync", 0, 1))
