"""P008 via bare acquire(): the A->B / B->A inversion where one side
takes its lock with acquire()/release() instead of `with`."""

import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def forward():
    LOCK_A.acquire()
    try:
        # line 14: B acquired while A held (bare) -> P008
        with LOCK_B:
            pass
    finally:
        LOCK_A.release()


def backward():
    with LOCK_B:
        # line 23: A acquired (bare) while B held -> P008
        LOCK_A.acquire()
        LOCK_A.release()
