"""P009 good twin: snapshot under the lock, block lock-free; timeouts on
the waits that stay inside."""

import os
import threading
import time


class Committer:
    def __init__(self):
        self._lock = threading.Lock()

    def commit(self, line):
        with self._lock:
            f = open("ledger", "a")
            f.write(line)
            f.flush()
        os.fsync(f.fileno())
        f.close()

    def drain(self):
        item = self._queue.get(timeout=1.0)
        with self._lock:
            self._drained += 1
        self._thread.join(1.0)
        return item

    def _settle(self):
        time.sleep(1.0)

    def indirect(self):
        with self._lock:
            snapshot = dict(self._state)
        self._settle()
        return snapshot
