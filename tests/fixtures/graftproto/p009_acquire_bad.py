"""P009 via bare acquire(): blocking calls inside the lexical
acquire()/release() window, including the try/finally idiom."""

import os
import threading
import time


class Committer:
    def __init__(self):
        self._lock = threading.Lock()
        self._fd = 3

    def commit(self):
        self._lock.acquire()
        try:
            os.fsync(self._fd)  # line 17 -> P009 (held via bare acquire)
        finally:
            self._lock.release()

    def settle(self):
        self._lock.acquire()
        time.sleep(0.5)  # line 23 -> P009
        self._lock.release()
