"""P004 dataflow good twin: the round guard compares a LOCAL whose value
flows from the message's round key — no round token in the compare itself.
The dataflow pass must recognize it; the textual match alone cannot."""


class Defines:
    MSG_TYPE_S2C_SYNC = "s2c_sync"
    MSG_TYPE_C2S_RESULT = "c2s_result"


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_SYNC, self._on_sync
        )

    def _on_sync(self, msg):
        # the guard variable carries no round-ish name of its own …
        r = int(msg.get("round_idx", 0))
        limit = r - self.window
        if limit < self.floor:
            return  # stale replay: identity checked via dataflow
        self.round_idx = r
        self._models[msg.get_sender_id()] = msg.get_arrays()
        self.send_message(Message(Defines.MSG_TYPE_C2S_RESULT, 1, 0))
        self.finish()


class ServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_RESULT, self._on_result
        )

    def _on_result(self, msg):
        self.finish()

    def _sync(self):
        self.send_message(Message(Defines.MSG_TYPE_S2C_SYNC, 0, 1))
