"""P005 pairing fixture: the client CAN finish, but only on a terminal
message no peer ever sends — both roles block forever (also P002)."""


class Defines:
    MSG_TYPE_S2C_WORK = "s2c_work"
    MSG_TYPE_S2C_FINISH = "s2c_finish"


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_WORK, self._on_work
        )
        # line 16: the only finish() path, and nobody sends it -> P005+P002
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_FINISH, self._on_finish
        )

    def _on_work(self, msg):
        pass

    def _on_finish(self, msg):
        self.done.set()
        self.finish()


class ServerManager:
    def _drive(self):
        self.send_message(Message(Defines.MSG_TYPE_S2C_WORK, 0, 1))
