"""graftproto file-level pragma fixture: prologue pragma silences the
whole file."""
# graftproto: disable=P009

import os
import threading


class Committer:
    def __init__(self):
        self._lock = threading.Lock()

    def commit(self, fd):
        with self._lock:
            os.fsync(fd)
