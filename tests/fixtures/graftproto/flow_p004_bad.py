"""Flow-DSL known-bad: an add_flow callback mutates round state with no
round comparison anywhere in its closure — P004 must fire on the callback
even though no register_message_receive_handler site exists."""


class MyMessage:
    MSG_TYPE_FLOW = "flow_step"


class Message:
    def __init__(self, msg_type, sender=0, receiver=0):
        self.type = msg_type


class ReplayableFlowManager:
    def __init__(self, flow):
        self.round_idx = 0
        self.history = {}
        flow.add_flow("train", self._train_step, "client")
        flow.add_flow("finish", self._finish_step, "server", "FINISH")

    def _train_step(self, executor):
        self.round_idx = self.round_idx + 1   # line 23: unguarded mutation
        self.history[self.round_idx] = "x"
        return executor.get_params()

    def _finish_step(self, executor):
        self.finish()

    def finish(self):
        pass

    def _dispatch(self):
        return Message(MyMessage.MSG_TYPE_FLOW, 0, 1)
