"""P002 fixture: a registered type no peer ever sends (dead handler)."""


class Defines:
    MSG_TYPE_S2C_BCAST = "s2c_bcast"
    MSG_TYPE_S2C_GHOST = "s2c_ghost"


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_BCAST, self._on_bcast
        )
        # line 15: nobody sends S2C_GHOST -> P002
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_GHOST, self._on_ghost
        )

    def _on_bcast(self, msg):
        self.finish()

    def _on_ghost(self, msg):
        pass


class ServerManager:
    def _announce(self):
        self.send_message(Message(Defines.MSG_TYPE_S2C_BCAST, 0, 1))
