"""P006 good twin: the send goes through FedMLCommManager.send_message."""


class Defines:
    MSG_TYPE_C2S_RESULT = "c2s_result"


class ClientManager:
    def _report(self):
        out = Message(Defines.MSG_TYPE_C2S_RESULT, 1, 0)
        self.send_message(out)


class ServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_RESULT, self._on_result
        )

    def _on_result(self, msg):
        self.finish()
