"""P007 fixture: arrays offloaded to the payload store with no sha256
digest attached — the receiver cannot verify the blob."""


class Uploader:
    def offload(self, message):
        # line 8: payload-store write, no digest in this function -> P007
        key = self.payload_store.put_dedup(message.arrays)
        message.add("payload_ref", key)
        message.set_arrays([])
