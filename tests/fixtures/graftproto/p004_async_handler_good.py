"""Async-traffic-plane handler shape (ISSUE 7): a buffered FedBuff-style
server handler with a staleness guard (version compare, not a literal
"round" compare) and a shed NACK through self.send_message. Must be clean
under P004 (replay safety via the version dataflow) and P006 (no raw
com_manager send)."""


class Defines:
    MSG_TYPE_C2S_SEND_MODEL = "c2s_send_model"
    MSG_TYPE_S2C_SHED = "s2c_shed"
    MSG_TYPE_S2C_SYNC = "s2c_sync"


class AsyncServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_SEND_MODEL, self._on_model
        )

    def _on_model(self, msg):
        sender = msg.get_sender_id()
        client_version = int(msg.get("round_idx", 0))
        staleness = self.model_version - client_version
        if staleness > self.max_staleness:
            return  # version guard: too stale to fold
        if not self.admission.try_admit():
            nack = Message(Defines.MSG_TYPE_S2C_SHED, 0, sender)
            self.send_message(nack)
            return
        self._buffer[sender] = msg.get_arrays()
        self.send_message(Message(Defines.MSG_TYPE_S2C_SYNC, 0, sender))

    def _on_done(self):
        self.finish()


class AsyncClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_SHED, self._on_shed
        )
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_SYNC, self._on_sync
        )

    def _on_shed(self, msg):
        self._retry_pending = True

    def _on_sync(self, msg):
        version = int(msg.get("round_idx", 0))
        if version <= self.model_version:
            return  # replayed dispatch
        self.model_version = version
        self.send_message(Message(Defines.MSG_TYPE_C2S_SEND_MODEL, 1, 0))
        self.finish()
