"""P004 good twin: the same handler guarded by a round comparison."""


class Defines:
    MSG_TYPE_S2C_SYNC = "s2c_sync"
    MSG_TYPE_C2S_RESULT = "c2s_result"


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_SYNC, self._on_sync
        )

    def _on_sync(self, msg):
        round_idx = int(msg.get("round_idx", 0))
        if round_idx < self.round_idx:
            return  # stale replay: already past this round
        self.round_idx = round_idx
        self._models[msg.get_sender_id()] = msg.get_arrays()
        self.send_message(Message(Defines.MSG_TYPE_C2S_RESULT, 1, 0))
        self.finish()


class ServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_RESULT, self._on_result
        )

    def _on_result(self, msg):
        self.finish()

    def _sync(self):
        self.send_message(Message(Defines.MSG_TYPE_S2C_SYNC, 0, 1))
