"""graftproto pragma fixture: one suppressed P009, one live."""

import os
import threading


class Committer:
    def __init__(self):
        self._lock = threading.Lock()

    def commit(self, fd):
        with self._lock:
            os.fsync(fd)  # graftproto: disable=P009
            os.fsync(fd)  # line 14: NOT suppressed -> P009
