"""P003 good twin: unique values, live constants, constant-only use sites."""


class Defines:
    MSG_TYPE_S2C_SYNC = "s2c_sync"


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_SYNC, self._on_sync
        )

    def _on_sync(self, msg):
        self.finish()


class ServerManager:
    def _sync(self):
        self.send_message(Message(Defines.MSG_TYPE_S2C_SYNC, 0, 1))
