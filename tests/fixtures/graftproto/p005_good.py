"""P005 good twin: the terminal edge exists and its trigger is sent."""


class Defines:
    MSG_TYPE_S2C_WORK = "s2c_work"
    MSG_TYPE_S2C_FINISH = "s2c_finish"
    MSG_TYPE_C2S_DONE = "c2s_done"


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_WORK, self._on_work
        )
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_FINISH, self._on_finish
        )

    def _on_work(self, msg):
        self.send_message(Message(Defines.MSG_TYPE_C2S_DONE, 1, 0))

    def _on_finish(self, msg):
        self.done.set()
        self.finish()


class ServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_DONE, self._on_done
        )

    def _on_done(self, msg):
        self.send_message(Message(Defines.MSG_TYPE_S2C_FINISH, 0, 1))
        self.finish()

    def _drive(self):
        self.send_message(Message(Defines.MSG_TYPE_S2C_WORK, 0, 1))
