"""P001 good twin: every sent type is handled, on the right role."""


class Defines:
    MSG_TYPE_C2S_UPLOAD = "c2s_upload"
    MSG_TYPE_S2C_FINISH = "s2c_finish"


class ServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_UPLOAD, self._on_upload
        )

    def _on_upload(self, msg):
        self.send_message(Message(Defines.MSG_TYPE_S2C_FINISH, 0, 1))
        self.finish()


class ClientManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_FINISH, self._on_finish
        )

    def _on_finish(self, msg):
        self.done.set()
        self.finish()

    def _report(self):
        self.send_message(Message(Defines.MSG_TYPE_C2S_UPLOAD, 1, 0))
