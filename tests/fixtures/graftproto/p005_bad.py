"""P005 fixture: two FSMs with handlers but no path to finish() at all —
the receive loops can never terminate."""


class Defines:
    MSG_TYPE_S2C_WORK = "s2c_work"
    MSG_TYPE_C2S_DONE = "c2s_done"


class ClientManager:
    def register_message_receive_handlers(self):
        # line 13: handlers, but no finish()/done.set() anywhere -> P005
        self.register_message_receive_handler(
            Defines.MSG_TYPE_S2C_WORK, self._on_work
        )

    def _on_work(self, msg):
        self.send_message(Message(Defines.MSG_TYPE_C2S_DONE, 1, 0))


class ServerManager:
    def register_message_receive_handlers(self):
        # line 24: same on the server side -> P005
        self.register_message_receive_handler(
            Defines.MSG_TYPE_C2S_DONE, self._on_done
        )

    def _on_done(self, msg):
        self.send_message(Message(Defines.MSG_TYPE_S2C_WORK, 0, 1))
