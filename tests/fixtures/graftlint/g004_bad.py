"""G004 known-bad: side effects inside traced round functions."""

import jax
import jax.numpy as jnp

from fedml_tpu.core.mlops import telemetry

_HISTORY = []


class Engine:
    def build(self):
        def core(state, grads):
            self.last_state = state            # line 14: attribute write
            telemetry.counter_inc("rounds")    # line 15: telemetry call
            _HISTORY.append(grads)             # line 16: captured-list append
            return jax.tree.map(lambda s, g: s - g, state, grads)

        return jax.jit(core, donate_argnums=(0,))
