"""G005 known-bad: unguarded cross-thread state."""

import threading


class Worker:
    def __init__(self):
        self.results = []
        self._running = False
        self._thread = threading.Thread(target=self._poll, daemon=True)

    def _poll(self):
        while self._running:
            self.results.append(1)       # line 14: thread-side write

    def start(self):
        self._running = True             # line 17: main-side write
        self._thread.start()

    def stop(self):
        self._running = False            # line 21: main-side write
        return list(self.results)        # line 22: main-side read


class Registry:
    enabled = False
    ema = None


def update(value):
    prev = Registry.ema                  # line 31: read
    Registry.ema = value if prev is None else 0.5 * (prev + value)  # line 32
