"""G005 known-good: Event liveness, locked shared containers."""

import threading


class Worker:
    def __init__(self):
        self.results = []
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._poll, daemon=True)

    def _poll(self):
        while not self._stop_evt.is_set():
            with self._lock:
                self.results.append(1)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop_evt.set()
        with self._lock:
            return list(self.results)


class Registry:
    ema = None


_REG_LOCK = threading.Lock()


def update(value):
    with _REG_LOCK:
        prev = Registry.ema
        Registry.ema = value if prev is None else 0.5 * (prev + value)
