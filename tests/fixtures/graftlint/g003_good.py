"""G003 known-good: scalars enter via static_argnums; pytrees come from
deterministically ordered containers."""

import jax
import jax.numpy as jnp


def _core(x, n):
    return x[:n].sum()


step = jax.jit(_core, static_argnums=(1,))


def run(batch):
    return step(batch, len(batch))   # static arg — recompile is intentional


def build_tree(names, batch):
    params = {k: jnp.zeros(4) for k in sorted(names)}   # ordered — fine
    return params, batch
