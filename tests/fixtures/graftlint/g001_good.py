"""G001 known-good: everything stays on device; host casts only touch
static metadata or config."""

import jax
import jax.numpy as jnp

CONFIG_LR = "0.1"


@jax.jit
def good_step(x, y):
    n = int(x.shape[0])           # static shape metadata — fine
    lr = float(CONFIG_LR)         # module constant, not a tracer — fine
    total = jnp.sum(x) / n
    return total + lr * jnp.mean(y)


def host_driver(x):
    out = good_step(x, x)
    return float(out)             # host sync OUTSIDE the traced region — fine
