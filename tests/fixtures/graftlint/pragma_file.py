"""File-level pragma fixture: the pragma sits in the prologue (after the
module docstring, before any code) and suppresses G001 for the whole file."""
# graftlint: disable=G001

import jax


@jax.jit
def step(x):
    n = int(x)        # suppressed by the file-level pragma
    return float(x) + n
