"""G003 known-bad: recompile hazards at the jit boundary."""

import jax
import jax.numpy as jnp


def _core(x, n):
    return x[:n].sum()


step = jax.jit(_core)  # no static_argnums


def run(batch):
    return step(batch, len(batch))       # line 15: data-derived scalar


def run_shape(batch):
    return step(batch, batch.shape[0])   # line 19: shape fed dynamically


def build_tree(names, batch):
    params = {k: jnp.zeros(4) for k in set(names)}   # line 23: set order
    return params, batch
