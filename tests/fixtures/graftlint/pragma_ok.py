"""Pragma fixture: the same G001 pattern, suppressed inline."""

import jax


@jax.jit
def step(x):
    n = int(x)        # line 8: unsuppressed — must still be reported
    m = int(x)        # graftlint: disable=G001 — suppressed
    return n + m
