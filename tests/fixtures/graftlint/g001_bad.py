"""G001 known-bad: host syncs inside a jit-traced function (never imported,
only parsed by the analyzer — line numbers are asserted by the tests)."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_step(x, y):
    total = float(x.sum())        # line 11: float() on a traced value
    print("loss", total)          # line 12: print at trace time
    host = np.asarray(y)          # line 13: device->host pull
    scalar = x.mean().item()      # line 14: .item() host sync
    return total + host.sum() + scalar


def make_scan(xs):
    def body(carry, x):
        v = int(x)                # line 20: int() inside a lax.scan body
        return carry + v, x

    return jax.lax.scan(body, 0, xs)
