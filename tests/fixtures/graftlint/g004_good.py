"""G004 known-good: pure round function; effects live on the host side."""

import jax

from fedml_tpu.core.mlops import telemetry


class Engine:
    def build(self):
        def core(state, grads):
            metrics = {"examples": grads["w"].sum()}
            new_state = dict(state)            # local copy — fine to mutate
            new_state["w"] = state["w"] - grads["w"]
            return new_state, metrics

        return jax.jit(core, donate_argnums=(0,))

    def round(self, step, state, grads):
        with telemetry.phase("dispatch"):      # host side — fine
            state, metrics = step(state, grads)
        telemetry.counter_inc("rounds")        # host side — fine
        return state, metrics
