"""G002 known-bad: use-after-donate."""

import jax
import jax.numpy as jnp


def _core(state, grads):
    return jax.tree.map(lambda s, g: s - 0.1 * g, state, grads)


step = jax.jit(_core, donate_argnums=(0,))


def train(state, grads):
    new_state = step(state, grads)    # line 15: `state` donated here
    norm = jnp.linalg.norm(state)     # line 16: read of the donated buffer
    return new_state, norm


class Runner:
    def __init__(self):
        self._step = jax.jit(_core, donate_argnums=(0,))

    def round(self, state, grads):
        out = self._step(state, grads)   # line 25: donated via attribute
        stale = state                    # line 26: use-after-donate
        return out, stale
