"""G002 known-good: the donated name is rebound from the call's result."""

import jax


def _core(state, grads):
    return jax.tree.map(lambda s, g: s - 0.1 * g, state, grads)


step = jax.jit(_core, donate_argnums=(0,))


def train(state, grads):
    state = step(state, grads)    # rebind: the old buffer is never read
    return state


def branches(state, grads, fused):
    if fused:
        return step(state, grads)   # consumed, but this branch returns
    return jax.tree.map(lambda s: s * 0.5, state)   # distinct path — fine
