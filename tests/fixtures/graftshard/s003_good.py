"""S003 known-good: placement outside jit, one layout per combination."""

import jax
from jax.sharding import PartitionSpec as P


def place_then_step(step_fn, state, batch, sh):
    batch = jax.device_put(batch, sh)  # host side: placement is fine here
    return step_fn(state, batch)


@jax.jit
def combine(a, b):
    x = jax.lax.with_sharding_constraint(a, P("fsdp", None))
    y = jax.lax.with_sharding_constraint(b, P("fsdp", None))
    return x + y  # same layout on both operands
