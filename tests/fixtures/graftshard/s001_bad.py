"""S001 known-bad: a partition-rule set with no catch-all (never imported,
only parsed by the analyzer — line numbers are asserted by the tests)."""

from jax.sharding import PartitionSpec as P

MODEL_RULES = (  # line 6: only specific patterns — unexpected leaves
    # silently replicate via the fallback
    (r"embedding", P("tensor", "fsdp")),
    (r"attention/.*", P("fsdp", "tensor")),
)
