"""S002 known-bad: unknown mesh axis + repeated axis in one spec."""

from jax.sharding import PartitionSpec as P

MESH_AXIS_STAGE = "stage"  # a legitimate extra axis, used below

BAD_AXIS = P("fsdp", "shards")        # line 7: 'shards' is not a mesh axis
DUP_AXIS = P("fsdp", "fsdp")          # line 8: fsdp repeated
DUP_IN_TUPLE = P(("data", "fsdp"), "data")  # line 9: data repeated
OK_EXTRA = P(MESH_AXIS_STAGE, None)   # fine: declared axis constant
