"""S002 known-good: canonical axes, no repeats, dynamic dims skipped."""

from jax.sharding import PartitionSpec as P

SPEC_A = P("fsdp", "tensor")
SPEC_B = P(("data", "fsdp"), None, "sequence")
SPEC_C = P(None)


def dynamic(axis):
    return P(axis, None)  # unresolvable dim: exempt, never guessed
