"""S003 known-bad: device_put inside traced code; cross-spec binop."""

import jax
from jax.sharding import PartitionSpec as P


@jax.jit
def step(state, batch, sh):
    moved = jax.device_put(batch, sh)  # line 9: cross-device copy in jit
    return state + moved.sum()


@jax.jit
def combine(a, b):
    x = jax.lax.with_sharding_constraint(a, P("fsdp", None))
    y = jax.lax.with_sharding_constraint(b, P("tensor", None))
    return x + y  # line 17: cross-spec binop -> hidden all-gather
