"""S001 known-good: the rule set ends in an explicit catch-all."""

from jax.sharding import PartitionSpec as P

MODEL_RULES = (
    (r"embedding", P("tensor", "fsdp")),
    (r"attention/.*", P("fsdp", "tensor")),
    (r".*", P()),  # every remaining leaf replicates ON PURPOSE
)
