"""graftshard file-level pragma fixture: whole file exempt from S002."""
# graftshard: disable=S002

from jax.sharding import PartitionSpec as P

A = P("fsdp", "fsdp")
B = P("bogus_axis")
