"""S004 delivery-plane prong bad: codec inputs materialized on host
inside a delivery-plane encode/decode path (module name carries
"delivery", so the prong is in scope)."""

import numpy as np


class HostDeltaCodec:
    @staticmethod
    def encode(base_vec, new_vec):
        base = np.asarray(base_vec)
        new = np.asarray(new_vec)
        frame = np.ascontiguousarray(new_vec)
        wire = (base ^ new).tobytes()
        return [frame, wire], {"dim": int(new.shape[0])}

    @staticmethod
    def decode(base_vec, arrays, meta):
        base = np.asarray(base_vec)
        return base + np.asarray(arrays[0])
