"""S004 known-good: reduce on device, pull once after the loop;
device-to-device resharding without the host hop."""

import jax
import jax.numpy as jnp
import numpy as np


def round_loop(ds, shardings, metrics_fn):
    cohort = jax.device_put(ds.cohort, shardings)
    total = jnp.zeros(())
    for _r in range(100):
        total = total + metrics_fn(cohort).mean()  # stays on device
    return float(np.asarray(total))  # one pull, outside the loop


def replace_aux(arr, sharding):
    return jax.device_put(arr, sharding)  # device-to-device reshard
