"""S004 known-bad: host pulls of sharded arrays inside the round loop,
and a device_get -> device_put host round-trip."""

import jax
import numpy as np


def round_loop(ds, shardings, metrics_fn):
    cohort = jax.device_put(ds.cohort, shardings)
    losses = []
    for r in range(100):
        host = np.asarray(cohort)       # line 12: full gather, every round
        losses.append(float(metrics_fn(host).mean()))
    return losses


def replace_aux(arr, sharding):
    pulled = jax.device_get(arr)
    return jax.device_put(pulled, sharding)  # line 19: host round-trip
