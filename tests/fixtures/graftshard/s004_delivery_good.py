"""S004 delivery-plane prong good: the pragma'd allowance keeps the host
codec visible without firing, and non-codec helpers in a delivery module
stay out of scope."""

import numpy as np


class AllowedHostCodec:
    @staticmethod
    def encode(base_vec, new_vec):
        base = np.asarray(base_vec)  # graftshard: disable=S004
        new = np.asarray(new_vec)  # graftshard: disable=S004
        return [new - base], {"dim": int(new.shape[0])}


def flatten_frames(frames):
    return np.concatenate([np.asarray(f).ravel() for f in frames])


def _as_host(a):
    return a if isinstance(a, np.ndarray) else np.ascontiguousarray(a)


class DeviceDirectCodec:
    @staticmethod
    def encode(base_vec, new_vec):
        # module-helper conversions and memoryview emission are the
        # device-direct idiom: no np.* materialization of params, no
        # tobytes, nothing for the prong to flag
        base = _as_host(base_vec)
        new = _as_host(new_vec)
        return [memoryview(new), base], {"dim": int(new.shape[0])}
