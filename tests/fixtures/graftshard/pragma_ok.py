"""graftshard pragma fixture: one suppressed S002, one live."""

from jax.sharding import PartitionSpec as P

SUPPRESSED = P("fsdp", "fsdp")  # graftshard: disable=S002
LIVE = P("fsdp", "fsdp")        # line 6: NOT suppressed -> S002
