"""I002 good: the same reaches, but every access path carries a
run/world discriminator — the world scope on the receiver chain, or the
scoping key in the call itself."""

import threading


class MetricsRegistry:
    def inc(self, name):
        pass


_REG = MetricsRegistry()


class ServerRegistry:
    _servers = {}
    _lock = threading.Lock()

    @classmethod
    def acquire(cls, run_id):
        with cls._lock:
            return cls._servers.get(run_id)


class GoodManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        self.world.telemetry.counter_inc("rounds")
        srv = ServerRegistry.acquire(self.world.run_id)
        srv.route(msg)
