"""I005 good: every thread/timer tethered — world registration, a join
reachable from the shutdown path, and a joined comprehension batch."""

import threading


class GoodWorkerHost:
    def __init__(self, world):
        self.world = world
        self._worker = threading.Thread(target=self._run, daemon=True)
        self.world.register_thread(self._worker)
        self._worker.start()

    def _run(self):
        pass

    def delay(self, fn):
        t = threading.Timer(0.1, fn)
        self.world.register_timer(t)
        t.start()


class JoinedWorkerHost:
    def __init__(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        pass

    def stop(self):
        self._worker.join(timeout=5.0)


def launch_and_wait(jobs):
    workers = [threading.Thread(target=job) for job in jobs]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
