"""I002 bad: handler code reaches process-wide singletons with no
run/world discriminator — one resolved hop through a module helper, and a
foreign class registry touched directly."""

import threading


class MetricsRegistry:
    def inc(self, name):
        pass


_REG = MetricsRegistry()


def counter_inc(name):
    _REG.inc(name)


class ServerRegistry:
    _servers = {}
    _lock = threading.Lock()

    @classmethod
    def acquire(cls, run_id):
        with cls._lock:
            return cls._servers.get(run_id)


class BadManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        counter_inc("rounds")
        srv = ServerRegistry._servers.get("main")
        srv.route(msg)
