"""I003 good: the intentional registry carries a class-level Lock
companion (keyed access is I002's business), instance state lives in
__init__, and the only hand-off target is the world root."""

import threading


class GoodRegistry:
    _instances = {}
    _lock = threading.Lock()

    @classmethod
    def get(cls, run_id):
        with cls._lock:
            return cls._instances.get(run_id)


class WorldScope:
    def __init__(self, store):
        self.store = store


class GoodOwner:
    def __init__(self):
        self._models = {}

    def export(self):
        return WorldScope(self._models)
