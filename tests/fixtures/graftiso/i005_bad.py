"""I005 bad: untethered thread lifecycle — an attr worker no shutdown
path ever joins, a chained-start thread nothing can ever join, and a
local timer that is never cancelled or registered."""

import threading


class BadWorkerHost:
    def __init__(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        pass

    def kick(self):
        threading.Thread(target=self._run, daemon=True).start()

    def delay(self, fn):
        t = threading.Timer(0.1, fn)
        t.start()
