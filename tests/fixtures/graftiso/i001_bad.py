"""I001 bad: module-global mutable state written from handler code, and
an unlocked install-once latch."""

_ROUND_CACHE = {}
_INSTALLED = False


class BadServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        _ROUND_CACHE[msg.round] = msg.params
        _ROUND_CACHE.update(msg.extras)


def install_listeners():
    global _INSTALLED
    if _INSTALLED:
        return
    _INSTALLED = True
