"""I001 pragma: the handler write is suppressed on its own line."""

_ROUND_CACHE = {}


class PragmaServerManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        _ROUND_CACHE[msg.round] = msg.params  # graftiso: disable=I001
