"""I004 bad: ambient configuration — a module global captured from the
environment at import time, an environment read inside a handler, and the
ambient process args pulled from inside the serving path."""

import os

DEBUG_MODE = os.environ.get("FEDML_DEBUG", "")


def get_args():
    return None


class BadManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)
        self.register_message_receive_handler("pull", self._on_pull)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        root = os.environ.get("FEDML_STORE", "/tmp")
        self.save(root, msg)

    def _on_pull(self, msg):
        args = get_args()
        self.save(args.store_dir, msg)

    def save(self, root, msg):
        pass
