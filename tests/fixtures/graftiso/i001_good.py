"""I001 good: state owned by the instance; the process-wide latch is
checked-and-set under a module-level lock."""

import threading

_INSTALLED = False
_INSTALL_LOCK = threading.Lock()


class GoodServerManager:
    def __init__(self):
        self._round_cache = {}

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        self._round_cache[msg.round] = msg.params


def install_listeners():
    global _INSTALLED
    with _INSTALL_LOCK:
        if _INSTALLED:
            return
        _INSTALLED = True
