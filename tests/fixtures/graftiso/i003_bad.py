"""I003 bad: a class-level mutable default (one object shared by every
instance), and a mutable attr escaping its owner — into another class's
constructor and onto a foreign object."""


class BadCache:
    shared = {}

    def put(self, key, value):
        self.shared[key] = value


class Holder:
    def __init__(self, models):
        self.models = models


class BadOwner:
    def __init__(self, sink):
        self._models = {}
        sink.stash = self._models

    def hand_off(self):
        return Holder(self._models)
