"""I004 good: configuration resolved once at construction from the args
the manager was built with; handlers only read their own state."""

import os


def store_root_from_args(args):
    return getattr(args, "store_dir", "") or os.environ.get(
        "FEDML_STORE", "/tmp")


class GoodManager:
    def __init__(self, args):
        self._store_root = store_root_from_args(args)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        self.save(self._store_root, msg)

    def save(self, root, msg):
        pass
