"""M004 good: the parking set drains from the finish path."""


class GoodParkingManager:
    def __init__(self):
        self._pending_pulls = set()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("pull", self._on_pull)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_pull(self, msg):
        self._pending_pulls.add(msg.sender)

    def finish(self):
        self._pending_pulls.clear()
