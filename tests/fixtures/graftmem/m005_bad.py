"""M005 bad: the decoded Message payload is retained with no release."""


class BadRetainManager:
    def __init__(self):
        self._last_model_msg: Optional[Message] = None

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("model", self._on_model)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_model(self, msg):
        self._last_model_msg = msg
