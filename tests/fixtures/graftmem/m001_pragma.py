"""M001 pragma: the growth write is suppressed on its own line."""


class PragmaGrowthManager:
    def __init__(self):
        self._seen_updates = {}

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        self._seen_updates[msg.sender] = msg.params  # graftmem: disable=M001
