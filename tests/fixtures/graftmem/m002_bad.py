"""M002 bad: a capacity-less jit cache written from the serving path."""


class BadCacheManager:
    def __init__(self):
        self._jit_cache = {}

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("train", self._on_train)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_train(self, msg):
        if msg.shape not in self._jit_cache:
            self._jit_cache[msg.shape] = object()
