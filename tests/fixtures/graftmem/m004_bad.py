"""M004 bad: parked work with no drain reachable from shutdown."""


class BadParkingManager:
    def __init__(self):
        self._pending_pulls = set()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("pull", self._on_pull)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_pull(self, msg):
        self._pending_pulls.add(msg.sender)
