"""M001 good: the sender-keyed dict is cleared on the finish path."""


class GoodGrowthManager:
    def __init__(self):
        self._seen_updates = {}

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)
        self.register_message_receive_handler("finish", self._on_finish)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        self._seen_updates[msg.sender] = msg.params

    def _on_finish(self, msg):
        self._seen_updates.clear()
