"""M003 good: fixed metric vocabulary; the id rides as a value."""


class GoodMetricsManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        self.telemetry.counter_inc("edge.folds")
        self.telemetry.gauge_set("edge.last_sender", float(msg.sender))
