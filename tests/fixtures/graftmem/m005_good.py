"""M005 good: the retained payload is released on the finish path."""


class GoodRetainManager:
    def __init__(self):
        self._last_model_msg: Optional[Message] = None

    def register_message_receive_handlers(self):
        self.register_message_receive_handler("model", self._on_model)
        self.register_message_receive_handler("finish", self._on_finish)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_model(self, msg):
        self._last_model_msg = msg

    def _on_finish(self, msg):
        self._last_model_msg = None
