"""M003 bad: sender id interpolated into a metric name."""


class BadMetricsManager:
    def register_message_receive_handlers(self):
        self.register_message_receive_handler("sync", self._on_sync)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def _on_sync(self, msg):
        self.telemetry.counter_inc(f"edge.{msg.sender}.folds")
