"""Known-bad: wall-clock/host identity leaking into ledger-bound state."""
import socket
import time


def commit_with_wallclock(ledger, round_idx):
    stamp = time.time()
    ledger.commit_round(round_idx, committed_at=stamp)


class Engine:
    def _ledger_world(self):
        return {"engine": "sp", "host": socket.gethostname()}


def clocked_control(server, msg):
    if time.time() % 2 > 1:
        server.send_message(msg)
