"""Known-good: explicit narrow dtypes on device; numpy stays host-side."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def narrow_in_traced(x):
    scale = jnp.float32(0.5)
    acc = jnp.zeros((4,), dtype=jnp.float32)
    return x.astype(jnp.float32) * scale + acc


def host_side_report(history):
    return float(np.mean(np.asarray(history, np.float32)))
