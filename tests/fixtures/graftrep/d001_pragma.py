"""Pragma fixture: the same D001 shape, suppressed inline with a reason."""
import jax


def double_sample(key):
    a = jax.random.normal(key, (4,))
    # trace-time-static demo: both draws bake into one compile-time constant
    b = jax.random.uniform(key, (4,))  # graftrep: disable=D001
    return a + b
