"""Known-bad: float64 promotion inside traced round code."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def promote_in_traced(x):
    scale = np.float64(0.5)
    wide = x.astype(float)
    acc = jnp.zeros((4,), dtype=np.float64)
    return wide * scale + acc


@jax.jit
def host_reduce_in_traced(x):
    return x - np.mean(np.ones(3))
