"""Known-good: disciplined key handling — derive first, consume once."""
import jax


def fold_in_fanout(key):
    k1 = jax.random.fold_in(key, 1)
    k2 = jax.random.fold_in(key, 2)
    k3 = jax.random.fold_in(key, 3)
    return (jax.random.normal(k1, (4,)) + jax.random.normal(k2, (4,))
            + jax.random.normal(k3, (4,)))


def split_then_consume(key):
    perm_rng, step_rng = jax.random.split(key)
    perm = jax.random.permutation(perm_rng, 8)
    return perm, jax.random.normal(step_rng, (4,))


def branch_exclusive(key, flag):
    if flag:
        return jax.random.normal(key, (4,))
    return jax.random.uniform(key, (4,))


def loop_derived(key, n):
    outs = []
    for i in range(n):
        outs.append(jax.random.normal(jax.random.fold_in(key, i), (4,)))
    return outs


def rebind_each_round(key, n):
    for _ in range(n):
        key, sub = jax.random.split(key)
        _ = jax.random.normal(sub, (4,))
    return key
