"""Known-bad: unordered iteration feeding order-visible accumulation."""
import jax.numpy as jnp


def float_sum_over_set(values):
    total = 0.0
    for v in set(values):
        total += v
    return total


def stack_over_set(arrs):
    pool = set(arrs)
    return jnp.stack([a for a in pool])


class Manager:
    def __init__(self):
        self._clients = {}
        self._dead = set()

    def fan_out(self, make_message):
        for rank in self._clients.keys():
            self.send_message(make_message(rank))

    def weigh(self, weights):
        return sum(weights[r] for r in self._dead)
