"""Known-good: seeds derived from config/round only; seeded instances."""
import jax
import numpy as np


def config_seed(args):
    return jax.random.PRNGKey(int(args.random_seed))


def round_sampler(round_idx, total, per):
    rs = np.random.RandomState(round_idx)
    return rs.choice(total, per, replace=False)


def seeded_instance(seed, n):
    rng = np.random.default_rng(seed)
    return rng.normal(size=n)
