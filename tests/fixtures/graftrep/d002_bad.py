"""Known-bad: PRNG seeds from wall-clock/entropy, bare module samplers."""
import os
import time

import jax
import numpy as np


def clock_seed():
    return jax.random.PRNGKey(int(time.time()))


def entropy_seed():
    seed = int.from_bytes(os.urandom(4), "little")
    return np.random.RandomState(seed)


def bare_module_sampler(n):
    return np.random.rand(n)


@jax.jit
def traced_clock(x):
    return x * time.time()
