"""Known-good: sorted iteration, integer counting, local dict literals."""
import jax.numpy as jnp


def float_sum_sorted(values):
    total = 0.0
    for v in sorted(set(values)):
        total += v
    return total


def count_over_set(values):
    seen = set(values)
    return sum(1 for v in seen if v is not None)


def stack_ordered(arrs):
    return jnp.stack([a for a in sorted(arrs)])


class Manager:
    def __init__(self):
        self._clients = {}

    def fan_out(self, make_message):
        for rank in sorted(self._clients):
            self.send_message(make_message(rank))
