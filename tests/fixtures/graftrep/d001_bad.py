"""Known-bad: PRNG keys reused after a sampler consumed them (D001)."""
import jax


def double_sample(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))
    return a + b


def sample_then_derive(key):
    noise = jax.random.normal(key, (4,))
    sub = jax.random.fold_in(key, 1)
    return noise, sub


def loop_reuse(key, xs):
    out = []
    for _x in xs:
        out.append(jax.random.bernoulli(key))
    return out


def closure_reuse(key):
    perm = jax.random.permutation(key, 8)

    def body(i):
        return jax.random.fold_in(key, i)

    return perm, body


def helper_consumes(key):
    return jax.random.normal(key, (4,))


def call_then_sample(key):
    a = helper_consumes(key)
    b = jax.random.normal(key, (4,))
    return a + b
