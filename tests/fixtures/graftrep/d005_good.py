"""Known-good: pure ledger payloads; durations are telemetry, not state."""
import time


def commit_pure(ledger, round_idx, ckpt_step, cohort):
    ledger.commit_round(round_idx, ckpt_step=ckpt_step, cohort=cohort)


def duration_telemetry(telemetry, t0):
    telemetry.observe("round.duration_s", time.time() - t0)


class Engine:
    def _ledger_world(self):
        return {"engine": "sp", "optimizer": "FedAvg"}


def deadline_control(server, msg, deadline):
    if time.monotonic() > deadline:
        server.send_message(msg)
