"""Native host-pipeline tests: C++ gather + prefetcher vs numpy semantics."""

import numpy as np
import pytest

from fedml_tpu import native


class TestGather:
    def test_gather_matches_numpy(self):
        rng = np.random.RandomState(0)
        src = rng.randn(100, 7, 3).astype(np.float32)
        idx = rng.randint(0, 100, 33)
        out = native.gather_rows(src, idx)
        np.testing.assert_array_equal(out, src[idx])

    def test_gather_int32(self):
        rng = np.random.RandomState(1)
        src = rng.randint(0, 1000, (50, 4)).astype(np.int32)
        idx = rng.randint(0, 50, 17)
        out = native.gather_rows(src, idx)
        np.testing.assert_array_equal(out, src[idx])

    def test_native_lib_builds(self):
        # the image ships g++ (environment contract); the fast path must be on
        assert native.have_native()


class TestPrefetcher:
    def test_batches_cover_epoch_exactly(self):
        n, b = 64, 16
        x = np.arange(n, dtype=np.float32).reshape(n, 1)
        y = np.arange(n, dtype=np.int32).reshape(n, 1)
        pf = native.BatchPrefetcher(x, y, b, seed=3)
        seen = []
        for _ in range(n // b):
            bx, by, epoch = pf.next()
            assert epoch == 0
            np.testing.assert_array_equal(bx.ravel().astype(np.int32), by.ravel())
            seen.extend(by.ravel().tolist())
        # first epoch = a permutation of the dataset
        assert sorted(seen) == list(range(n))
        # next batch starts epoch 1
        _, _, epoch = pf.next()
        assert epoch == 1
        pf.close()

    def test_shuffles_differ_across_epochs(self):
        n, b = 32, 32
        x = np.arange(n, dtype=np.float32).reshape(n, 1)
        y = np.arange(n, dtype=np.int32).reshape(n, 1)
        pf = native.BatchPrefetcher(x, y, b, seed=5)
        _, y0, _ = pf.next()
        _, y1, _ = pf.next()
        assert sorted(y0.ravel()) == sorted(y1.ravel())
        assert not np.array_equal(y0, y1)  # reshuffled
        pf.close()

    def test_double_close_is_safe(self):
        x = np.zeros((8, 1), np.float32)
        y = np.zeros((8, 1), np.int32)
        pf = native.BatchPrefetcher(x, y, 4)
        pf.next()
        pf.close()
        pf.close()


def test_gather_windows_matches_numpy():
    rng = np.random.default_rng(3)
    stream = rng.integers(0, 1000, size=5000).astype(np.int32)
    starts = rng.integers(0, 5000 - 64, size=37)
    out = native.gather_windows(stream, starts, 64)
    expect = stream[np.asarray(starts)[:, None] + np.arange(64)]
    np.testing.assert_array_equal(out, expect)
    # overlapping windows are legal (LM sampling overlaps freely)
    out2 = native.gather_windows(stream, np.array([0, 1, 2]), 16)
    np.testing.assert_array_equal(out2[1], stream[1:17])


def test_gather_windows_rejects_out_of_range():
    import pytest

    stream = np.arange(100, dtype=np.int32)
    with pytest.raises(ValueError):
        native.gather_windows(stream, np.array([-1]), 10)
    with pytest.raises(ValueError):
        native.gather_windows(stream, np.array([95]), 10)
    # exactly-at-the-end window is fine
    out = native.gather_windows(stream, np.array([90]), 10)
    np.testing.assert_array_equal(out[0], stream[90:100])
