import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core import aggregate, compression, dp, partition, schedule, topology


# ---------------- aggregation ----------------
def test_weighted_average_matches_manual():
    trees = [
        {"w": jnp.full((3,), 1.0), "b": jnp.ones(())},
        {"w": jnp.full((3,), 2.0), "b": jnp.zeros(())},
    ]
    stacked = aggregate.stack_trees(trees)
    agg = aggregate.weighted_average(stacked, jnp.array([1.0, 3.0]))
    np.testing.assert_allclose(agg["w"], np.full((3,), 1.75), rtol=1e-6)
    np.testing.assert_allclose(agg["b"], 0.25, rtol=1e-6)


def test_masked_weighted_average_ignores_padding():
    stacked = {"w": jnp.array([[1.0], [2.0], [99.0]])}
    agg = aggregate.masked_weighted_average(
        stacked, jnp.array([1.0, 1.0, 5.0]), jnp.array([1.0, 1.0, 0.0])
    )
    np.testing.assert_allclose(agg["w"], [1.5])


def test_stack_unstack_roundtrip():
    trees = [{"a": jnp.arange(4.0)}, {"a": jnp.arange(4.0) + 10}]
    stacked = aggregate.stack_trees(trees)
    back = aggregate.unstack_tree(stacked, 2)
    np.testing.assert_allclose(back[1]["a"], trees[1]["a"])


# ---------------- partition ----------------
def test_dirichlet_partition_covers_all_samples():
    labels = np.random.RandomState(0).randint(0, 10, size=1000)
    m = partition.non_iid_partition_with_dirichlet_distribution(labels, 7, 10, 0.5)
    all_idx = np.concatenate([m[i] for i in range(7)])
    assert sorted(all_idx.tolist()) == list(range(1000))


def test_dirichlet_skew_increases_with_small_alpha():
    labels = np.random.RandomState(0).randint(0, 10, size=2000)
    m_skew = partition.non_iid_partition_with_dirichlet_distribution(
        labels, 5, 10, 0.05, seed=1
    )
    stats = partition.record_data_stats(labels, m_skew)
    # with heavy skew, some client should be missing several classes
    missing = [10 - len(stats[i]) for i in range(5)]
    assert max(missing) >= 1


def test_homo_partition_even():
    m = partition.homo_partition(100, 4)
    sizes = [len(m[i]) for i in range(4)]
    assert sizes == [25, 25, 25, 25]


def test_pack_partitions_shapes_and_mask():
    data = np.arange(20, dtype=np.float32).reshape(10, 2)
    labels = np.arange(10)
    m = {0: np.array([0, 1, 2]), 1: np.array([3, 4])}
    x, y, counts = partition.pack_partitions(data, labels, m)
    assert x.shape == (2, 3, 2)
    assert counts.tolist() == [3, 2]
    np.testing.assert_allclose(x[1, 2], 0)  # padded slot zeroed


# ---------------- dp ----------------
def test_gaussian_mechanism_noise_scale():
    mech = dp.GaussianMechanism(epsilon=1.0, delta=1e-5, sensitivity=1.0)
    tree = {"w": jnp.zeros((20000,))}
    noised = mech.add_noise(tree, jax.random.PRNGKey(0))
    emp = jnp.std(noised["w"])
    assert abs(float(emp) - mech.sigma) / mech.sigma < 0.05


def test_laplace_mechanism_changes_values():
    mech = dp.LaplaceMechanism(epsilon=0.5)
    tree = {"w": jnp.ones((100,))}
    noised = mech.add_noise(tree, jax.random.PRNGKey(1))
    assert not np.allclose(noised["w"], 1.0)


def test_clip_by_global_norm():
    tree = {"a": jnp.full((4,), 3.0)}  # norm 6
    clipped = dp.clip_tree_by_global_norm(tree, 1.0)
    total = jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree.leaves(clipped)))
    np.testing.assert_allclose(float(total), 1.0, rtol=1e-5)


def test_fed_privacy_mechanism_dispatch():
    m = dp.FedPrivacyMechanism(1.0, mechanism_type="gaussian", dp_type="ldp")
    out = m.randomize({"w": jnp.zeros((10,))}, jax.random.PRNGKey(0))
    assert out["w"].shape == (10,)
    with pytest.raises(ValueError):
        dp.FedPrivacyMechanism(1.0, mechanism_type="nope")


# ---------------- compression ----------------
def test_topk_roundtrip_keeps_largest():
    vec = jnp.array([0.1, -5.0, 0.2, 3.0, -0.05])
    payload = compression.topk_compress(vec, 2)
    dec = compression.topk_decompress(payload)
    np.testing.assert_allclose(dec, [0, -5.0, 0, 3.0, 0])


def test_ef_topk_carries_residual():
    vec = jnp.array([1.0, 2.0, 3.0])
    payload, res = compression.ef_topk_compress(vec, jnp.zeros(3), 1)
    np.testing.assert_allclose(res, [1.0, 2.0, 0.0])
    # next round: residual compensates
    payload2, res2 = compression.ef_topk_compress(jnp.zeros(3), res, 1)
    np.testing.assert_allclose(compression.topk_decompress(payload2), [0, 2.0, 0])


def test_qsgd_unbiased():
    vec = jnp.linspace(-1, 1, 64)
    keys = jax.random.split(jax.random.PRNGKey(0), 200)
    decs = jax.vmap(
        lambda k: compression.qsgd_decompress(compression.qsgd_compress(vec, k, s=16))
    )(keys)
    np.testing.assert_allclose(decs.mean(0), vec, atol=0.02)


def test_uniform_quantize_roundtrip():
    vec = jnp.linspace(-2, 5, 100)
    p = compression.uniform_quantize(vec, bits=8)
    dec = compression.uniform_dequantize(p)
    assert float(jnp.max(jnp.abs(dec - vec))) < (7.0 / 255) + 1e-6


# ---------------- schedule ----------------
def test_lpt_schedule_balances_makespan():
    ids = np.arange(6)
    runtimes = np.array([10.0, 9, 8, 1, 1, 1])
    buckets = schedule.lpt_schedule(ids, runtimes, 3)
    loads = [float(runtimes[b].sum()) for b in buckets]
    assert max(loads) <= 12  # LPT: 10+1, 9+1, 8+1
    assert sorted(np.concatenate(buckets).tolist()) == ids.tolist()


def test_pad_schedules_static_shape():
    padded, mask = schedule.pad_schedules([np.array([1, 2, 3]), np.array([4])])
    assert padded.shape == (2, 3)
    assert mask.sum() == 4


# ---------------- topology ----------------
def test_symmetric_topology_row_stochastic():
    tm = topology.SymmetricTopologyManager(6, 2)
    tm.generate_topology()
    W = tm.mixing_matrix()
    np.testing.assert_allclose(W.sum(1), 1.0, rtol=1e-6)
    assert tm.get_in_neighbor_idx_list(0) == [1, 5]


def test_asymmetric_topology_out_neighbors():
    tm = topology.AsymmetricTopologyManager(5, 2, seed=0)
    tm.generate_topology()
    W = tm.mixing_matrix()
    np.testing.assert_allclose(W.sum(1), 1.0, rtol=1e-6)
    assert len(tm.get_in_neighbor_idx_list(0)) >= 1


def test_all_zero_mask_yields_zeros_not_nan():
    stacked = {"w": jnp.ones((3, 2))}
    agg = aggregate.masked_weighted_average(
        stacked, jnp.ones(3), jnp.zeros(3)
    )
    assert not np.any(np.isnan(agg["w"]))


def test_two_node_ring_still_mixes():
    tm = topology.SymmetricTopologyManager(2, 2)
    tm.generate_topology()
    assert tm.get_in_neighbor_idx_list(0) == [1]


class TestBranchAndBoundScheduler:
    """reference core/schedule/scheduler.py:4-183 parity (VERDICT #22)."""

    def test_beats_or_matches_lpt(self):
        from fedml_tpu.core.schedule import (
            branch_and_bound_schedule, lpt_schedule,
        )

        rng = np.random.RandomState(0)
        for _ in range(5):
            w = rng.randint(1, 50, size=10).astype(float)
            speeds = rng.uniform(0.5, 2.0, size=3)
            assign, makespan = branch_and_bound_schedule(w, speeds)
            assert assign.shape == (10,)
            # verify reported makespan
            costs = np.zeros(3)
            for i, j in enumerate(assign):
                costs[j] += speeds[j] * w[i]
            assert makespan == pytest.approx(costs.max())
            # LPT upper bound: b&b must not be worse than greedy on
            # homogeneous speeds
        w = np.asarray([7, 5, 4, 3, 3, 2], float)
        assign, mk = branch_and_bound_schedule(w, np.ones(2))
        assert mk == pytest.approx(12.0)  # optimal split of 24 total

    def test_memory_caps_respected(self):
        from fedml_tpu.core.schedule import branch_and_bound_schedule

        w = np.asarray([4.0, 4.0, 4.0, 4.0])
        assign, mk = branch_and_bound_schedule(
            w, np.ones(2), memory_caps=np.asarray([8.0, 100.0])
        )
        costs = np.zeros(2)
        for i, j in enumerate(assign):
            costs[j] += w[i]
        assert costs[0] <= 8.0
        with pytest.raises(ValueError):
            branch_and_bound_schedule(
                w, np.ones(1), memory_caps=np.asarray([1.0])
            )
