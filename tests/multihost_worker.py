"""Worker entry for the multi-host launcher test (not collected by pytest).

Joins the coordinated runtime, checks the global/local device split, and
runs a cross-process collective: a global-sum over an array sharded across
both processes' devices — the data path every mesh API rides multi-host.
"""

import numpy as np


def main() -> None:
    from fedml_tpu.parallel.multihost import initialize

    initialize()  # env contract from spawn()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 2, jax.local_device_count()
    assert jax.device_count() == 4, jax.device_count()

    from fedml_tpu.parallel.sharding import make_mesh

    mesh = make_mesh({"data": 2, "fsdp": 2})
    shard = NamedSharding(mesh, P(("data", "fsdp")))

    # each device contributes its global position; the jitted sum crosses
    # the process boundary through the coordinator-backed backend
    x = jax.jit(lambda: jnp.arange(4.0), out_shardings=shard)()
    total = jax.jit(jnp.sum)(x)
    np.testing.assert_allclose(np.asarray(total), 6.0)

    print(f"WORKER_OK rank={jax.process_index()}")


if __name__ == "__main__":
    main()
