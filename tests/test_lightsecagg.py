"""LightSecAgg tests: field math unit tests (reference analog:
``core/security/test``-style colocated unit tests) + the full masked
aggregation protocol end-to-end over loopback.
"""

import threading
import time

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.mpc import lightsecagg as lsa
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer


class TestFieldMath:
    def test_mod_inverse(self):
        for a in (1, 2, 17, 30000):
            assert (a * lsa.mod_inverse(a)) % lsa.FIELD_P == 1

    def test_lagrange_interpolation_identity(self):
        """Encoding at the β points themselves must return the values."""
        rng = np.random.RandomState(0)
        X = rng.randint(0, lsa.FIELD_P, (4, 6)).astype(np.int64)
        beta = [11, 12, 13, 14]
        out = lsa.lcc_encode(X, beta, beta)
        np.testing.assert_array_equal(out, X % lsa.FIELD_P)

    def test_encode_decode_roundtrip(self):
        """Any U of N shares reconstruct the original U chunks."""
        rng = np.random.RandomState(1)
        N, U = 6, 4
        X = rng.randint(0, lsa.FIELD_P, (U, 8)).astype(np.int64)
        alpha = list(range(1, N + 1))
        beta = list(range(N + 1, N + 1 + U))
        shares = lsa.lcc_encode(X, alpha, beta)
        pick = [0, 2, 3, 5]  # arbitrary U of N
        rec = lsa.lcc_decode(shares[pick], [alpha[i] for i in pick], beta)
        np.testing.assert_array_equal(rec, X % lsa.FIELD_P)

    def test_quantize_roundtrip(self):
        x = np.array([-1.5, -0.25, 0.0, 0.125, 2.0], np.float32)
        f = lsa.quantize_to_field(x, q_bits=8)
        assert (f >= 0).all() and (f < lsa.FIELD_P).all()
        np.testing.assert_allclose(lsa.dequantize_from_field(f, 8), x, atol=1 / 256)

    def test_mask_sum_reconstruction(self):
        """Σ of per-client masks is recoverable from U aggregate shares."""
        rng = np.random.RandomState(2)
        N, U, T, d = 5, 3, 1, 17
        masks, all_shares = [], []
        for i in range(N):
            z, shares = lsa.mask_encoding(d, N, U, T, rng)
            masks.append(z)
            all_shares.append(shares)
        survivors = [0, 1, 3]  # a dropout scenario: clients 2,4 vanish
        # client j's aggregate share over the surviving set
        agg = [
            lsa.aggregate_shares([all_shares[i][j] for i in survivors])
            for j in survivors
        ]
        rec = lsa.decode_aggregate_mask(
            agg, [j + 1 for j in survivors], d, N, U, T
        )
        expected = np.zeros(d, np.int64)
        for i in survivors:
            expected = (expected + masks[i]) % lsa.FIELD_P
        np.testing.assert_array_equal(rec % lsa.FIELD_P, expected)

    def test_masking_hides_model(self):
        rng = np.random.RandomState(3)
        import jax.numpy as jnp

        q = lsa.quantize_to_field(rng.randn(32).astype(np.float32))
        z = rng.randint(0, lsa.FIELD_P, 32)
        masked = np.asarray(lsa.model_masking(jnp.asarray(q, jnp.int32),
                                              jnp.asarray(z, jnp.int32)))
        assert not np.array_equal(masked, q)
        unmasked = np.asarray(lsa.model_unmasking(
            jnp.asarray(masked, jnp.int32), jnp.asarray(z, jnp.int32)))
        np.testing.assert_array_equal(unmasked % lsa.FIELD_P, q)


class TestLSAProtocol:
    def _run(self, run_id, n_clients=3, **kw):
        base = dict(
            training_type="cross_silo", dataset="synthetic", model="lr",
            client_num_in_total=n_clients, client_num_per_round=n_clients,
            comm_round=2, epochs=2, batch_size=8, learning_rate=0.2,
            backend="LOOPBACK", run_id=run_id, frequency_of_the_test=1,
            federated_optimizer="LSA",
        )
        base.update(kw)

        def make(role, rank=0):
            a = fedml.init(Arguments(overrides={**base, "role": role,
                                                "rank": rank}),
                           should_init_logs=False)
            ds, od = data_mod.load(a)
            bundle = model_mod.create(a, od)
            return a, ds, bundle

        a, ds, bundle = make("server")
        server = FedMLCrossSiloServer(a, None, ds, bundle)
        clients = []
        for rank in range(1, n_clients + 1):
            ac, dsc, bc = make("client", rank)
            clients.append(FedMLCrossSiloClient(ac, None, dsc, bc))
        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        for t in threads:
            t.start()
        time.sleep(0.05)
        result = server.run()
        for t in threads:
            t.join(timeout=60)
        return result, server, clients

    def test_lsa_end_to_end(self):
        result, server, clients = self._run("lsa1")
        assert server.manager.round_idx == 2
        assert result is not None
        # masked aggregation still learns (quantization costs a little)
        assert result["test_acc"] > 0.4
        for c in clients:
            assert c.manager.done.is_set()

    def test_lsa_matches_plain_fedavg_closely(self):
        lsa_res, *_ = self._run("lsa2")
        plain, *_ = self._run("lsa3", federated_optimizer="FedAvg")
        assert abs(lsa_res["test_acc"] - plain["test_acc"]) < 0.2
