"""Stall-proofing tests for the bench orchestrator (bench.py).

Round 4 recorded ``BENCH_r04.json: rc=124, parsed=null`` — a single wedged
leg zeroed the whole round. These tests pin the r5 guarantees with an
injected leg runner (no jax, no subprocesses):

- a cumulative JSON line is printed after EVERY leg, so an external kill
  leaves the most complete line as the tail;
- a leg that times out or crashes costs one key, never the headline;
- the global budget skips remaining legs with explicit markers;
- completed TPU legs are checkpointed to BENCH_PARTIAL.json and reused on a
  digest match (and NOT reused after a config/source change).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (repo root is not on sys.path under bare `pytest`)


@pytest.fixture()
def partial_path(tmp_path, monkeypatch):
    p = tmp_path / "BENCH_PARTIAL.json"
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(p))
    return p


def _tpu_runner(argv, timeout):
    """Fake every leg succeeding on a TPU host."""
    joined = " ".join(argv)
    if "--leg fedavg" in joined:
        return {"rounds_per_sec": 1.25, "platform": "tpu",
                "device_kind": "TPU v5 lite"}
    if "--leg cheetah" in joined:
        return {"cheetah_mfu": 0.758, "cheetah_tokens_per_sec_per_chip": 1e5,
                "cheetah_device_kind": "TPU v5 lite", "platform": "tpu"}
    if "--leg million" in joined:
        return {"million_rounds_per_sec": 2.5, "million_registry_n": 1000000,
                "million_cohort_k": 10000, "million_prefetch_overlap": 0.9,
                "million_steady_compiles": 0, "platform": "tpu",
                "device_kind": "TPU v5 lite"}
    if "--leg wire" in joined:
        return {"wire_host_cpu_reduction_x": 3.3, "wire_parity": True,
                "wire_soak_ok": True, "wire_frame_mb": 16.0,
                "platform": "tpu", "device_kind": "TPU v5 lite"}
    if "--leg compressed" in joined:
        return {"compressed_reduction_x": 11.6, "compressed_acc": 0.999,
                "uncompressed_acc": 1.0, "compressed_bytes_per_round": 22000.0,
                "uncompressed_bytes_per_round": 257000.0, "platform": "tpu",
                "device_kind": "TPU v5 lite"}
    return {"mfu": 0.5, "tok_s": 9e4, "params_m": 600.0, "n_chips": 1,
            "step_s": 0.2, "device_kind": "TPU v5 lite"}


V5E = lambda: "TPU v5 lite"  # noqa: E731  — injected device prober


def _lines(capsys):
    return [json.loads(ln) for ln in capsys.readouterr().out.strip().splitlines()]


def test_emits_cumulative_line_after_every_leg(partial_path, capsys):
    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=_tpu_runner, device_prober=V5E)
    lines = _lines(capsys)
    # one startup line (parseable tail from second zero) + one per leg
    assert len(lines) == len(bench.leg_specs()) + 1
    # every line is a full headline line — the tail is always parseable
    for ln in lines:
        assert ln["metric"] == (
            "fedavg_rounds_per_sec_100clients_cifar10_resnet56")
        assert "unit" in ln and "vs_baseline" in ln
    assert lines[0]["value"] is None  # startup line precedes any leg
    assert lines[0]["bench_device_probe"] == "TPU v5 lite"
    assert lines[1]["value"] == 1.25  # headline present from the first leg
    assert final == lines[-1]
    assert final["cheetah_mfu"] == 0.758
    assert final["cheetah_moe_mfu"] == 0.5
    # all TPU legs checkpointed
    cache = json.loads(partial_path.read_text())
    assert set(cache["legs"]) == {n for n, *_ in bench.leg_specs()}


def test_one_wedged_leg_does_not_zero_the_round(partial_path, capsys):
    def runner(argv, timeout):
        if "--leg fedavg" in " ".join(argv):
            raise subprocess.TimeoutExpired(argv, timeout)
        return _tpu_runner(argv, timeout)

    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner, device_prober=V5E)
    assert final["value"] is None
    assert final["fedavg_error"] == "leg timeout"
    assert final["cheetah_mfu"] == 0.758  # later legs still ran
    cache = json.loads(partial_path.read_text())
    assert "fedavg" not in cache["legs"]  # failures are never cached


def test_budget_skips_remaining_legs_with_markers(partial_path, capsys):
    calls = []

    def runner(argv, timeout):
        calls.append(argv)
        return _tpu_runner(argv, timeout)

    # budget already below min_leg_s: every leg skipped, line still printed
    final = bench.run_legs(budget_s=10, ttl_s=1e6, min_leg_s=240,
                           runner=runner, device_prober=V5E)
    assert not calls
    for name, *_ in bench.leg_specs():
        assert final[f"{name}_skipped"] == "budget"
    assert final["value"] is None  # explicit null beats rc=124 and no line


def test_cache_reuse_and_invalidation(partial_path, capsys, monkeypatch):
    calls = []

    def runner(argv, timeout):
        calls.append(argv)
        return _tpu_runner(argv, timeout)

    # a row written by ANOTHER overlapping run must survive our writes
    partial_path.write_text(json.dumps(
        {"legs": {"foreign_leg": {"digest": "x", "t": 1, "platform": "tpu",
                                  "result": {}}}}))

    bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                   device_prober=V5E)
    n_first = len(calls)
    assert n_first == len(bench.leg_specs())
    assert "foreign_leg" in json.loads(partial_path.read_text())["legs"]

    # second run: every leg served from cache, zero subprocesses
    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                           device_prober=V5E)
    assert len(calls) == n_first
    assert final["value"] == 1.25
    assert final["fedavg_cached"] is True and final["cheetah_cached"] is True

    # a config change invalidates exactly the changed leg
    monkeypatch.setitem(bench.MOE_CFG, "moe_capacity_factor", 9.9)
    bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner, device_prober=V5E)
    assert len(calls) == n_first + 1
    assert "mfu_sweep" in " ".join(calls[-1])

    # an expired cache re-runs everything
    calls.clear()
    bench.run_legs(budget_s=1e6, ttl_s=0, runner=runner, device_prober=V5E)
    assert len(calls) == len(bench.leg_specs())


def test_cache_dropped_on_device_kind_mismatch(partial_path, capsys):
    calls = []

    def runner(argv, timeout):
        calls.append(argv)
        return _tpu_runner(argv, timeout)

    bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner, device_prober=V5E)
    n = len(calls)

    # same chip generation → all cached
    bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner, device_prober=V5E)
    assert len(calls) == n

    # a v6e host must NOT serve v5e numbers: every row re-measures fresh
    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                           device_prober=lambda: "TPU v6e")
    assert len(calls) == 2 * n
    assert "fedavg_cached" not in final

    # unknown kind (wedged tunnel — the insurance case) accepts the cache
    calls.clear()
    bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                   device_prober=lambda: None)
    assert not calls


def test_cpu_results_are_not_cached_and_not_ref_compared(partial_path, capsys):
    def cpu_runner(argv, timeout):
        joined = " ".join(argv)
        if "--leg fedavg" in joined:
            return {"rounds_per_sec": 50.0, "platform": "cpu",
                    "device_kind": "cpu"}
        if "--leg cheetah" in joined:
            return {"cheetah_mfu": 0.01, "platform": "cpu"}
        return {"skipped": "not a tpu host"}

    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=cpu_runner, device_prober=V5E)
    # the smoke number must never masquerade as the resnet56 headline metric
    assert final["value"] is None
    assert final["fedavg_cpu_smoke_rounds_per_sec"] == 50.0
    assert final["vs_baseline"] is None
    assert "cpu smoke" in final["fedavg_note"]
    assert not partial_path.exists() or not json.loads(
        partial_path.read_text())["legs"]


def test_crashed_leg_records_error_and_continues(partial_path, capsys):
    def runner(argv, timeout):
        if "mfu_sweep" in " ".join(argv):
            raise RuntimeError("rc=1 <no output> XlaRuntimeError: oom")
        return _tpu_runner(argv, timeout)

    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner, device_prober=V5E)
    assert final["value"] == 1.25
    assert "oom" in final["cheetah_hd512_error"]
    assert "oom" in final["cheetah_moe_error"]


def test_bench_legs_env_filters_legs(partial_path, capsys, monkeypatch):
    calls = []

    def runner(argv, timeout):
        calls.append(argv)
        return _tpu_runner(argv, timeout)

    monkeypatch.setenv("BENCH_LEGS", "fedavg")
    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                           device_prober=V5E)
    assert len(calls) == 1 and "--leg fedavg" in " ".join(calls[0])
    assert final["value"] == 1.25
    assert "cheetah_mfu" not in final  # unselected legs neither run nor skip
    assert "cheetah_skipped" not in final


def test_fedavg_compile_fields_pass_through(partial_path, capsys):
    """Compile wall and steady-state rounds/s are separate fields, so cache
    wins are visible in BENCH_*.json (ISSUE 1 satellite)."""

    def runner(argv, timeout):
        if "--leg fedavg" in " ".join(argv):
            return {"rounds_per_sec": 2.5, "platform": "tpu",
                    "device_kind": "TPU v5 lite", "fedavg_compile_s": 61.2,
                    "fedavg_round_fused": True, "fedavg_superround_k": 10}
        return _tpu_runner(argv, timeout)

    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                           device_prober=V5E)
    assert final["value"] == 2.5
    assert final["fedavg_compile_s"] == 61.2
    assert final["fedavg_round_fused"] is True
    assert final["fedavg_superround_k"] == 10

    # the CPU smoke translation keeps them too (bench_smoke.sh reads them)
    res, platform = bench._translate_fedavg(
        {"rounds_per_sec": 9.0, "platform": "cpu", "device_kind": "cpu",
         "fedavg_compile_s": 1.5, "fedavg_round_fused": True})
    assert platform == "cpu"
    assert res["fedavg_compile_s"] == 1.5 and res["fedavg_round_fused"] is True


def test_unreachable_tunnel_fails_fast_with_parseable_tail(partial_path,
                                                           capsys):
    """Tunnel down (probe fails FAST with an error) + empty cache: legs
    shrink to the fast-fail timeout and the startup line already carries
    the probe verdict. A probe TIMEOUT must NOT shrink (a slow-but-healthy
    host can blow the probe budget and still serve 900s legs)."""
    seen_timeouts = []

    def runner(argv, timeout):
        seen_timeouts.append(timeout)
        raise subprocess.TimeoutExpired(argv, timeout)

    final = bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                           device_prober=lambda: (None, "error"))
    lines = _lines(capsys)
    assert lines[0]["bench_device_probe"] == "unreachable"
    assert all(t <= 240.0 for t in seen_timeouts)
    for name, *_ in bench.leg_specs():
        assert final[f"{name}_error"] == "leg timeout"

    # probe timeout: full leg timeouts retained, verdict disclosed
    seen_timeouts.clear()
    bench.run_legs(budget_s=1e6, ttl_s=1e6, runner=runner,
                   device_prober=lambda: (None, "timeout"))
    lines = _lines(capsys)
    assert lines[0]["bench_device_probe"] == "probe-timeout"
    assert any(t > 240.0 for t in seen_timeouts)
