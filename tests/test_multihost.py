"""Multi-host launcher: a 2-process × 2-device mesh with a cross-process
collective (the MPI-plane analog, parallel/multihost.py)."""

import os

import pytest

pytestmark = pytest.mark.slow

HERE = os.path.dirname(os.path.abspath(__file__))


def test_spawn_two_process_mesh():
    from fedml_tpu.parallel.multihost import spawn

    repo_root = os.path.dirname(HERE)
    pythonpath = ":".join(
        p for p in (repo_root, os.environ.get("PYTHONPATH", "")) if p
    )
    results = spawn(
        [os.path.join(HERE, "multihost_worker.py")],
        n_processes=2, local_device_count=2, timeout_s=280.0,
        # children must NOT inherit this process's single-chip TPU pin,
        # and need the repo on their import path
        env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": pythonpath},
    )
    assert len(results) == 2
    for r in results:
        assert "WORKER_OK" in r.stdout


def test_initialize_env_contract_parsing(monkeypatch):
    """The env contract resolves without touching the jax backend."""
    from fedml_tpu.parallel import multihost

    captured = {}

    def fake_init(**kw):
        captured.update(kw)

    monkeypatch.setenv(multihost.ENV_COORDINATOR, "127.0.0.1:999")
    monkeypatch.setenv(multihost.ENV_PROCESS_ID, "1")
    monkeypatch.setenv(multihost.ENV_NUM_PROCESSES, "2")
    import jax

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: fake_init(**kw))
    multihost.initialize()
    assert captured == {
        "coordinator_address": "127.0.0.1:999",
        "num_processes": 2,
        "process_id": 1,
    }
