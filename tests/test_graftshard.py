"""graftshard sharding/HBM analysis tests (tools/graftshard — ISSUE 8).

Pins six guarantees:

1. **Per-rule fixtures**: each of S001–S004 fires on its known-bad snippet
   with exact rule ids and line numbers, and stays silent on the known-good
   twin (``tests/fixtures/graftshard/``).
2. **Suppression machinery**: inline ``# graftshard: disable=S00X`` pragmas
   (graftlint's parser under graftshard's marker) and the baseline
   round-trip.
3. **Model extraction**: the shipped tree's in-code rule-set literals
   (``DEFAULT_COHORT_RULES``/``DEFAULT_STATE_RULES`` — AnnAssign form) and
   construction-site mesh axes (``silo_dp``) are visible to the model — a
   regression here silently blinds S001/S002.
4. **HBM golden**: the S005 estimator's 7B row on a 4-chip abstract mesh
   matches a hand-computed byte total within 1%, and over-budget rows
   produce S005 findings; indivisible meshes produce S002 findings.
5. **Tier-1 gate**: the shipped tree has ZERO non-baselined findings, and
   the runtime pass (real mesh_api placement + cheetah AOT sharding
   stability on a forced 4-device CPU mesh) agrees.
6. **Exit codes**: 0 clean / 1 findings / 2 analyzer crash, shared with
   the sibling suites.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftlint import baseline as baseline_mod  # noqa: E402
from tools.graftshard.analyzer import (  # noqa: E402
    analyze_paths,
    analyze_paths_with_model,
    default_baseline_path,
)
from tools.graftshard.hbm import parse_mesh_arg  # noqa: E402
from tools.graftshard.model import enumerate_rule_sets, is_catch_all  # noqa: E402

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftshard")
TREE = os.path.join(REPO_ROOT, "fedml_tpu")


def _findings(*names):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return analyze_paths(paths, repo_root=REPO_ROOT)


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


class TestRuleFixtures:
    """Exact rule ids + line numbers on known-bad, silence on known-good."""

    def test_s001_bad(self):
        fs = _findings("s001_bad.py")
        assert {f.rule for f in fs} == {"S001"}
        assert _rule_lines(fs, "S001") == [6]

    def test_s001_good(self):
        assert _findings("s001_good.py") == []

    def test_s002_bad(self):
        fs = _findings("s002_bad.py")
        assert {f.rule for f in fs} == {"S002"}
        # 7: unknown axis; 8: repeated axis; 9: repeat inside a multi-axis
        # dim. The fixture's own MESH_AXIS_STAGE extends the vocabulary.
        assert _rule_lines(fs, "S002") == [7, 8, 9]

    def test_s002_good(self):
        assert _findings("s002_good.py") == []

    def test_s003_bad(self):
        fs = _findings("s003_bad.py")
        assert {f.rule for f in fs} == {"S003"}
        # 9: device_put inside jit; 17: cross-spec binop
        assert _rule_lines(fs, "S003") == [9, 17]

    def test_s003_good(self):
        assert _findings("s003_good.py") == []

    def test_s004_bad(self):
        fs = _findings("s004_bad.py")
        assert {f.rule for f in fs} == {"S004"}
        # 12: per-round host gather; 19: device_get -> device_put round-trip
        assert _rule_lines(fs, "S004") == [12, 19]

    def test_s004_good(self):
        assert _findings("s004_good.py") == []

    def test_s004_delivery_prong_bad(self):
        """Delivery-plane prong (ISSUE 11 satellite): host materialization
        of codec inputs inside delivery-module encode/decode paths."""
        fs = _findings("s004_delivery_bad.py")
        assert {f.rule for f in fs} == {"S004"}
        # 11/12: encode inputs; 13: ascontiguousarray materialization;
        # 14: tobytes frame copy; 19/20: decode base + frame
        assert _rule_lines(fs, "S004") == [11, 12, 13, 14, 19, 20]
        assert all("delivery-plane" in f.message for f in fs)

    def test_s004_delivery_prong_good(self):
        """Pragma'd allowance, non-codec helpers, and the device-direct
        idiom (module-helper conversions + memoryview emission) stay
        silent."""
        assert _findings("s004_delivery_good.py") == []

    def test_delta_codec_has_no_allowances(self):
        """The device-direct wire path deleted the host codec's pragma'd
        S004 allowances — the codec surface (host reference AND device
        kernels) must now be clean with ZERO pragmas, not pragma'd debt."""
        for fname in ("delta_codec.py", "device_codec.py"):
            path = os.path.join(
                REPO_ROOT, "fedml_tpu", "delivery", fname)
            src = open(path).read()
            assert src.count("graftshard: disable=S004") == 0, fname
            fs = analyze_paths([path], repo_root=REPO_ROOT)
            assert fs == [], [f.render() for f in fs]


class TestSuppression:
    def test_inline_pragma(self):
        fs = _findings("pragma_ok.py")
        assert _rule_lines(fs, "S002") == [6]  # line 5 suppressed

    def test_file_level_pragma(self):
        assert _findings("pragma_file.py") == []

    def test_baseline_round_trip(self, tmp_path):
        fs = _findings("s002_bad.py")
        assert fs
        path = str(tmp_path / "baseline.json")
        baseline_mod.save(path, fs, tool="graftshard")
        new, old = baseline_mod.split(fs, baseline_mod.load(path))
        assert new == [] and len(old) == len(fs)

    def test_baseline_is_line_number_free(self, tmp_path):
        fs = _findings("s002_bad.py")
        keys = [f.baseline_key() for f in fs]
        assert all(str(f.line) not in k.split("::")[0] for f, k in
                   zip(fs, keys))


class TestModelExtraction:
    """The shard model must see the shipped tree's real GSPMD surface."""

    def test_shipped_rule_sets_visible_and_covered(self):
        rule_sets = enumerate_rule_sets([TREE], REPO_ROOT)
        names = {rs.name for rs in rule_sets}
        # AnnAssign-form literals: a parser regression hides them silently
        assert {"DEFAULT_COHORT_RULES", "DEFAULT_STATE_RULES"} <= names
        assert all(rs.has_catch_all() for rs in rule_sets), [
            (rs.name, rs.patterns) for rs in rule_sets if not
            rs.has_catch_all()]

    def test_mesh_construction_axes_extend_vocabulary(self):
        _fs, model = analyze_paths_with_model([TREE], repo_root=REPO_ROOT)
        # the cross-silo plane's private axis, declared only at its
        # Mesh(...) construction site
        assert "silo_dp" in model.vocabulary

    def test_shadowing_catch_all_is_s001(self, tmp_path):
        # first-match-wins: a catch-all BEFORE other rules makes them dead
        p = tmp_path / "shadow.py"
        p.write_text(
            "from jax.sharding import PartitionSpec as P\n\n"
            "RULES = (\n"
            "    (r'.*', P()),\n"
            "    (r'cohort/.*', P('clients')),\n"
            ")\n")
        fs = analyze_paths([str(p)], repo_root=REPO_ROOT)
        assert [f.rule for f in fs] == ["S001"]
        assert "shadows" in fs[0].message

    def test_catch_all_recognizer(self):
        assert is_catch_all(".*")
        assert is_catch_all(".+")
        assert is_catch_all("")
        assert not is_catch_all("embedding")
        assert not is_catch_all("^cohort/.*$")
        assert not is_catch_all("(")  # unparsable regex is not a catch-all


class TestTreeGate:
    """The shipped tree is CLEAN — graftshard is a tier-1 zero-findings
    gate with an EMPTY baseline (real findings get fixed, not suppressed)."""

    def test_tree_has_zero_findings(self):
        fs = analyze_paths([TREE], repo_root=REPO_ROOT)
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_baseline_ships_empty(self):
        baseline = baseline_mod.load(default_baseline_path(REPO_ROOT))
        assert sum(baseline.values()) == 0


class TestMeshArg:
    def test_topology_product(self):
        rows = parse_mesh_arg("4x4")
        assert rows == [(None, "4x4", {"fsdp": 16})]

    def test_chip_prefix_and_axes(self):
        rows = parse_mesh_arg("v5e:2x4,v5p:fsdp=4+tensor=2")
        assert rows[0] == ("v5e", "2x4", {"fsdp": 8})
        assert rows[1] == ("v5p", "fsdp=4+tensor=2",
                           {"fsdp": 4, "tensor": 2})

    def test_unknown_chip_rejected(self):
        with pytest.raises(ValueError):
            parse_mesh_arg("v9x:4x4")


class TestHBMBudget:
    """S005 — the static estimator against hand-computed ground truth."""

    @pytest.fixture(scope="class")
    def report_7b(self):
        from tools.graftshard.hbm import estimate_budget

        findings, report = estimate_budget("7b", "v5p:4", seq_len=2048,
                                           batch_per_device=1,
                                           mu_dtype="bfloat16")
        return findings, report

    def test_7b_golden_within_1pct(self, report_7b):
        """The 7B row on a 4-chip abstract mesh vs the closed-form total."""
        _fs, report = report_7b
        (row,) = report["rows"]
        assert row["chip"] == "v5p" and row["devices"] == 4

        # llama2_7b closed form (fedml_tpu/parallel/transformer.py):
        V, D, L, F = 32000, 4096, 32, 11008
        H = Hkv = 32
        hd = D // H
        sharded = (
            V * D                       # embed
            + L * (D * (H + 2 * Hkv) * hd   # wqkv
                   + (H * hd) * D           # wo
                   + D * 2 * F              # w_gate_up
                   + F * D)                 # w_down
            + D * V                     # w_lm_head
        )
        norms = (2 * L + 1) * D         # RMSNorm weights, replicated
        assert row["params"] == sharded + norms

        n_dev = 4
        params_dev = 4 * (sharded / n_dev + norms)      # fp32
        grads_dev = params_dev                          # mirrors params
        opt_dev = (2 + 4) * (sharded / n_dev + norms)   # mu bf16 + nu fp32
        batch_dev = 1 * 2048 * 4 * 2                    # tokens+mask i32
        expected = params_dev + grads_dev + opt_dev + batch_dev

        GiB = 1024 ** 3
        got = row["total_gib_per_device"] * GiB
        assert math.isclose(got, expected, rel_tol=0.01), (
            f"estimator {got / GiB:.3f} GiB vs hand-computed "
            f"{expected / GiB:.3f} GiB")

    def test_7b_4_chips_does_not_fit_v5e(self):
        """21.97 GiB of resident state on a 16 GiB chip must be an S005."""
        from tools.graftshard.hbm import estimate_budget

        findings, report = estimate_budget("7b", "v5e:2x2")
        assert any(f.rule == "S005" for f in findings)
        (row,) = report["rows"]
        assert not row["fits"]

    def test_7b_16_chips_fits_both_chip_kinds(self, report_7b):
        from tools.graftshard.hbm import estimate_budget

        findings, report = estimate_budget("7b", "v5e:4x4,v5p:4x4")
        assert findings == []
        assert all(r["fits"] for r in report["rows"])
        chips = {r["chip"] for r in report["rows"]}
        assert chips == {"v5e", "v5p"}

    def test_indivisible_mesh_is_s002(self):
        from tools.graftshard.hbm import estimate_budget

        findings, _report = estimate_budget("tiny", "v5e:fsdp=3")
        assert any(f.rule == "S002" for f in findings)

    def test_unknown_model_rejected(self):
        from tools.graftshard.hbm import estimate_budget

        with pytest.raises(ValueError):
            estimate_budget("13b", "4x4")


def _run_cli(*argv, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftshard", *argv],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


class TestExitCodes:
    def test_clean_tree_is_0(self):
        r = _run_cli("fedml_tpu")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_findings_are_1(self):
        r = _run_cli("tests/fixtures/graftshard/s002_bad.py",
                     "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr

    def test_usage_error_is_2(self):
        r = _run_cli("tests/fixtures/graftshard/s002_bad.py",
                     "--no-baseline", "--select", "S002",
                     "--write-baseline")
        assert r.returncode == 2

    def test_unknown_model_is_2(self):
        r = _run_cli("fedml_tpu/scale", "--model", "not_a_model")
        assert r.returncode == 2, r.stdout + r.stderr

    def test_json_payload_shape(self):
        r = _run_cli("fedml_tpu", "--json")
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert payload["exit_code"] == 0
        assert payload["findings"] == []

    def test_json_hbm_report_rides_payload(self):
        r = _run_cli("fedml_tpu/scale", "--json", "--model", "tiny",
                     "--mesh", "v5e:2x2", timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        rows = payload["hbm"]["rows"]
        assert rows and rows[0]["model"] == "tiny"

    def test_check_rules_flag(self):
        r = _run_cli("tests/fixtures/graftshard/s001_good.py",
                     "--no-baseline", "--check-rules",
                     "cohort/.*=clients", timeout=300)
        assert r.returncode == 1  # no catch-all -> S001
        r = _run_cli("tests/fixtures/graftshard/s001_good.py",
                     "--no-baseline", "--check-rules",
                     "cohort/.*=clients;.*=", timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr


class TestRuntimePass:
    """--runtime: real factories over a forced 4-device CPU mesh."""

    def test_runtime_pass_is_clean_on_tree(self):
        r = _run_cli("fedml_tpu/scale/partition_rules.py", "--runtime",
                     timeout=540)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_spec_normalization_mod_extent_1(self):
        from tools.graftshard.runtime_check import _normalize

        extents = {"fsdp": 4, "tensor": 1}
        assert _normalize(("tensor", "fsdp"), extents) == \
            _normalize((None, "fsdp"), extents)
        assert _normalize(("fsdp", None), extents) == ("fsdp",)
        assert _normalize((("data", "fsdp"),), {"data": 2, "fsdp": 4}) \
            == ((("data", "fsdp"),))


class TestLintCLI:
    def test_lint_shard_subcommand(self):
        from fedml_tpu.cli import main

        assert main(["lint", "--shard",
                     os.path.join(TREE, "scale")]) == 0

    def test_lint_shard_proto_conflict(self):
        from fedml_tpu.cli import main

        assert main(["lint", "--shard", "--proto"]) == 2

    def test_lint_mesh_without_shard_model(self):
        from fedml_tpu.cli import main

        assert main(["lint", "--mesh", "4x4"]) == 2
