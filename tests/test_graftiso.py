"""graftiso isolation tests (tools/graftiso — ISSUE 11).

Pins seven guarantees:

1. **Per-rule fixtures**: each of I001–I005 fires on its known-bad snippet
   with exact rule ids and line numbers, and stays silent on the known-good
   twin (``tests/fixtures/graftiso/``).
2. **Suppression machinery**: inline ``# graftiso: disable=I00X`` pragmas
   (graftlint's parser under graftiso's marker) and the baseline
   round-trip.
3. **Tier-1 gate**: the shipped tree has ZERO non-baselined findings and
   the checked-in baseline is EMPTY — no mutable serving-plane state is
   reachable from a handler outside a world-scoped path, and every
   federation thread is tethered (the dogfood refactors in
   comm_manager/server_manager/client_manager/swarm/chaos stay fixed).
4. **Serving model**: handler closure reaches the registered callbacks,
   the base class's dispatch/send path, and worker-thread targets; the
   ownership graph distinguishes dominated from escaping attrs.
5. **WorldScope runtime**: thread/timer registration + shutdown semantics
   (joins workers, cancels timers, skips the calling thread, idempotent)
   and the leak-witness helpers the swarm/chaos soaks assert with.
6. **Exit codes**: 0 clean / 1 findings / 2 analyzer crash, shared with
   the sibling suites; ``fedml_tpu lint --iso`` conflict guards.
7. **Dogfood regression pins**: the real fixes (locked latches in
   telemetry/native/fedml.init, the world-registered async worker and
   shed timers) stay finding-free.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftlint import baseline as baseline_mod  # noqa: E402
from tools.graftiso.analyzer import (  # noqa: E402
    analyze_paths,
    analyze_paths_with_model,
    default_baseline_path,
)

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftiso")
TREE = os.path.join(REPO_ROOT, "fedml_tpu")


def _findings(*names):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return analyze_paths(paths, repo_root=REPO_ROOT)


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


class TestRuleFixtures:
    """Exact rule ids + line numbers on known-bad, silence on known-good."""

    def test_i001_bad(self):
        fs = _findings("i001_bad.py")
        assert {f.rule for f in fs} == {"I001"}
        # 16: handler subscript-writes a module dict; 17: handler mutates
        # it via .update; 24: global latch rebound without a lock
        assert _rule_lines(fs, "I001") == [16, 17, 24]

    def test_i001_good(self):
        assert _findings("i001_good.py") == []

    def test_i002_bad(self):
        fs = _findings("i002_bad.py")
        assert {f.rule for f in fs} == {"I002"}
        # 38: one resolved hop through counter_inc into _REG; 39: foreign
        # class registry touched with no scoping key
        assert _rule_lines(fs, "I002") == [38, 39]

    def test_i002_good(self):
        assert _findings("i002_good.py") == []

    def test_i003_bad(self):
        fs = _findings("i003_bad.py")
        assert {f.rule for f in fs} == {"I003"}
        # 7: class-level mutable default; 21: attr assigned onto a foreign
        # object; 24: attr passed into another class's constructor
        assert _rule_lines(fs, "I003") == [7, 21, 24]

    def test_i003_good(self):
        assert _findings("i003_good.py") == []

    def test_i004_bad(self):
        fs = _findings("i004_bad.py")
        assert {f.rule for f in fs} == {"I004"}
        # 7: import-time env capture; 23: env read inside a handler;
        # 27: get_args() inside a handler
        assert _rule_lines(fs, "I004") == [7, 23, 27]

    def test_i004_good(self):
        assert _findings("i004_good.py") == []

    def test_i005_bad(self):
        fs = _findings("i005_bad.py")
        assert {f.rule for f in fs} == {"I005"}
        # 10: attr worker with no shutdown-reachable join; 17: chained
        # .start(); 20: local timer never cancelled/registered
        assert _rule_lines(fs, "I005") == [10, 17, 20]

    def test_i005_good(self):
        assert _findings("i005_good.py") == []


class TestSuppression:
    def test_pragma_suppresses_on_its_line(self):
        assert _findings("i001_pragma.py") == []

    def test_baseline_round_trip(self, tmp_path):
        fs = _findings("i001_bad.py")
        assert fs
        path = tmp_path / "baseline.json"
        baseline_mod.save(str(path), fs, tool="graftiso")
        new, old = baseline_mod.split(fs, baseline_mod.load(str(path)))
        assert new == []
        assert len(old) == len(fs)

    def test_baseline_is_line_number_free(self):
        fs = _findings("i001_bad.py")
        keys = {f.baseline_key() for f in fs}
        assert all("::" in k for k in keys)


class TestTreeGate:
    """The shipped tree is clean and the checked-in baseline is EMPTY."""

    def test_tree_zero_findings(self):
        fs = analyze_paths([TREE], repo_root=REPO_ROOT)
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_checked_in_baseline_empty(self):
        path = default_baseline_path(REPO_ROOT)
        with open(path) as f:
            payload = json.load(f)
        assert payload["findings"] == {}

    def test_dogfood_fixes_hold(self):
        """The real fixes stay fixed: world-scoped telemetry + registered
        threads in the serving plane, lock-guarded process latches."""
        pins = {
            "fedml_tpu/cross_silo/server_manager.py":
                "self.world.register_thread(self._async_worker)",
            "fedml_tpu/cross_silo/client_manager.py":
                "self.world.register_timer(t)",
            "fedml_tpu/core/mlops/telemetry.py": "with _STATE_LOCK:",
            "fedml_tpu/native/__init__.py": "with _LIB_LOCK:",
            "fedml_tpu/__init__.py": "with _global_args_lock:",
        }
        for rel, needle in pins.items():
            src = open(os.path.join(REPO_ROOT, rel)).read()
            assert needle in src, rel
            fs = analyze_paths([os.path.join(REPO_ROOT, rel)],
                               repo_root=REPO_ROOT)
            assert fs == [], (rel, [f.render() for f in fs])


class TestServingModel:
    def test_serving_classes_and_closure(self):
        _, model = analyze_paths_with_model(
            [os.path.join(REPO_ROOT,
                          "fedml_tpu/cross_silo/server_manager.py"),
             os.path.join(REPO_ROOT,
                          "fedml_tpu/core/distributed/comm_manager.py")],
            repo_root=REPO_ROOT)
        classes = {c for _, c in model.serving_classes}
        # the registering subclass AND its resolvable base join the family
        assert "FedMLServerManager" in classes
        assert "FedMLCommManager" in classes
        names = {fi.qualname.rsplit(".", 1)[-1] for fi in model.closure}
        # registered handler callbacks
        assert "_on_model_received" in names
        # worker-thread target started by serving code
        assert "_async_worker_loop" in names
        # the base class's dispatch/send path
        assert "receive_message" in names
        assert "send_message" in names

    def test_ownership_graph_dominated_vs_escaping(self):
        _, model = analyze_paths_with_model(
            [os.path.join(FIXTURES, "i003_bad.py"),
             os.path.join(FIXTURES, "i003_good.py")],
            repo_root=REPO_ROOT)
        bad = model.ownership["tests.fixtures.graftiso.i003_bad"]
        good = model.ownership["tests.fixtures.graftiso.i003_good"]
        # escaping: passed into Holder(...) and assigned onto sink.stash
        assert not bad.dominated("BadOwner", "_models")
        assert {(e.cls, e.attr) for e in bad.escapes} == {
            ("BadOwner", "_models")}
        assert len(bad.escapes) == 2
        # dominated: only handed to the world root
        assert good.dominated("GoodOwner", "_models")
        assert good.escapes == []

    def test_singleton_inventory(self):
        _, model = analyze_paths_with_model(
            [os.path.join(REPO_ROOT,
                          "fedml_tpu/core/mlops/telemetry.py")],
            repo_root=REPO_ROOT)
        names = {n for _, n in model.singletons}
        assert "_REG" in names  # the module instance
        # a never-written constant map is config, not a registry
        assert "PEAK_BF16_FLOPS" not in names


class TestWorldScope:
    def test_shutdown_joins_threads_and_cancels_timers(self):
        from fedml_tpu.core.world import WorldScope

        w = WorldScope("test-run", 0)
        stop = threading.Event()
        t = threading.Thread(target=stop.wait, daemon=True)
        w.register_thread(t)
        t.start()
        fired = []
        timer = threading.Timer(30.0, lambda: fired.append(1))
        timer.daemon = True
        w.register_timer(timer)
        timer.start()
        w.add_shutdown(stop.set)
        w.shutdown(timeout_s=5.0)
        assert not t.is_alive()
        assert not timer.is_alive()
        assert fired == []
        assert w.closed
        w.shutdown()  # idempotent
        # a timer registered after shutdown (callback racing teardown and
        # re-arming) is cancelled immediately, never left armed
        late = threading.Timer(30.0, lambda: fired.append(2))
        late.daemon = True
        w.register_timer(late)
        late.start()
        late.join(timeout=1.0)
        assert not late.is_alive() and fired == []

    def test_shutdown_skips_calling_thread(self):
        from fedml_tpu.core.world import WorldScope

        w = WorldScope("test-run-2", 0)
        done = threading.Event()

        def worker():
            w.shutdown(timeout_s=1.0)  # a worker driving its own shutdown
            done.set()

        t = threading.Thread(target=worker, daemon=True)
        w.register_thread(t)
        t.start()
        assert done.wait(timeout=5.0)

    def test_scope_index_keyed_by_run_and_rank(self):
        from fedml_tpu.core.world import WorldScope

        class A:
            run_id = "world-key-test"
            rank = 3

        w = WorldScope.for_args(A())
        assert WorldScope.get("world-key-test", 3) is w
        assert WorldScope.get("world-key-test", 4) is None
        WorldScope.release("world-key-test", 3)
        assert WorldScope.get("world-key-test", 3) is None
        assert w.closed

    def test_shutdown_drops_index_entry(self):
        """A long-lived multi-run process must not accumulate closed
        scopes: shutdown() (what finish() drives) pops the index."""
        from fedml_tpu.core.world import WorldScope

        class A:
            run_id = "world-gc-test"
            rank = 0

        w = WorldScope.for_args(A())
        assert WorldScope.get("world-gc-test", 0) is w
        w.shutdown()
        assert WorldScope.get("world-gc-test", 0) is None

    def test_leak_witness(self):
        from fedml_tpu.core import world

        snap = world.thread_snapshot()
        release = threading.Event()
        t = threading.Thread(target=release.wait, daemon=False,
                             name="leak-witness-test")
        t.start()
        try:
            leaked = world.leaked_threads(snap, join_grace_s=0.05)
            assert "leak-witness-test" in leaked
        finally:
            release.set()
            t.join(timeout=5.0)
        assert world.leaked_threads(snap, join_grace_s=0.5) == []

    def test_default_scope_is_process_registry(self):
        from fedml_tpu.core.mlops import telemetry

        scope = telemetry.scope_for(None)
        scope.counter_inc("iso.test.default_scope", 2.0)
        assert telemetry.registry().counter(
            "iso.test.default_scope") == 2.0
        dedicated = telemetry.install_scope("iso-test-run")
        try:
            assert telemetry.scope_for("iso-test-run") is dedicated
            dedicated.counter_inc("iso.test.dedicated")
            # the dedicated scope is its own namespace…
            assert dedicated.counter("iso.test.dedicated") == 1.0
            # …and never bleeds into the process registry
            assert telemetry.registry().counter(
                "iso.test.dedicated") == 0.0
        finally:
            telemetry.uninstall_scope("iso-test-run")
        assert telemetry.scope_for("iso-test-run") is scope


class TestExitCodes:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftiso", *argv],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )

    def test_clean_file_exits_zero(self):
        p = self._run(os.path.join(FIXTURES, "i001_good.py"),
                      "--no-baseline")
        assert p.returncode == 0, p.stdout + p.stderr

    def test_findings_exit_one_with_json(self):
        p = self._run(os.path.join(FIXTURES, "i005_bad.py"),
                      "--no-baseline", "--json")
        assert p.returncode == 1, p.stdout + p.stderr
        payload = json.loads(p.stdout)
        assert payload["exit_code"] == 1
        assert payload["counts"]["I005"] == 3
        assert "serving" in payload

    def test_missing_path_exits_two(self):
        p = self._run(os.path.join(FIXTURES, "no_such_file.py"))
        assert p.returncode == 2

    def test_lint_iso_conflict_guards(self):
        p = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint", "--iso",
             "--rep"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 2
        p = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint", "--iso",
             "--runtime"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 2
        assert "thread-leak" in p.stdout
