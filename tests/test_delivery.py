"""Idempotent at-least-once delivery: retry, dedup, checksums, and the
extended fault rules (duplicate / corrupt / visible loss / timer delays).

reference analog: none — the reference transports are fire-and-forget; a
replayed C2S_SEND_MODEL double-counts a client (SURVEY §5). Here the
delivery layer makes retried/duplicated/corrupted messages *effectively
once* end to end.
"""

import os
import threading
import time

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed import FedMLCommManager, Message
from fedml_tpu.core.distributed.delivery import (
    DedupWindow,
    RetryPolicy,
    TransientSendError,
    arrays_digest,
)
from fedml_tpu.core.distributed.faults import FaultPlan, FaultyComm
from fedml_tpu.core.mlops import telemetry


class _Sink:
    """Minimal BaseCommunicationManager capturing delivered messages."""

    def __init__(self):
        self.delivered = []

    def send_message(self, m):
        self.delivered.append(Message.deserialize(m.serialize(),
                                                  verify=False))

    def add_observer(self, o): ...
    def remove_observer(self, o): ...
    def handle_receive_message(self): ...
    def stop_receive_message(self): ...


def _msg(seq=None, arrays=True):
    m = Message("model", 1, 0)
    m.add(Message.MSG_ARG_KEY_ROUND_IDX, 0)
    if seq is not None:
        m.add(Message.MSG_ARG_KEY_SEQ, seq)
        m.add(Message.MSG_ARG_KEY_EPOCH, 1)
    if arrays:
        m.set_arrays([np.arange(32, dtype=np.float32)])
    return m


class TestRetryPolicy:
    def test_backoff_grows_and_caps(self):
        p = RetryPolicy(max_attempts=8, base_s=0.1, max_s=0.4, jitter=0.0)
        assert p.backoff_s(1) == pytest.approx(0.1)
        assert p.backoff_s(2) == pytest.approx(0.2)
        assert p.backoff_s(3) == pytest.approx(0.4)
        assert p.backoff_s(7) == pytest.approx(0.4)  # capped

    def test_jitter_bounded(self):
        p = RetryPolicy(base_s=0.1, max_s=0.1, jitter=0.5)
        vals = [p.backoff_s(1) for _ in range(50)]
        assert all(0.05 <= v <= 0.1 for v in vals)

    def test_budget_exhaustion_reraises(self):
        p = RetryPolicy(max_attempts=2, base_s=0.001, max_s=0.001)
        calls = []

        def always_fail():
            calls.append(1)
            raise TransientSendError("down")

        with pytest.raises(TransientSendError):
            p.call(always_fail, is_transient=lambda e: True)
        assert len(calls) == 3  # 1 try + 2 retries

    def test_non_transient_never_retried(self):
        p = RetryPolicy(max_attempts=5, base_s=0.001)
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            p.call(boom, is_transient=lambda e: isinstance(
                e, TransientSendError))
        assert len(calls) == 1


class TestDedupWindow:
    def test_exact_duplicate_dropped(self):
        w = DedupWindow()
        assert w.accept(1, 10, 1) == "accept"
        assert w.accept(1, 10, 1) == "duplicate"
        assert w.accept(2, 10, 1) == "accept"  # per-sender spaces

    def test_epoch_supersession(self):
        w = DedupWindow()
        assert w.accept(1, 10, 5) == "accept"
        assert w.accept(1, 11, 1) == "accept"       # restarted sender
        assert w.accept(1, 10, 6) == "stale_epoch"  # previous life
        assert w.accept(1, 11, 1) == "duplicate"

    def test_window_eviction_keeps_memory_bounded(self):
        w = DedupWindow(window=8)
        for s in range(1, 100):
            assert w.accept(1, 1, s) == "accept"
        # inside the window: still recognized
        assert w.accept(1, 1, 99) == "duplicate"
        # far below the floor: treated as a replay, not re-accepted
        assert w.accept(1, 1, 1) == "duplicate"

    def test_out_of_order_within_window_accepted(self):
        w = DedupWindow(window=64)
        assert w.accept(1, 1, 5) == "accept"
        assert w.accept(1, 1, 3) == "accept"  # delayed, not a duplicate
        assert w.accept(1, 1, 3) == "duplicate"


class TestRehomeEpochBump:
    """Why re-homing calls ``FedMLCommManager.bump_epoch`` (docs/
    robustness.md "Edge tier failure domains"): a client's SenderStamp seq
    counter is shared across receivers, so by the time an orphan re-homes,
    the cached update it must replay carries a seq far below whatever
    window its adoptive edge has accumulated — without a fresh epoch the
    replay is indistinguishable from a replay attack and gets dropped."""

    def test_old_seq_below_floor_is_false_duplicate_without_bump(self):
        w = DedupWindow(window=8)
        # the adoptive edge has been hearing this sender (heartbeats,
        # resync probes) long enough to fill its window...
        for seq in range(100, 108):
            assert w.accept(1, 10, seq) == "accept"
        # ...so the cached update's ORIGINAL early seq reads as a replay:
        # this is the misclassification bump_epoch exists to prevent
        assert w.accept(1, 10, 3) == "duplicate"

    def test_bumped_epoch_resets_the_window_and_accepts(self):
        w = DedupWindow(window=8)
        for seq in range(100, 108):
            assert w.accept(1, 10, seq) == "accept"
        # re-home: the client starts a fresh epoch (new SenderStamp, seq
        # from 0) and re-stamps the replay under it — accepted, and the
        # new life's window is clean
        assert w.accept(1, 11, 1) == "accept"
        assert w.accept(1, 11, 1) == "duplicate"  # at-least-once retry

    def test_old_home_edge_still_dedups_the_original_stamp(self):
        # the OTHER half of the invariant: the old edge (live, merely
        # partitioned away) already holds the original stamped copy —
        # a straggler duplicate of it must still drop there, so the same
        # logical update can never count at two edges
        w = DedupWindow(window=8)
        assert w.accept(1, 10, 3) == "accept"      # original delivery
        assert w.accept(1, 10, 3) == "duplicate"   # straggler copy
        # and the old life's stragglers stay dead even after the client's
        # re-home epoch reaches this edge too
        assert w.accept(1, 11, 1) == "accept"
        assert w.accept(1, 10, 4) == "stale_epoch"


class TestPayloadChecksum:
    def test_digest_is_canonical(self):
        a = [np.arange(6, dtype=np.float32).reshape(2, 3)]
        assert arrays_digest(a) == arrays_digest(
            [np.asarray(a[0], order="C")])
        b = [a[0].copy()]
        b[0][0, 0] += 1
        assert arrays_digest(a) != arrays_digest(b)
        # dtype/shape are part of identity, not just bytes
        assert arrays_digest([np.zeros(4, np.float32)]) != \
            arrays_digest([np.zeros(2, np.float64)])

    def test_wire_roundtrip_carries_digest(self):
        m = _msg()
        back = Message.deserialize(m.serialize())
        assert back.get(Message.MSG_ARG_KEY_PAYLOAD_SHA256) == \
            arrays_digest(m.get_arrays())

    def test_corrupt_frame_rejected(self):
        m = _msg()
        m.corrupt_on_wire = True
        with pytest.raises(Exception):
            Message.deserialize(m.serialize())


class TestFaultRules:
    def test_duplicate_rule_delivers_twice(self):
        sink = _Sink()
        comm = FaultyComm(sink, FaultPlan().duplicate(p=1.0), rank=1)
        comm.send_message(_msg(seq=1))
        assert len(sink.delivered) == 2
        assert [d.get(Message.MSG_ARG_KEY_SEQ)
                for d in sink.delivered] == [1, 1]

    def test_corrupt_rule_delivers_damaged_copy_and_nacks(self):
        sink = _Sink()

        class RawSink(_Sink):
            def send_message(self, m):
                self.delivered.append(m.serialize())

        raw = RawSink()
        comm = FaultyComm(raw, FaultPlan().corrupt(p=1.0), rank=1)
        with pytest.raises(TransientSendError):
            comm.send_message(_msg(seq=1))
        assert len(raw.delivered) == 1
        from fedml_tpu.core.distributed.delivery import safe_deserialize

        assert safe_deserialize(raw.delivered[0], "test") is None
        del sink

    def test_visible_loss_raises_for_retry(self):
        sink = _Sink()
        comm = FaultyComm(sink, FaultPlan().loss(1.0, visible=True), rank=1)
        with pytest.raises(TransientSendError):
            comm.send_message(_msg(seq=1))
        assert sink.delivered == []

    def test_silent_loss_stays_silent(self):
        sink = _Sink()
        comm = FaultyComm(sink, FaultPlan().loss(1.0), rank=1)
        comm.send_message(_msg(seq=1))  # no raise, no delivery
        assert sink.delivered == []

    def test_seeded_rules_reproducible(self):
        def run(seed):
            sink = _Sink()
            comm = FaultyComm(
                sink, FaultPlan().duplicate(p=0.5, seed=seed), rank=1)
            for i in range(40):
                comm.send_message(_msg(seq=i))
            return len(sink.delivered)

        assert run(3) == run(3)
        assert 40 < run(3) < 80

    def test_delayed_link_does_not_stall_other_sends(self):
        """Satellite: delay() must deliver from a timer thread — the
        caller's thread returns immediately, so a slow link cannot stall
        the server FSM's unrelated sends. No sleeps in the asserts: the
        immediate send is checked before the delayed one ARRIVES, then the
        delayed delivery is awaited on an event."""
        delivered = threading.Event()

        class EventSink(_Sink):
            def send_message(self, m):
                super().send_message(m)
                if m.get_sender_id() == 9:
                    delivered.set()

        sink = EventSink()
        plan = FaultPlan().delay(0.3, sender=9)
        comm = FaultyComm(sink, plan, rank=9)
        slow = _msg(seq=1)
        slow.sender_id = 9
        slow.add(Message.MSG_ARG_KEY_SENDER, 9)
        slow.init(slow.get_params())
        t0 = time.perf_counter()
        comm.send_message(slow)           # delayed 0.3s — must NOT block
        blocked_for = time.perf_counter() - t0
        fast = _msg(seq=2)                # different sender: undelayed
        comm.send_message(fast)
        assert blocked_for < 0.15, "delay() stalled the sender thread"
        assert [d.get(Message.MSG_ARG_KEY_SEQ)
                for d in sink.delivered] == [2], \
            "delayed message arrived before the undelayed one"
        assert delivered.wait(timeout=5.0)
        assert sorted(d.get(Message.MSG_ARG_KEY_SEQ)
                      for d in sink.delivered) == [1, 2]

    def test_delay_rule_can_target_a_round(self):
        sink = _Sink()
        plan = FaultPlan().delay(0.2, sender=1, round_idx=1)
        comm = FaultyComm(sink, plan, rank=1)
        m0 = _msg(seq=1)  # round 0: undelayed
        comm.send_message(m0)
        assert len(sink.delivered) == 1


def run_world(run_id, client_plans=None, n_clients=2, **kw):
    """Loopback cross-silo world (threads); returns (result, server)."""
    from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer

    def make_args(role, rank=0):
        base = dict(
            training_type="cross_silo", dataset="synthetic", model="lr",
            client_num_in_total=n_clients, client_num_per_round=n_clients,
            comm_round=2, epochs=2, batch_size=8, learning_rate=0.2,
            backend="LOOPBACK", run_id=run_id, frequency_of_the_test=1,
            role=role, rank=rank,
        )
        base.update(kw)
        return fedml.init(Arguments(overrides=base), should_init_logs=False)

    args_s = make_args("server")
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)
    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args("client", rank)
        if client_plans and rank in client_plans:
            args_c.fault_plan = client_plans[rank]
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    result = server.run()
    return result, server


class TestEndToEndDelivery:
    def test_duplicated_model_never_double_counts(self):
        """Every client message duplicated on the wire: the server's dedup
        window drops the copies — per-round contribution counters all 1."""
        plans = {r: FaultPlan().duplicate(p=1.0) for r in (1, 2)}
        before = telemetry.registry().snapshot()["counters"].get(
            "comm.dedup_drops", 0)
        result, server = run_world("dup1", plans)
        assert server.manager.round_idx == 2
        for rnd, per in server.manager.contrib_counts.items():
            assert all(v == 1 for v in per.values()), (rnd, per)
        after = telemetry.registry().snapshot()["counters"].get(
            "comm.dedup_drops", 0)
        assert after > before  # the duplicates really flowed and were cut
        assert result["test_acc"] > 0.4

    def test_visible_loss_retried_to_completion(self):
        """50% visible loss on every client link: the at-least-once retry
        delivers everything; no round aggregates a partial cohort."""
        plans = {r: FaultPlan().loss(0.5, seed=11 + r, visible=True)
                 for r in (1, 2)}
        before = telemetry.registry().snapshot()["counters"].get(
            "comm.send_retries", 0)
        result, server = run_world("loss1", plans,
                                   comm_retry_backoff_s=0.01)
        assert server.manager.round_idx == 2
        for rnd, per in server.manager.contrib_counts.items():
            assert sorted(per) == [1, 2] and all(
                v == 1 for v in per.values())
        assert telemetry.registry().snapshot()["counters"].get(
            "comm.send_retries", 0) > before
        assert result["test_acc"] > 0.4

    def test_corrupt_payload_rejected_and_resent(self):
        """30% payload corruption: receivers drop damaged frames (counted),
        the NACKed sender re-delivers clean copies, training completes."""
        plans = {r: FaultPlan().corrupt(p=0.3, seed=5 + r) for r in (1, 2)}
        before = telemetry.registry().snapshot()["counters"].get(
            "comm.corrupt_payloads", 0)
        result, server = run_world("cor1", plans,
                                   comm_retry_backoff_s=0.01)
        assert server.manager.round_idx == 2
        for rnd, per in server.manager.contrib_counts.items():
            assert sorted(per) == [1, 2] and all(
                v == 1 for v in per.values())
        assert telemetry.registry().snapshot()["counters"].get(
            "comm.corrupt_payloads", 0) > before
        assert result["test_acc"] > 0.4


class TestClientReplayGuard:
    def test_replayed_sync_resends_cached_result_without_retraining(self):
        """A replayed INIT/SYNC for the round the client last answered must
        RE-SEND the cached stamped message (a restarted server that lost
        the in-flight round needs it; a live server dedups it by seq) —
        and must NOT retrain. Older rounds are dropped outright."""
        import jax

        from fedml_tpu.cross_silo.client_manager import ClientMasterManager
        from fedml_tpu.cross_silo.message_define import MyMessage
        from fedml_tpu.ml.trainer import create_model_trainer

        args = fedml.init(Arguments(overrides=dict(
            training_type="cross_silo", dataset="synthetic", model="lr",
            client_num_in_total=1, client_num_per_round=1, comm_round=2,
            epochs=1, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
            run_id=f"replay-{os.getpid()}", role="client", rank=1,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        trainer = create_model_trainer(bundle, args)
        trainer.set_id(1)
        mgr = ClientMasterManager(args, trainer, rank=1, size=2,
                                  dataset=ds)
        sent, trains = [], []
        mgr.send_message = lambda m: sent.append(m)
        orig_train = trainer.train
        trainer.train = lambda *a, **k: (trains.append(1),
                                         orig_train(*a, **k))[1]

        def sync_msg(round_idx):
            m = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, 1)
            m.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, round_idx)
            m.set_arrays([np.asarray(l) for l in jax.tree.leaves(
                bundle.init(jax.random.PRNGKey(0)))])
            return m

        mgr._on_sync(sync_msg(0))
        assert len(trains) == 1 and len(sent) == 1
        first = sent[0]
        # replay of the SAME round: cached message re-sent verbatim
        mgr._on_sync(sync_msg(0))
        assert len(trains) == 1, "replayed SYNC retrained"
        assert len(sent) == 2 and sent[1] is first, \
            "replay must re-send the cached stamped message"
        # an OLDER round is stale: dropped, nothing sent
        mgr._on_sync(sync_msg(-1))
        assert len(sent) == 2 and len(trains) == 1
        # a NEWER round trains normally
        mgr._on_sync(sync_msg(1))
        assert len(trains) == 2 and len(sent) == 3
