"""graftrep determinism & round-equivalence tests (tools/graftrep — ISSUE 10).

Pins six guarantees:

1. **Per-rule fixtures**: each of D001–D005 fires on its known-bad snippet
   with exact rule ids and line numbers, and stays silent on the known-good
   twin (``tests/fixtures/graftrep/``).
2. **Suppression machinery**: inline ``# graftrep: disable=D00X`` pragmas
   (graftlint's parser under graftrep's marker) and the baseline
   round-trip.
3. **Tier-1 gate**: the shipped tree has ZERO non-baselined findings and
   the checked-in baseline is EMPTY — the determinism discipline holds
   everywhere the bitwise guarantees reach (the D001 dogfood fixes in
   ml/local_train.py and cross_silo/trainer_dist_adapter.py stay fixed).
4. **Canonicalization**: alpha-renaming, dead code, and equation order
   cannot produce false divergences; changed constants cannot hide.
5. **--equiv**: the fused mirror (``round_engine.build_round_core``) is
   structurally equal to ``_train_round`` for FedAvg/FedOpt/SCAFFOLD, and
   a deliberately-skewed mirror is caught with the first diverging
   canonical equation named.
6. **Exit codes**: 0 clean / 1 findings / 2 analyzer crash, shared with
   the sibling suites.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftlint import baseline as baseline_mod  # noqa: E402
from tools.graftrep.analyzer import (  # noqa: E402
    analyze_paths,
    default_baseline_path,
)

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftrep")
TREE = os.path.join(REPO_ROOT, "fedml_tpu")


def _findings(*names):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return analyze_paths(paths, repo_root=REPO_ROOT)


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


class TestRuleFixtures:
    """Exact rule ids + line numbers on known-bad, silence on known-good."""

    def test_d001_bad(self):
        fs = _findings("d001_bad.py")
        assert {f.rule for f in fs} == {"D001"}
        # 7: sampler twice; 13: derive-after-consume; 20: loop consumption;
        # 28: consumed key captured by a closure; 39: reuse after a helper
        # whose summary consumes its key param
        assert _rule_lines(fs, "D001") == [7, 13, 20, 28, 39]

    def test_d001_good(self):
        assert _findings("d001_good.py") == []

    def test_d002_bad(self):
        fs = _findings("d002_bad.py")
        assert {f.rule for f in fs} == {"D002"}
        # 10: PRNGKey(time.time()); 15: RandomState from urandom (dataflow);
        # 19: bare np.random sampler; 24: wall-clock inside traced code
        assert _rule_lines(fs, "D002") == [10, 15, 19, 24]

    def test_d002_good(self):
        assert _findings("d002_good.py") == []

    def test_d003_bad(self):
        fs = _findings("d003_bad.py")
        assert {f.rule for f in fs} == {"D003"}
        # 8: float += over a set; 14: jnp.stack over a set-built list;
        # 24: message fan-out over a shared attr dict; 27: sum over a
        # shared attr set
        assert _rule_lines(fs, "D003") == [8, 14, 24, 27]

    def test_d003_good(self):
        assert _findings("d003_good.py") == []

    def test_d004_bad(self):
        fs = _findings("d004_bad.py")
        assert {f.rule for f in fs} == {"D004"}
        # 9: np.float64(); 10: astype(float); 11: dtype=np.float64 kw;
        # 17: numpy reducer inside traced code
        assert _rule_lines(fs, "D004") == [9, 10, 11, 17]

    def test_d004_good(self):
        assert _findings("d004_good.py") == []

    def test_d005_bad(self):
        fs = _findings("d005_bad.py")
        assert {f.rule for f in fs} == {"D005"}
        # 8: wall-clock into commit_round; 13: hostname into the
        # _ledger_world dict; 17: wall-clock gating send_message
        assert _rule_lines(fs, "D005") == [8, 13, 17]

    def test_d005_good(self):
        assert _findings("d005_good.py") == []


class TestSuppression:
    def test_pragma_suppresses_on_its_line(self):
        assert _findings("d001_pragma.py") == []

    def test_baseline_round_trip(self, tmp_path):
        fs = _findings("d001_bad.py")
        assert fs
        path = tmp_path / "baseline.json"
        baseline_mod.save(str(path), fs, tool="graftrep")
        new, old = baseline_mod.split(fs, baseline_mod.load(str(path)))
        assert new == []
        assert len(old) == len(fs)

    def test_baseline_is_line_number_free(self, tmp_path):
        fs = _findings("d001_bad.py")
        keys = {f.baseline_key() for f in fs}
        assert all("::" in k for k in keys)
        assert not any(str(f.line) in k.split("::")[0] for f, k in
                       zip(fs, sorted(keys)))


class TestTreeGate:
    """The shipped tree is clean and the checked-in baseline is EMPTY."""

    def test_tree_zero_findings(self):
        fs = analyze_paths([TREE], repo_root=REPO_ROOT)
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_checked_in_baseline_empty(self):
        path = default_baseline_path(REPO_ROOT)
        with open(path) as f:
            payload = json.load(f)
        assert payload["findings"] == {}

    def test_dogfood_fixes_hold(self):
        """The two real D001 fixes: the epoch key fans out BEFORE the
        permutation consumes anything (a regression re-introducing
        fold_in on the consumed key would fire D001 again)."""
        for rel in ("fedml_tpu/ml/local_train.py",
                    "fedml_tpu/cross_silo/trainer_dist_adapter.py"):
            src = open(os.path.join(REPO_ROOT, rel)).read()
            assert "jax.random.split(erng)" in src, rel
            fs = analyze_paths([os.path.join(REPO_ROOT, rel)],
                               repo_root=REPO_ROOT)
            assert [f for f in fs if f.rule == "D001"] == []


class TestCanonicalization:
    """Alpha-renaming / dead code / eqn order / constant content."""

    def test_alpha_and_name_invariance(self):
        import jax
        import jax.numpy as jnp

        from tools.graftrep.equiv import canonicalize, diff_canonical

        def f(x, y):
            a = x * 2.0
            b = a + y
            return jnp.sum(b)

        def g(p, q):
            left = p * 2.0
            out = left + q
            return jnp.sum(out)

        ca = canonicalize(jax.make_jaxpr(f)(jnp.ones(3), jnp.ones(3)))
        cb = canonicalize(jax.make_jaxpr(g)(jnp.ones(3), jnp.ones(3)))
        assert diff_canonical(ca, cb) is None

    def test_dead_code_removed(self):
        import jax
        import jax.numpy as jnp

        from tools.graftrep.equiv import canonicalize, diff_canonical

        def lean(x):
            return x * 3.0

        def chatty(x):
            _unused = jnp.sum(x ** 2)  # dead: not returned
            return x * 3.0

        ca = canonicalize(jax.make_jaxpr(lean)(jnp.ones(3)))
        cb = canonicalize(jax.make_jaxpr(chatty)(jnp.ones(3)))
        assert diff_canonical(ca, cb) is None

    def test_parallel_safe_order_canonicalizes(self):
        import jax
        import jax.numpy as jnp

        from tools.graftrep.equiv import canonicalize, diff_canonical

        def ab(x, y):
            a = jnp.sin(x)
            b = jnp.cos(y)
            return a + b

        def ba(x, y):
            b = jnp.cos(y)
            a = jnp.sin(x)
            return a + b

        ca = canonicalize(jax.make_jaxpr(ab)(jnp.ones(3), jnp.ones(3)))
        cb = canonicalize(jax.make_jaxpr(ba)(jnp.ones(3), jnp.ones(3)))
        assert diff_canonical(ca, cb) is None

    def test_changed_constant_diverges(self):
        import jax
        import jax.numpy as jnp

        from tools.graftrep.equiv import canonicalize, diff_canonical

        def f(x):
            return x * 2.0

        def g(x):
            return x * 3.0

        ca = canonicalize(jax.make_jaxpr(f)(jnp.ones(3)))
        cb = canonicalize(jax.make_jaxpr(g)(jnp.ones(3)))
        delta = diff_canonical(ca, cb)
        assert delta is not None
        idx, la, lb = delta
        assert la != lb


class TestEquiv:
    """--equiv: the fused mirror is structurally equal to _train_round."""

    def test_mirrors_match_all_optimizers(self):
        from tools.graftrep.equiv import check_round_equivalence

        findings, report = check_round_equivalence(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)
        assert {r["optimizer"] for r in report} == {
            "FedAvg", "FedOpt", "SCAFFOLD"}
        assert all(r["equal"] for r in report), report
        assert all(r["eqn_count_fused"] > 10 for r in report), report

    def test_skewed_mirror_is_caught(self):
        """A deliberately-drifted mirror (extra scale on the new global)
        must fail with the first diverging equation named."""
        import jax

        from fedml_tpu.simulation.round_engine import build_round_core
        from tools.graftlint.runtime_check import _tiny_api
        from tools.graftrep.equiv import compare_round_paths

        def skewed_factory(api, n_cohort, n_valid):
            core = build_round_core(api, n_cohort=n_cohort, n_valid=n_valid)

            def skew(state, *rest):
                new_state, metrics = core(state, *rest)
                return dict(new_state, global_params=jax.tree.map(
                    lambda x: x * 1.0000001,
                    new_state["global_params"])), metrics

            return skew

        api = _tiny_api(dict(federated_optimizer="FedAvg"))
        row = compare_round_paths(api, core_factory=skewed_factory)
        assert row["equal"] is False
        assert isinstance(row["diverges_at"], int)
        assert row["unfused_eqn"] != row["fused_eqn"]

    def test_equiv_rides_json_payload(self):
        """`--equiv --json` reports per-optimizer verdicts under "equiv"
        (run on a single config via the finding-free CLI path is too slow
        to repeat — reuse the cached report shape instead)."""
        from tools.graftrep.equiv import compare_round_paths
        from tools.graftlint.runtime_check import _tiny_api

        api = _tiny_api(dict(federated_optimizer="FedAvg"))
        row = compare_round_paths(api)
        assert set(row) >= {"optimizer", "equal", "eqn_count_unfused",
                            "eqn_count_fused", "diverges_at"}
        assert row["equal"] is True


class TestExitCodes:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftrep", *argv],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )

    def test_clean_file_exits_zero(self):
        p = self._run(os.path.join(FIXTURES, "d001_good.py"),
                      "--no-baseline")
        assert p.returncode == 0, p.stdout + p.stderr

    def test_findings_exit_one_with_json(self):
        p = self._run(os.path.join(FIXTURES, "d001_bad.py"),
                      "--no-baseline", "--json")
        assert p.returncode == 1, p.stdout + p.stderr
        payload = json.loads(p.stdout)
        assert payload["exit_code"] == 1
        assert payload["counts"]["D001"] == 5

    def test_missing_path_exits_two(self):
        p = self._run(os.path.join(FIXTURES, "no_such_file.py"))
        assert p.returncode == 2

    def test_lint_rep_conflict_guards(self):
        p = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint", "--rep",
             "--shard"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 2
        p = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint", "--equiv"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 2
        assert "--rep" in p.stdout
