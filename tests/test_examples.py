"""Every example and quick_start script must run from a fresh checkout.

VERDICT r2 weak #1: the example surface rotted silently because nothing
executed it — ``python examples/<any>.py`` failed with ModuleNotFoundError.
These tests run each script exactly the way the README tells a user to
(``python <script>.py`` from the repo, NO install, NO PYTHONPATH help), so a
broken run-from-checkout path or a rotted example fails CI.

The whole module is in the ``examples`` tier (each case pays a fresh
interpreter + jax import); the smoke tier runs one representative script.
"""

import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
# decentralized_dsgd is covered by the smoke-tier canary below — don't pay
# the same subprocess twice in the full gate
EXAMPLES = sorted(
    p for p in (REPO / "examples").glob("*.py")
    if p.stem != "decentralized_dsgd"
)
# cold-cache XLA:CPU compiles dominate some scripts; give the known-heavy
# ones headroom (long_context's header documents ~10 min cold)
TIMEOUTS = {"long_context_ring_attention": 1500, "fedseg_miou": 900,
            "app_tasks": 900}
PARROT = REPO / "quick_start" / "parrot"
OCTOPUS = REPO / "quick_start" / "octopus"
BEEHIVE = REPO / "quick_start" / "beehive"

SMOKE_YAML = """\
common_args:
  training_type: "simulation"
  random_seed: 0
data_args:
  dataset: "synthetic"
model_args:
  model: "lr"
train_args:
  federated_optimizer: "FedAvg"
  client_num_in_total: 8
  client_num_per_round: 4
  comm_round: 3
  epochs: 1
  batch_size: 16
  learning_rate: 0.1
validation_args:
  frequency_of_the_test: 1
"""


def _env():
    """The subprocess environment a user would have — crucially, the repo is
    NOT on PYTHONPATH (the in-file shim must do that) — on the virtual CPU
    mesh with the shared compile cache."""
    env = dict(os.environ)
    # the axon sitecustomize registers the TPU plugin (and overrides
    # jax_platforms) whenever PALLAS_AXON_POOL_IPS is set — drop it so the
    # subprocess really runs on the virtual CPU mesh
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    env["XLA_FLAGS"] = flags
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/fedml_tpu_jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and Path(p).resolve() != REPO
    )
    return env


def run_script(path: Path, args=(), timeout=None, cwd=None):
    timeout = timeout or TIMEOUTS.get(path.stem, 600)
    proc = subprocess.run(
        [sys.executable, str(path), *args],
        env=_env(), cwd=str(cwd or path.parent),
        capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{path.name} exited {proc.returncode}\n"
        f"--- stdout tail ---\n{proc.stdout[-3000:]}\n"
        f"--- stderr tail ---\n{proc.stderr[-3000:]}"
    )
    return proc


@pytest.mark.examples
@pytest.mark.parametrize("script", EXAMPLES, ids=lambda p: p.stem)
def test_example_runs(script, tmp_path):
    run_script(script, cwd=tmp_path)


@pytest.mark.examples
@pytest.mark.parametrize(
    "script",
    sorted(PARROT.glob("*.py")),
    ids=lambda p: f"parrot-{p.stem}",
)
def test_quick_start_parrot(script, tmp_path):
    """Parrot quick starts with a tiny --cf override (the shipped YAML is the
    full 1000-client benchmark config)."""
    cf = tmp_path / "smoke.yaml"
    cf.write_text(SMOKE_YAML)
    run_script(script, args=("--cf", str(cf)), cwd=tmp_path)


@pytest.mark.examples
def test_quick_start_octopus(tmp_path):
    """Server + 2 clients as 3 local processes over gRPC loopback — the
    reference's cross-silo smoke shape (tests/smoke_test/cross_silo/)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cf = tmp_path / "octopus.yaml"
    cf.write_text(SMOKE_YAML.replace(
        'training_type: "simulation"', 'training_type: "cross_silo"'
    ).replace("client_num_in_total: 8", "client_num_in_total: 2")
     .replace("client_num_per_round: 4", "client_num_per_round: 2")
     + f'comm_args:\n  backend: "GRPC"\n  comm_host: "127.0.0.1"\n'
       f"  comm_port: {port}\n")
    env = _env()
    server = subprocess.Popen(
        [sys.executable, str(OCTOPUS / "server.py"),
         "--cf", str(cf), "--rank", "0", "--role", "server"],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    time.sleep(2.0)
    clients = [
        subprocess.Popen(
            [sys.executable, str(OCTOPUS / "client.py"),
             "--cf", str(cf), "--rank", str(rank), "--role", "client"],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for rank in (1, 2)
    ]
    procs = [server, *clients]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"rc={p.returncode}\n{out[-3000:]}"


@pytest.mark.examples
def test_quick_start_beehive(tmp_path):
    run_script(BEEHIVE / "server.py", cwd=tmp_path, timeout=420)


def test_one_example_runs_in_smoke_tier(tmp_path):
    """The smoke tier keeps one end-to-end run-from-checkout canary."""
    run_script(REPO / "examples" / "decentralized_dsgd.py", cwd=tmp_path)
