"""graftmem retention tests (tools/graftmem — ISSUE 20).

Pins seven guarantees:

1. **Per-rule fixtures**: each of M001–M005 fires on its known-bad snippet
   with exact rule ids and line numbers, and stays silent on the known-good
   twin (``tests/fixtures/graftmem/``).
2. **Suppression machinery**: inline ``# graftmem: disable=M00X`` pragmas
   (graftlint's parser under graftmem's marker) and the baseline
   round-trip.
3. **Tier-1 gate**: the shipped tree has ZERO non-baselined findings and
   the checked-in baseline is EMPTY — every piece of serving-plane state
   is bounded, clamped, drained, or released (the dogfood fixes in
   delivery/tracing/flow/server/client/edge/trainer stay fixed).
4. **Retention model**: the analyzed universe reaches serving families,
   world-root classes and ctor/factory/argument-bound helpers; the
   container inventory distinguishes bounded from unbounded state.
5. **BoundedDict runtime**: capacity, LRU recency, eviction accounting and
   the ``mem.*`` occupancy/evictions telemetry the swarm leak witness
   gates on — plus dict-subclass fidelity (JSON, isinstance).
6. **Exit codes**: 0 clean / 1 findings / 2 analyzer crash, shared with
   the sibling suites; ``fedml_tpu lint --mem`` conflict guards.
7. **Dogfood regression pins**: the real fixes stay bounded — a
   pre-refactor DedupWindow (plain dict sender map) FAILS the sender-bound
   test here.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftlint import baseline as baseline_mod  # noqa: E402
from tools.graftmem.analyzer import (  # noqa: E402
    analyze_paths,
    analyze_paths_with_model,
    default_baseline_path,
)

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftmem")
TREE = os.path.join(REPO_ROOT, "fedml_tpu")


def _findings(*names):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return analyze_paths(paths, repo_root=REPO_ROOT)


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


class TestRuleFixtures:
    """Exact rule ids + line numbers on known-bad, silence on known-good."""

    def test_m001_bad(self):
        fs = _findings("m001_bad.py")
        assert {f.rule for f in fs} == {"M001"}
        # 15: handler subscript-writes a sender-keyed dict, no eviction
        assert _rule_lines(fs, "M001") == [15]

    def test_m001_good(self):
        assert _findings("m001_good.py") == []

    def test_m002_bad(self):
        fs = _findings("m002_bad.py")
        assert {f.rule for f in fs} == {"M002"}
        # 6: the capacity-less cache's definition line
        assert _rule_lines(fs, "M002") == [6]

    def test_m002_good(self):
        assert _findings("m002_good.py") == []

    def test_m003_bad(self):
        fs = _findings("m003_bad.py")
        assert {f.rule for f in fs} == {"M003"}
        # 12: sender id f-string-interpolated into the metric name
        assert _rule_lines(fs, "M003") == [12]

    def test_m003_good(self):
        assert _findings("m003_good.py") == []

    def test_m004_bad(self):
        fs = _findings("m004_bad.py")
        assert {f.rule for f in fs} == {"M004"}
        # 6: the parking set's definition line (never drained)
        assert _rule_lines(fs, "M004") == [6]

    def test_m004_good(self):
        assert _findings("m004_good.py") == []

    def test_m005_bad(self):
        fs = _findings("m005_bad.py")
        assert {f.rule for f in fs} == {"M005"}
        # 6: the Message-annotated attr's definition line (no release)
        assert _rule_lines(fs, "M005") == [6]

    def test_m005_good(self):
        assert _findings("m005_good.py") == []

    def test_rule_precedence_one_finding_per_attr(self):
        """A cache-named attr with tainted keys yields M002 only — the
        most specific rule claims the attr, never a double report."""
        fs = _findings("m002_bad.py")
        assert len(fs) == 1


class TestSuppression:
    def test_pragma_suppresses_on_its_line(self):
        assert _findings("m001_pragma.py") == []

    def test_baseline_round_trip(self, tmp_path):
        fs = _findings("m001_bad.py")
        assert fs
        path = tmp_path / "baseline.json"
        baseline_mod.save(str(path), fs, tool="graftmem")
        new, old = baseline_mod.split(fs, baseline_mod.load(str(path)))
        assert new == []
        assert len(old) == len(fs)

    def test_baseline_is_line_number_free(self):
        fs = _findings("m001_bad.py")
        keys = {f.baseline_key() for f in fs}
        assert all("::" in k for k in keys)


class TestTreeGate:
    """The shipped tree is clean and the checked-in baseline is EMPTY."""

    def test_tree_zero_findings(self):
        fs = analyze_paths([TREE], repo_root=REPO_ROOT)
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_checked_in_baseline_empty(self):
        path = default_baseline_path(REPO_ROOT)
        with open(path) as f:
            payload = json.load(f)
        assert payload["findings"] == {}

    def test_dogfood_fixes_hold(self):
        """The real fixes stay fixed: bounded containers, clamped keys and
        terminal releases in the serving plane."""
        pins = {
            "fedml_tpu/core/distributed/delivery.py":
                'name="delivery.dedup_senders"',
            "fedml_tpu/core/mlops/tracing.py":
                'name="trace.clock_estimators"',
            "fedml_tpu/core/distributed/flow.py":
                "self._ready.clear()",
            "fedml_tpu/cross_silo/server_manager.py":
                'name="server.committed_clients"',
            "fedml_tpu/cross_silo/client_manager.py":
                "self._last_model_msg = None",
            "fedml_tpu/cross_silo/trainer_dist_adapter.py":
                'name="trainer.jit_cache"',
            "fedml_tpu/hierarchy/edge_manager.py":
                'name="edge.forwarded"',
        }
        for rel, needle in pins.items():
            src = open(os.path.join(REPO_ROOT, rel)).read()
            assert needle in src, rel
        # the staleness histogram key stays clamped into a finite domain
        edge = open(os.path.join(
            REPO_ROOT, "fedml_tpu/hierarchy/edge_manager.py")).read()
        assert 'min(int(entry["staleness"]), 64)' in edge


class TestRetentionModel:
    def test_serving_and_helper_universe(self):
        _, model = analyze_paths_with_model([TREE], repo_root=REPO_ROOT)
        helpers = {c for _, c in model.helper_classes}
        # ctor-attr-bound helper
        assert "DedupWindow" in helpers
        # factory-attr-bound helper (world.trace = tracing.tracer_for(...))
        assert "Tracer" in helpers
        # local-ctor-passed-into-analyzed-ctor helper
        assert "TrainerDistAdapter" in helpers
        analyzed = {c for _, c in model.analyzed_classes}
        assert "FedMLServerManager" in analyzed
        assert "WorldScope" in analyzed  # world-root by name
        assert len(model.containers) > 20

    def test_bounded_inventory(self):
        # helper reachability needs the serving classes in scope — the
        # tree scan is what inventories DedupWindow (ctor-attr-bound)
        _, model = analyze_paths_with_model([TREE], repo_root=REPO_ROOT)
        info = model.find_container(
            "fedml_tpu.core.distributed.delivery", "DedupWindow",
            "_senders")
        assert info is not None and info.bounded


class TestBoundedDict:
    def test_capacity_evicts_oldest_first(self):
        from fedml_tpu.core.containers import BoundedDict

        d = BoundedDict(3)
        for i in range(5):
            d[i] = i * 10
        assert len(d) == 3
        assert list(d) == [2, 3, 4]
        assert d.evictions == 2

    def test_lru_read_refreshes_recency(self):
        from fedml_tpu.core.containers import BoundedDict

        d = BoundedDict(3, lru=True)
        d[1], d[2], d[3] = "a", "b", "c"
        assert d[1] == "a"       # touch: 1 becomes most-recent
        d[4] = "d"               # evicts 2, the coldest
        assert set(d) == {1, 3, 4}

    def test_setdefault_and_update_respect_capacity(self):
        from fedml_tpu.core.containers import BoundedDict

        d = BoundedDict(2)
        d.setdefault(1, []).append("x")
        assert d.setdefault(1, []) == ["x"]  # existing key untouched
        d.update({2: "b", 3: "c"})
        assert len(d) == 2

    def test_rejects_nonpositive_capacity(self):
        from fedml_tpu.core.containers import BoundedDict

        with pytest.raises(ValueError):
            BoundedDict(0)

    def test_is_json_serializable_dict(self):
        from fedml_tpu.core.containers import BoundedDict

        d = BoundedDict(4, seed={"a": 1})
        assert isinstance(d, dict)
        assert json.loads(json.dumps(d)) == {"a": 1}

    def test_mem_telemetry_family(self):
        from fedml_tpu.core.containers import BoundedDict
        from fedml_tpu.core.mlops import telemetry

        telemetry.registry().reset()
        d = BoundedDict(2, name="graftmem.test")
        d[1], d[2], d[3] = "a", "b", "c"
        snap = telemetry.registry().snapshot()
        assert snap["gauges"]["mem.graftmem.test.occupancy"] == 2.0
        assert telemetry.registry().counter(
            "mem.graftmem.test.evictions") == 1.0
        telemetry.registry().reset()


class TestDogfoodRegression:
    def test_dedup_window_sender_map_is_bounded(self):
        """Pre-refactor DedupWindow kept a plain per-sender dict — at N
        distinct senders it held N entries forever. The bounded map must
        cap at max_senders and an evicted sender must re-enter cleanly."""
        from fedml_tpu.core.distributed.delivery import DedupWindow

        w = DedupWindow(window=16, max_senders=4)
        for sender in range(10):
            assert w.accept(sender, epoch=1, seq=1) == "accept"
        assert len(w._senders) <= 4
        # evicted sender 0 re-enters as a first sighting, not a crash
        assert w.accept(0, epoch=1, seq=1) == "accept"
        # live dedup still works for a resident sender
        assert w.accept(9, epoch=1, seq=1) == "duplicate"

    def test_tracer_estimator_map_is_bounded(self):
        from fedml_tpu.core.mlops.tracing import Tracer

        t = Tracer("graftmem-test-run", 0)
        for peer in range(2000):
            t.clock_probe(peer, 0.0, 1.0, 2.0, 3.0)
        assert len(t._estimators) <= 1024

    def test_trainer_jit_cache_is_bounded(self):
        from fedml_tpu.core.containers import BoundedDict
        from fedml_tpu.cross_silo.trainer_dist_adapter import (
            TrainerDistAdapter,
        )

        class _Trainer:
            model = None

        adapter = TrainerDistAdapter(object(), _Trainer())
        assert isinstance(adapter._jitted, BoundedDict)
        assert adapter._jitted.capacity == 8


class TestExitCodes:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftmem", *argv],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )

    def test_clean_file_exits_zero(self):
        p = self._run(os.path.join(FIXTURES, "m001_good.py"),
                      "--no-baseline")
        assert p.returncode == 0, p.stdout + p.stderr

    def test_findings_exit_one_with_json(self):
        p = self._run(os.path.join(FIXTURES, "m001_bad.py"),
                      "--no-baseline", "--json")
        assert p.returncode == 1, p.stdout + p.stderr
        payload = json.loads(p.stdout)
        assert payload["exit_code"] == 1
        assert payload["counts"]["M001"] == 1
        assert "mem" in payload

    def test_missing_path_exits_two(self):
        p = self._run(os.path.join(FIXTURES, "no_such_file.py"))
        assert p.returncode == 2

    def test_lint_mem_conflict_guards(self):
        p = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint", "--mem",
             "--iso"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 2
        p = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint", "--mem",
             "--runtime"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 2
        assert "leak_check" in p.stdout
