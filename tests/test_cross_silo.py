"""Cross-silo (Octopus) tests: full FSM over the loopback backend, message
serialization fidelity, and the gRPC backend on localhost.

reference analog: ``python/tests/smoke_test/cross_silo/`` (3 local processes);
here server + clients run as threads over in-process or localhost transports.
"""

import threading
import time

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import constants
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.message import Message
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer


def make_args(run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        epochs=3, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1,
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_world(run_id: str, n_clients: int = 2, backend="LOOPBACK", **kw):
    args_s = make_args(run_id, backend=backend, role="server",
                       client_num_in_total=n_clients, **kw)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)

    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args(run_id, backend=backend, role="client", rank=rank,
                           client_num_in_total=n_clients, **kw)
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    result = server.run()
    for t in threads:
        t.join(timeout=30)
    for c in clients:
        assert c.manager.done.is_set(), "client did not reach FINISH"
    return result, server, clients


class TestMessage:
    def test_roundtrip(self):
        msg = Message("test_type", 3, 7)
        msg.add("round_idx", 4)
        arrays = [np.arange(12, dtype=np.float32).reshape(3, 4),
                  np.ones((2,), np.int32)]
        msg.set_arrays(arrays)
        back = Message.deserialize(msg.serialize())
        assert back.get_type() == "test_type"
        assert back.get_sender_id() == 3 and back.get_receiver_id() == 7
        assert back.get("round_idx") == 4
        np.testing.assert_array_equal(back.get_arrays()[0], arrays[0])
        np.testing.assert_array_equal(back.get_arrays()[1], arrays[1])

    def test_no_pickle_on_wire(self):
        """Wire format must be JSON + npz, never pickle."""
        msg = Message("t", 0, 1)
        msg.set_arrays([np.zeros(4)])
        data = msg.serialize()
        assert b"pickle" not in data
        # npz with allow_pickle defaults False on load — deserialization of
        # object arrays must fail, proving no code-execution channel
        evil = Message("t", 0, 1)
        evil.arrays = [np.array([{"a": 1}], dtype=object)]
        with pytest.raises(Exception):
            Message.deserialize(evil.serialize())


class TestCrossSiloLoopback:
    def test_full_fsm_three_rounds(self):
        result, server, clients = run_world("w1")
        assert server.manager.round_idx == 3
        assert result is not None and result["test_acc"] > 0.5

    def test_model_actually_distributed(self):
        """Clients end with the server's final global params."""
        import jax

        result, server, clients = run_world("w2")
        g = jax.tree.leaves(server.manager.global_params)
        for c in clients:
            cl = jax.tree.leaves(c.manager.trainer.get_model_params())
            for a, b in zip(g, cl):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_with_defense(self):
        result, *_ = run_world("w3", enable_defense=True,
                               defense_type="geometric_median")
        assert result["test_acc"] > 0.4

    def test_four_clients(self):
        result, server, _ = run_world("w4", n_clients=4)
        assert server.manager.round_idx == 3
        assert result["test_acc"] > 0.5


class TestCrossSiloGRPC:
    def test_full_fsm_over_grpc(self):
        result, server, clients = run_world(
            "g1", backend="GRPC", comm_port=18890, comm_host="127.0.0.1",
            comm_round=2,
        )
        assert server.manager.round_idx == 2
        assert result["test_acc"] > 0.4


class TestHierarchicalSilo:
    """VERDICT next #4: intra-silo data parallelism (reference
    cross_silo/client/{process_group_manager,fedml_client_slave_manager,
    fedml_trainer_dist_adapter}.py) — both the ICI path (one jit over a local
    silo mesh, per-step gradient psum) and the DCN path (slave FSM +
    round-level silo averaging)."""

    def test_split_silo_shard(self):
        from fedml_tpu.cross_silo.client_slave_manager import split_silo_shard

        x = np.arange(10, dtype=np.float32).reshape(10, 1)
        y = np.arange(10, dtype=np.int32)
        parts = split_silo_shard(x, y, n=7, m=2)
        assert len(parts) == 2
        assert parts[0][2] == 5 and parts[1][2] == 2  # real counts
        assert parts[0][0].shape[0] == parts[1][0].shape[0] == 5
        np.testing.assert_array_equal(parts[1][1][:2], y[5:7])

    def test_trainer_dist_adapter_matches_semantics(self):
        """The 2-device silo-DP kernel trains: loss decreases, params stay
        replicated, and padding rows don't contribute."""
        import jax

        from fedml_tpu.cross_silo.process_group import SiloProcessGroup
        from fedml_tpu.cross_silo.trainer_dist_adapter import TrainerDistAdapter
        from fedml_tpu.ml.trainer import create_model_trainer

        args = make_args("silo-adapter")
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        trainer = create_model_trainer(bundle, args)
        trainer.set_id(1)
        trainer.set_model_params(bundle.init(jax.random.PRNGKey(0)))
        adapter = TrainerDistAdapter(
            args, trainer, SiloProcessGroup(device_indices=[0, 1])
        )
        x, y, n = ds.client_shard(0)
        args.round_idx = 0
        m1 = adapter.train((x, y, n), None, args)
        args.round_idx = 1
        m2 = adapter.train((x, y, n), None, args)
        assert np.isfinite(m1["train_loss"]) and np.isfinite(m2["train_loss"])
        assert m2["train_loss"] < m1["train_loss"]
        assert m1["num_samples"] == float(n)
        for leaf in jax.tree.leaves(adapter.get_model_params()):
            assert np.all(np.isfinite(np.asarray(leaf)))

    def test_mixed_silo_world_three_rounds(self):
        """2-chip silo (ICI mesh) + 1-chip silo + DCN silo (1 master + 1
        slave) complete 3 FSM rounds and converge."""
        args_s = make_args("hier1", role="server", client_num_in_total=3)
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        server = FedMLCrossSiloServer(args_s, None, ds, bundle)

        silo_cfgs = [
            dict(silo_device_indices=[0, 1]),  # ICI: 2-chip mesh
            dict(),                            # plain 1-chip silo
            dict(silo_proc_num=2),             # DCN: master + 1 slave
        ]
        clients = []
        for rank, extra in enumerate(silo_cfgs, start=1):
            args_c = make_args("hier1", role="client", rank=rank,
                               client_num_in_total=3, **extra)
            clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        for t in threads:
            t.start()
        time.sleep(0.05)
        result = server.run()
        for t in threads:
            t.join(timeout=60)
        assert server.manager.round_idx == 3
        assert result is not None and result["test_acc"] > 0.5
        for c in clients:
            assert c.manager.done.is_set()
        # DCN slaves reached FINISH too (async wrt the master's join)
        for slave in clients[2]._slaves:
            assert slave.done.wait(timeout=30)
