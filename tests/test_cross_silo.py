"""Cross-silo (Octopus) tests: full FSM over the loopback backend, message
serialization fidelity, and the gRPC backend on localhost.

reference analog: ``python/tests/smoke_test/cross_silo/`` (3 local processes);
here server + clients run as threads over in-process or localhost transports.
"""

import threading
import time

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import constants
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.message import Message
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer


def make_args(run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        epochs=3, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1,
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_world(run_id: str, n_clients: int = 2, backend="LOOPBACK", **kw):
    args_s = make_args(run_id, backend=backend, role="server",
                       client_num_in_total=n_clients, **kw)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)

    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args(run_id, backend=backend, role="client", rank=rank,
                           client_num_in_total=n_clients, **kw)
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    result = server.run()
    for t in threads:
        t.join(timeout=30)
    for c in clients:
        assert c.manager.done.is_set(), "client did not reach FINISH"
    return result, server, clients


class TestMessage:
    def test_roundtrip(self):
        msg = Message("test_type", 3, 7)
        msg.add("round_idx", 4)
        arrays = [np.arange(12, dtype=np.float32).reshape(3, 4),
                  np.ones((2,), np.int32)]
        msg.set_arrays(arrays)
        back = Message.deserialize(msg.serialize())
        assert back.get_type() == "test_type"
        assert back.get_sender_id() == 3 and back.get_receiver_id() == 7
        assert back.get("round_idx") == 4
        np.testing.assert_array_equal(back.get_arrays()[0], arrays[0])
        np.testing.assert_array_equal(back.get_arrays()[1], arrays[1])

    def test_no_pickle_on_wire(self):
        """Wire format must be JSON + npz, never pickle."""
        msg = Message("t", 0, 1)
        msg.set_arrays([np.zeros(4)])
        data = msg.serialize()
        assert b"pickle" not in data
        # npz with allow_pickle defaults False on load — deserialization of
        # object arrays must fail, proving no code-execution channel
        evil = Message("t", 0, 1)
        evil.arrays = [np.array([{"a": 1}], dtype=object)]
        with pytest.raises(Exception):
            Message.deserialize(evil.serialize())


class TestCrossSiloLoopback:
    def test_full_fsm_three_rounds(self):
        result, server, clients = run_world("w1")
        assert server.manager.round_idx == 3
        assert result is not None and result["test_acc"] > 0.5

    def test_model_actually_distributed(self):
        """Clients end with the server's final global params."""
        import jax

        result, server, clients = run_world("w2")
        g = jax.tree.leaves(server.manager.global_params)
        for c in clients:
            cl = jax.tree.leaves(c.manager.trainer.get_model_params())
            for a, b in zip(g, cl):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_with_defense(self):
        result, *_ = run_world("w3", enable_defense=True,
                               defense_type="geometric_median")
        assert result["test_acc"] > 0.4

    def test_four_clients(self):
        result, server, _ = run_world("w4", n_clients=4)
        assert server.manager.round_idx == 3
        assert result["test_acc"] > 0.5

    def test_broker_mailbox_single_instance_under_contention(self):
        """Concurrent first-touch of one rank's mailbox must yield ONE Queue.

        The pre-r5 defaultdict broker could race ``__missing__``: two sender
        threads each built a Queue, the second dict store won, and whatever
        went through the losing instance vanished — the intermittent
        multi-hour dryrun_multichip wedge (r4 VERDICT weak #6)."""
        import threading

        from fedml_tpu.core.distributed.loopback import _Broker

        for trial in range(50):
            world = f"race-{trial}"
            broker = _Broker.get(world)
            start = threading.Barrier(8)
            got = []

            def hammer():
                start.wait()
                got.append(broker.queue_for(7))

            threads = [threading.Thread(target=hammer) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert len(got) == 8
            assert all(q is got[0] for q in got), "mailbox instance split"
            _Broker.reset(world)


class TestCrossSiloGRPC:
    def test_full_fsm_over_grpc(self):
        result, server, clients = run_world(
            "g1", backend="GRPC", comm_port=18890, comm_host="127.0.0.1",
            comm_round=2,
        )
        assert server.manager.round_idx == 2
        assert result["test_acc"] > 0.4


class TestHierarchicalSilo:
    """VERDICT next #4: intra-silo data parallelism (reference
    cross_silo/client/{process_group_manager,fedml_client_slave_manager,
    fedml_trainer_dist_adapter}.py) — both the ICI path (one jit over a local
    silo mesh, per-step gradient psum) and the DCN path (slave FSM +
    round-level silo averaging)."""

    def test_split_silo_shard(self):
        from fedml_tpu.cross_silo.client_slave_manager import split_silo_shard

        x = np.arange(10, dtype=np.float32).reshape(10, 1)
        y = np.arange(10, dtype=np.int32)
        parts = split_silo_shard(x, y, n=7, m=2)
        assert len(parts) == 2
        assert parts[0][2] == 5 and parts[1][2] == 2  # real counts
        assert parts[0][0].shape[0] == parts[1][0].shape[0] == 5
        np.testing.assert_array_equal(parts[1][1][:2], y[5:7])

    def test_trainer_dist_adapter_matches_semantics(self):
        """The 2-device silo-DP kernel trains: loss decreases, params stay
        replicated, and padding rows don't contribute."""
        import jax

        from fedml_tpu.cross_silo.process_group import SiloProcessGroup
        from fedml_tpu.cross_silo.trainer_dist_adapter import TrainerDistAdapter
        from fedml_tpu.ml.trainer import create_model_trainer

        args = make_args("silo-adapter")
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        trainer = create_model_trainer(bundle, args)
        trainer.set_id(1)
        trainer.set_model_params(bundle.init(jax.random.PRNGKey(0)))
        adapter = TrainerDistAdapter(
            args, trainer, SiloProcessGroup(device_indices=[0, 1])
        )
        x, y, n = ds.client_shard(0)
        args.round_idx = 0
        m1 = adapter.train((x, y, n), None, args)
        args.round_idx = 1
        m2 = adapter.train((x, y, n), None, args)
        assert np.isfinite(m1["train_loss"]) and np.isfinite(m2["train_loss"])
        assert m2["train_loss"] < m1["train_loss"]
        assert m1["num_samples"] == float(n)
        for leaf in jax.tree.leaves(adapter.get_model_params()):
            assert np.all(np.isfinite(np.asarray(leaf)))

    def test_mixed_silo_world_three_rounds(self):
        """2-chip silo (ICI mesh) + 1-chip silo + DCN silo (1 master + 1
        slave) complete 3 FSM rounds and converge."""
        args_s = make_args("hier1", role="server", client_num_in_total=3)
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        server = FedMLCrossSiloServer(args_s, None, ds, bundle)

        silo_cfgs = [
            dict(silo_device_indices=[0, 1]),  # ICI: 2-chip mesh
            dict(),                            # plain 1-chip silo
            dict(silo_proc_num=2),             # DCN: master + 1 slave
        ]
        clients = []
        for rank, extra in enumerate(silo_cfgs, start=1):
            args_c = make_args("hier1", role="client", rank=rank,
                               client_num_in_total=3, **extra)
            clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        for t in threads:
            t.start()
        time.sleep(0.05)
        result = server.run()
        for t in threads:
            t.join(timeout=60)
        assert server.manager.round_idx == 3
        assert result is not None and result["test_acc"] > 0.5
        for c in clients:
            assert c.manager.done.is_set()
        # DCN slaves reached FINISH too (async wrt the master's join)
        for slave in clients[2]._slaves:
            assert slave.done.wait(timeout=30)



def make_object_gateway():
    """In-process HTTP object gateway (PUT/GET/HEAD/DELETE over a dict) for
    the HttpPayloadStore tests. Returns (httpd, blobs, puts)."""
    import http.server

    import email.utils
    import time as _time

    blobs = {}
    puts = []
    mtimes = {}

    class Gateway(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _key(self):
            return self.path.lstrip("/")

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", 0))
            blobs[self._key()] = self.rfile.read(n)
            mtimes[self._key()] = _time.time()
            puts.append(self._key())
            self.send_response(201)
            self.end_headers()

        def do_GET(self):
            data = blobs.get(self._key())
            if data is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_HEAD(self):
            # real object gateways report Last-Modified; HttpPayloadStore
            # uses it to decide whether a dedup hit needs a TTL-refresh PUT
            if self._key() in blobs:
                self.send_response(200)
                self.send_header("Last-Modified", email.utils.formatdate(
                    mtimes.get(self._key(), _time.time()), usegmt=True))
            else:
                self.send_response(404)
            self.end_headers()

        def do_DELETE(self):
            blobs.pop(self._key(), None)
            self.send_response(204)
            self.end_headers()

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Gateway)
    httpd.mtimes = mtimes  # tests can age blobs to exercise TTL refresh
    return httpd, blobs, puts


class TestLivenessAndPayloadRef:
    """VERDICT next #6: dropout tolerance + payload-by-reference transport
    (reference MQTT last-will + MQTT+S3 split)."""

    def test_payload_store_roundtrip(self, tmp_path):
        from fedml_tpu.core.distributed.payload_store import PayloadStore

        store = PayloadStore(str(tmp_path))
        arrays = [np.arange(12, dtype=np.float32).reshape(3, 4),
                  np.ones((2,), np.int64)]
        key = store.new_key("model-0to1")
        store.put(key, arrays)
        back = store.get(key, delete=True)
        for a, b in zip(arrays, back):
            np.testing.assert_array_equal(a, b)
        with pytest.raises(OSError):
            store.get(key)  # consumed
        with pytest.raises(ValueError):
            store.put("../escape.npz", arrays)

    def test_http_payload_store_against_object_gateway(self, monkeypatch):
        """Object-store backend (reference: S3 remote_storage role): same
        PayloadStore contract over HTTP PUT/GET/DELETE, exercised against an
        in-process object gateway; put_dedup uploads a repeated payload once."""
        import threading

        from fedml_tpu.core.distributed.payload_store import (
            HttpPayloadStore,
            store_from_args,
        )

        httpd, blobs, puts = make_object_gateway()
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            store = store_from_args(
                type("A", (), {"payload_store_dir": url})())
            assert isinstance(store, HttpPayloadStore)
            arrays = [np.arange(12, dtype=np.float32).reshape(3, 4),
                      np.ones((2,), np.int64)]
            key = store.new_key("model")
            store.put(key, arrays)
            back = store.get(key, delete=True)
            for a, b in zip(arrays, back):
                np.testing.assert_array_equal(a, b)
            assert key not in blobs  # delete-on-read reached the gateway
            # content-addressed dedup: second identical put is a HEAD hit
            k1 = store.put_dedup(arrays)
            k2 = store.put_dedup(arrays)
            assert k1 == k2 and puts.count(k1) == 1
            # a near-expired blob is re-PUT on dedup hit so an in-flight
            # reference never points at a gateway-lifecycle sweep target
            httpd.mtimes[k1] -= store.dedup_refresh_age_s + 60
            store.put_dedup(arrays)
            assert puts.count(k1) == 2
            with pytest.raises(ValueError):
                store.put("../escape", arrays)
            # missing blob and corrupt blob both surface as OSError (the
            # receive loops' drop-message contract)
            with pytest.raises(OSError):
                store.get("missing-blob.npz")
            blobs["corrupt.npz"] = b"not an npz"
            with pytest.raises(OSError):
                store.get("corrupt.npz")
            # auth/timeout are reachable from the args surface (env token
            # would win over the args one — isolate it)
            monkeypatch.delenv("FEDML_TPU_PAYLOAD_TOKEN", raising=False)
            auth = store_from_args(type("A", (), {
                "payload_store_dir": url,
                "payload_store_auth_token": "tok123",
                "payload_store_timeout_s": 7,
            })())
            assert auth.headers["Authorization"] == "Bearer tok123"
            assert auth.timeout_s == 7.0
        finally:
            httpd.shutdown()

    def test_cross_silo_payload_by_reference(self, tmp_path):
        """Full FSM with bulk payloads riding the store: the control messages
        stay small (>=4x smaller than inline), training still converges."""
        from fedml_tpu.core.distributed.loopback import LoopbackCommManager

        sizes = []
        orig = LoopbackCommManager.send_message

        def spy(self, msg):
            sizes.append(len(msg.serialize()))
            return orig(self, msg)

        LoopbackCommManager.send_message = spy
        try:
            result, server, clients = run_world(
                "pr1", payload_store_dir=str(tmp_path),
                payload_inline_limit_bytes=64,
            )
        finally:
            LoopbackCommManager.send_message = orig
        assert result["test_acc"] > 0.5
        # every wire message is control-sized; the lr model inline would be
        # ~25 KB (3x65x4B x2 leaves + header)
        assert max(sizes) < 4096, f"bulk payload leaked onto the wire: {max(sizes)}"

    def test_cross_silo_fsm_over_http_object_store(self):
        """The full cross-silo FSM with bulk payloads riding the HTTP object
        backend (payload_store_dir = an http:// URL): cross-org Octopus with
        no shared filesystem."""
        import threading

        httpd, blobs, puts = make_object_gateway()
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            result, server, clients = run_world(
                "httpstore1",
                payload_store_dir=(
                    f"http://127.0.0.1:{httpd.server_address[1]}"
                ),
                payload_inline_limit_bytes=64,
            )
            assert result["test_acc"] > 0.5
            # the bulk channel REALLY rode the gateway (uploads happened;
            # inline fallback would leave it untouched)
            assert puts, "no payload ever reached the object gateway"
        finally:
            httpd.shutdown()

    def test_round_timeout_drops_dead_client(self):
        """4 clients; 1 dies after reporting ONLINE (never trains). With
        round_timeout the server aggregates the 3 live models and training
        completes; without it the round would hang forever."""
        n = 4
        # the deadline is ALWAYS consumed (rank 4 never answers, so round 1
        # waits it out), so keep it as small as load-safety allows: live
        # clients' training must land inside it even when the single host
        # core is starved by a parallel suite run (flaky at 3 s under load)
        args_s = make_args("live1", role="server", client_num_in_total=n,
                           round_timeout=8.0, comm_round=2)
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        server = FedMLCrossSiloServer(args_s, None, ds, bundle)

        clients = []
        for rank in range(1, n):  # ranks 1..3 are real
            args_c = make_args("live1", role="client", rank=rank,
                               client_num_in_total=n, comm_round=2)
            clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

        # rank 4: sends ONLINE, then goes silent (killed mid-round)
        from fedml_tpu.core.distributed import FedMLCommManager, Message
        from fedml_tpu.cross_silo.message_define import MyMessage

        class DeadClient(FedMLCommManager):
            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_ready
                )

            def _on_ready(self, msg):
                status = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
                                 self.rank, 0)
                status.add(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                           MyMessage.CLIENT_STATUS_ONLINE)
                self.send_message(status)
                self.finish()  # dies here: receives nothing, sends nothing

        args_d = make_args("live1", role="client", rank=n,
                           client_num_in_total=n, comm_round=2)
        dead = DeadClient(args_d, rank=n, size=n + 1, backend="LOOPBACK")

        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        threads.append(threading.Thread(target=dead.run, daemon=True))
        for t in threads:
            t.start()
        time.sleep(0.05)
        result = server.run()
        assert server.manager.round_idx == 2
        assert n in server.manager._dead
        assert result is not None and result["test_acc"] > 0.4
        for c in clients:
            # manager.join() is a no-op here (threads belong to the test,
            # not run_async), so wait on the event — asserting is_set()
            # races the last client's FINISH handling
            assert c.manager.done.wait(timeout=30)

    def test_offline_status_shrinks_expectation(self):
        """A client that declares OFFLINE mid-training is not waited for."""
        from fedml_tpu.core.distributed import FedMLCommManager, Message
        from fedml_tpu.cross_silo.message_define import MyMessage

        n = 3
        args_s = make_args("live2", role="server", client_num_in_total=n,
                           comm_round=2)
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        server = FedMLCrossSiloServer(args_s, None, ds, bundle)

        clients = []
        for rank in range(1, n):
            args_c = make_args("live2", role="client", rank=rank,
                               client_num_in_total=n, comm_round=2)
            clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

        class QuittingClient(FedMLCommManager):
            """ONLINE, then OFFLINE on INIT (graceful mid-run departure)."""

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_ready
                )
                self.register_message_receive_handler(
                    MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_init
                )

            def _on_ready(self, msg):
                s = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
                s.add(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                      MyMessage.CLIENT_STATUS_ONLINE)
                self.send_message(s)

            def _on_init(self, msg):
                s = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
                s.add(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                      MyMessage.CLIENT_STATUS_OFFLINE)
                self.send_message(s)
                self.finish()

        args_q = make_args("live2", role="client", rank=n,
                           client_num_in_total=n, comm_round=2)
        quitter = QuittingClient(args_q, rank=n, size=n + 1, backend="LOOPBACK")

        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        threads.append(threading.Thread(target=quitter.run, daemon=True))
        for t in threads:
            t.start()
        time.sleep(0.05)
        result = server.run()
        assert server.manager.round_idx == 2
        assert result is not None


class TestWireCompression:
    """VERDICT next #8: per-client update compression in the C2S message with
    error feedback (reference fedavg_seq + utils/compression.py hook)."""

    @pytest.mark.parametrize("scheme", ["eftopk", "qsgd", "quantize"])
    def test_compressed_fsm_converges(self, scheme):
        from fedml_tpu.core.distributed.loopback import LoopbackCommManager

        c2s_sizes = {}
        orig = LoopbackCommManager.send_message

        def spy(self, msg):
            if msg.get_type() == "c2s_send_model_to_server":
                c2s_sizes.setdefault(scheme, []).append(
                    sum(a.nbytes for a in msg.get_arrays())
                )
            return orig(self, msg)

        LoopbackCommManager.send_message = spy
        try:
            result, server, clients = run_world(
                f"comp-{scheme}", compression=scheme, compression_ratio=0.1,
            )
        finally:
            LoopbackCommManager.send_message = orig
        baseline, *_ = run_world(f"comp-base-{scheme}")
        assert result["test_acc"] > baseline["test_acc"] - 0.15, (
            f"{scheme}: compressed acc {result['test_acc']} too far below "
            f"uncompressed {baseline['test_acc']}"
        )
        # payload reduction >= 4x: uncompressed arrays are the full fp32
        # param vector; eftopk@0.1 sends ~10% (values+int32 indices)
        import jax

        inline_bytes = sum(
            np.asarray(l).nbytes
            for l in jax.tree.leaves(server.manager.global_params)
        )
        if scheme == "eftopk":
            assert max(c2s_sizes[scheme]) * 4 <= inline_bytes

    def test_ef_residual_reinjects_dropped_mass(self):
        """EF-TopK: mass dropped in round r re-surfaces in round r+1."""
        import jax
        import jax.numpy as jnp

        from fedml_tpu.core.compression import UpdateCodec

        class A: pass
        a = A(); a.compression = "eftopk"; a.compression_ratio = 0.25
        a.random_seed = 0
        codec = UpdateCodec(a)
        g = jnp.zeros(8)
        v = jnp.asarray([5.0, 4.0, 0.5, 0.4, 0.3, 0.2, 0.1, 0.05])
        arrays, meta = codec.encode(g, v, 0)
        r1 = UpdateCodec.decode(g, arrays, meta)
        # k=2: only the two largest survive round 1
        assert float(r1[0]) == 5.0 and float(r1[1]) == 4.0
        assert float(jnp.abs(r1[2:]).sum()) == 0.0
        # round 2 with zero new delta: residual re-emits the next-largest
        arrays2, meta2 = codec.encode(g, g, 1)
        r2 = UpdateCodec.decode(g, arrays2, meta2)
        assert float(r2[2]) == pytest.approx(0.5)
        assert float(r2[3]) == pytest.approx(0.4)
