"""MoE / expert parallelism (SURVEY §2.5 component #35, new capability)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fedml_tpu.parallel.sharding import make_mesh
from fedml_tpu.parallel.train_step import CheetahTrainer, make_optimizer
from fedml_tpu.parallel.transformer import TransformerConfig


def moe_cfg(**kw):
    base = dict(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                n_kv_heads=4, d_ff=128, max_seq_len=64, remat=False,
                moe_experts=4, moe_capacity_factor=2.0)
    base.update(kw)
    return TransformerConfig(**base)


class TestMoELayer:
    def test_forward_and_aux(self):
        from fedml_tpu.parallel.moe import MoEFeedForward

        cfg = moe_cfg()
        layer = MoEFeedForward(cfg)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 64), jnp.bfloat16)
        variables = layer.init(jax.random.PRNGKey(1), x)
        (y, aux), _ = layer.apply(variables, x, mutable=["intermediates"])
        assert y.shape == x.shape
        assert np.isfinite(float(aux))
        # balanced-uniform routing gives aux ~= 1; collapse gives ~= E
        assert 0.5 < float(aux) < 4.5

    def test_expert_params_stacked(self):
        from fedml_tpu.parallel.moe import MoEFeedForward

        cfg = moe_cfg()
        layer = MoEFeedForward(cfg)
        x = jnp.zeros((1, 8, 64), jnp.bfloat16)
        variables = layer.init(jax.random.PRNGKey(0), x)
        p = jax.tree.map(
            lambda t: t.value if hasattr(t, "value") else t,
            variables["params"],
            is_leaf=lambda t: hasattr(t, "value"),
        )
        assert p["w_gate_up"].shape == (4, 64, 256)
        assert p["w_down"].shape == (4, 128, 64)


class TestTop2Routing:
    def test_top2_matches_dense_oracle_with_ample_capacity(self):
        """With capacity >= T every token reaches both chosen experts, so the
        layer must equal g1*FFN_e1(x) + g2*FFN_e2(x) computed densely."""
        from fedml_tpu.parallel.moe import MoEFeedForward

        cfg = moe_cfg(moe_top_k=2, moe_capacity_factor=float(4))  # C = 2T
        layer = MoEFeedForward(cfg)
        x = jax.random.normal(jax.random.PRNGKey(5), (1, 8, 64), jnp.float32)
        variables = layer.init(jax.random.PRNGKey(6), x)
        (y, _aux), _ = layer.apply(variables, x, mutable=["intermediates"])

        p = jax.tree.map(
            lambda t: t.value if hasattr(t, "value") else t,
            variables["params"], is_leaf=lambda t: hasattr(t, "value"),
        )
        xt = np.asarray(x.reshape(8, 64), np.float32)
        probs = np.asarray(
            jax.nn.softmax(jnp.asarray(xt) @ p["w_router"], axis=-1)
        )
        want = np.zeros_like(xt)
        for t in range(8):
            order = np.argsort(-probs[t])
            e1, e2 = int(order[0]), int(order[1])
            g = probs[t, [e1, e2]] / probs[t, [e1, e2]].sum()
            for gate, e in zip(g, (e1, e2)):
                gu = xt[t] @ np.asarray(p["w_gate_up"][e], np.float32)
                gate_h, up = np.split(gu, 2)
                h = (gate_h / (1 + np.exp(-gate_h))) * up  # silu(gate)*up
                want[t] += gate * (h @ np.asarray(p["w_down"][e], np.float32))
        np.testing.assert_allclose(
            np.asarray(y.reshape(8, 64), np.float32), want,
            rtol=2e-2, atol=2e-3,
        )

    def test_top2_second_choice_respects_leftover_capacity(self):
        """Dropped second choices pass through silently: with tight capacity
        (C = 2 slots/expert for 8 tokens x 2 routes), overflow must not
        corrupt the output."""
        from fedml_tpu.parallel.moe import MoEFeedForward

        cfg = moe_cfg(moe_top_k=2, moe_capacity_factor=float(0.5))  # C=2
        layer = MoEFeedForward(cfg)
        x = jax.random.normal(jax.random.PRNGKey(7), (1, 8, 64), jnp.float32)
        variables = layer.init(jax.random.PRNGKey(8), x)
        (y, aux), _ = layer.apply(variables, x, mutable=["intermediates"])
        assert y.shape == x.shape and np.isfinite(float(aux))
        assert np.isfinite(np.asarray(y, np.float32)).all()

    def test_tight_capacity_matches_priority_oracle(self):
        """At overflowing capacity the kept set follows GShard priority —
        per expert: first choices (in token order), then second choices;
        everything past C drops. Pinned against a python oracle so the r5
        sort-based dispatch provably preserves the r4 cumsum semantics."""
        from fedml_tpu.parallel.moe import MoEFeedForward

        T, E, C = 8, 4, 2
        cfg = moe_cfg(moe_top_k=2, moe_capacity_factor=float(0.5))  # C=2
        layer = MoEFeedForward(cfg)
        x = jax.random.normal(jax.random.PRNGKey(11), (1, T, 64), jnp.float32)
        variables = layer.init(jax.random.PRNGKey(12), x)
        (y, _aux), _ = layer.apply(variables, x, mutable=["intermediates"])

        p = jax.tree.map(
            lambda t: t.value if hasattr(t, "value") else t,
            variables["params"], is_leaf=lambda t: hasattr(t, "value"),
        )
        xt = np.asarray(x.reshape(T, 64), np.float32)
        probs = np.asarray(
            jax.nn.softmax(jnp.asarray(xt) @ p["w_router"], axis=-1)
        )
        e1 = probs.argmax(-1)
        probs2 = probs.copy()
        probs2[np.arange(T), e1] = 0
        e2 = probs2.argmax(-1)
        # assignment priority order: all first choices, then all seconds
        load = {e: 0 for e in range(E)}
        kept = set()
        for j, e in enumerate(np.concatenate([e1, e2])):
            if load[int(e)] < C:
                kept.add(j)
                load[int(e)] += 1
        want = np.zeros_like(xt)
        for t in range(T):
            g1, g2 = probs[t, e1[t]], probs2[t, e2[t]]
            denom = g1 + g2
            for j, (gate, e) in ((t, (g1 / denom, e1[t])),
                                 (T + t, (g2 / denom, e2[t]))):
                if j not in kept:
                    continue
                gu = xt[t] @ np.asarray(p["w_gate_up"][e], np.float32)
                gate_h, up = np.split(gu, 2)
                h = (gate_h / (1 + np.exp(-gate_h))) * up
                want[t] += gate * (h @ np.asarray(p["w_down"][e], np.float32))
        np.testing.assert_allclose(
            np.asarray(y.reshape(T, 64), np.float32), want,
            rtol=2e-2, atol=2e-3,
        )

    def test_top2_trains(self):
        cfg = moe_cfg(moe_top_k=2)
        mesh = make_mesh({"fsdp": 1}, devices=jax.devices()[:1])
        tr = CheetahTrainer(cfg, mesh, optimizer=make_optimizer(
            3e-3, warmup_steps=2, total_steps=50))
        state = tr.init_state(jax.random.PRNGKey(3))
        rng = np.random.RandomState(3)
        tok = jnp.asarray(rng.randint(0, 128, (4, 64)).astype(np.int32))
        m = jnp.ones((4, 64), jnp.int32)
        first = None
        for _ in range(15):
            state, metrics = tr.train_step(state, tok, m)
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first - 0.5

    def test_top2_expert_parallel_mesh(self):
        cfg = moe_cfg(moe_top_k=2)
        mesh = make_mesh({"data": 2, "expert": 2, "fsdp": 2})
        tr = CheetahTrainer(cfg, mesh, optimizer=make_optimizer(1e-3))
        state = tr.init_state(jax.random.PRNGKey(4))
        rng = np.random.RandomState(4)
        tok = jnp.asarray(rng.randint(0, 128, (4, 64)).astype(np.int32))
        m = jnp.ones((4, 64), jnp.int32)
        state, metrics = tr.train_step(state, tok, m)
        assert np.isfinite(float(metrics["loss"]))


class TestMoETraining:
    def test_moe_transformer_trains_single_device(self):
        cfg = moe_cfg()
        mesh = make_mesh({"fsdp": 1}, devices=jax.devices()[:1])
        tr = CheetahTrainer(cfg, mesh, optimizer=make_optimizer(
            3e-3, warmup_steps=2, total_steps=50))
        state = tr.init_state(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tok = jnp.asarray(rng.randint(0, 128, (4, 64)).astype(np.int32))
        m = jnp.ones((4, 64), jnp.int32)
        first = None
        for _ in range(15):
            state, metrics = tr.train_step(state, tok, m)
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first - 0.5

    def test_moe_expert_parallel_mesh(self):
        """Expert weights sharded over the expert axis; one step executes."""
        cfg = moe_cfg()
        mesh = make_mesh({"data": 2, "expert": 2, "fsdp": 2})
        tr = CheetahTrainer(cfg, mesh, optimizer=make_optimizer(1e-3))
        state = tr.init_state(jax.random.PRNGKey(0))
        # expert weights actually sharded over the expert mesh axis
        gu = state.params["Block_0"]["MoEFeedForward_0"]["w_gate_up"]
        spec = gu.sharding.spec
        assert "expert" in str(spec), spec
        rng = np.random.RandomState(1)
        tok = jnp.asarray(rng.randint(0, 128, (4, 64)).astype(np.int32))
        m = jnp.ones((4, 64), jnp.int32)
        state, metrics = tr.train_step(state, tok, m)
        assert np.isfinite(float(metrics["loss"]))
