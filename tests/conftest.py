"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax backend
initialisation, so multi-chip sharding paths are exercised without TPU hardware
(SURVEY.md §4: multi-host emulation via --xla_force_host_platform_device_count).

The environment pins JAX_PLATFORMS=axon (the TPU tunnel), so we must override
via jax.config, not the env var.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
