"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax backend
initialisation, so multi-chip sharding paths are exercised without TPU hardware
(SURVEY.md §4: multi-host emulation via --xla_force_host_platform_device_count).

The environment pins JAX_PLATFORMS=axon (the TPU tunnel), so we must override
via jax.config, not the env var.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: this box has ONE host core, so XLA:CPU
# compiles dominate suite wall-clock; caching them across runs cuts repeat
# suites from tens of minutes to minutes.
_cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                            "/tmp/fedml_tpu_jax_cache")
os.makedirs(_cache_dir, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
