"""Tests for auxiliary subsystems: Flow DSL, MLOps-lite tracing, CLI,
cross-device artifact server (SURVEY.md §2.3 flow, §2.10 mlops/cli, §2.8).
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.alg_frame import Params
from fedml_tpu.core.distributed.flow import (
    FLOW_TAG_FINISH,
    FLOW_TAG_ONCE,
    ROLE_CLIENT,
    ROLE_SERVER,
    FedMLAlgorithmFlow,
    FedMLExecutor,
)


def make_args(run_id, **kw):
    base = dict(dataset="synthetic", model="lr", client_num_in_total=2,
                client_num_per_round=2, comm_round=2, epochs=1, batch_size=8,
                run_id=run_id)
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


class TestFlowDSL:
    def test_two_executor_flow(self):
        """reference analog: core/distributed/flow/test_fedml_flow.py —
        server init → clients work → server gathers → finish."""
        world = 3  # 1 server + 2 clients
        record = {"client_runs": 0, "server_gathers": 0}
        lock = threading.Lock()

        def server_init(executor):
            p = Params()
            p.add("w", np.zeros(4, np.float32))
            p.add("round", 0)
            return p

        def client_work(executor):
            p = executor.get_params()
            out = Params()
            out.add("w", np.asarray(p.get("w")) + 1.0)
            out.add("round", p.get("round"))
            with lock:
                record["client_runs"] += 1
            return out

        gathered = []

        def server_gather(executor):
            p = executor.get_params()
            with lock:
                record["server_gathers"] += 1
                gathered.append(np.asarray(p.get("w")))
            out = Params()
            out.add("w", np.mean(gathered, axis=0))
            out.add("round", int(p.get("round")) + 1)
            return out

        def server_finish(executor):
            return executor.get_params()

        flows = []
        for rank in range(world):
            args = make_args("flow1", rank=rank)  # shared loopback world
            ex = FedMLExecutor(id=rank)
            flow = FedMLAlgorithmFlow(args, ex, rank=rank, size=world)
            flow.add_flow("init", server_init, ROLE_SERVER, FLOW_TAG_ONCE)
            flow.add_flow("local_work", client_work, ROLE_CLIENT)
            flow.add_flow("gather", server_gather, ROLE_SERVER)
            flow.add_flow("finish", server_finish, ROLE_SERVER, FLOW_TAG_FINISH)
            flow.build()
            flows.append(flow)

        threads = [f.run_async() for f in flows]
        deadline = time.time() + 30
        for f in flows:
            f.done.wait(timeout=max(deadline - time.time(), 0.1))
        for f in flows:
            assert f.done.is_set(), "flow did not complete"
        assert record["client_runs"] == 2  # both clients ran
        assert record["server_gathers"] >= 1
        # final params propagated to clients
        for f in flows[1:]:
            assert f.executor.get_params() is not None
            assert "w" in f.executor.get_params()


class TestMLOps:
    def test_event_jsonl_written(self, tmp_path):
        args = make_args("mlops1", enable_tracking=True)
        args.tracking_dir = str(tmp_path)
        from fedml_tpu.core import mlops

        mlops.init(args)
        with mlops.MLOpsProfilerEvent("train"):
            pass
        mlops.log({"acc": 0.5}, step=1)
        mlops.log_round_info(1, 10)
        events = mlops.read_events()
        kinds = [e["kind"] for e in events]
        assert "event" in kinds and "metrics" in kinds and "round_info" in kinds
        started = [e for e in events if e.get("phase") == "started"]
        ended = [e for e in events if e.get("phase") == "ended"]
        assert len(started) == 1 and len(ended) == 1
        assert ended[0]["event_value"].endswith("s")

    def test_disabled_is_noop(self, tmp_path):
        args = make_args("mlops2", enable_tracking=False)
        from fedml_tpu.core import mlops

        mlops.init(args)
        mlops.log({"x": 1})  # must not raise nor write
        assert mlops.MLOpsStore.jsonl_path is None or not os.path.exists(
            mlops.MLOpsStore.jsonl_path
        ) or True


class TestCLI:
    def test_version_env_status(self, capsys):
        from fedml_tpu.cli import main

        assert main(["version"]) == 0
        assert "fedml_tpu version" in capsys.readouterr().out
        assert main(["env"]) == 0
        out = capsys.readouterr().out
        assert "jax:" in out and "python:" in out

    def test_build_package(self, tmp_path, capsys):
        src = tmp_path / "app"
        src.mkdir()
        (src / "main.py").write_text("print('hi')\n")
        (src / "config.yaml").write_text("a: 1\n")
        out = tmp_path / "pkg.zip"
        from fedml_tpu.cli import main

        rc = main(["build", "-sf", str(src), "-ep", "main.py",
                   "-o", str(out), "-t", "client"])
        assert rc == 0 and out.exists()
        import zipfile

        with zipfile.ZipFile(out) as z:
            names = z.namelist()
            assert "main.py" in names and "fedml_package.json" in names
            manifest = json.loads(z.read("fedml_package.json"))
            assert manifest["entry_point"] == "main.py"

    def test_build_missing_entry(self, tmp_path):
        src = tmp_path / "app"
        src.mkdir()
        from fedml_tpu.cli import main

        assert main(["build", "-sf", str(src), "-ep", "nope.py"]) == 1


class TestCrossDevice:
    def test_artifact_roundtrip_and_aggregation(self, tmp_path):
        from fedml_tpu.cross_device import (
            ServerMNN,
            read_artifact_as_tensor_dict,
        )
        from fedml_tpu.cross_device.server import (
            params_to_tensor_dict,
            tensor_dict_to_params,
            write_tensor_dict_to_artifact,
        )

        args = make_args("xd1", comm_round=1)
        args.global_model_file_path = str(tmp_path / "global.npz")
        args.device_upload_dir = str(tmp_path / "uploads")
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        server = ServerMNN(args, None, ds, bundle)
        server.publish_global_model()
        assert os.path.exists(args.global_model_file_path)

        # simulate two devices: download global, perturb, upload
        td = read_artifact_as_tensor_dict(args.global_model_file_path)
        os.makedirs(args.device_upload_dir, exist_ok=True)
        for i, delta in enumerate((0.5, 1.5)):
            up = {k: v + delta for k, v in td.items()}
            write_tensor_dict_to_artifact(
                up, os.path.join(args.device_upload_dir, f"client_{i}.npz")
            )
            with open(os.path.join(args.device_upload_dir,
                                   f"client_{i}.samples"), "w") as f:
                f.write("10")
        server.run_one_round()
        # equal weights → aggregate = global + 1.0
        agg = read_artifact_as_tensor_dict(args.global_model_file_path)
        for k in td:
            np.testing.assert_allclose(agg[k], td[k] + 1.0, atol=1e-5)

        # roundtrip params <-> tensor dict
        back = tensor_dict_to_params(server.global_params,
                                     params_to_tensor_dict(server.global_params))
        for a, b in zip(jax.tree.leaves(back),
                        jax.tree.leaves(server.global_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))


class TestCrossDeviceLSA:
    """VERDICT missing #10: secure aggregation on the artifact server
    (reference cross_device/server_mnn_lsa)."""

    def test_masked_roundtrip_with_dropout(self, tmp_path):
        import jax

        import fedml_tpu as fedml
        from fedml_tpu import data as data_mod, models as model_mod
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.cross_device import DeviceLSA, ServerMNNLSA
        from fedml_tpu.utils.tree import tree_flatten_to_vector

        N, U, T = 4, 3, 1
        args = fedml.init(Arguments(overrides=dict(
            training_type="cross_device", dataset="synthetic", model="lr",
            client_num_in_total=N, client_num_per_round=N, comm_round=1,
            batch_size=8, lsa_privacy_guarantee=T, lsa_surviving_threshold=U,
            device_upload_dir=str(tmp_path),
            global_model_file_path=str(tmp_path / "global.npz"),
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        server = ServerMNNLSA(args, None, ds, bundle)
        server.publish_global_model()

        dim = server._dim
        rng = np.random.RandomState(7)
        device_vecs = [rng.randn(dim).astype(np.float32) * 0.1 for _ in range(N)]
        devices = [DeviceLSA(d, str(tmp_path), N, U, T) for d in range(N)]
        for d in devices:
            d.write_shares(dim)
        # device 3 DROPS OUT: uploads nothing after the share phase
        for d in devices[:3]:
            d.write_masked_model(device_vecs[d.d_id], 10.0)
        assert server.run_one_round() is None  # names survivors, waits
        import json as _json

        with open(tmp_path / "survivors.json") as f:
            survivors = _json.load(f)
        assert survivors == [0, 1, 2]
        for d in devices[:3]:
            d.write_aggregate_share(survivors)
        res = server.run_one_round()
        assert res is not None and server.round_idx == 1
        # the aggregate equals the survivors' plain average (quantization
        # tolerance), even though the server never saw an unmasked model
        got, _, _ = tree_flatten_to_vector(server.global_params)
        want = np.mean(device_vecs[:3], axis=0)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-2)

    def test_masked_upload_hides_model(self, tmp_path):
        """The masked artifact is field-uniform — nowhere near the model."""
        from fedml_tpu.cross_device import DeviceLSA
        from fedml_tpu.core.mpc import lightsecagg as lsa

        dim = 256
        dev = DeviceLSA(0, str(tmp_path), 3, 2, 1)
        dev.write_shares(dim)
        vec = np.zeros(dim, np.float32)  # all-zero model
        dev.write_masked_model(vec, 1.0)
        with np.load(tmp_path / "masked_0.npz") as z:
            masked = z["masked"]
        # an unmasked all-zero model quantizes to a constant; the upload must
        # instead look uniform over the field
        assert len(np.unique(masked)) > dim // 4
        assert masked.std() > lsa.FIELD_P / 10


class TestBackendsAndSysStats:
    def test_mqtt_backend_gated(self):
        """MQTT backend raises a clear error without paho (reference parity:
        the transport exists; broker-less pods get pointed at GRPC+store)."""
        from fedml_tpu.core.distributed.mqtt_backend import MqttCommManager

        try:
            import paho.mqtt.client  # noqa: F401

            pytest.skip("paho installed; gate not exercised")
        except ImportError:
            pass
        with pytest.raises(RuntimeError, match="paho-mqtt"):
            MqttCommManager("127.0.0.1", 1883, 0, 2)

    def test_device_stats_schema(self):
        from fedml_tpu.core import mlops

        stats = mlops.device_stats()
        assert isinstance(stats, list) and stats
        assert {"device", "mem_used_mb", "mem_util"} <= set(stats[0])
