"""Fused round engine tests (simulation/round_engine.py — ISSUE 1).

Pins the four guarantees of the fused, donated, cache-warm round engine:

1. **Numerical parity**: the fused single-program round produces the same
   global params as the legacy multi-dispatch ``_train_round`` (atol 1e-5,
   and in practice bitwise on most paths) for every FedAvg-family optimizer
   and the DP/attack/defense trust paths, on both the sp and mesh backends.
2. **Donation safety**: the round state really is donated (use-after-donate
   raises), and ``CheckpointManager.save`` copies every leaf to host BEFORE
   the next round's dispatch can invalidate the buffers — so checkpoint /
   resume under fusion matches an uninterrupted run exactly.
3. **Recompilation regression guard**: steady state is ONE compile of the
   fused ``round_step`` per (backend, optimizer) config — 5 rounds, cache
   size 1 (lowering-cache inspection via ``jit._cache_size()``).
4. **Superround**: K rounds per launch under ``lax.scan`` with on-device
   sampling — under full participation (sampling degenerates to ``arange``
   on both paths) it matches the unfused reference exactly; eval/checkpoint
   schedules are preserved by the chunker; at most two programs compile.
"""

from __future__ import annotations

import jax
import numpy as np
import orbax.checkpoint as ocp
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.simulation.mesh_api import MeshFedAvgAPI
from fedml_tpu.simulation.sp_api import FedAvgAPI


def make_api(fusion="auto", backend="sp", **kw):
    base = dict(
        dataset="synthetic", model="lr", client_num_in_total=16,
        client_num_per_round=8, comm_round=3, epochs=1, batch_size=16,
        learning_rate=0.1, frequency_of_the_test=100, round_fusion=fusion,
    )
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, od = data_mod.load(args)
    cls = MeshFedAvgAPI if backend == "mesh" else FedAvgAPI
    return cls(args, fedml.get_device(args), ds, model_mod.create(args, od))


def max_param_diff(a, b) -> float:
    la = jax.tree.leaves(a.global_params)
    lb = jax.tree.leaves(b.global_params)
    return max(
        float(np.abs(np.asarray(x) - np.asarray(y)).max())
        for x, y in zip(la, lb)
    )


class TestFusionParity:
    """Fused round_step vs the unfused reference, 3 rounds, atol 1e-5."""

    @pytest.mark.parametrize(
        "opt", ["FedAvg", "FedProx", "FedOpt", "FedNova", "SCAFFOLD", "FedSGD"]
    )
    def test_optimizer_parity(self, opt):
        kw = dict(federated_optimizer=opt)
        if opt == "FedOpt":
            kw.update(server_optimizer="adam", server_lr=0.03)
        ref = make_api("off", **kw)
        fused = make_api("on", **kw)
        assert fused._round_step is None  # built lazily
        for r in range(3):
            mr = ref.run_round(r)
            mf = fused.run_round(r)
            assert np.isclose(
                float(np.asarray(mf["train_loss"])), mr["train_loss"],
                atol=1e-5,
            )
        assert fused._round_step is not None
        assert ref._round_step is None  # "off" stays on the legacy path
        assert max_param_diff(ref, fused) < 1e-5

    @pytest.mark.parametrize("dp_type", ["cdp", "ldp"])
    def test_dp_parity(self, dp_type):
        kw = dict(enable_dp=True, dp_type=dp_type, mechanism_type="gaussian",
                  epsilon=5.0)
        ref = make_api("off", **kw)
        fused = make_api("on", **kw)
        for r in range(3):
            ref.run_round(r)
            fused.run_round(r)
        assert max_param_diff(ref, fused) < 1e-5

    def test_attack_defense_parity(self):
        kw = dict(enable_attack=True, attack_type="byzantine_random",
                  byzantine_client_frac=0.3, byzantine_scale=30.0,
                  enable_defense=True, defense_type="multikrum",
                  byzantine_client_num=3)
        ref = make_api("off", **kw)
        fused = make_api("on", **kw)
        for r in range(3):
            ref.run_round(r)
            fused.run_round(r)
        assert max_param_diff(ref, fused) < 1e-5

    @pytest.mark.parametrize("kw", [
        dict(),
        dict(client_num_per_round=6),  # cohort padding + zero-weight mask
        dict(federated_optimizer="SCAFFOLD"),
    ])
    def test_mesh_parity(self, kw):
        ref = make_api("off", backend="mesh", **kw)
        fused = make_api("on", backend="mesh", **kw)
        for r in range(3):
            ref.run_round(r)
            fused.run_round(r)
        assert max_param_diff(ref, fused) < 1e-5

    def test_blocked_configs_fall_back_and_on_raises(self):
        from fedml_tpu.ml.aggregator import DefaultServerAggregator

        base = dict(
            dataset="synthetic", model="lr", client_num_in_total=8,
            client_num_per_round=4, comm_round=1, epochs=1, batch_size=16,
            learning_rate=0.1,
        )
        # auto + custom aggregator: silently unfused
        args = fedml.init(Arguments(overrides=base), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        api = FedAvgAPI(args, fedml.get_device(args), ds, bundle,
                        server_aggregator=DefaultServerAggregator(bundle, args))
        api.run_round(0)
        assert api._round_step is None
        # on + custom aggregator: loud error
        args2 = fedml.init(
            Arguments(overrides=dict(base, round_fusion="on")),
            should_init_logs=False,
        )
        with pytest.raises(ValueError, match="cannot fuse"):
            FedAvgAPI(args2, fedml.get_device(args2), ds, bundle,
                      server_aggregator=DefaultServerAggregator(bundle, args2))
        # bad mode string: loud error
        with pytest.raises(ValueError, match="round_fusion"):
            make_api("sideways")

    def test_aggregate_override_blocks_fusion(self):
        """TurboAggregate's additive-share _aggregate must never be bypassed
        by the fused mirror — a fused round would silently degrade secure
        aggregation to a trusted-server weighted average."""
        from fedml_tpu.simulation.turboaggregate_api import TurboAggregateAPI

        base = dict(
            dataset="synthetic", model="lr", client_num_in_total=8,
            client_num_per_round=4, comm_round=1, epochs=1, batch_size=16,
            learning_rate=0.1,
        )
        args = fedml.init(Arguments(overrides=base), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        api = TurboAggregateAPI(args, fedml.get_device(args), ds, bundle)
        assert any("_aggregate" in b for b in api._fusion_blockers())
        api.run_round(0)
        assert api._round_step is None  # auto fell back to the unfused path


class TestDonationSafety:
    def test_state_is_donated(self):
        api = make_api("on")
        api.run_round(0)  # builds the program; state now holds round-0 output
        old_leaf = jax.tree.leaves(api.global_params)[0]
        api.run_round(1)  # donates round-0 buffers
        with pytest.raises(RuntimeError):
            np.asarray(old_leaf)  # use-after-donate must raise, not read junk

    def test_checkpoint_copies_to_host_before_next_dispatch(self, tmp_path):
        from fedml_tpu.checkpoint import CheckpointManager

        api = make_api("on", federated_optimizer="SCAFFOLD")
        api.run_round(0)
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        received = {}
        orig_save = mgr._mgr.save

        def spy(step, args=None, **kw):
            received["state"] = args.item
            return orig_save(step, args=args, **kw)

        mgr._mgr.save = spy
        try:
            mgr.save(api._ckpt_state(), step=0)
            # every leaf orbax sees must already be a HOST array — a device
            # reference would be invalidated by the next round's donation
            assert all(
                isinstance(leaf, np.ndarray)
                for leaf in jax.tree.leaves(received["state"])
            )
            api.run_round(1)  # donates the checkpointed device buffers
            restored = mgr.restore_latest(api._ckpt_state())
            assert restored is not None  # checkpoint survives the donation
            for leaf in jax.tree.leaves(restored):
                np.asarray(leaf)  # every restored leaf is readable
        finally:
            mgr.close()

    @pytest.mark.parametrize("opt", ["FedAvg", "FedOpt", "SCAFFOLD"])
    def test_fused_resume_matches_uninterrupted(self, tmp_path, opt):
        kw = dict(federated_optimizer=opt, round_fusion="on")
        if opt == "FedOpt":
            kw.update(server_optimizer="adam", server_lr=0.03)
        ref = make_api(comm_round=6, **kw)
        ref.train()

        ck = dict(kw, checkpoint_dir=str(tmp_path / f"ck_{opt}"))
        api1 = make_api(comm_round=3, **ck)
        api1.train()  # "crash" after 3 rounds
        api2 = make_api(comm_round=6, **ck)
        api2.train()
        assert [e["round"] for e in api2.history] == [3, 4, 5]
        assert max_param_diff(ref, api2) < 1e-6


class TestRecompilationGuard:
    """Steady state = ONE compile of round_step per (backend, optimizer)."""

    @pytest.mark.parametrize("backend", ["sp", "mesh"])
    @pytest.mark.parametrize("opt", ["FedAvg", "FedOpt"])
    def test_one_compile_across_five_rounds(self, backend, opt):
        kw = dict(federated_optimizer=opt, comm_round=5,
                  frequency_of_the_test=2)
        if opt == "FedOpt":
            kw.update(server_optimizer="adam", server_lr=0.03)
        api = make_api("on", backend=backend, **kw)
        api.train()
        assert len(api.history) == 5
        # lowering-cache inspection: one entry == one compile of round_step
        assert api._round_step._cache_size() == 1

    def test_losses_realized_as_floats(self):
        api = make_api("on", comm_round=4)
        api.train()
        for e in api.history:
            assert isinstance(e["train_loss"], float)
            assert np.isfinite(e["train_loss"])


class TestSuperround:
    def _mk(self, fusion="on", **kw):
        base = dict(client_num_in_total=8, client_num_per_round=8,
                    frequency_of_the_test=1000)
        base.update(kw)
        return make_api(fusion, **base)

    def test_full_participation_matches_unfused_exactly(self):
        # full participation: both the host sampler and the on-device sampler
        # degenerate to arange, so the trajectories must coincide bit for bit
        ref = self._mk("off", comm_round=7)
        for r in range(7):
            ref.run_round(r)
        sup = self._mk("on", comm_round=7, superround_k=3)
        sup.train()
        assert [e["round"] for e in sup.history] == list(range(7))
        assert max_param_diff(ref, sup) < 1e-6
        # at most two programs: the K-scan and the single-round step
        assert sup._superround_step._cache_size() == 1
        assert sup._round_step._cache_size() <= 1

    def test_partial_participation_trains_and_is_deterministic(self):
        a = make_api("on", client_num_in_total=16, client_num_per_round=4,
                     comm_round=9, superround_k=4, frequency_of_the_test=1000)
        res_a = a.train()
        b = make_api("on", client_num_in_total=16, client_num_per_round=4,
                     comm_round=9, superround_k=4, frequency_of_the_test=1000)
        res_b = b.train()
        assert res_a["test_acc"] == pytest.approx(res_b["test_acc"])
        assert res_a["test_acc"] > 0.5
        assert [e["round"] for e in a.history] == list(range(9))

    def test_eval_schedule_preserved_under_chunking(self):
        # freq=2: an eval lands inside any 4-round chunk, so the chunker must
        # fall back to single rounds — and every eval round gets its metrics
        api = self._mk("on", comm_round=6, superround_k=4,
                       frequency_of_the_test=2)
        api.train()
        evaled = [e["round"] for e in api.history if "test_acc" in e]
        assert evaled == [0, 2, 4, 5]

    def test_superround_respects_checkpoint_schedule(self, tmp_path):
        api = self._mk("on", comm_round=8, superround_k=4,
                       checkpoint_dir=str(tmp_path / "ck"),
                       checkpoint_every_rounds=8)
        api.train()
        mgr = ocp.CheckpointManager(str(tmp_path / "ck"))
        try:
            assert mgr.latest_step() == 7
        finally:
            mgr.close()

    def test_run_rounds_helper_falls_back_without_superround(self):
        api = make_api("on", client_num_in_total=16, client_num_per_round=4,
                       comm_round=4)
        out = api.run_rounds(0, 3)  # no compiled K=3 scan: python loop
        assert len(out["train_loss"]) == 3
        assert api._superround_step is None
