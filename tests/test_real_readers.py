"""Real-format readers (VERDICT r2 next #5): stackoverflow lr/nwp, ImageNet
folders, Landmarks csv — parsed from tiny checked-in fixtures that mirror the
reference's on-disk layouts (``data/stackoverflow_nwp/``, ``data/ImageNet/
datasets.py``, ``data/Landmarks/data_loader.py``)."""

import os
import shutil
from types import SimpleNamespace

import numpy as np
import pytest

from fedml_tpu import data as data_mod

# the readers themselves degrade to synthetic without these; the fixture
# tests need them (declared in pyproject's [readers]/[test] extras)
h5py = pytest.importorskip("h5py")
PIL = pytest.importorskip("PIL")

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _args(dataset, cache_dir, **kw):
    base = dict(dataset=dataset, data_cache_dir=cache_dir,
                client_num_in_total=0, batch_size=4, random_seed=0,
                partition_method="hetero", partition_alpha=0.5)
    base.update(kw)
    return SimpleNamespace(**base)


@pytest.fixture
def staged(tmp_path):
    """Copy fixtures into a data_cache_dir the way a user would stage files."""
    def stage(sub):
        src = os.path.join(FIXTURES, sub)
        dst = tmp_path / "cache"
        shutil.copytree(src, dst, dirs_exist_ok=True)
        return str(dst)

    return stage


def test_stackoverflow_nwp_reader(staged):
    cache = staged("stackoverflow")
    ds, class_num = data_mod.load(_args("stackoverflow_nwp", cache))
    # 3 h5 clients, natural partition
    assert ds.client_num == 3
    assert ds.task == "nwp"
    # seq_len windows: x is the row shifted against y
    assert ds.train_x.shape[-1] == 20 and ds.train_y.shape[-1] == 20
    # vocab fixture has 12 words: pad=0, words 1..12, bos=13, eos=14, oov=15
    x0 = ds.train_x[0][: ds.train_counts[0]]
    assert x0[:, 0].max() == 13 and x0[:, 0].min() == 13  # every row starts bos
    # "how to fix the error" → ids for how,to,fix,the,error all in 1..12
    row = x0[0]
    assert set(row[1:6].tolist()) <= set(range(1, 13))
    # y is x shifted: y[t] == next token
    y0 = ds.train_y[0][: ds.train_counts[0]]
    np.testing.assert_array_equal(x0[0][1:], y0[0][:-1])
    # the unknown word in user_b's sentence maps to the oov bucket (15)
    ub = 1 if ds.train_counts[1] else None
    assert ub is not None
    assert (ds.train_x[1][: ds.train_counts[1]] == 15).any()
    # test split comes from the test h5
    assert ds.test_x.shape[0] == 2


def test_stackoverflow_lr_reader(staged):
    cache = staged("stackoverflow")
    ds, class_num = data_mod.load(_args("stackoverflow_lr", cache))
    assert ds.client_num == 3 and ds.task == "tagpred"
    V = ds.train_x.shape[-1]  # fixture vocab: 12 words
    assert V == 12
    # "print the list" + title "the list": all 5 tokens known → BoW sums to 1
    c0 = ds.train_x[0][: ds.train_counts[0]]
    sums = c0.sum(-1)
    assert np.isclose(sums[1], 1.0)
    # user_b's "the code zzzunknown data" + title "python" (reference joins
    # tokens + " " + title): 4/5 known → mass 0.8
    c1 = ds.train_x[1][: ds.train_counts[1]]
    assert np.isclose(c1[0].sum(), 0.8)
    # tags: fixture has 4 tags; "python|list" → two-hot
    t0 = ds.train_y[0][: ds.train_counts[0]]
    assert t0.shape[-1] == 4 and t0[0].sum() == 2.0
    # unknown tag ("mystery") dropped
    t1 = ds.train_y[1][: ds.train_counts[1]]
    assert t1[0].sum() == 1.0


def test_imagenet_folder_reader(staged):
    cache = staged("imagenet")
    ds, class_num = data_mod.load(_args("ILSVRC2012", cache))
    # natural partition: one client per class dir
    assert ds.client_num == 2
    assert class_num == 1000  # registry class space
    assert tuple(ds.train_x.shape[2:]) == (224, 224, 3)
    assert int(ds.train_counts.sum()) == 6  # 2 classes x 3 train images
    # labels: client i holds only class i
    for ci in range(2):
        y = ds.train_y[ci][: ds.train_counts[ci]]
        assert (y == ci).all()
    assert ds.test_x.shape[0] == 4  # 2 classes x 2 val images
    assert 0.0 <= float(ds.train_x.max()) <= 1.0


def test_landmarks_reader(staged):
    cache = staged("gld")
    ds, class_num = data_mod.load(_args("gld23k", cache))
    # natural partition: one client per user_id (u1: 2 imgs, u2: 3)
    assert ds.client_num == 2
    assert sorted(ds.train_counts.tolist()) == [2, 3]
    assert tuple(ds.train_x.shape[2:]) == (224, 224, 3)
    u2 = ds.train_y[1][: ds.train_counts[1]]
    assert sorted(u2.tolist()) == [0, 1, 2]
    assert ds.test_x.shape[0] == 2


def test_unstaged_falls_back_to_synthetic(tmp_path):
    """No files staged → every key still loads (synthetic fallback)."""
    for name in ("stackoverflow_nwp", "stackoverflow_lr", "gld23k"):
        ds, _ = data_mod.load(_args(name, str(tmp_path / "empty"),
                                    client_num_in_total=4))
        assert ds.client_num == 4 and ds.train_data_num > 0


def test_coco_detection_reader(staged):
    """COCO-format annotations json + image dirs (VERDICT r4 #7): sparse
    category ids remap to contiguous classes, boxes land in the right
    stride-4 cell of the dense CenterNet target, dominant-category clients
    form the natural partition."""
    import json

    cache = staged("coco_det")
    ds, class_num = data_mod.load(_args("fedcv_det224", cache))
    assert ds.task == "detection"
    # images resized to the spec resolution; dense stride-4 targets
    assert tuple(ds.train_x.shape[2:]) == (224, 224, 3)
    assert tuple(ds.train_y.shape[2:]) == (56, 56, 6 + 3)
    assert ds.meta["natural_partition"] is True
    assert 1 <= ds.client_num <= 3  # one client per dominant category
    assert ds.test_x.shape[0] == 4  # val2017 fixture images

    # cross-check one annotation against the dense target encoding
    with open(os.path.join(cache, "coco", "annotations",
                           "instances_val2017.json")) as f:
        blob = json.load(f)
    cat_map = {c["id"]: i for i, c in
               enumerate(sorted(blob["categories"], key=lambda c: c["id"]))}
    img0 = blob["images"][0]["id"]
    anns0 = [a for a in blob["annotations"] if a["image_id"] == img0]
    ty0 = np.asarray(ds.test_y[0])
    centers = np.nonzero(ty0[..., -1] > 0.5)
    assert len(centers[0]) >= 1
    # every annotated box has its center cell set with its (remapped) class
    hits = 0
    for a in anns0:
        x, y, w, h = a["bbox"]
        cy = int((y + h / 2) * 224 / 32) // 4
        cx = int((x + w / 2) * 224 / 32) // 4
        if ty0[cy, cx, -1] > 0.5 and ty0[cy, cx, cat_map[a["category_id"]]] == 1.0:
            hits += 1
    assert hits >= 1
    # sizes normalized to (0, 1]
    hw = ty0[..., 6:8][ty0[..., -1] > 0.5]
    assert (hw > 0).all() and (hw <= 1.0).all()


def test_coco_reader_unstaged_falls_back(tmp_path):
    ds, _ = data_mod.load(_args("fedcv_det224", str(tmp_path),
                                client_num_in_total=4))
    assert ds.meta.get("natural_partition") is None  # synthetic path
