"""Cheetah parallel-layer tests: transformer math, sharding rules, full
sharded train step on the 8-device virtual mesh, and the driver entry points.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.parallel.sharding import make_mesh, param_shardings, unbox
from fedml_tpu.parallel.train_step import CheetahTrainer, lm_loss, make_optimizer
from fedml_tpu.parallel.transformer import (
    Transformer,
    TransformerConfig,
    apply_rotary,
    rotary_embedding,
)


@pytest.fixture(scope="module")
def tiny_cfg():
    return TransformerConfig.tiny()


class TestTransformer:
    def test_forward_shape_and_dtype(self, tiny_cfg):
        model = Transformer(tiny_cfg)
        toks = jnp.zeros((2, 16), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), toks)
        logits = model.apply(variables, toks)
        assert logits.shape == (2, 16, tiny_cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, tiny_cfg):
        """Changing a future token must not change past logits."""
        model = Transformer(tiny_cfg)
        toks = jnp.ones((1, 16), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), toks)
        a = model.apply(variables, toks)
        toks2 = toks.at[0, 10].set(5)
        b = model.apply(variables, toks2)
        np.testing.assert_allclose(a[0, :10], b[0, :10], atol=2e-2)
        assert not np.allclose(a[0, 10:], b[0, 10:], atol=1e-3)

    def test_rotary_preserves_norm(self):
        pos = jnp.arange(8)[None]
        cos, sin = rotary_embedding(pos, 16, 10000.0)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16))
        y = apply_rotary(x, cos, sin)
        np.testing.assert_allclose(
            jnp.linalg.norm(x, axis=-1), jnp.linalg.norm(y, axis=-1), rtol=1e-4
        )

    def test_gqa_fewer_kv_heads(self):
        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_layers=1, n_heads=8, n_kv_heads=2,
            d_ff=128, max_seq_len=32, remat=False,
        )
        model = Transformer(cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), toks)
        wqkv = variables["params"]["Block_0"]["Attention_0"]["wqkv"]
        expected = (cfg.n_heads + 2 * cfg.n_kv_heads) * cfg.head_dim
        assert unbox(wqkv).shape == (cfg.d_model, expected)

    def test_lm_loss_masking(self):
        logits = jnp.zeros((1, 4, 8), jnp.float32)
        tokens = jnp.zeros((1, 4), jnp.int32)
        full = lm_loss(logits, tokens, jnp.ones((1, 4)))
        none = lm_loss(logits, tokens, jnp.zeros((1, 4)))
        assert float(full) == pytest.approx(np.log(8), rel=1e-4)
        assert float(none) == 0.0


class TestShardedTraining:
    def test_param_shardings_follow_rules(self, tiny_cfg):
        mesh = make_mesh({"fsdp": 4, "tensor": 2})
        model = Transformer(tiny_cfg)
        boxed = jax.eval_shape(
            lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
            jax.random.PRNGKey(0),
        )
        sh = param_shardings(mesh, boxed["params"])
        wqkv_sh = sh["Block_0"]["Attention_0"]["wqkv"]
        assert wqkv_sh.spec == jax.sharding.PartitionSpec("fsdp", "tensor")
        embed_sh = sh["embed"]
        assert embed_sh.spec == jax.sharding.PartitionSpec("tensor", "fsdp")

    def test_train_step_runs_sharded(self, tiny_cfg):
        mesh = make_mesh({"data": 2, "fsdp": 2, "tensor": 2})
        tr = CheetahTrainer(tiny_cfg, mesh,
                            optimizer=make_optimizer(learning_rate=1e-2,
                                                     warmup_steps=1))
        state = tr.init_state(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        toks = jnp.asarray(rng.randint(0, 255, (8, 32)), jnp.int32)
        mask = jnp.ones((8, 32), jnp.int32)
        losses = []
        for _ in range(4):
            state, m = tr.train_step(state, toks, mask)
            losses.append(float(m["loss"]))
        assert int(state.step) == 4
        assert losses[-1] < losses[0]  # memorizes the fixed batch
        # flagship invariant: params actually sharded over the mesh
        wqkv = state.params["Block_0"]["Attention_0"]["wqkv"]
        assert wqkv.sharding.spec == jax.sharding.PartitionSpec("fsdp", "tensor")

    def test_grad_accumulation_matches_large_batch(self, tiny_cfg):
        mesh = make_mesh({"fsdp": 8})
        opt = make_optimizer(learning_rate=1e-2, warmup_steps=1)
        rng = np.random.RandomState(0)
        toks = jnp.asarray(rng.randint(0, 255, (8, 32)), jnp.int32)
        mask = jnp.ones((8, 32), jnp.int32)

        tr1 = CheetahTrainer(tiny_cfg, mesh, optimizer=opt, accum_steps=1)
        s1 = tr1.init_state(jax.random.PRNGKey(0))
        s1, m1 = tr1.train_step(s1, toks, mask)

        toks2 = jnp.concatenate([toks, toks]).reshape(2, 8, 32)
        mask2 = jnp.concatenate([mask, mask]).reshape(2, 8, 32)
        tr2 = CheetahTrainer(tiny_cfg, mesh, optimizer=opt, accum_steps=2)
        s2 = tr2.init_state(jax.random.PRNGKey(0))
        s2, m2 = tr2.train_step(s2, toks2, mask2)
        assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-3)


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, ex = g.entry()
        out = jax.jit(fn)(*ex)
        assert out.shape[-1] == 2048

    def test_dryrun_multichip(self, capsys):
        import __graft_entry__ as g

        g.dryrun_multichip(8)
        assert "dryrun_multichip ok" in capsys.readouterr().out
