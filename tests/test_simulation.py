"""End-to-end simulation tests — the framework's version of the reference's
smoke tests (``python/tests/smoke_test/simulation_sp/main.py``; SURVEY.md §4
"tiny-config real training"), plus convergence assertions the reference never
had. Runs on the 8-device virtual CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner


def run_sim(**kw):
    base = dict(
        dataset="synthetic", model="lr", client_num_in_total=16,
        client_num_per_round=8, comm_round=6, epochs=1, batch_size=16,
        learning_rate=0.1, frequency_of_the_test=10, backend="sp",
    )
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    runner = FedMLRunner(args, fedml.get_device(args), dataset, model)
    return runner.run()


class TestSPFedAvg:
    def test_fedavg_converges(self):
        res = run_sim(comm_round=10, epochs=2)
        assert res["test_acc"] > 0.9

    def test_fedavg_deterministic(self):
        a = run_sim(comm_round=3)
        b = run_sim(comm_round=3)
        assert a["test_acc"] == pytest.approx(b["test_acc"])
        assert a["test_loss"] == pytest.approx(b["test_loss"])

    @pytest.mark.parametrize("opt", ["FedProx", "FedNova", "SCAFFOLD", "FedSGD"])
    def test_optimizer_family_learns(self, opt):
        res = run_sim(federated_optimizer=opt)
        assert res["test_acc"] > 0.5  # well above 10-class chance

    def test_fedopt_adam(self):
        res = run_sim(federated_optimizer="FedOpt", server_optimizer="adam",
                      server_lr=0.03)
        assert res["test_acc"] > 0.5

    @pytest.mark.slow
    def test_cnn_on_mnist(self):
        res = run_sim(dataset="mnist", model="cnn", client_num_in_total=8,
                      client_num_per_round=8, comm_round=6, epochs=2,
                      batch_size=8, learning_rate=0.05)
        assert res["test_acc"] > 0.8

    @pytest.mark.slow
    def test_rnn_nwp_learns(self):
        res = run_sim(dataset="shakespeare", model="rnn",
                      client_num_in_total=4, client_num_per_round=4,
                      comm_round=6, epochs=3, batch_size=8,
                      client_optimizer="adam", learning_rate=0.01)
        # synthetic Markov stream: bigram-optimal accuracy is ~25%
        assert res["test_acc"] > 0.15


class TestMeshSimulator:
    def test_mesh_matches_sp_closely(self):
        """Mesh and SP run the same math; accuracy must agree to a few %."""
        sp = run_sim(backend="sp", comm_round=5)
        mesh = run_sim(backend="mesh", comm_round=5)
        assert mesh["test_acc"] > 0.5
        assert abs(sp["test_acc"] - mesh["test_acc"]) < 0.15

    def test_mesh_uses_all_devices(self):
        assert len(jax.devices()) == 8  # conftest forced 8 virtual devices
        res = run_sim(backend="mesh", client_num_per_round=8)
        assert res["test_acc"] > 0.5

    def test_mesh_with_cohort_padding(self):
        # cohort size 6 over 8 shards → 2 padded slots with zero weight
        res = run_sim(backend="mesh", client_num_per_round=6, comm_round=4)
        assert res["test_acc"] > 0.4


class TestTrustHooks:
    """The attack → defend → aggregate → DP pipeline must behave identically
    on the single-device (sp) and client-sharded (mesh) engines — the mesh
    path is exactly where the trust layer matters most."""

    @pytest.mark.parametrize("backend", ["sp", "mesh"])
    def test_defense_neutralizes_byzantine(self, backend):
        atk = dict(enable_attack=True, attack_type="byzantine_random",
                   byzantine_client_frac=0.3, byzantine_scale=30.0,
                   comm_round=8, backend=backend)
        poisoned = run_sim(**atk)
        defended = run_sim(**atk, enable_defense=True,
                           defense_type="multikrum", byzantine_client_num=3)
        assert poisoned["test_acc"] < 0.3  # attack destroys training
        assert defended["test_acc"] > 0.5  # multikrum excludes the outliers

    @pytest.mark.parametrize("backend", ["sp", "mesh"])
    def test_ldp_still_learns(self, backend):
        res = run_sim(enable_dp=True, dp_type="ldp", mechanism_type="gaussian",
                      epsilon=50.0, comm_round=8, backend=backend)
        assert res["test_acc"] > 0.4

    def test_cdp_noise_applied(self):
        clean = run_sim(comm_round=2)
        noised = run_sim(comm_round=2, enable_dp=True, dp_type="cdp",
                         mechanism_type="gaussian", epsilon=0.5)
        assert clean["test_acc"] != pytest.approx(noised["test_acc"])

    def test_mesh_defense_with_cohort_padding(self):
        """6 real clients pad to 8 shards; multikrum must only ever see the
        6 real rows (padding rows would otherwise skew its neighbour sums)."""
        res = run_sim(backend="mesh", client_num_per_round=6, comm_round=6,
                      enable_defense=True, defense_type="multikrum",
                      byzantine_client_num=1)
        assert res["test_acc"] > 0.5

    @pytest.mark.parametrize("opt", ["FedOpt", "FedSGD", "SCAFFOLD"])
    def test_mesh_optimizer_family(self, opt):
        """Server-optimizer + control-variate paths on the sharded engine."""
        kw = dict(backend="mesh", federated_optimizer=opt, comm_round=6)
        if opt == "FedOpt":
            kw.update(server_optimizer="adam", server_lr=0.03)
        res = run_sim(**kw)
        assert res["test_acc"] > 0.5

    def test_fedsgd_reports_loss(self):
        """Weak-item fix: FedSGD used to report train_loss = nan."""
        import fedml_tpu as fedml
        from fedml_tpu.arguments import Arguments
        from fedml_tpu import data as data_mod, models as model_mod
        from fedml_tpu.simulation.sp_api import FedAvgAPI

        args = fedml.init(Arguments(overrides=dict(
            dataset="synthetic", model="lr", client_num_in_total=8,
            client_num_per_round=4, comm_round=2, epochs=1, batch_size=16,
            learning_rate=0.1, federated_optimizer="FedSGD",
        )), should_init_logs=False)
        ds, out_dim = data_mod.load(args)
        api = FedAvgAPI(args, fedml.get_device(args), ds,
                        model_mod.create(args, out_dim))
        m = api._train_round(0)
        assert np.isfinite(m["train_loss"])


class TestCustomSeams:
    def test_custom_server_aggregator(self):
        from fedml_tpu.ml.aggregator import DefaultServerAggregator

        calls = {"before": 0, "after": 0}

        class MyAgg(DefaultServerAggregator):
            def on_before_aggregation(self, raw):
                calls["before"] += 1
                return raw

            def on_after_aggregation(self, agg):
                calls["after"] += 1
                return agg

        args = fedml.init(Arguments(overrides=dict(
            dataset="synthetic", model="lr", client_num_in_total=8,
            client_num_per_round=4, comm_round=2, epochs=2, batch_size=16,
            learning_rate=0.2,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        agg = MyAgg(bundle, args)
        runner = FedMLRunner(args, fedml.get_device(args), ds, bundle,
                             server_aggregator=agg)
        res = runner.run()
        assert calls["before"] == 2 and calls["after"] == 2
        assert res["test_acc"] > 0.3

    def test_custom_aggregator_with_defense_raises(self):
        """Defense replaces the aggregation rule — combining it with a user
        ServerAggregator must error, not silently drop the override."""
        from fedml_tpu.ml.aggregator import DefaultServerAggregator
        from fedml_tpu.simulation.sp_api import FedAvgAPI

        args = fedml.init(Arguments(overrides=dict(
            dataset="synthetic", model="lr", client_num_in_total=8,
            client_num_per_round=4, comm_round=1, epochs=1, batch_size=16,
            learning_rate=0.1, enable_defense=True, defense_type="krum",
            byzantine_client_num=1,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        with pytest.raises(ValueError, match="mutually exclusive"):
            FedAvgAPI(args, fedml.get_device(args), ds, bundle,
                      server_aggregator=DefaultServerAggregator(bundle, args))

    def test_custom_aggregator_composes_with_model_attack(self):
        """A model attack transforms client rows; the user's aggregation
        rule must still run on the attacked rows (was: silently bypassed)."""
        from fedml_tpu.ml.aggregator import DefaultServerAggregator

        calls = {"agg": 0}

        class MyAgg(DefaultServerAggregator):
            def aggregate(self, raw):
                calls["agg"] += 1
                return super().aggregate(raw)

        args = fedml.init(Arguments(overrides=dict(
            dataset="synthetic", model="lr", client_num_in_total=8,
            client_num_per_round=4, comm_round=2, epochs=1, batch_size=16,
            learning_rate=0.1, enable_attack=True,
            attack_type="byzantine_zero", byzantine_client_frac=0.25,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        runner = FedMLRunner(args, fedml.get_device(args), ds, bundle,
                             server_aggregator=MyAgg(bundle, args))
        runner.run()
        assert calls["agg"] == 2


class TestRoundCheckpointResume:
    """FL-round checkpoint/resume (r5; the reference restarts killed runs
    from round 0 — SURVEY §5). A run killed mid-federation must resume at
    the next round with the saved global and finish IDENTICALLY to an
    uninterrupted run (same cohorts, same rngs — both are round-keyed)."""

    def _api(self, tmp_path, rounds, **kw):
        from fedml_tpu.simulation.sp_api import FedAvgAPI

        args = fedml.init(Arguments(overrides=dict(
            dataset="synthetic", model="lr", client_num_in_total=16,
            client_num_per_round=8, comm_round=rounds, epochs=1,
            batch_size=16, learning_rate=0.1, frequency_of_the_test=100,
            checkpoint_dir=str(tmp_path / "ckpt"), **kw,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        return FedAvgAPI(args, fedml.get_device(args), ds,
                         model_mod.create(args, od)), ds

    def test_sp_resume_matches_uninterrupted(self, tmp_path):
        import numpy as np

        # uninterrupted 6-round reference run (no checkpointing)
        from fedml_tpu.simulation.sp_api import FedAvgAPI

        args = fedml.init(Arguments(overrides=dict(
            dataset="synthetic", model="lr", client_num_in_total=16,
            client_num_per_round=8, comm_round=6, epochs=1, batch_size=16,
            learning_rate=0.1, frequency_of_the_test=100,
        )), should_init_logs=False)
        ds, od = data_mod.load(args)
        ref = FedAvgAPI(args, fedml.get_device(args), ds,
                        model_mod.create(args, od))
        ref.train()

        # "crash" after 3 rounds, then a FRESH api resumes and finishes
        api1, _ = self._api(tmp_path, rounds=3)
        api1.train()
        api2, _ = self._api(tmp_path, rounds=6)
        api2.train()
        assert [e["round"] for e in api2.history] == [3, 4, 5]  # resumed

        for a, b in zip(
            __import__("jax").tree.leaves(ref.global_params),
            __import__("jax").tree.leaves(api2.global_params),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)

        # re-invoking a COMPLETED federation trains nothing and still
        # returns metrics of the restored model (not an empty dict)
        api3, _ = self._api(tmp_path, rounds=6)
        res3 = api3.train()
        assert api3.history == [] and "test_acc" in res3

    def test_cross_silo_server_resume(self, tmp_path):
        """A restarted cross-silo server resumes at the saved round: the
        second world runs only the remaining rounds and reaches FINISH."""
        import threading
        import time as _time

        from fedml_tpu.cross_silo import (
            FedMLCrossSiloClient, FedMLCrossSiloServer,
        )

        def world(run_id, rounds):
            def mk(role, rank=0):
                return fedml.init(Arguments(overrides=dict(
                    training_type="cross_silo", dataset="synthetic",
                    model="lr", client_num_in_total=2, client_num_per_round=2,
                    comm_round=rounds, epochs=1, batch_size=8,
                    learning_rate=0.2, backend="LOOPBACK", run_id=run_id,
                    role=role, rank=rank,
                    checkpoint_dir=str(tmp_path / "silo_ckpt"),
                )), should_init_logs=False)

            args_s = mk("server")
            ds, od = data_mod.load(args_s)
            bundle = model_mod.create(args_s, od)
            server = FedMLCrossSiloServer(args_s, None, ds, bundle)
            clients = [
                FedMLCrossSiloClient(mk("client", r), None, ds, bundle)
                for r in (1, 2)
            ]
            threads = [threading.Thread(target=c.run, daemon=True)
                       for c in clients]
            for t in threads:
                t.start()
            _time.sleep(0.05)
            res = server.run()
            for t in threads:
                t.join(timeout=60)
            return res, server

        _, s1 = world("ckpt-w1", rounds=2)
        assert s1.manager.round_idx == 2
        # restart with a LARGER budget: resumes at round 2, runs 2..3
        res2, s2 = world("ckpt-w2", rounds=4)
        assert s2.manager.round_idx == 4
        assert res2 is not None and "test_acc" in res2
        # restarting the COMPLETED federation must not train a round past
        # the budget: clients get FINISH immediately, round index unmoved
        res3, s3 = world("ckpt-w3", rounds=4)
        assert s3.manager.round_idx == 4
        assert res3 is not None and "test_acc" in res3
