"""Round telemetry plane tests (core/mlops/telemetry.py — ISSUE 2).

Pins the plane's four contracts:

1. **RoundRecords**: with ``--enable_tracking``, every round — fused,
   unfused, and superround-scanned — emits exactly one structured JSONL
   ``round_record`` whose phase spans cover the measured round wall-clock.
2. **Zero cost when disabled**: the fused path performs NO extra host sync
   (``jax.block_until_ready`` is never called, the returned loss stays a
   device array), ``begin_round`` returns None, and ``phase`` returns the
   shared no-op span — tracking must not tax the PR 1 rounds/s.
3. **Registry + exporters**: counters/gauges/fixed-bucket histograms with
   interpolated p50/p95/p99, a parseable Prometheus exposition file, and
   the ``fedml top`` phase-breakdown CLI.
4. **Profiler windows**: ``--profile_rounds N:M`` opens/closes one
   ``jax.profiler`` trace exactly at the requested rounds and blocks
   superround chunks that would swallow a window boundary.

Plus the ISSUE 2 satellites: log_daemon resume/sinks/batching coverage and
the JSONL sink's close-at-exit durability.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core import mlops
from fedml_tpu.core.mlops import telemetry
from fedml_tpu.core.mlops.log_daemon import LogProcessor
from fedml_tpu.simulation.sp_api import FedAvgAPI


@pytest.fixture(autouse=True)
def clean_state():
    """Each test gets a fresh registry and a closed sink."""
    telemetry.registry().reset()
    yield
    mlops.close()
    telemetry.registry().reset()
    telemetry._State.enabled = False
    telemetry._State.metrics_file = None
    telemetry._State.profiler = None
    mlops.MLOpsStore.enabled = False
    mlops.MLOpsStore.jsonl_path = None


def make_api(tmp_path, run_id, **kw):
    base = dict(dataset="synthetic", model="lr", client_num_in_total=8,
                client_num_per_round=8, comm_round=4, epochs=1, batch_size=16,
                learning_rate=0.1, frequency_of_the_test=1000,
                enable_tracking=True, tracking_dir=str(tmp_path),
                run_id=run_id)
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, od = data_mod.load(args)
    return FedAvgAPI(args, fedml.get_device(args), ds,
                     model_mod.create(args, od))


def round_records(path=None):
    return [e for e in mlops.read_events(path)
            if e.get("kind") == "round_record"]


# ---------------------------------------------------------------------------
# RoundRecords
# ---------------------------------------------------------------------------


class TestRoundRecords:
    def test_fused_rounds_emit_one_record_each(self, tmp_path):
        api = make_api(tmp_path, "fused")
        api.train()
        recs = round_records()
        assert [r["round_idx"] for r in recs] == [0, 1, 2, 3]
        for r in recs:
            assert r["fused"] is True
            assert r["dispatch_latency_s"] is not None
            assert r["examples"] and r["examples"] > 0
            assert np.isfinite(r["train_loss"])
            assert r["rounds_per_sec_ema"] > 0
            assert {"sample", "gather", "prep", "dispatch",
                    "device_wait"} <= set(r["phases"])
            # phase spans never exceed the round wall and cover its bulk
            # (sub-ms CPU lr rounds leave some span-bookkeeping remainder)
            assert sum(r["phases"].values()) <= r["wall_s"] + 1e-6
            assert sum(r["phases"].values()) >= 0.3 * r["wall_s"]

    def test_unfused_rounds_emit_records_with_loop_phases(self, tmp_path):
        api = make_api(tmp_path, "unfused", round_fusion="off")
        api.train()
        recs = round_records()
        assert len(recs) == 4
        for r in recs:
            assert r["fused"] is False
            assert {"sample", "gather", "train", "aggregate",
                    "loss_sync"} <= set(r["phases"])
            assert r["examples"] and r["examples"] > 0

    def test_superround_scan_unpacks_one_record_per_round(self, tmp_path):
        api = make_api(tmp_path, "sup", comm_round=9, superround_k=4)
        api.train()
        recs = round_records()
        assert [r["round_idx"] for r in recs] == list(range(9))
        scanned = [r for r in recs if r["superround"]]
        # round 0 evals (freq rule) so chunks start at 1 and 5: 8 scanned
        assert len(scanned) == 8
        for r in scanned:
            assert r["phases"] == pytest.approx(
                {"superround_scan": r["wall_s"]})
            assert r["examples"] and r["examples"] > 0
            assert np.isfinite(r["train_loss"])

    def test_phase_sum_tracks_total_wall_clock(self, tmp_path):
        """Acceptance: per-round phase durations must account for the bulk
        of measured wall time (the bench asserts 10% on its leg; here the
        rounds are sub-millisecond so we pin coverage, not noise)."""
        api = make_api(tmp_path, "wall", comm_round=6)
        t0 = time.perf_counter()
        api.train()
        wall = time.perf_counter() - t0
        recs = round_records()
        total_phase = sum(sum(r["phases"].values()) for r in recs)
        total_wall = sum(r["wall_s"] for r in recs)
        assert total_phase <= total_wall * 1.01
        assert total_wall <= wall

    def test_compile_events_counted_on_first_round(self, tmp_path):
        api = make_api(tmp_path, "compiles")
        api.train()
        recs = round_records()
        # listeners are installed under tracking: round 0 carries the
        # compile wall, steady-state rounds compile nothing
        assert recs[0]["compiles"] > 0
        assert all(r["compiles"] == 0 for r in recs[2:])


# ---------------------------------------------------------------------------
# Zero-cost when disabled
# ---------------------------------------------------------------------------


class TestZeroCostDisabled:
    def test_fused_path_adds_no_host_sync(self, tmp_path, monkeypatch):
        """The PR 1 contract: with tracking off, a fused round is one async
        dispatch — no block_until_ready, loss returned as a device array."""
        api = make_api(tmp_path, "zc", enable_tracking=False)
        calls = []
        orig = jax.block_until_ready
        monkeypatch.setattr(
            jax, "block_until_ready",
            lambda x: (calls.append(1), orig(x))[1])
        out = api.run_round(0)
        assert not calls
        assert not isinstance(out["train_loss"], float)  # still on device
        assert telemetry.current_record() is None
        assert not mlops.read_events()  # no sink opened, nothing written

    def test_disabled_primitives_are_noops(self):
        telemetry.set_enabled(False)
        assert telemetry.begin_round(0) is None
        assert telemetry.phase("x") is telemetry._NULL_SPAN
        telemetry.end_round(None)  # must not raise
        telemetry.record_lazy("examples", 1)  # no record: no-op

    def test_superround_stays_async_when_disabled(self, tmp_path,
                                                  monkeypatch):
        api = make_api(tmp_path, "zc2", enable_tracking=False,
                       comm_round=8, superround_k=4)
        calls = []
        orig = jax.block_until_ready
        monkeypatch.setattr(
            jax, "block_until_ready",
            lambda x: (calls.append(1), orig(x))[1])
        api.run_rounds(0, 4)
        assert not calls


# ---------------------------------------------------------------------------
# Registry + exporters
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_counters_and_gauges(self):
        reg = telemetry.MetricsRegistry()
        reg.inc("a")
        reg.inc("a", 2.5)
        reg.gauge_set("g", 7.0)
        snap = reg.snapshot()
        assert snap["counters"]["a"] == 3.5
        assert snap["gauges"]["g"] == 7.0
        reg.reset()
        assert reg.snapshot() == {"counters": {}, "gauges": {},
                                  "histograms": {}}

    def test_histogram_quantiles_interpolate(self):
        reg = telemetry.MetricsRegistry()
        for v in np.linspace(0.001, 0.099, 99):
            reg.observe("lat", float(v))
        h = reg.snapshot()["histograms"]["lat"]
        assert h["count"] == 99
        assert h["p50"] == pytest.approx(0.05, rel=0.5)
        assert h["p95"] >= h["p50"]
        assert h["p99"] >= h["p95"]

    def test_histogram_overflow_bucket(self):
        reg = telemetry.MetricsRegistry()
        reg.observe("lat", 500.0)  # beyond the last bucket bound
        h = reg.snapshot()["histograms"]["lat"]
        assert h["count"] == 1
        assert h["p99"] >= telemetry.DEFAULT_BUCKETS[-1]

    def test_prometheus_exposition_parses(self):
        reg = telemetry.MetricsRegistry()
        reg.inc("comm.grpc.bytes_sent", 1024)
        reg.gauge_set("cheetah.tokens_per_sec", 123.5)
        reg.observe("phase.train.seconds", 0.004)
        text = reg.render_prometheus()
        assert "fedml_comm_grpc_bytes_sent_total 1024" in text
        assert "fedml_cheetah_tokens_per_sec 123.5" in text
        assert 'fedml_phase_train_seconds_bucket{le="+Inf"} 1' in text
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            name, value = line.rsplit(" ", 1)
            float(value)

    def test_metrics_file_written_during_tracked_run(self, tmp_path):
        mf = tmp_path / "metrics.prom"
        api = make_api(tmp_path, "mf", metrics_file=str(mf))
        api.train()
        telemetry.write_metrics_file(force=True)
        text = mf.read_text()
        assert "fedml_rounds_total" in text
        assert "fedml_round_wall_seconds_count" in text

    def test_telemetry_summary_emitted_at_close(self, tmp_path):
        api = make_api(tmp_path, "summary")
        api.train()
        path = mlops.MLOpsStore.jsonl_path
        mlops.close()
        events = mlops.read_events(path)
        summary = [e for e in events if e.get("kind") == "telemetry_summary"]
        assert len(summary) == 1
        assert summary[0]["metrics"]["counters"]["rounds.total"] == 4.0


class TestCommCounters:
    def test_payload_store_counts_puts_hits_gets(self, tmp_path):
        from fedml_tpu.core.distributed.payload_store import PayloadStore

        reg = telemetry.registry()
        store = PayloadStore(str(tmp_path / "blobs"))
        arrays = [np.arange(10, dtype=np.float32)]
        k1 = store.put_dedup(arrays)
        k2 = store.put_dedup(arrays)  # content-addressed: same key, a hit
        assert k1 == k2
        assert reg.counter("payload_store.puts") == 1
        assert reg.counter("payload_store.dedup_hits") == 1
        store.get(k1)
        assert reg.counter("payload_store.gets") == 1
        assert reg.counter("payload_store.get_bytes") > 0

    def test_comm_manager_counts_offloads(self, tmp_path):
        from fedml_tpu.core.distributed.comm_manager import FedMLCommManager
        from fedml_tpu.core.distributed.message import Message

        class A:
            run_id = "cnt"
            payload_store_dir = str(tmp_path / "store")
            payload_inline_limit_bytes = 64

        reg = telemetry.registry()
        node = FedMLCommManager(A(), rank=0, size=1)
        try:
            msg = Message("m", 0, 0)
            msg.set_arrays([np.zeros(1024, np.float32)])
            node.send_message(msg)
        finally:
            node.finish()
        assert reg.counter("comm.payload_offloads") == 1
        assert reg.counter("comm.payload_offload_bytes") == 4096


class TestTopCLI:
    def test_top_prints_phase_table(self, tmp_path, capsys):
        api = make_api(tmp_path, "topcli")
        api.train()
        path = mlops.MLOpsStore.jsonl_path
        mlops.close()
        from fedml_tpu.cli import main

        assert main(["top", path]) == 0
        out = capsys.readouterr().out
        assert "rounds: 4" in out
        assert "dispatch" in out and "gather" in out
        assert "% wall" in out

    def test_top_without_records_fails_cleanly(self, tmp_path, capsys):
        p = tmp_path / "empty.jsonl"
        p.write_text(json.dumps({"kind": "metrics", "x": 1}) + "\n")
        from fedml_tpu.cli import main

        assert main(["top", str(p)]) == 1

    def test_cache_cli_reports_hit_miss_telemetry(self, tmp_path, capsys):
        run = tmp_path / "run_x_edge_0.jsonl"
        run.write_text(json.dumps({
            "kind": "telemetry_summary",
            "metrics": {"counters": {
                "jax.compilation_cache.hits": 5,
                "jax.compilation_cache.misses": 2,
                "jax.compiles": 7,
            }},
        }) + "\n")
        from fedml_tpu.cli import main

        assert main(["cache", "--dir", str(tmp_path / "nocache"),
                     "--run_file", str(run)]) == 0
        out = capsys.readouterr().out
        assert "cache hits/misses: 5/2" in out
        assert "backend compiles:  7" in out


# ---------------------------------------------------------------------------
# Profiler windows
# ---------------------------------------------------------------------------


class TestProfilerWindows:
    @pytest.fixture()
    def trace_calls(self, monkeypatch):
        calls = {"start": [], "stop": 0}
        monkeypatch.setattr(telemetry, "_start_trace",
                            lambda d: calls["start"].append(d))

        def stop():
            calls["stop"] += 1

        monkeypatch.setattr(telemetry, "_stop_trace", stop)
        return calls

    def test_window_opens_and_closes_on_requested_rounds(self, tmp_path,
                                                         trace_calls):
        api = make_api(tmp_path, "prof", comm_round=6,
                       profile_rounds="2:4", profile_dir=str(tmp_path))
        api.train()
        assert trace_calls["start"] == [str(tmp_path)]
        assert trace_calls["stop"] == 1
        prof = telemetry._State.profiler
        assert prof.done and not prof.active

    def test_bare_round_spec_traces_one_round(self, tmp_path, trace_calls):
        w = telemetry.ProfilerWindow.parse("3", "logs")
        assert (w.start_round, w.stop_round) == (3, 4)
        with pytest.raises(ValueError):
            telemetry.ProfilerWindow.parse("4:2", "logs")

    def test_window_blocks_superround_chunking(self, tmp_path, trace_calls):
        api = make_api(tmp_path, "profsup", comm_round=8, superround_k=4,
                       profile_rounds="2:3", profile_dir=str(tmp_path))
        api.train()
        assert trace_calls["start"] == [str(tmp_path)]
        assert trace_calls["stop"] == 1
        # the window round ran UNfused-chunked: its record is a single round
        recs = {r["round_idx"]: r for r in round_records()}
        assert recs[2]["superround"] is False

    def test_unclosed_window_stopped_at_close(self, trace_calls):
        telemetry._State.profiler = telemetry.ProfilerWindow(0, 100, "d")
        telemetry.on_round_start(0)
        assert telemetry._State.profiler.active
        telemetry.close()
        assert trace_calls["stop"] == 1


# ---------------------------------------------------------------------------
# Sys-perf sampler + sink durability (satellites)
# ---------------------------------------------------------------------------


class TestSysPerfSampler:
    def test_sampler_emits_periodic_sys_perf_events(self, tmp_path):
        make_api(tmp_path, "sysperf", sys_perf_interval_s=0.01)
        args = fedml.get_args()
        sampler = telemetry.start_sys_perf_sampler(args)
        assert sampler is not None
        deadline = time.time() + 5.0
        while time.time() < deadline:
            events = [e for e in mlops.read_events()
                      if e.get("kind") == "sys_perf"]
            if len(events) >= 2:
                break
            time.sleep(0.02)
        sampler.stop()
        assert len(events) >= 2
        assert "devices" in events[0]

    def test_sampler_off_by_default_and_when_untracked(self, tmp_path):
        make_api(tmp_path, "sysoff")
        assert telemetry.start_sys_perf_sampler(fedml.get_args()) is None
        make_api(tmp_path, "sysoff2", enable_tracking=False,
                 sys_perf_interval_s=0.01)
        assert telemetry.start_sys_perf_sampler(fedml.get_args()) is None


class TestSinkDurability:
    def test_close_flushes_and_reinit_rolls_files(self, tmp_path):
        make_api(tmp_path, "dur1")
        mlops.log({"x": 1})
        p1 = mlops.MLOpsStore.jsonl_path
        # re-init must close the first handle (no leak) and open a new file
        make_api(tmp_path, "dur2")
        assert mlops.MLOpsStore.jsonl_path != p1
        mlops.log({"y": 2})
        p2 = mlops.MLOpsStore.jsonl_path
        mlops.close()
        assert mlops.MLOpsStore._jsonl_file is None
        assert any(e.get("x") == 1 for e in mlops.read_events(p1))
        assert any(e.get("y") == 2 for e in mlops.read_events(p2))
        # close is registered atexit exactly once
        assert mlops.MLOpsStore._atexit_registered

    def test_emit_after_close_is_safe(self, tmp_path):
        make_api(tmp_path, "dur3")
        mlops.close()
        mlops.log({"z": 1})  # must not raise with a closed sink


class TestWriteBehindSink:
    """The buffered JSONL sink (ISSUE 17 satellite): events buffer in
    memory and drain on interval / buffer limit / explicit flush / close —
    and NEVER get lost, including on a preemption exit(75)."""

    def _init(self, tmp_path, run_id, flush_s):
        import types

        ns = types.SimpleNamespace(enable_tracking=True, run_id=run_id,
                                   rank=0, tracking_dir=str(tmp_path),
                                   tracking_flush_s=flush_s)
        mlops.init(ns)
        return mlops.MLOpsStore.jsonl_path

    def _lines(self, path):
        with open(path) as f:
            return [ln for ln in f if ln.strip()]

    def test_interval_buffering_holds_events_off_disk(self, tmp_path):
        path = self._init(tmp_path, "wb1", flush_s=3600.0)
        for i in range(5):
            mlops.log({"i": i})
        assert len(mlops.MLOpsStore._buffer) == 5
        assert self._lines(path) == []  # nothing on disk yet
        mlops.flush()
        assert mlops.MLOpsStore._buffer == []
        assert len(self._lines(path)) == 5

    def test_buffer_limit_forces_drain(self, tmp_path):
        path = self._init(tmp_path, "wb2", flush_s=3600.0)
        for i in range(mlops.BUFFER_EVENT_LIMIT):
            mlops.log({"i": i})
        # hitting the cap drains synchronously — bounded memory
        assert mlops.MLOpsStore._buffer == []
        assert len(self._lines(path)) == mlops.BUFFER_EVENT_LIMIT

    def test_zero_interval_restores_per_event_writes(self, tmp_path):
        path = self._init(tmp_path, "wb3", flush_s=0.0)
        mlops.log({"a": 1})
        assert len(self._lines(path)) == 1
        mlops.log({"b": 2})
        assert len(self._lines(path)) == 2

    def test_read_events_sees_buffered_tail(self, tmp_path):
        self._init(tmp_path, "wb4", flush_s=3600.0)
        mlops.log({"tail": True})
        # live readers (fedml top, swarm reports) must not miss the buffer
        assert any(e.get("tail") for e in mlops.read_events())

    def test_close_drains_pending_buffer(self, tmp_path):
        path = self._init(tmp_path, "wb5", flush_s=3600.0)
        for i in range(7):
            mlops.log({"i": i})
        mlops.close()
        # 7 logged events all land (close also appends its summary record)
        recs = [json.loads(ln) for ln in self._lines(path)]
        assert sorted(r["i"] for r in recs if "i" in r) == list(range(7))

    def test_preemption_exit_75_loses_nothing(self, tmp_path):
        """A preempted worker exits via sys.exit(EXIT_PREEMPTED), which DOES
        run atexit hooks — every buffered event must reach disk."""
        import subprocess
        import sys

        child = (
            "import sys, types\n"
            "from fedml_tpu.core import mlops\n"
            "from fedml_tpu.core.runstate import EXIT_PREEMPTED\n"
            "ns = types.SimpleNamespace(enable_tracking=True,\n"
            "    run_id='exit75', rank=0, tracking_dir=sys.argv[1],\n"
            "    tracking_flush_s=3600.0)\n"
            "mlops.init(ns)\n"
            "for i in range(25):\n"
            "    mlops.log({'i': i})\n"
            "assert len(mlops.MLOpsStore._buffer) == 25\n"
            "sys.exit(EXIT_PREEMPTED)\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run([sys.executable, "-c", child, str(tmp_path)],
                              env=env, capture_output=True, text=True,
                              timeout=120)
        assert proc.returncode == 75, proc.stderr
        recs = [json.loads(ln) for ln in
                open(tmp_path / "run_exit75_edge_0.jsonl")]
        assert sorted(r["i"] for r in recs if "i" in r) == list(range(25))


# ---------------------------------------------------------------------------
# log_daemon coverage (satellite: resume, sinks, batching bounds)
# ---------------------------------------------------------------------------


class TestLogDaemon:
    def _write(self, path, lines):
        with open(path, "a") as f:
            f.writelines(line + "\n" for line in lines)

    def test_resume_by_index_after_restart(self, tmp_path):
        log = tmp_path / "run.log"
        shipped = []

        def sink(run_id, edge_id, lines):
            shipped.extend(lines)
            return True

        self._write(log, [f"line{i}" for i in range(5)])
        proc = LogProcessor(str(log), "r", 0, sink, index_dir=str(tmp_path))
        assert proc.poll_once() == 5
        # "restart": a NEW processor over the same index dir resumes where
        # the old one stopped — only new lines ship
        self._write(log, ["line5", "line6"])
        proc2 = LogProcessor(str(log), "r", 0, sink, index_dir=str(tmp_path))
        assert proc2.poll_once() == 2
        assert [ln.strip() for ln in shipped] == [f"line{i}" for i in range(7)]
        assert proc2.poll_once() == 0  # fully drained

    def test_dir_sink_appends_to_shared_file(self, tmp_path):
        log = tmp_path / "run.log"
        self._write(log, ["a", "b"])
        dest = tmp_path / "shipped"
        proc = LogProcessor(str(log), "42", 7, f"dir:{dest}",
                            index_dir=str(tmp_path))
        assert proc.poll_once() == 2
        out = (dest / "run_42_edge_7.log").read_text()
        assert out == "a\nb\n"

    def test_callable_sink_failure_retries_same_offset(self, tmp_path):
        log = tmp_path / "run.log"
        self._write(log, ["x", "y"])
        state = {"ok": False, "calls": 0}

        def sink(run_id, edge_id, lines):
            state["calls"] += 1
            return state["ok"]

        proc = LogProcessor(str(log), "r", 0, sink, index_dir=str(tmp_path))
        assert proc.poll_once() == 0  # sink down: nothing consumed
        state["ok"] = True
        assert proc.poll_once() == 2  # same lines re-shipped after recovery
        assert state["calls"] == 2

    def test_batching_bounds(self, tmp_path, monkeypatch):
        from fedml_tpu.core.mlops import log_daemon

        monkeypatch.setattr(log_daemon, "MAX_LINES_PER_BATCH", 3)
        log = tmp_path / "run.log"
        self._write(log, [f"l{i}" for i in range(8)])
        batches = []
        proc = LogProcessor(
            str(log), "r", 0,
            lambda r, e, lines: (batches.append(list(lines)), True)[1],
            index_dir=str(tmp_path),
        )
        assert proc.poll_once() == 8
        assert [len(b) for b in batches] == [3, 3, 2]

    def test_partial_line_not_shipped(self, tmp_path):
        log = tmp_path / "run.log"
        with open(log, "w") as f:
            f.write("complete\npartial-without-newline")
        shipped = []
        proc = LogProcessor(str(log), "r", 0,
                            lambda r, e, lines: (shipped.extend(lines), True)[1],
                            index_dir=str(tmp_path))
        assert proc.poll_once() == 1
        assert shipped == ["complete\n"]
        with open(log, "a") as f:
            f.write("\n")
        assert proc.poll_once() == 1
        assert shipped[-1] == "partial-without-newline\n"
