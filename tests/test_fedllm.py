"""FedLLM: cross-silo federated fine-tuning of the Cheetah transformer.

The pillar-meeting tests (reference gap: Cheetah is an empty stub at
``python/fedml/distributed/`` and no transformer exists in
``model/model_hub.py`` — FL-of-an-LLM is new capability, verified here
against exact mathematical mirrors):

- single-silo federation over the full FSM == the same Cheetah local steps
  run centrally (bit-faithful through serialization, payload store, and
  aggregation of one);
- two-silo FedAvg with one SGD step == the hand-computed weighted average of
  two independent sharded steps;
- multi-round convergence over the payload store with compressed updates.
"""

import threading
import time

import jax
import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer


def make_args(run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="shakespeare", model="cheetah",
        model_size="tiny", client_num_in_total=2, client_num_per_round=2,
        comm_round=2, batch_size=8, learning_rate=0.05,
        client_optimizer="adam", local_steps=3, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1, random_seed=7,
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_world(run_id: str, n_clients: int = 2, **kw):
    kw.setdefault("client_num_per_round", n_clients)
    args_s = make_args(run_id, role="server", client_num_in_total=n_clients,
                       **kw)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)
    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args(run_id, role="client", rank=rank,
                           client_num_in_total=n_clients, **kw)
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    result = server.run()
    for t in threads:
        t.join(timeout=60)
    for c in clients:
        assert c.manager.done.is_set(), "client did not reach FINISH"
    return result, server, clients


def _windows(x, y):
    # mirror of CheetahClientTrainer.train(): the packed x rows are the
    # token windows; the Cheetah loss shifts internally
    return np.asarray(x).astype(np.int32)


def _mirror_local_round(trainer, params, shard, args, round_idx, client_id):
    """Replicate CheetahClientTrainer.train()'s exact batch draws + steps."""
    import jax.numpy as jnp

    x, y, n = shard
    tokens_all = _windows(x, y)
    batch = int(args.batch_size)
    steps = int(args.local_steps)
    seed = (int(args.random_seed) * 1000003 + round_idx * 100003 + client_id)
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    state = trainer.state_from_params(params)
    for _ in range(steps):
        idx = rng.randint(0, max(int(n), 1), size=batch)
        tok = tokens_all[idx]
        mask = (tok != 0).astype(np.float32)
        state, _ = trainer.train_step(state, jnp.asarray(tok), jnp.asarray(mask))
    return state.params


def test_cheetah_bundle_contract():
    """models.create('cheetah') returns an FL-ready transformer bundle with
    the dataset's token space."""
    args = make_args("bundle1", role="server")
    ds, od = data_mod.load(args)
    bundle = model_mod.create(args, od)
    assert bundle.task == "nwp" and bundle.cfg.vocab_size == 90
    assert bundle.cfg.max_seq_len == 80  # shakespeare windows
    params = bundle.init(jax.random.PRNGKey(0))
    logits = bundle.apply(params, np.zeros((2, 80), np.int32))
    assert logits.shape == (2, 80, 90)


def test_fedllm_single_silo_matches_centralized_exactly():
    """One silo over the full FSM == the identical Cheetah run done by hand:
    round trips through npz serialization, the loopback wire, and
    single-client aggregation must be value-faithful."""
    from fedml_tpu.ml.optimizer import create_client_optimizer
    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer

    rounds = 2
    result, server, clients = run_world(
        "fedllm-parity1", n_clients=1, comm_round=rounds
    )
    args = make_args("fedllm-parity1-mirror", role="client", rank=1,
                     client_num_in_total=1, client_num_per_round=1,
                     comm_round=rounds)
    ds, od = data_mod.load(args)
    bundle = model_mod.create(args, od)
    trainer = CheetahTrainer(
        bundle.cfg, make_mesh(None),
        optimizer=create_client_optimizer(args), accum_steps=1,
    )
    params = bundle.init(jax.random.PRNGKey(int(args.random_seed)))["params"]
    shard = ds.client_shard(0)
    for r in range(rounds):
        # FSM: broadcast → local train → aggregate(1 client) == identity
        params = _mirror_local_round(trainer, params, shard, args, r,
                                     client_id=1)
        params = jax.tree.map(lambda p: np.asarray(p), params)
    fed_leaves = jax.tree.leaves(server.manager.global_params["params"])
    mirror_leaves = jax.tree.leaves(params)
    assert len(fed_leaves) == len(mirror_leaves)
    for f, m in zip(fed_leaves, mirror_leaves):
        np.testing.assert_allclose(np.asarray(f), np.asarray(m),
                                   rtol=1e-6, atol=1e-7)


def test_fedllm_two_silos_equals_weighted_average():
    """comm_round=1, one SGD step per silo: the federated result must equal
    the sample-weighted average of two independent sharded local steps."""
    kw = dict(comm_round=1, local_steps=1, client_optimizer="sgd",
              learning_rate=0.1)
    result, server, clients = run_world("fedllm-avg1", n_clients=2, **kw)

    from fedml_tpu.ml.optimizer import create_client_optimizer
    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer

    args = make_args("fedllm-avg1-mirror", role="client", rank=1,
                     client_num_in_total=2, **kw)
    ds, od = data_mod.load(args)
    bundle = model_mod.create(args, od)
    trainer = CheetahTrainer(
        bundle.cfg, make_mesh(None),
        optimizer=create_client_optimizer(args), accum_steps=1,
    )
    g0 = bundle.init(jax.random.PRNGKey(int(args.random_seed)))["params"]
    locals_ = []
    weights = []
    for ci in range(2):
        shard = ds.client_shard(ci)
        locals_.append(_mirror_local_round(
            trainer, g0, shard, args, 0, client_id=ci + 1))
        weights.append(float(shard[2]))
    w = np.asarray(weights) / sum(weights)
    expect = jax.tree.map(
        lambda a, b: w[0] * np.asarray(a, np.float64)
        + w[1] * np.asarray(b, np.float64),
        locals_[0], locals_[1],
    )
    for f, m in zip(jax.tree.leaves(server.manager.global_params["params"]),
                    jax.tree.leaves(expect)):
        np.testing.assert_allclose(np.asarray(f, np.float64), m,
                                   rtol=1e-5, atol=1e-6)


def test_fedllm_converges_over_payload_store(tmp_path):
    """Multi-round federation with bulk weights riding the payload store
    (GB-scale product path): the control channel stays control-sized and
    the federated LM beats the uniform-predictor loss floor."""
    from fedml_tpu.core.distributed.loopback import LoopbackCommManager

    sizes = []
    orig = LoopbackCommManager.send_message

    def spy(self, msg):
        sizes.append(len(msg.serialize()))
        return orig(self, msg)

    LoopbackCommManager.send_message = spy
    try:
        result, server, clients = run_world(
            "fedllm-store1", n_clients=2, comm_round=3, local_steps=20,
            payload_store_dir=str(tmp_path), payload_inline_limit_bytes=1024,
        )
    finally:
        LoopbackCommManager.send_message = orig
    assert result is not None
    # uniform over vocab 90 → CE = ln(90) = 4.4998; the Markov-chain corpus
    # is learnable, so even 3 rounds must land clearly below the floor
    assert result["test_loss"] < 4.3, result
    # the ~0.9M-param model never rode the control channel
    assert max(sizes) < 16 * 1024, f"bulk payload leaked: {max(sizes)}"


def test_fedllm_sharded_silo_mesh():
    """A silo whose local step is genuinely multi-device: fsdp×tensor mesh
    over the virtual CPU devices; federation result stays finite and the
    trainer reports the sharded mesh."""
    result, server, clients = run_world(
        "fedllm-mesh1", n_clients=1, comm_round=1, local_steps=2,
        mesh_shape="fsdp:4,tensor:2",
    )
    tr = clients[0].manager.trainer
    assert dict(tr.mesh.shape)["fsdp"] == 4
    assert dict(tr.mesh.shape)["tensor"] == 2
    assert np.isfinite(result["test_loss"])


@pytest.mark.slow
def test_fedllm_100m_scale_transport(tmp_path):
    """Scale-proof of the FedLLM transport contract (VERDICT r4 #8): a
    ~115M-param Cheetah federated across 2 silos with the payload store
    carrying the weights and UpdateCodec (8-bit quantize) shrinking the C2S
    delta. Asserts bulk bytes never ride the control channel and the
    encoded update is a fraction of the raw fp32 params."""
    from fedml_tpu.core.compression import UpdateCodec
    from fedml_tpu.core.distributed.loopback import LoopbackCommManager

    wire_sizes = []
    orig_send = LoopbackCommManager.send_message

    def spy_send(self, msg):
        wire_sizes.append(len(msg.serialize()))
        return orig_send(self, msg)

    encoded_ratios = []
    orig_encode = UpdateCodec.encode

    def spy_encode(self, gvec, vec, round_idx=0):
        arrays, meta = orig_encode(self, gvec, vec, round_idx)
        raw = int(np.asarray(vec).nbytes)
        enc = sum(int(np.asarray(a).nbytes) for a in arrays)
        encoded_ratios.append(enc / raw)
        return arrays, meta

    LoopbackCommManager.send_message = spy_send
    UpdateCodec.encode = spy_encode
    t0 = time.time()
    try:
        result, server, clients = run_world(
            "scale100m",
            # ~115M params: d896 x 12L MHA hd112 + SwiGLU ff2368 (the
            # dataset owns vocab/seq: shakespeare 90 x 80)
            model_size="mid", d_model=896, n_layers=12, n_heads=8,
            n_kv_heads=8, d_ff=2368,
            comm_round=1, local_steps=1, batch_size=8, epochs=1,
            compression="quantize", quantize_bits=8,
            payload_store_dir=str(tmp_path), payload_inline_limit_bytes=1 << 20,
            # 1-device silo mesh: at 115M params the default fsdp-8 VIRTUAL
            # mesh starves one per-device thread past XLA:CPU's 40s
            # collective-rendezvous deadline (two silos train concurrently
            # on ONE physical core) and the runtime hard-aborts; 8-way
            # silo sharding is covered at tiny scale by
            # test_fedllm_sharded_silo_mesh — THIS test proves transport
            mesh_shape="data:1", silo_device_indices=[0],
        )
    finally:
        LoopbackCommManager.send_message = orig_send
        UpdateCodec.encode = orig_encode
    wall = time.time() - t0

    n_params = sum(
        int(p.size)
        for p in jax.tree.leaves(server.manager.global_params)
    )
    assert n_params >= 100e6, f"model too small for the claim: {n_params}"
    assert result is not None and np.isfinite(result["test_loss"])
    # bulk weights ride the store: every control message stays small
    assert max(wire_sizes) < (1 << 20), max(wire_sizes)
    # the C2S delta really shrank: 8-bit quantize ≈ 1/4 of fp32 + scales
    assert len(encoded_ratios) >= 2  # one per silo
    assert max(encoded_ratios) < 0.35, encoded_ratios
    print(f"fedllm-100m: params={n_params/1e6:.1f}M wall={wall:.1f}s "
          f"wire_max={max(wire_sizes)}B "
          f"compression={np.mean(encoded_ratios):.3f}x-of-raw")
