"""Async traffic plane tests (fedml_tpu/traffic/ — ISSUE 7).

Pins five guarantees:

1. **Sync-parity**: async aggregation with staleness weight 1.0 (alpha=0)
   and buffer size = cohort size reproduces the synchronous FedAvg
   trajectory BITWISE — and the sync path itself is deterministic
   (bitwise-reproducible run to run), which is what "sync stays
   bitwise-identical" means going forward.
2. **Admission control**: token-bucket rate limiting and the bounded fold
   queue shed with explicit retry-after verdicts; shed clients re-offer
   and the federation still completes.
3. **Staleness machinery**: exact version-tagged staleness, polynomial
   decay weighting, max-staleness drops.
4. **Swarm determinism**: the seeded think-time/dropout processes depend
   only on (seed, rank) — two swarms with one seed share a schedule.
5. **Soak behavior** (the tools/swarm_smoke.sh contract, in-process): zero
   shed at light load; nonzero shed + completion under overload.
"""

import threading
import time
import types

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.mlops import telemetry
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer
from fedml_tpu.traffic.admission import (
    AdmissionController,
    TokenBucket,
    queue_limit_from_args,
)
from fedml_tpu.traffic.async_aggregator import (
    AsyncConfig,
    AsyncUpdateBuffer,
    staleness_weight,
)
from fedml_tpu.traffic.swarm import SwarmSchedule, swarm_soak


def make_args(run_id, **kw):
    base = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=3, client_num_per_round=3, comm_round=3,
        epochs=2, batch_size=8, learning_rate=0.2, backend="LOOPBACK",
        run_id=run_id, frequency_of_the_test=1,
    )
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


def run_world(run_id, n_clients=3, **kw):
    args_s = make_args(run_id, role="server", client_num_in_total=n_clients,
                       **kw)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)
    clients = []
    for rank in range(1, n_clients + 1):
        args_c = make_args(run_id, role="client", rank=rank,
                           client_num_in_total=n_clients, **kw)
        clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    result = server.run()
    for t in threads:
        t.join(timeout=60)
    return result, server, clients


def global_leaves(server):
    import jax

    return [np.asarray(l)
            for l in jax.tree.leaves(server.manager.global_params)]


def swarm_cfg(**kw):
    base = dict(
        clients=12, steps=4, buffer=4, staleness_alpha=0.5, max_staleness=0,
        flush_s=5.0, admit_rate=0.0, admit_burst=0, queue_limit=0,
        think_s=0.02, dropout=0.0, seed=7, backend="loopback", procs=1,
        port=0, timeout=90.0, run_id=f"swarm-{kw.pop('run_id', 'test')}",
    )
    base.update(kw)
    return types.SimpleNamespace(**base)


# ---------------------------------------------------------------------------
# units: staleness weighting, token bucket, buffer
# ---------------------------------------------------------------------------


class TestStalenessWeight:
    def test_alpha_zero_is_exactly_flat(self):
        for s in (0, 1, 7, 1000):
            assert staleness_weight(s, 0.0) == 1.0

    def test_polynomial_decay(self):
        assert staleness_weight(0, 0.5) == 1.0
        assert staleness_weight(3, 0.5) == pytest.approx(0.5)
        assert staleness_weight(1, 1.0) == pytest.approx(0.5)
        # monotone non-increasing in staleness
        ws = [staleness_weight(s, 0.7) for s in range(10)]
        assert all(a >= b for a, b in zip(ws, ws[1:]))

    def test_negative_staleness_clamps(self):
        assert staleness_weight(-3, 1.0) == 1.0


class TestTokenBucket:
    def test_burst_then_rate(self):
        now = [0.0]
        b = TokenBucket(rate=2.0, burst=3, clock=lambda: now[0])
        assert [b.take() for _ in range(3)] == [0.0, 0.0, 0.0]
        wait = b.take()
        assert wait == pytest.approx(0.5)  # 1 token at 2/s
        now[0] += 0.5
        assert b.take() == 0.0
        # refill caps at burst
        now[0] += 100.0
        assert [b.take() for _ in range(3)] == [0.0, 0.0, 0.0]
        assert b.take() > 0

    def test_rate_zero_never_sheds(self):
        b = TokenBucket(rate=0.0, burst=1)
        assert all(b.take() == 0.0 for _ in range(1000))


class TestAdmissionController:
    def test_rate_shed_carries_retry_after(self):
        now = [0.0]
        c = AdmissionController(rate=1.0, burst=1, clock=lambda: now[0])
        assert c.offer().admitted
        v = c.offer()
        assert not v.admitted and v.reason == "rate"
        assert v.retry_after_s == pytest.approx(1.0)

    def test_queue_full_shed(self):
        c = AdmissionController(rate=0.0, burst=1)
        v = c.offer(queue_put=lambda: False)
        assert not v.admitted and v.reason == "queue_full"
        assert v.retry_after_s > 0
        assert c.offer(queue_put=lambda: True).admitted

    def test_queue_full_refunds_the_token(self):
        """A queue-full shed must not ALSO drain the rate budget — the
        client's retry would be double-penalized (rate-shed right after a
        queue_full-shed for one overload event)."""
        now = [0.0]
        c = AdmissionController(rate=1.0, burst=1, clock=lambda: now[0])
        v = c.offer(queue_put=lambda: False)
        assert not v.admitted and v.reason == "queue_full"
        # the refunded token is immediately available once the queue drains
        assert c.offer(queue_put=lambda: True).admitted

    def test_queue_limit_resolution(self):
        a = types.SimpleNamespace(async_queue_limit=0)
        assert queue_limit_from_args(a, 10) == 40
        a = types.SimpleNamespace(async_queue_limit=3)
        assert queue_limit_from_args(a, 10) == 10  # never below one step


class TestAsyncBuffer:
    def cfg(self, **kw):
        base = dict(buffer_size=3, staleness_alpha=1.0, max_staleness=2,
                    flush_s=0.0)
        base.update(kw)
        return AsyncConfig(**base)

    def test_fold_ready_drain_sorted(self):
        buf = AsyncUpdateBuffer(self.cfg())
        p = {"w": np.ones(2)}
        assert buf.fold(3, 4.0, p, client_version=5, server_version=6) \
            == "buffered"
        assert buf.fold(1, 2.0, p, client_version=6, server_version=6) \
            == "buffered"
        assert not buf.ready()
        assert buf.fold(2, 1.0, p, client_version=4, server_version=6) \
            == "buffered"
        assert buf.ready()
        entries = buf.drain()
        assert [e.sender for e in entries] == [1, 2, 3]
        assert [e.staleness for e in entries] == [0, 2, 1]
        # weight = n * (1+s)^-alpha
        assert entries[0].weight == pytest.approx(2.0)
        assert entries[1].weight == pytest.approx(1.0 / 3.0)
        assert entries[2].weight == pytest.approx(2.0)
        assert buf.occupancy() == 0 and not buf.ready()

    def test_max_staleness_drops(self):
        buf = AsyncUpdateBuffer(self.cfg(max_staleness=2))
        p = {"w": np.ones(2)}
        assert buf.fold(1, 1.0, p, client_version=0, server_version=3) \
            == "stale"
        assert buf.occupancy() == 0
        # max_staleness=0 disables the drop
        buf2 = AsyncUpdateBuffer(self.cfg(max_staleness=0))
        assert buf2.fold(1, 1.0, p, client_version=0, server_version=99) \
            == "buffered"


# ---------------------------------------------------------------------------
# the parity pins
# ---------------------------------------------------------------------------


class TestAsyncSyncParity:
    def test_sync_mode_is_bitwise_deterministic(self):
        """The --aggregation_mode sync default must keep producing the same
        trajectory run over run — the executable form of "sync stays
        bitwise-identical to the pre-traffic-plane server"."""
        _, s1, _ = run_world("par-det-a")
        _, s2, _ = run_world("par-det-b")
        for i, (a, b) in enumerate(zip(global_leaves(s1),
                                       global_leaves(s2))):
            assert a.dtype == b.dtype and np.array_equal(a, b), f"leaf {i}"

    def test_async_k_equals_cohort_reproduces_sync_bitwise(self):
        """ISSUE 7 acceptance: staleness weight 1.0 (alpha=0) + buffer size
        = cohort size → the async trajectory IS the sync FedAvg
        trajectory, bitwise, including eval metrics."""
        r_sync, s_sync, _ = run_world("par-sync")
        r_async, s_async, _ = run_world(
            "par-async", aggregation_mode="async", async_buffer_size=3,
            async_staleness_alpha=0.0,
        )
        assert s_async.manager.round_idx == s_sync.manager.round_idx == 3
        for i, (a, b) in enumerate(zip(global_leaves(s_sync),
                                       global_leaves(s_async))):
            assert a.dtype == b.dtype and np.array_equal(a, b), \
                f"leaf {i} diverged async vs sync"
        assert r_async["test_acc"] == r_sync["test_acc"]

    def test_async_with_defense_matches_sync(self):
        """The hook chain (attack→defend→DP) rides the SAME aggregation
        core in both modes."""
        r_sync, s_sync, _ = run_world(
            "par-def-sync", enable_defense=True,
            defense_type="geometric_median",
        )
        r_async, s_async, _ = run_world(
            "par-def-async", enable_defense=True,
            defense_type="geometric_median", aggregation_mode="async",
            async_buffer_size=3, async_staleness_alpha=0.0,
        )
        for a, b in zip(global_leaves(s_sync), global_leaves(s_async)):
            assert np.array_equal(a, b)

    def test_async_with_compression_runs_end_to_end(self):
        """ISSUE 9: the async×compression refusal is GONE — compressed
        C2S deltas decode against the version-indexed model store
        (fedml_tpu/delivery/), so the combination runs end-to-end. The
        exact stale-base decode is pinned in tests/test_delta_plane.py."""
        reg = telemetry.registry()
        decodes0 = reg.counter("comm.delta.c2s_delta_decodes")
        result, server, _ = run_world(
            "par-comp", aggregation_mode="async", async_buffer_size=3,
            async_staleness_alpha=0.5, compression="topk",
            compression_ratio=0.1,
        )
        assert server.manager.round_idx == 3
        assert result is not None
        assert reg.counter("comm.delta.c2s_delta_decodes") > decodes0


class TestAsyncShedAndRetry:
    def test_shed_clients_reoffer_and_federation_completes(self):
        """A starved token bucket sheds real ClientMasterManager uploads;
        the S2C_SHED_NOTICE → backoff → freshly-stamped re-offer path must
        still finish every round with every client contributing."""
        reg = telemetry.registry()
        shed0 = reg.counter("traffic.shed_updates")
        retry0 = reg.counter("traffic.client_retries")
        result, server, clients = run_world(
            "shed-retry", aggregation_mode="async", async_buffer_size=3,
            async_staleness_alpha=0.0, async_admit_rate=2.0,
            async_admit_burst=1, comm_round=2,
        )
        assert server.manager.round_idx == 2
        assert result is not None
        assert reg.counter("traffic.shed_updates") > shed0
        assert reg.counter("traffic.client_retries") > retry0
        for c in clients:
            assert c.manager.done.wait(timeout=30)

    def test_async_partial_buffer_flush_unwedges(self):
        """Buffer size larger than the world (K=5 > 3 clients with one
        answer each per version) must flush via async_flush_s instead of
        wedging the federation."""
        result, server, _ = run_world(
            "flush", aggregation_mode="async", async_buffer_size=5,
            async_flush_s=0.3, comm_round=2,
        )
        assert server.manager.round_idx == 2
        assert result is not None


class TestAsyncLedger:
    def test_async_steps_are_ledgered_and_identity_pinned(self, tmp_path):
        """Async server steps commit to the PR 4 run ledger with their
        staleness vector; the buffer config is run identity — reopening
        the ledger under a different aggregation mode is refused."""
        from fedml_tpu.core.runstate import RunLedger

        ckpt = str(tmp_path / "ckpt")
        result, server, _ = run_world(
            "async-ledger", aggregation_mode="async", async_buffer_size=3,
            async_staleness_alpha=0.0, checkpoint_dir=ckpt,
            checkpoint_rounds=1,
        )
        assert server.manager.round_idx == 3
        ledger = RunLedger.for_checkpoint_dir(ckpt)
        rounds = ledger.rounds()
        assert [e["round"] for e in rounds] == [0, 1, 2]
        for e in rounds:
            assert e["mode"] == "async"
            assert e["staleness"] == [0, 0, 0]  # K=N lockstep
            assert sorted(e["cohort"]) == [1, 2, 3]
        meta = ledger.meta()
        assert meta["world"]["aggregation_mode"] == "async"
        assert meta["world"]["buffer_size"] == 3
        # resuming under sync (different world identity) must refuse
        args_s = make_args("async-ledger-2", role="server",
                           checkpoint_dir=ckpt)
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        with pytest.raises(RuntimeError, match="different federation"):
            FedMLCrossSiloServer(args_s, None, ds, bundle)


# ---------------------------------------------------------------------------
# swarm harness
# ---------------------------------------------------------------------------


class TestSwarmDeterminism:
    def test_schedule_depends_only_on_seed_and_rank(self):
        a = SwarmSchedule(7, 3, think_s=0.5, dropout_p=0.1)
        b = SwarmSchedule(7, 3, think_s=0.5, dropout_p=0.1)
        assert [a.next_think_s() for _ in range(50)] \
            == [b.next_think_s() for _ in range(50)]
        assert [a.drops_out() for _ in range(50)] \
            == [b.drops_out() for _ in range(50)]

    def test_ranks_are_decorrelated(self):
        a = SwarmSchedule(7, 1, think_s=0.5, dropout_p=0.0)
        b = SwarmSchedule(7, 2, think_s=0.5, dropout_p=0.0)
        assert [a.next_think_s() for _ in range(10)] \
            != [b.next_think_s() for _ in range(10)]


class TestSwarmSoak:
    """The tools/swarm_smoke.sh contract, in-process and fast."""

    def test_light_load_zero_shed(self):
        report = swarm_soak(swarm_cfg(run_id="light"))
        assert report["ok"], report
        # thread-leak witness (graftiso I005's runtime half): no non-daemon
        # thread survives world shutdown
        assert report["leaked_threads"] == [], report
        assert report["steps_completed"] == 4
        assert report["shed_updates"] == 0
        assert report["accepted_updates"] >= 4 * 4  # steps x buffer
        assert report["devices_finished"] == 12
        assert report["dispatch_ready_s"]["count"] > 0
        assert report["dispatch_ready_s"]["p99"] is not None

    def test_overload_sheds_and_still_completes(self):
        from fedml_tpu.traffic.swarm import rss_peak_mb

        # ru_maxrss is PROCESS-lifetime peak: inside the shared pytest
        # process earlier jax suites dominate it, so bound the soak's
        # GROWTH, not an absolute cap (the absolute cap lives in
        # tools/swarm_smoke.sh, which runs in a dedicated process)
        rss_before = rss_peak_mb()
        report = swarm_soak(swarm_cfg(
            run_id="overload", clients=20, admit_rate=10.0, admit_burst=2,
            think_s=0.01,
        ))
        assert report["ok"], report
        assert report["shed_updates"] > 0
        assert report["steps_completed"] == 4
        assert report["rss_peak_mb"] - rss_before < 2048

    def test_dropout_soak_flushes_partial_buffers(self):
        report = swarm_soak(swarm_cfg(
            run_id="dropout", clients=10, buffer=5, dropout=0.25,
            flush_s=0.3, steps=3,
        ))
        assert report["ok"], report
        assert report["steps_completed"] == 3

    def test_staleness_histogram_populates_with_small_buffer(self):
        report = swarm_soak(swarm_cfg(
            run_id="stale", clients=12, buffer=3, think_s=0.05,
            staleness_alpha=0.5,
        ))
        assert report["ok"], report
        assert report["staleness"]["count"] > 0


class TestArgumentsSurface:
    def test_mode_validation(self):
        with pytest.raises(ValueError, match="aggregation_mode"):
            Arguments(overrides=dict(aggregation_mode="bonkers"))

    def test_async_knobs_schema(self):
        a = Arguments(overrides=dict(
            aggregation_mode="async", async_buffer_size="7",
            async_staleness_alpha="0.25", async_admit_rate="100",
        ))
        assert a.async_buffer_size == 7
        assert a.async_staleness_alpha == 0.25
        assert a.async_admit_rate == 100.0

    def test_swarm_cli_registered(self):
        from fedml_tpu.cli import main as cli_main

        with pytest.raises(SystemExit):
            cli_main(["swarm", "--no-such-flag"])


class TestTopTrafficCounters:
    """`fedml_tpu top` surfaces the traffic.* backpressure family (the PR 7
    residual named in ROADMAP) from the run's telemetry summary."""

    @staticmethod
    def _run_file(tmp_path, metrics):
        import json as _json

        p = tmp_path / "run_traffic_edge_0.jsonl"
        events = [
            {"kind": "round_record", "round": 0, "wall_s": 1.0,
             "phases": {"dispatch": 0.5}},
            {"kind": "telemetry_summary", "metrics": metrics},
        ]
        p.write_text("".join(_json.dumps(e) + "\n" for e in events))
        return str(p)

    def test_traffic_block_rendered(self, tmp_path, capsys):
        from fedml_tpu.cli import main

        path = self._run_file(tmp_path, {
            "counters": {
                "traffic.accepted_updates": 120,
                "traffic.shed_rate_limited": 7,
                "traffic.shed_queue_full": 3,
                "traffic.stale_dropped_updates": 2,
                "traffic.server_steps": 40,
            },
            "gauges": {"traffic.buffer_occupancy": 5},
            "histograms": {
                "traffic.staleness": {"count": 120, "sum": 60.0,
                                      "p50": 0.4, "p95": 2.0, "p99": 3.0},
                "traffic.dispatch_ready_s": {"count": 120, "sum": 2.0,
                                             "p50": 0.01, "p95": 0.05,
                                             "p99": 0.08},
            },
        })
        assert main(["top", path]) == 0
        out = capsys.readouterr().out
        assert "traffic plane" in out
        assert "accepted: 120" in out
        assert "shed: 10 (rate-limited 7, queue-full 3)" in out
        assert "stale-dropped: 2" in out
        assert "buffer occupancy: 5" in out
        assert "staleness: p50 0.400" in out
        assert "dispatch→ready: p50 0.010s" in out

    def test_sync_runs_stay_silent(self, tmp_path, capsys):
        from fedml_tpu.cli import main

        path = self._run_file(tmp_path, {"counters": {"rounds": 4}})
        assert main(["top", path]) == 0
        assert "traffic plane" not in capsys.readouterr().out
