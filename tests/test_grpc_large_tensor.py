"""gRPC bulk-tensor path: the role the reference assigns to TRPC.

reference: ``core/distributed/communication/trpc/trpc_comm_manager.py`` —
torch RPC exists in the reference specifically to move big model tensors
between hosts; its gRPC manager caps messages at 1 GB. Here the single gRPC
backend owns that role, so this proves a model-scale payload (a 64 MB
float32 tree, bigger than any CIFAR-ResNet in the zoo) survives the wire
bit-exact through the JSON+npz frame.
"""

import threading

import numpy as np
import pytest

pytestmark = pytest.mark.slow

grpc = pytest.importorskip("grpc")

from fedml_tpu.core.distributed.grpc_backend import GRPCCommManager
from fedml_tpu.core.distributed.message import Message


class _Collector:
    def __init__(self):
        self.messages = []
        self.got = threading.Event()

    def receive_message(self, msg_type, msg):
        if msg_type == "big_model":
            self.messages.append(msg)
            self.got.set()


def _free_consecutive_ports(n: int) -> int:
    """A base such that base..base+n-1 are all bindable right now."""
    import socket

    for _ in range(50):
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            base = probe.getsockname()[1]
        if base + n >= 65536:
            continue
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no consecutive free ports found")


def test_64mb_model_payload_roundtrip():
    base = _free_consecutive_ports(2)
    sender = GRPCCommManager("127.0.0.1", base + 0, rank=0, world_size=2,
                             base_port=base)
    receiver = GRPCCommManager("127.0.0.1", base + 1, rank=1, world_size=2,
                               base_port=base)
    collector = _Collector()
    receiver.add_observer(collector)
    rx = threading.Thread(target=receiver.handle_receive_message, daemon=True)
    rx.start()
    try:
        rng = np.random.default_rng(0)
        arrays = [
            rng.standard_normal((2048, 4096)).astype(np.float32),
            rng.standard_normal((4096, 2048)).astype(np.float32),
            rng.standard_normal((4096,)).astype(np.float32),
        ]  # ≈ 64 MB
        msg = Message("big_model", sender_id=0, receiver_id=1)
        msg.add("num_arrays", len(arrays))
        msg.set_arrays(arrays)
        sender.send_message(msg)

        assert collector.got.wait(timeout=120), "large payload never arrived"
        got = collector.messages[0]
        assert got.get("num_arrays") == len(arrays)
        out = got.get_arrays()
        assert len(out) == len(arrays)
        for a, b in zip(arrays, out):
            assert b.dtype == a.dtype
            np.testing.assert_array_equal(b, a)
    finally:
        receiver.stop_receive_message()
        sender.stop_receive_message()
        rx.join(timeout=5)
