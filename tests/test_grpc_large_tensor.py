"""gRPC bulk-tensor path: the role the reference assigns to TRPC.

reference: ``core/distributed/communication/trpc/trpc_comm_manager.py`` —
torch RPC exists in the reference specifically to move big model tensors
between hosts; its gRPC manager caps messages at 1 GB. Here the single gRPC
backend owns that role, so this proves a model-scale payload (a 64 MB
float32 tree, bigger than any CIFAR-ResNet in the zoo) survives the wire
bit-exact through the JSON+npz frame.
"""

import threading

import numpy as np
import pytest

pytestmark = pytest.mark.slow

grpc = pytest.importorskip("grpc")

from fedml_tpu.core.distributed.grpc_backend import GRPCCommManager
from fedml_tpu.core.distributed.message import Message


class _Collector:
    def __init__(self):
        self.messages = []
        self.got = threading.Event()

    def receive_message(self, msg_type, msg):
        if msg_type == "big_model":
            self.messages.append(msg)
            self.got.set()


def _free_consecutive_ports(n: int) -> int:
    """A base such that base..base+n-1 are all bindable right now."""
    import socket

    for _ in range(50):
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            base = probe.getsockname()[1]
        if base + n >= 65536:
            continue
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no consecutive free ports found")


def test_64mb_model_payload_roundtrip():
    base = _free_consecutive_ports(2)
    sender = GRPCCommManager("127.0.0.1", base + 0, rank=0, world_size=2,
                             base_port=base)
    receiver = GRPCCommManager("127.0.0.1", base + 1, rank=1, world_size=2,
                               base_port=base)
    collector = _Collector()
    receiver.add_observer(collector)
    rx = threading.Thread(target=receiver.handle_receive_message, daemon=True)
    rx.start()
    try:
        rng = np.random.default_rng(0)
        arrays = [
            rng.standard_normal((2048, 4096)).astype(np.float32),
            rng.standard_normal((4096, 2048)).astype(np.float32),
            rng.standard_normal((4096,)).astype(np.float32),
        ]  # ≈ 64 MB
        msg = Message("big_model", sender_id=0, receiver_id=1)
        msg.add("num_arrays", len(arrays))
        msg.set_arrays(arrays)
        sender.send_message(msg)

        assert collector.got.wait(timeout=120), "large payload never arrived"
        got = collector.messages[0]
        assert got.get("num_arrays") == len(arrays)
        out = got.get_arrays()
        assert len(out) == len(arrays)
        for a, b in zip(arrays, out):
            assert b.dtype == a.dtype
            np.testing.assert_array_equal(b, a)
    finally:
        receiver.stop_receive_message()
        sender.stop_receive_message()
        rx.join(timeout=5)


def test_raw_frames_roundtrip_and_sniffing():
    """The TRPC-role direct-tensor format (tensor_transport.py): dtype/shape
    preservation incl. non-contiguous inputs, zero-copy decode, and
    mixed-format interop (deserialize sniffs npz vs raw)."""
    from fedml_tpu.core.distributed.tensor_transport import (
        decode_frames, encode_frames,
    )

    rng = np.random.RandomState(0)
    arrays = [
        rng.standard_normal((33, 17)).astype(np.float32),
        np.arange(11, dtype=np.int32),
        rng.standard_normal((8, 8)).astype(np.float64)[::2],  # non-contig
        np.float16(rng.standard_normal((5,))),
        np.float32(3.5).reshape(()),  # 0-d scalar: shape must survive as ()
    ]
    body = encode_frames(arrays)
    back = decode_frames(body)
    for a, b in zip(arrays, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.ascontiguousarray(a), b)
    assert not back[0].flags["OWNDATA"]  # zero-copy view

    for fmt in ("npz", "raw"):
        msg = Message("t", 1, 2)
        msg.set_arrays(arrays)
        msg.wire_format = fmt
        back_msg = Message.deserialize(msg.serialize())
        for a, b in zip(arrays, back_msg.get_arrays()):
            np.testing.assert_array_equal(np.ascontiguousarray(a), b)


def test_streamed_payload_past_cap_is_resource_exhausted(monkeypatch):
    """The stream handler must bound reassembly at MAX_MESSAGE_BYTES like
    the unary path does — an over-cap stream aborts RESOURCE_EXHAUSTED
    instead of growing server memory without limit (ADVICE.md)."""
    from fedml_tpu.core.distributed import grpc_backend

    base = _free_consecutive_ports(4)
    recv = GRPCCommManager("127.0.0.1", base + 2, rank=2, world_size=3,
                           base_port=base, wire_format="raw",
                           stream_threshold_bytes=1 << 20)
    send = GRPCCommManager("127.0.0.1", base + 1, rank=1, world_size=3,
                           base_port=base, wire_format="raw",
                           stream_threshold_bytes=1 << 20)
    # shrink the cap AFTER server start: the handler reads the module
    # global per request, so the 12 MB payload below is now over-limit
    monkeypatch.setattr(grpc_backend, "MAX_MESSAGE_BYTES", 4 * 1024 * 1024)
    try:
        big = np.zeros(3 * 1024 * 1024, np.float32)  # 12 MB > 4 MB cap
        msg = Message("big_model", 1, 2)
        msg.set_arrays([big])
        with pytest.raises(grpc.RpcError) as ei:
            send.send_message(msg)
        assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    finally:
        send.stop_receive_message()
        recv.stop_receive_message()


def test_streamed_raw_payload_roundtrip():
    """A payload past the stream threshold rides Comm/SendStream in chunks
    and reassembles bit-exact (wire_format='raw')."""
    base = _free_consecutive_ports(4)
    recv = GRPCCommManager("127.0.0.1", base + 2, rank=2, world_size=3,
                           base_port=base, wire_format="raw",
                           stream_threshold_bytes=1 << 20)
    send = GRPCCommManager("127.0.0.1", base + 1, rank=1, world_size=3,
                           base_port=base, wire_format="raw",
                           stream_threshold_bytes=1 << 20)
    col = _Collector()
    recv.add_observer(col)
    t = threading.Thread(target=recv.handle_receive_message, daemon=True)
    t.start()
    try:
        rng = np.random.RandomState(1)
        big = rng.standard_normal(3 * 1024 * 1024).astype(np.float32)  # 12MB
        msg = Message("big_model", 1, 2)
        msg.set_arrays([big])
        send.send_message(msg)
        assert col.got.wait(timeout=60)
        np.testing.assert_array_equal(col.messages[0].get_arrays()[0], big)
    finally:
        send.stop_receive_message()
        recv.stop_receive_message()
