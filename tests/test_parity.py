"""Convergence parity gate (VERDICT r2 next #1): the sp engine's per-round
global-parameter trajectories must exactly match (a) the reference's own
FedAvgAPI driven in-process on identical data/partition/cohorts/seeds, and
(b) independent numpy oracles of the published FedProx/SCAFFOLD update rules.
See tools/parity_check.py for the full design, including the reference's
round-0 state-aliasing quirk this pins down."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_lr_trajectory_parity(tmp_path):
    if not os.path.isdir("/root/reference/python/fedml"):
        pytest.skip("reference checkout not available")
    out = tmp_path / "PARITY.json"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # run both stacks on CPU
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parity_check.py"),
         "--skip-resnet", "--out", str(out)],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    results = json.loads(out.read_text())
    assert results["all_ok"], results
    # the head-to-head itself, not just the oracles
    head = results["results"]["fedavg_lr_vs_reference_aliasing_fixed"]
    assert head["rel_l2_max"] < 1e-3
    assert results["results"]["scaffold_lr_vs_oracle"]["rel_l2_max"] < 1e-3
