import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.security import attacks, defenses
from fedml_tpu.core.security.attacker import FedMLAttacker
from fedml_tpu.core.security.defender import FedMLDefender


def _honest_and_bad(n=8, dim=16, bad=2, seed=0):
    rng = np.random.RandomState(seed)
    honest = rng.normal(1.0, 0.1, size=(n - bad, dim))
    malicious = rng.normal(-20.0, 0.1, size=(bad, dim))
    return jnp.asarray(np.concatenate([honest, malicious]), jnp.float32)


def test_krum_rejects_outliers():
    updates = _honest_and_bad()
    agg, mask = defenses.krum(updates, byzantine_count=2, krum_param_m=1)
    assert float(jnp.mean(agg)) > 0.5  # picked an honest client
    assert float(mask[-1]) == 0.0 and float(mask[-2]) == 0.0


def test_multikrum_weighted_rejects_outliers():
    updates = _honest_and_bad()
    agg = defenses.multikrum_weighted(updates, jnp.ones(8), byzantine_count=2, m=4)
    assert float(jnp.mean(agg)) > 0.5


def test_geometric_median_robust():
    updates = _honest_and_bad()
    med = defenses.geometric_median(updates, jnp.ones(8))
    assert float(jnp.mean(med)) > 0.5


def test_trimmed_mean_and_median():
    updates = _honest_and_bad()
    tm = defenses.trimmed_mean(updates, 0.25)
    cm = defenses.coordinate_median(updates)
    assert float(jnp.mean(tm)) > 0.5
    assert float(jnp.mean(cm)) > 0.5
    with pytest.raises(ValueError):
        defenses.trimmed_mean(updates, 0.5)


def test_bulyan_robust():
    updates = _honest_and_bad(n=10, bad=2)
    agg = defenses.bulyan(updates, byzantine_count=2)
    assert float(jnp.mean(agg)) > 0.5


def test_norm_diff_clipping_bounds_delta():
    g = jnp.zeros((16,))
    updates = _honest_and_bad()
    clipped = defenses.norm_diff_clipping(updates, g, norm_bound=1.0)
    norms = jnp.linalg.norm(clipped - g[None, :], axis=1)
    assert float(jnp.max(norms)) <= 1.0 + 1e-5


def test_cclip_closer_to_honest():
    updates = _honest_and_bad()
    v = defenses.cclip(updates, jnp.ones(8), tau=2.0)
    naive = jnp.mean(updates, axis=0)
    assert float(jnp.mean(v)) > float(jnp.mean(naive))


def test_robust_lr_flips_uncertain_coords():
    g = jnp.zeros((4,))
    updates = jnp.array([[1.0, 1, 1, -1], [1.0, 1, -1, 1], [1.0, -1, 1, 1]])
    out = defenses.robust_learning_rate(updates, g, threshold=3, server_lr=1.0)
    assert float(out[0]) > 0  # unanimous coordinate keeps +lr
    assert float(out[1]) < 0 or float(out[2]) < 0  # split coordinates flipped


def test_byzantine_attack_modes():
    updates = jnp.ones((4, 8))
    mask = jnp.array([0.0, 0, 0, 1])
    z = attacks.byzantine_attack(updates, mask, jax.random.PRNGKey(0), "zero")
    np.testing.assert_allclose(z[3], 0.0)
    np.testing.assert_allclose(z[0], 1.0)
    f = attacks.byzantine_attack(updates, mask, jax.random.PRNGKey(0), "flip")
    np.testing.assert_allclose(f[3], -1.0)
    r = attacks.byzantine_attack(updates, mask, jax.random.PRNGKey(0), "random")
    assert not np.allclose(r[3], 1.0)


def test_label_flipping():
    labels = jnp.array([0, 1, 2, 0])
    flipped = attacks.label_flipping(labels, 0, 9)
    np.testing.assert_array_equal(flipped, [9, 1, 2, 9])


def test_dlg_reconstructs_linear_input():
    # one linear layer, square loss: gradients fully determine the input
    W = jnp.eye(4)

    def grad_fn(x, y):
        def loss(W_):
            return jnp.sum((x @ W_ - y) ** 2)

        return (jax.grad(loss)(W),)

    true_x = jnp.array([[1.0, -2.0, 3.0, 0.5]])
    true_y = jax.nn.softmax(jnp.array([[0.2, 0.3, 0.1, 0.4]]))
    true_grads = grad_fn(true_x, true_y)
    # gradient inversion is nonconvex: assert convergence from a nearby init
    init_x = true_x + 0.3
    dx, dy = attacks.dlg_attack(
        grad_fn, true_grads, init_x, jnp.zeros((1, 4)), lr=0.05, iters=500
    )
    assert float(jnp.linalg.norm(dx - true_x)) < 0.1
    # and that the attack's own objective (gradient match) is near zero
    rec = grad_fn(dx, jax.nn.softmax(dy))
    assert float(sum(jnp.sum((a - b) ** 2) for a, b in zip(rec, true_grads))) < 1e-3


def test_attacker_manager_hooks():
    class A:
        enable_attack = True
        attack_type = "byzantine_zero"
        byzantine_client_frac = 0.5
        random_seed = 0

    atk = FedMLAttacker.get_instance()
    atk.init(A())
    assert atk.is_model_attack()
    updates = jnp.ones((4, 6))
    out = atk.attack_model(updates, jnp.ones(4), jax.random.PRNGKey(0))
    zeroed = int((jnp.linalg.norm(out, axis=1) == 0).sum())
    assert zeroed == 2


def test_defender_manager_dispatch():
    class A:
        enable_defense = True
        defense_type = "krum"
        byzantine_client_num = 2

    d = FedMLDefender.get_instance()
    d.init(A())
    assert d.is_defense_enabled()
    updates = _honest_and_bad()
    agg = d.defend(updates, jnp.ones(8), jnp.zeros(16), jax.random.PRNGKey(0))
    assert float(jnp.mean(agg)) > 0.5

    A.defense_type = "nope"
    with pytest.raises(ValueError):
        d.init(A())
    A.defense_type = "krum"
    d.init(A())


def test_attacker_zero_frac_is_noop():
    class A:
        enable_attack = True
        attack_type = "byzantine_zero"
        byzantine_client_frac = 0.0
        random_seed = 0

    atk = FedMLAttacker.get_instance()
    atk.init(A())
    updates = jnp.ones((4, 6))
    out = atk.attack_model(updates, jnp.ones(4), jax.random.PRNGKey(0))
    np.testing.assert_allclose(out, updates)
