import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.security import attacks, defenses
from fedml_tpu.core.security.attacker import FedMLAttacker
from fedml_tpu.core.security.defender import FedMLDefender


def _honest_and_bad(n=8, dim=16, bad=2, seed=0):
    rng = np.random.RandomState(seed)
    honest = rng.normal(1.0, 0.1, size=(n - bad, dim))
    malicious = rng.normal(-20.0, 0.1, size=(bad, dim))
    return jnp.asarray(np.concatenate([honest, malicious]), jnp.float32)


def test_krum_rejects_outliers():
    updates = _honest_and_bad()
    agg, mask = defenses.krum(updates, byzantine_count=2, krum_param_m=1)
    assert float(jnp.mean(agg)) > 0.5  # picked an honest client
    assert float(mask[-1]) == 0.0 and float(mask[-2]) == 0.0


def test_multikrum_weighted_rejects_outliers():
    updates = _honest_and_bad()
    agg = defenses.multikrum_weighted(updates, jnp.ones(8), byzantine_count=2, m=4)
    assert float(jnp.mean(agg)) > 0.5


def test_geometric_median_robust():
    updates = _honest_and_bad()
    med = defenses.geometric_median(updates, jnp.ones(8))
    assert float(jnp.mean(med)) > 0.5


def test_trimmed_mean_and_median():
    updates = _honest_and_bad()
    tm = defenses.trimmed_mean(updates, 0.25)
    cm = defenses.coordinate_median(updates)
    assert float(jnp.mean(tm)) > 0.5
    assert float(jnp.mean(cm)) > 0.5
    with pytest.raises(ValueError):
        defenses.trimmed_mean(updates, 0.5)


def test_bulyan_robust():
    updates = _honest_and_bad(n=10, bad=2)
    agg = defenses.bulyan(updates, byzantine_count=2)
    assert float(jnp.mean(agg)) > 0.5


def test_norm_diff_clipping_bounds_delta():
    g = jnp.zeros((16,))
    updates = _honest_and_bad()
    clipped = defenses.norm_diff_clipping(updates, g, norm_bound=1.0)
    norms = jnp.linalg.norm(clipped - g[None, :], axis=1)
    assert float(jnp.max(norms)) <= 1.0 + 1e-5


def test_cclip_closer_to_honest():
    updates = _honest_and_bad()
    v = defenses.cclip(updates, jnp.ones(8), tau=2.0)
    naive = jnp.mean(updates, axis=0)
    assert float(jnp.mean(v)) > float(jnp.mean(naive))


def test_robust_lr_flips_uncertain_coords():
    g = jnp.zeros((4,))
    updates = jnp.array([[1.0, 1, 1, -1], [1.0, 1, -1, 1], [1.0, -1, 1, 1]])
    out = defenses.robust_learning_rate(updates, g, threshold=3, server_lr=1.0)
    assert float(out[0]) > 0  # unanimous coordinate keeps +lr
    assert float(out[1]) < 0 or float(out[2]) < 0  # split coordinates flipped


def test_byzantine_attack_modes():
    updates = jnp.ones((4, 8))
    mask = jnp.array([0.0, 0, 0, 1])
    z = attacks.byzantine_attack(updates, mask, jax.random.PRNGKey(0), "zero")
    np.testing.assert_allclose(z[3], 0.0)
    np.testing.assert_allclose(z[0], 1.0)
    f = attacks.byzantine_attack(updates, mask, jax.random.PRNGKey(0), "flip")
    np.testing.assert_allclose(f[3], -1.0)
    r = attacks.byzantine_attack(updates, mask, jax.random.PRNGKey(0), "random")
    assert not np.allclose(r[3], 1.0)


def test_label_flipping():
    labels = jnp.array([0, 1, 2, 0])
    flipped = attacks.label_flipping(labels, 0, 9)
    np.testing.assert_array_equal(flipped, [9, 1, 2, 9])


def test_dlg_reconstructs_linear_input():
    # one linear layer, square loss: gradients fully determine the input
    W = jnp.eye(4)

    def grad_fn(x, y):
        def loss(W_):
            return jnp.sum((x @ W_ - y) ** 2)

        return (jax.grad(loss)(W),)

    true_x = jnp.array([[1.0, -2.0, 3.0, 0.5]])
    true_y = jax.nn.softmax(jnp.array([[0.2, 0.3, 0.1, 0.4]]))
    true_grads = grad_fn(true_x, true_y)
    # gradient inversion is nonconvex: assert convergence from a nearby init
    init_x = true_x + 0.3
    dx, dy = attacks.dlg_attack(
        grad_fn, true_grads, init_x, jnp.zeros((1, 4)), lr=0.05, iters=500
    )
    assert float(jnp.linalg.norm(dx - true_x)) < 0.1
    # and that the attack's own objective (gradient match) is near zero
    rec = grad_fn(dx, jax.nn.softmax(dy))
    assert float(sum(jnp.sum((a - b) ** 2) for a, b in zip(rec, true_grads))) < 1e-3


def test_attacker_manager_hooks():
    class A:
        enable_attack = True
        attack_type = "byzantine_zero"
        byzantine_client_frac = 0.5
        random_seed = 0

    atk = FedMLAttacker.get_instance()
    atk.init(A())
    assert atk.is_model_attack()
    updates = jnp.ones((4, 6))
    out = atk.attack_model(updates, jnp.ones(4), jax.random.PRNGKey(0))
    zeroed = int((jnp.linalg.norm(out, axis=1) == 0).sum())
    assert zeroed == 2


def test_defender_manager_dispatch():
    class A:
        enable_defense = True
        defense_type = "krum"
        byzantine_client_num = 2

    d = FedMLDefender.get_instance()
    d.init(A())
    assert d.is_defense_enabled()
    updates = _honest_and_bad()
    agg = d.defend(updates, jnp.ones(8), jnp.zeros(16), jax.random.PRNGKey(0))
    assert float(jnp.mean(agg)) > 0.5

    A.defense_type = "nope"
    with pytest.raises(ValueError):
        d.init(A())
    A.defense_type = "krum"
    d.init(A())


def test_attacker_zero_frac_is_noop():
    class A:
        enable_attack = True
        attack_type = "byzantine_zero"
        byzantine_client_frac = 0.0
        random_seed = 0

    atk = FedMLAttacker.get_instance()
    atk.init(A())
    updates = jnp.ones((4, 6))
    out = atk.attack_model(updates, jnp.ones(4), jax.random.PRNGKey(0))
    np.testing.assert_allclose(out, updates)


# -- round-2 trust-suite additions ------------------------------------------

def test_alie_attack_within_std_range():
    """ALIE malicious rows sit at mean + z*std of honest rows — inside the
    plausible range (so norm defenses pass them) but biased."""
    key = jax.random.PRNGKey(0)
    updates = jax.random.normal(key, (8, 16))
    mask = jnp.array([1, 1, 0, 0, 0, 0, 0, 0], jnp.float32)
    out = attacks.alie_attack(updates, mask, num_std=1.5)
    honest = updates[2:]
    mean, std = honest.mean(0), honest.std(0)
    # malicious rows equal the prescribed point...
    np.testing.assert_allclose(out[0], mean + 1.5 * std, rtol=1e-5)
    np.testing.assert_allclose(out[0], out[1], rtol=1e-6)
    # ...honest rows untouched
    np.testing.assert_allclose(out[2:], honest)
    # and the malicious norm is comparable to honest norms (stealth)
    assert float(jnp.linalg.norm(out[0])) < 3 * float(
        jnp.linalg.norm(honest, axis=1).max()
    )


def test_pattern_backdoor_poison_images():
    x = jnp.zeros((2, 4, 8, 8, 3))  # [clients, cap, H, W, C]
    y = jnp.ones((2, 4), jnp.int32) * 5
    mask = jnp.zeros((2, 4)).at[0, :2].set(1.0)
    px, py = attacks.pattern_backdoor_poison(x, y, mask, target_class=0,
                                             pattern_value=2.8, pattern_size=3)
    # poisoned samples get the patch + target label
    assert float(px[0, 0, 0, 0, 0]) == pytest.approx(2.8)
    assert int(py[0, 0]) == 0
    # clean samples untouched
    assert float(jnp.abs(px[1]).max()) == 0.0
    assert int(py[1, 0]) == 5
    # patch is spatially confined
    assert float(jnp.abs(px[0, 0, 3:, 3:, :]).max()) == 0.0


def test_reveal_labels_from_gradients_idlg():
    """iDLG: with CE loss the true class's last-layer gradient row-sum is the
    unique negative one."""
    d_in, n_cls = 6, 4
    W = jax.random.normal(jax.random.PRNGKey(1), (d_in, n_cls)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(2), (d_in,))
    true_label = 2

    def loss(W_):
        logits = x @ W_
        return -jax.nn.log_softmax(logits)[true_label]

    g = jax.grad(loss)(W)
    scores = attacks.reveal_labels_from_gradients(g)
    assert int(jnp.argmin(scores)) == true_label
    assert float(scores[true_label]) < 0


def test_invert_gradient_reconstructs_input():
    """Cosine-matching inversion recovers a linear model's input (known
    labels), like the reference's InvertGradient on its convex toy case."""
    d = 8
    W = jax.random.normal(jax.random.PRNGKey(3), (d, 3)) * 0.5
    true_x = jax.random.normal(jax.random.PRNGKey(4), (d,))
    label = jnp.asarray(1)

    def grad_fn(x, y):
        def loss(W_):
            return -jax.nn.log_softmax(x @ W_)[y]

        return (jax.grad(loss)(W),)

    true_grads = grad_fn(true_x, label)
    dx = attacks.invert_gradient_attack(
        grad_fn, true_grads, jnp.zeros((d,)), label,
        lr=0.05, iters=800, tv_weight=0.0,
    )
    # cosine objective drives direction; scale is not identifiable — compare
    # normalized vectors
    cos = float(
        jnp.dot(dx, true_x) / (jnp.linalg.norm(dx) * jnp.linalg.norm(true_x))
    )
    assert cos > 0.95


def test_soteria_mask_prunes_leaky_features():
    """Features with tiny ||dr/dx||/|r| get pruned; informative ones stay."""

    def feature_fn(x):
        # feature 0 has tiny jacobian but large magnitude -> low ratio
        return jnp.stack([1000.0 + 1e-6 * x[0], x[1] * 3.0, x[0] + x[2]])

    mask = defenses.soteria_mask(feature_fn, jnp.ones(3), prune_percentile=40.0)
    assert float(mask[0]) == 0.0
    assert float(mask[1]) == 1.0 and float(mask[2]) == 1.0

    g = jnp.ones((3, 5))
    pruned = defenses.apply_soteria(g, mask)
    assert float(jnp.abs(pruned[0]).max()) == 0.0
    np.testing.assert_allclose(pruned[1:], g[1:])


def test_wbc_perturbs_stagnant_subspace_only():
    """Noise lands only where the gradient barely changed between rounds."""
    dim = 1000
    params = jnp.zeros(dim)
    grad = jnp.zeros(dim).at[: dim // 2].set(100.0)  # active half
    old = jnp.zeros(dim)
    out = defenses.wbc_perturb(params, grad, old, jax.random.PRNGKey(0),
                               pert_strength=1.0, learning_rate=0.1)
    active, stagnant = out[: dim // 2], out[dim // 2:]
    # active coordinates: |grad diff|=100 >> |noise| -> untouched
    np.testing.assert_allclose(active, 0.0)
    # stagnant coordinates: mostly perturbed
    assert float(jnp.mean((jnp.abs(stagnant) > 0).astype(jnp.float32))) > 0.9


def test_wbc_defender_dispatch():
    class A:
        enable_defense = True
        defense_type = "wbc"
        pert_strength = 0.01
        wbc_lr = 0.1

    d = FedMLDefender.get_instance()
    d.init(A())
    updates = jnp.ones((4, 16))
    agg1 = d.defend(updates, jnp.ones(4), jnp.zeros(16), jax.random.PRNGKey(0))
    assert agg1.shape == (16,)
    # second round uses stored old gradients without error
    agg2 = d.defend(updates * 1.1, jnp.ones(4), jnp.ones(16) * 0.5,
                    jax.random.PRNGKey(1))
    assert agg2.shape == (16,)
    # perturbation is small relative to the aggregate
    np.testing.assert_allclose(agg1, 1.0, atol=0.05)


def test_backdoor_pattern_manager_poisons_data():
    class A:
        enable_attack = True
        attack_type = "backdoor_pattern"
        byzantine_client_frac = 0.5
        poison_frac = 1.0
        target_class = 0
        pattern_value = 2.8
        pattern_size = 2
        random_seed = 0

    atk = FedMLAttacker.get_instance()
    atk.init(A())
    assert atk.is_data_attack() and not atk.is_model_attack()
    x = jnp.zeros((4, 6, 8, 8, 3))
    y = jnp.ones((4, 6), jnp.int32)
    px, py = atk.attack_data(x, y)
    poisoned_clients = int(
        (jnp.abs(px).reshape(4, -1).max(1) > 0).sum()
    )
    assert poisoned_clients == 2
    assert int((py == 0).sum()) == 12  # half the clients fully relabelled
