"""Pipeline parallelism (VERDICT next #10): the GPipe schedule over the
``pipeline`` mesh axis must match the unpipelined model exactly — same loss,
decreasing under training — and compose with data parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fedml_tpu.parallel.pipeline import PipelineCheetah, microbatch
from fedml_tpu.parallel.sharding import make_mesh
from fedml_tpu.parallel.transformer import (
    Block,
    TransformerConfig,
    rms_norm,
    rotary_embedding,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=64, n_layers=4, n_heads=4, n_kv_heads=4,
    d_ff=128, max_seq_len=32, remat=False,
)


def direct_loss(cfg, params, tokens, mask):
    """Unpipelined reference: same stacked params, plain layer loop."""
    block = Block(cfg)
    B, L = tokens.shape
    pos = jnp.arange(L)[None, :]
    cos, sin = rotary_embedding(pos, cfg.head_dim, cfg.rope_theta)
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    for i in range(cfg.n_layers):
        layer = jax.tree.map(lambda p: p[i], params["blocks"])
        x = block.apply({"params": layer}, x, cos, sin)
    h = rms_norm(x, params["norm_f"].astype(jnp.float32), cfg.norm_eps)
    logits = jnp.einsum(
        "bld,dv->blv", h, params["head"].astype(cfg.dtype)
    ).astype(jnp.float32)
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]
    )
    m = mask[:, 1:].astype(jnp.float32)
    return (per * m).sum() / jnp.maximum(m.sum(), 1.0)


def make_batch(rng, b=8, l=32):
    tokens = rng.randint(0, CFG.vocab_size, (b, l)).astype(np.int32)
    mask = np.ones_like(tokens)
    return tokens, mask


class TestPipelineParity:
    def test_two_stage_loss_matches_direct(self):
        mesh = make_mesh({"pipeline": 2}, devices=jax.devices()[:2])
        pp = PipelineCheetah(CFG, mesh, microbatches=2)
        params = pp.init_params(jax.random.PRNGKey(0))
        tokens, mask = make_batch(np.random.RandomState(0))
        mt, mm = microbatch(tokens, mask, 2)
        pl = float(pp.loss(params, jnp.asarray(mt), jnp.asarray(mm)))
        host = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), params)
        dl = float(direct_loss(CFG, host, jnp.asarray(tokens), jnp.asarray(mask)))
        assert pl == pytest.approx(dl, rel=2e-3), (pl, dl)

    def test_four_stage_with_data_axis(self):
        """pp=4 x dp=2 on the 8-device mesh, loss still matches direct."""
        mesh = make_mesh({"pipeline": 4, "data": 2})
        pp = PipelineCheetah(CFG, mesh, microbatches=4)
        params = pp.init_params(jax.random.PRNGKey(1))
        tokens, mask = make_batch(np.random.RandomState(1), b=8)
        mt, mm = microbatch(tokens, mask, 4)
        pl = float(pp.loss(params, jnp.asarray(mt), jnp.asarray(mm)))
        host = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), params)
        dl = float(direct_loss(CFG, host, jnp.asarray(tokens), jnp.asarray(mask)))
        assert pl == pytest.approx(dl, rel=2e-3), (pl, dl)

    def test_training_decreases_loss(self):
        mesh = make_mesh({"pipeline": 2}, devices=jax.devices()[:2])
        pp = PipelineCheetah(CFG, mesh, microbatches=2,
                             optimizer=optax.adamw(1e-3))
        params = pp.init_params(jax.random.PRNGKey(2))
        opt_state = pp.init_opt_state(params)
        rng = np.random.RandomState(2)
        # a tiny fixed corpus so the model can actually learn
        tokens, mask = make_batch(rng)
        mt, mm = microbatch(tokens, mask, 2)
        mt, mm = jnp.asarray(mt), jnp.asarray(mm)
        first = None
        for _ in range(30):
            params, opt_state, loss = pp.train_step(params, opt_state, mt, mm)
            if first is None:
                first = float(loss)
        assert float(loss) < first - 0.5, (first, float(loss))

    def test_grads_match_direct(self):
        """Cross-stage grad flow through the ppermute transpose is exact."""
        mesh = make_mesh({"pipeline": 2}, devices=jax.devices()[:2])
        pp = PipelineCheetah(CFG, mesh, microbatches=2)
        params = pp.init_params(jax.random.PRNGKey(3))
        tokens, mask = make_batch(np.random.RandomState(3))
        mt, mm = microbatch(tokens, mask, 2)

        # pipeline grads via one train step with SGD lr=1: delta = -grad
        sgd = optax.sgd(1.0)
        pp_sgd = PipelineCheetah(CFG, mesh, microbatches=2, optimizer=sgd)
        o = pp_sgd.init_opt_state(params)
        new_params, _, _ = pp_sgd.train_step(
            params, o, jnp.asarray(mt), jnp.asarray(mm)
        )
        host = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), params)
        ref_grads = jax.grad(
            lambda p: direct_loss(CFG, p, jnp.asarray(tokens), jnp.asarray(mask))
        )(host)
        for path in ("embed", "norm_f", "head"):
            got = np.asarray(params[path]) - np.asarray(new_params[path])
            want = np.asarray(ref_grads[path])
            np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-4)
        got_b = jax.tree.map(
            lambda a, b: np.asarray(a) - np.asarray(b),
            params["blocks"], new_params["blocks"],
        )
        for g, w in zip(jax.tree.leaves(got_b), jax.tree.leaves(ref_grads["blocks"])):
            np.testing.assert_allclose(g, np.asarray(w), rtol=5e-2, atol=5e-4)


@pytest.mark.slow
def test_bubble_fraction_measured():
    """The GPipe bubble is real and amortises with microbatch count: at
    fixed per-microbatch shape, per-token step time must drop as M grows,
    tracking the (S-1)/(M+S-1) schedule (loose band — CPU timing)."""
    import time

    mesh = make_mesh({"pipeline": 2}, devices=jax.devices()[:2])
    times = {}
    for m in (2, 8):
        pp = PipelineCheetah(CFG, mesh, microbatches=m)
        params = pp.init_params(jax.random.PRNGKey(0))
        tokens = np.random.RandomState(0).randint(
            0, CFG.vocab_size, (4 * m, 32)).astype(np.int32)
        mt, mm = microbatch(tokens, np.ones_like(tokens), m)
        mt, mm = jnp.asarray(mt), jnp.asarray(mm)
        pp.loss(params, mt, mm)  # compile
        t0 = time.perf_counter()
        for _ in range(3):
            float(pp.loss(params, mt, mm))
        times[m] = (time.perf_counter() - t0) / (3 * tokens.size)
    # theory: per-token time ∝ (M+S-1)/M = 1.5 @ M=2 vs 1.125 @ M=8
    speedup = times[2] / times[8]
    assert speedup > 1.05, (times, pp.bubble_fraction())
    assert PipelineCheetah(CFG, mesh, microbatches=2).bubble_fraction() == (
        pytest.approx(1 / 3)
    )
    assert PipelineCheetah(CFG, mesh, microbatches=8).bubble_fraction() == (
        pytest.approx(1 / 9)
    )


def test_opt_state_specs_match_by_path_not_shape():
    """Two same-shaped params with DIFFERENT shardings must not collide when
    optimizer-state specs are derived (was: matched by leaf shape)."""
    from jax.sharding import PartitionSpec as P

    from fedml_tpu.parallel.pipeline import _opt_state_specs

    params = {
        "stacked": jnp.ones((4, 8)),      # sharded over pipeline
        "replicated": jnp.ones((4, 8)),   # same shape, replicated
    }
    p_spec = {"stacked": P("pipeline"), "replicated": P()}
    opt_state = optax.adam(1e-3).init(params)
    o_spec = _opt_state_specs(p_spec, opt_state)
    flat = jax.tree_util.tree_flatten_with_path(
        o_spec, is_leaf=lambda x: isinstance(x, P)
    )[0]
    by_path = {"/".join(map(str, [getattr(k, "key", getattr(k, "name", k))
                                  for k in path])): sp
               for path, sp in flat}
    for name, sp in by_path.items():
        if name.endswith("stacked") and ("mu" in name or "nu" in name):
            assert sp == P("pipeline"), (name, sp)
        elif name.endswith("replicated"):
            assert sp == P(), (name, sp)
        elif "count" in name:
            assert sp == P(), (name, sp)


class Test1F1B:
    def test_1f1b_matches_gpipe(self):
        """The hand-scheduled 1F1B tick loop is gradient-exact: one SGD
        train step must produce the same params and loss as the
        autodiff-GPipe schedule."""
        mesh = make_mesh({"pipeline": 2}, devices=jax.devices()[:2])
        params = PipelineCheetah(CFG, mesh, microbatches=4).init_params(
            jax.random.PRNGKey(5)
        )
        tokens, mask = make_batch(np.random.RandomState(5))
        mt, mm = microbatch(tokens, mask, 4)
        results = {}
        for sched in ("gpipe", "1f1b"):
            pp = PipelineCheetah(CFG, mesh, microbatches=4,
                                 optimizer=optax.sgd(1.0), schedule=sched)
            o = pp.init_opt_state(params)
            new_params, _, loss = pp.train_step(
                params, o, jnp.asarray(mt), jnp.asarray(mm)
            )
            results[sched] = (new_params, float(loss))
        assert np.isclose(results["gpipe"][1], results["1f1b"][1],
                          rtol=1e-5), results
        for g, f in zip(jax.tree.leaves(results["gpipe"][0]),
                        jax.tree.leaves(results["1f1b"][0])):
            # bf16 recompute/reassociation noise between the two
            # schedules: abs diffs measure ~2e-4 on grads of ~1e-2
            np.testing.assert_allclose(np.asarray(g), np.asarray(f),
                                       rtol=2e-2, atol=8e-4)

    def test_1f1b_four_stage_with_data_axis(self):
        """1F1B composes with data parallelism and trains (loss drops)."""
        mesh = make_mesh({"pipeline": 4, "data": 2},
                         devices=jax.devices()[:8])
        pp = PipelineCheetah(CFG, mesh, microbatches=4,
                             optimizer=optax.adamw(3e-3), schedule="1f1b")
        params = pp.init_params(jax.random.PRNGKey(6))
        o = pp.init_opt_state(params)
        tokens, mask = make_batch(np.random.RandomState(6), b=8)
        mt, mm = jnp.asarray(microbatch(tokens, mask, 4)[0]), jnp.asarray(
            microbatch(tokens, mask, 4)[1])
        losses = []
        for _ in range(6):
            params, o, loss = pp.train_step(params, o, mt, mm)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_1f1b_activation_memory_beats_gpipe(self):
        """1F1B's reason to exist: in-flight activations are O(S), not
        O(M). Compare the compiled per-device temp footprint of both
        schedules at M=16 — same model, same batch; the 1F1B program must
        be materially smaller (the GPipe scan keeps all M + S - 1 stage
        outputs alive for autodiff)."""
        mesh = make_mesh({"pipeline": 2}, devices=jax.devices()[:2])
        M = 16
        tokens = np.random.RandomState(7).randint(
            0, CFG.vocab_size, (M * 2, 32)).astype(np.int32)
        mt, mm = microbatch(tokens, np.ones_like(tokens), M)
        temps = {}
        for sched in ("gpipe", "1f1b"):
            pp = PipelineCheetah(CFG, mesh, microbatches=M,
                                 optimizer=optax.sgd(0.1), schedule=sched)
            params = pp.init_params(jax.random.PRNGKey(7))
            o = pp.init_opt_state(params)
            pp.train_step(params, o, jnp.asarray(mt), jnp.asarray(mm))
            temps[sched] = int(
                pp._step.lower(params, o, jnp.asarray(mt), jnp.asarray(mm))
                .compile().memory_analysis().temp_size_in_bytes
            )
        assert temps["1f1b"] < 0.8 * temps["gpipe"], temps
